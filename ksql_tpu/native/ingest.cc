// Native batch ingest: record payloads -> columnar arrays.
//
// The C++ tier of the host ingest pipeline (SURVEY §2.2: the reference's
// native dependencies are RocksDB + Kafka client codecs; our equivalent is
// a columnar decoder feeding the device DMA path).  One call parses a
// whole micro-batch of payloads into fixed-width column arrays
// (numeric/boolean) and stable-hash64 codes (strings), bypassing per-record
// Python dict materialization entirely.  Three payload modes share the
// call (MODE_* below): wrapped JSON objects, unwrapped single JSON scalars
// (SerdeFeature UNWRAP_SINGLES), and DELIMITED (commons-csv minimal-quote)
// rows.  A payload the native grammar cannot take bit-identically to the
// Python serde marks its row not-ok and the caller replays it per record.
//
// Hash compatibility: string codes must be bit-identical to
// ksql_tpu/common/batch.py:stable_hash64 — blake2b(digest_size=8) over
// b"\x00" + utf8, little-endian signed.  The BLAKE2b core below follows
// RFC 7693.
//
// Build: g++ -O3 -shared -fPIC ingest.cc -o _libingest.so  (no deps).

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <cmath>
#include <string>
#include <unordered_map>
#include <vector>

// ------------------------------------------------------------------ blake2b

namespace {

static const uint64_t blake2b_IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

static const uint8_t blake2b_sigma[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

static inline uint64_t rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

struct Blake2bState {
  uint64_t h[8];
  uint64_t t[2];
  uint8_t buf[128];
  size_t buflen;
};

static void blake2b_compress(Blake2bState* S, const uint8_t block[128],
                             int last) {
  uint64_t m[16], v[16];
  for (int i = 0; i < 16; i++) {
    memcpy(&m[i], block + i * 8, 8);
  }
  for (int i = 0; i < 8; i++) v[i] = S->h[i];
  for (int i = 0; i < 8; i++) v[i + 8] = blake2b_IV[i];
  v[12] ^= S->t[0];
  v[13] ^= S->t[1];
  if (last) v[14] = ~v[14];
#define G(r, i, a, b, c, d)                      \
  do {                                           \
    a = a + b + m[blake2b_sigma[r][2 * i]];      \
    d = rotr64(d ^ a, 32);                       \
    c = c + d;                                   \
    b = rotr64(b ^ c, 24);                       \
    a = a + b + m[blake2b_sigma[r][2 * i + 1]];  \
    d = rotr64(d ^ a, 16);                       \
    c = c + d;                                   \
    b = rotr64(b ^ c, 63);                       \
  } while (0)
  for (int r = 0; r < 12; r++) {
    G(r, 0, v[0], v[4], v[8], v[12]);
    G(r, 1, v[1], v[5], v[9], v[13]);
    G(r, 2, v[2], v[6], v[10], v[14]);
    G(r, 3, v[3], v[7], v[11], v[15]);
    G(r, 4, v[0], v[5], v[10], v[15]);
    G(r, 5, v[1], v[6], v[11], v[12]);
    G(r, 6, v[2], v[7], v[8], v[13]);
    G(r, 7, v[3], v[4], v[9], v[14]);
  }
#undef G
  for (int i = 0; i < 8; i++) S->h[i] ^= v[i] ^ v[i + 8];
}

// blake2b with digest_size=8, no key (hashlib.blake2b(raw, digest_size=8))
static int64_t blake2b8(const uint8_t* data, size_t len) {
  Blake2bState S;
  memset(&S, 0, sizeof(S));
  for (int i = 0; i < 8; i++) S.h[i] = blake2b_IV[i];
  // parameter block: digest_length=8, fanout=1, depth=1
  S.h[0] ^= 0x01010008ULL;
  while (len > 128) {
    S.t[0] += 128;
    blake2b_compress(&S, data, 0);
    data += 128;
    len -= 128;
  }
  uint8_t block[128];
  memset(block, 0, 128);
  memcpy(block, data, len);
  S.t[0] += len;
  blake2b_compress(&S, block, 1);
  int64_t out;
  memcpy(&out, &S.h[0], 8);  // little-endian digest prefix
  return out;
}

// stable_hash64 of a string value: blake2b8 over b"\x00" + utf8
static int64_t hash_string(const char* s, size_t len) {
  std::vector<uint8_t> raw(len + 1);
  raw[0] = 0x00;
  memcpy(raw.data() + 1, s, len);
  return blake2b8(raw.data(), raw.size());
}

// ------------------------------------------------------------- JSON parser

struct Cursor {
  const char* p;
  const char* end;
};

static inline void skip_ws(Cursor* c) {
  while (c->p < c->end &&
         (*c->p == ' ' || *c->p == '\t' || *c->p == '\n' || *c->p == '\r'))
    c->p++;
}

// decode a JSON string starting at the opening quote into out (UTF-8);
// returns 0 on failure; cursor ends after closing quote
static int parse_string(Cursor* c, std::string* out) {
  if (c->p >= c->end || *c->p != '"') return 0;
  c->p++;
  out->clear();
  while (c->p < c->end) {
    char ch = *c->p;
    if (ch == '"') {
      c->p++;
      return 1;
    }
    if (ch == '\\') {
      c->p++;
      if (c->p >= c->end) return 0;
      char e = *c->p++;
      switch (e) {
        case '"': out->push_back('"'); break;
        case '\\': out->push_back('\\'); break;
        case '/': out->push_back('/'); break;
        case 'b': out->push_back('\b'); break;
        case 'f': out->push_back('\f'); break;
        case 'n': out->push_back('\n'); break;
        case 'r': out->push_back('\r'); break;
        case 't': out->push_back('\t'); break;
        case 'u': {
          if (c->end - c->p < 4) return 0;
          unsigned cp = 0;
          for (int i = 0; i < 4; i++) {
            char h = c->p[i];
            cp <<= 4;
            if (h >= '0' && h <= '9') cp |= h - '0';
            else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
            else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
            else return 0;
          }
          c->p += 4;
          // surrogate pair
          if (cp >= 0xD800 && cp <= 0xDBFF && c->end - c->p >= 6 &&
              c->p[0] == '\\' && c->p[1] == 'u') {
            unsigned lo = 0;
            for (int i = 0; i < 4; i++) {
              char h = c->p[2 + i];
              lo <<= 4;
              if (h >= '0' && h <= '9') lo |= h - '0';
              else if (h >= 'a' && h <= 'f') lo |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') lo |= h - 'A' + 10;
              else return 0;
            }
            if (lo >= 0xDC00 && lo <= 0xDFFF) {
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              c->p += 6;
            }
          }
          // UTF-8 encode
          if (cp < 0x80) {
            out->push_back((char)cp);
          } else if (cp < 0x800) {
            out->push_back((char)(0xC0 | (cp >> 6)));
            out->push_back((char)(0x80 | (cp & 0x3F)));
          } else if (cp < 0x10000) {
            out->push_back((char)(0xE0 | (cp >> 12)));
            out->push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
            out->push_back((char)(0x80 | (cp & 0x3F)));
          } else {
            out->push_back((char)(0xF0 | (cp >> 18)));
            out->push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
            out->push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
            out->push_back((char)(0x80 | (cp & 0x3F)));
          }
          break;
        }
        default: return 0;
      }
      continue;
    }
    if ((unsigned char)ch < 0x20) return 0;  // json.loads strict mode
    out->push_back(ch);
    c->p++;
  }
  return 0;
}

// skip any JSON value (for fields we don't extract); returns 0 on failure
static int skip_value(Cursor* c) {
  skip_ws(c);
  if (c->p >= c->end) return 0;
  char ch = *c->p;
  if (ch == '"') {
    std::string tmp;
    return parse_string(c, &tmp);
  }
  if (ch == '{' || ch == '[') {
    char open = ch, close = (ch == '{') ? '}' : ']';
    int depth = 0;
    while (c->p < c->end) {
      char x = *c->p;
      if (x == '"') {
        std::string tmp;
        if (!parse_string(c, &tmp)) return 0;
        continue;
      }
      if (x == open) depth++;
      if (x == close) {
        depth--;
        if (depth == 0) {
          c->p++;
          return 1;
        }
      }
      c->p++;
    }
    return 0;
  }
  // literal / number: scan to delimiter
  while (c->p < c->end && *c->p != ',' && *c->p != '}' && *c->p != ']' &&
         *c->p != ' ' && *c->p != '\t' && *c->p != '\n' && *c->p != '\r')
    c->p++;
  return 1;
}

// field type codes (mirror ksql_tpu/native/__init__.py)
enum FieldType {
  FT_BIGINT = 0,   // int64
  FT_INT = 1,      // int32
  FT_DOUBLE = 2,   // float64
  FT_BOOLEAN = 3,  // uint8
  FT_STRING = 4,   // int64 stable-hash codes
};

struct StringArena {
  // unique strings discovered this batch (for host dictionary learning)
  std::unordered_map<int64_t, uint32_t> seen;  // hash -> index
  std::string bytes;                           // concatenated utf-8
  std::vector<int64_t> offsets;                // per-unique end offset
  std::vector<int64_t> hashes;
};

// payload modes (mirror ksql_tpu/native/__init__.py)
enum ParseMode {
  MODE_JSON_WRAPPED = 0,    // one JSON object per payload
  MODE_JSON_UNWRAPPED = 1,  // one bare JSON scalar per payload (nf == 1)
  MODE_DELIMITED = 2,       // commons-csv minimal-quote row per payload
};

// shared per-batch parse context: output columns + string scratch
struct ParseCtx {
  int nf;
  const int32_t* types;
  void** out_data;
  uint8_t** out_valid;
  StringArena* arena;
  std::vector<std::string> fnames;
  std::string key, sval;              // scratch (object / single modes)
  std::vector<std::string> fields;    // scratch (delimited mode)
};

static void store_string(ParseCtx* x, int fi, int i, const std::string& s) {
  int64_t h = hash_string(s.data(), s.size());
  ((int64_t*)x->out_data[fi])[i] = h;
  x->out_valid[fi][i] = 1;
  if (x->arena && x->arena->seen.find(h) == x->arena->seen.end()) {
    x->arena->seen.emplace(h, (uint32_t)x->arena->hashes.size());
    x->arena->bytes.append(s);
    x->arena->offsets.push_back((int64_t)x->arena->bytes.size());
    x->arena->hashes.push_back(h);
  }
}

// strict JSON number grammar at the cursor (strtod alone would accept
// hex/inf/nan and fabricate values Python rejects).  On success advances
// the cursor past the token and returns 1 with [*tok_s, *tok_e) set;
// *integral is false when a fraction or exponent appeared.  The character
// after the token is NOT validated here — callers check their own
// delimiter/end expectations.
static int scan_json_number(Cursor* c, bool* integral, const char** tok_s,
                            const char** tok_e) {
  const char* start = c->p;
  const char* q = start;
  if (q < c->end && *q == '-') q++;
  const char* digs = q;
  while (q < c->end && *q >= '0' && *q <= '9') q++;
  *integral = true;
  // JSON forbids leading zeros ("01"); Python json drops the record
  bool grammar_ok = q > digs && !(*digs == '0' && q - digs > 1);
  if (q < c->end && *q == '.') {
    *integral = false;
    q++;
    const char* fr = q;
    while (q < c->end && *q >= '0' && *q <= '9') q++;
    grammar_ok = grammar_ok && q > fr;
  }
  if (grammar_ok && q < c->end && (*q == 'e' || *q == 'E')) {
    *integral = false;
    q++;
    if (q < c->end && (*q == '+' || *q == '-')) q++;
    const char* ex = q;
    while (q < c->end && *q >= '0' && *q <= '9') q++;
    grammar_ok = grammar_ok && q > ex;
  }
  if (!grammar_ok) return 0;
  *tok_s = start;
  *tok_e = q;
  c->p = q;
  return 1;
}

// store a validated JSON number token into a numeric column; returns 0
// when Python-fallback semantics apply (fractional into int, overflow)
static int store_number(ParseCtx* x, int fi, int i, const char* s,
                        const char* e, bool integral) {
  std::string tok(s, e - s);
  if (x->types[fi] == FT_DOUBLE) {
    ((double*)x->out_data[fi])[i] = strtod(tok.c_str(), nullptr);
    x->out_valid[fi][i] = 1;
    return 1;
  }
  if (!integral) return 0;  // fractional into an int column: Python semantics
  errno = 0;
  long long v = strtoll(tok.c_str(), nullptr, 10);
  if (errno == ERANGE) return 0;
  if (x->types[fi] == FT_BIGINT) {
    ((int64_t*)x->out_data[fi])[i] = (int64_t)v;
  } else {
    if (v < INT32_MIN || v > INT32_MAX) return 0;
    ((int32_t*)x->out_data[fi])[i] = (int32_t)v;
  }
  x->out_valid[fi][i] = 1;
  return 1;
}

// ---------------------------------------------------- mode 0: JSON object

static int parse_row_object(ParseCtx* x, Cursor c, int i) {
  skip_ws(&c);
  if (c.p >= c.end || *c.p != '{') return 0;
  c.p++;
  int ok = 1;
  while (ok) {
    skip_ws(&c);
    if (c.p < c.end && *c.p == '}') {
      c.p++;
      break;
    }
    if (!parse_string(&c, &x->key)) {
      ok = 0;
      break;
    }
    skip_ws(&c);
    if (c.p >= c.end || *c.p != ':') {
      ok = 0;
      break;
    }
    c.p++;
    skip_ws(&c);
    // exact field-name match, else case-insensitive
    int fi = -1;
    for (int f = 0; f < x->nf; f++) {
      if (x->fnames[f] == x->key) {
        fi = f;
        break;
      }
    }
    if (fi < 0) {
      for (int f = 0; f < x->nf; f++) {
        if (x->fnames[f].size() == x->key.size()) {
          bool eq = true;
          for (size_t j = 0; j < x->key.size(); j++) {
            char a = x->fnames[f][j], b = x->key[j];
            if (a >= 'a' && a <= 'z') a -= 32;
            if (b >= 'a' && b <= 'z') b -= 32;
            if (a != b) { eq = false; break; }
          }
          if (eq) { fi = f; break; }
        }
      }
    }
    if (fi < 0) {
      // Unmatched key with non-ASCII bytes: full-Unicode case folding
      // (the Python path's str.upper()) might still match it to a
      // field, so let the Python fallback decide the whole row.
      for (size_t j = 0; j < x->key.size(); j++) {
        if ((unsigned char)x->key[j] >= 0x80) { ok = 0; break; }
      }
      if (!ok) break;
      if (!skip_value(&c)) ok = 0;
    } else {
      char ch = (c.p < c.end) ? *c.p : 0;
      if (ch == 'n' && c.end - c.p >= 4 && !memcmp(c.p, "null", 4)) {
        c.p += 4;  // null -> invalid; clears an earlier duplicate key's
        x->out_valid[fi][i] = 0;  // value (Python dict semantics: last wins)
      } else if (x->types[fi] == FT_STRING) {
        if (ch == '"') {
          if (!parse_string(&c, &x->sval)) { ok = 0; break; }
          store_string(x, fi, i, x->sval);
        } else {
          ok = 0;  // non-string value for a string field: Python decides
        }
      } else if (x->types[fi] == FT_BOOLEAN) {
        if (ch == 't' && c.end - c.p >= 4 && !memcmp(c.p, "true", 4)) {
          c.p += 4;
          ((uint8_t*)x->out_data[fi])[i] = 1;
          x->out_valid[fi][i] = 1;
        } else if (ch == 'f' && c.end - c.p >= 5 && !memcmp(c.p, "false", 5)) {
          c.p += 5;
          ((uint8_t*)x->out_data[fi])[i] = 0;
          x->out_valid[fi][i] = 1;
        } else {
          ok = 0;
        }
      } else {
        bool integral;
        const char* ts;
        const char* te;
        if (!scan_json_number(&c, &integral, &ts, &te) ||
            (c.p < c.end && *c.p != ',' && *c.p != '}' && *c.p != ']' &&
             *c.p != ' ' && *c.p != '\t' && *c.p != '\n' && *c.p != '\r')) {
          ok = 0;
        } else if (!store_number(x, fi, i, ts, te, integral)) {
          ok = 0;
          continue;
        }
      }
    }
    if (!ok) break;
    skip_ws(&c);
    if (c.p < c.end && *c.p == ',') {
      c.p++;
      continue;
    }
    if (c.p < c.end && *c.p == '}') {
      c.p++;
      break;
    }
    ok = 0;
  }
  if (!ok) return 0;
  skip_ws(&c);
  return c.p == c.end ? 1 : 0;
}

// ------------------------------------------- mode 1: unwrapped JSON scalar
//
// One bare JSON value per payload into the single requested column,
// mirroring JsonFormat(wrap=False) + _coerce.  Cross-type coercions the
// Python serde applies (string->int, number->str, bool()->truthiness, ...)
// defer to the fallback; a payload json.loads would reject lands a single
// STRING column as raw text (JsonFormat's unwrapped raw-text path).
static int parse_row_single(ParseCtx* x, Cursor c, int i) {
  const char* raw_s = c.p;
  const char* raw_e = c.end;
  int32_t t = x->types[0];
  skip_ws(&c);
  if (c.p >= c.end) {
    // whitespace-only payload: json.loads raises -> raw text for STRING
    if (t != FT_STRING) return 0;
    x->sval.assign(raw_s, raw_e - raw_s);
    store_string(x, 0, i, x->sval);
    return 1;
  }
  char ch = *c.p;
  if (ch == '"') {
    if (parse_string(&c, &x->sval)) {
      skip_ws(&c);
      if (c.p == c.end) {
        if (t != FT_STRING) return 0;  // string into numeric/bool: Python
        store_string(x, 0, i, x->sval);
        return 1;
      }
    }
    // bad string / trailing garbage: json.loads fails on both
    if (t != FT_STRING) return 0;
    x->sval.assign(raw_s, raw_e - raw_s);
    store_string(x, 0, i, x->sval);
    return 1;
  }
  if (ch == 'n' && c.end - c.p >= 4 && !memcmp(c.p, "null", 4)) {
    Cursor after{c.p + 4, c.end};
    skip_ws(&after);
    if (after.p == after.end) return 1;  // null -> NULL (valid stays 0)
    // "null..." trailing garbage: invalid JSON
    if (t != FT_STRING) return 0;
    x->sval.assign(raw_s, raw_e - raw_s);
    store_string(x, 0, i, x->sval);
    return 1;
  }
  if (ch == 't' || ch == 'f') {
    int len = ch == 't' ? 4 : 5;
    const char* lit = ch == 't' ? "true" : "false";
    if (c.end - c.p >= len && !memcmp(c.p, lit, len)) {
      Cursor after{c.p + len, c.end};
      skip_ws(&after);
      if (after.p == after.end) {
        if (t != FT_BOOLEAN) return 0;  // bool coercion: Python decides
        ((uint8_t*)x->out_data[0])[i] = ch == 't' ? 1 : 0;
        x->out_valid[0][i] = 1;
        return 1;
      }
    }
    // not the literal: invalid JSON -> raw text for STRING
    if (t != FT_STRING) return 0;
    x->sval.assign(raw_s, raw_e - raw_s);
    store_string(x, 0, i, x->sval);
    return 1;
  }
  if (ch == '{' || ch == '[') return 0;  // composite: Python decides
  if (ch == 'I' || ch == 'N' || (ch == '-' && c.end - c.p >= 2 &&
                                 c.p[1] == 'I')) {
    // Python's json accepts Infinity/-Infinity/NaN constants: defer
    return 0;
  }
  if (ch == '-' || (ch >= '0' && ch <= '9')) {
    bool integral;
    const char* ts;
    const char* te;
    if (scan_json_number(&c, &integral, &ts, &te)) {
      skip_ws(&c);
      if (c.p == c.end) {
        if (t == FT_STRING || t == FT_BOOLEAN) return 0;  // coercion: Python
        return store_number(x, 0, i, ts, te, integral);
      }
    }
    // invalid number / trailing garbage: invalid JSON
    if (t != FT_STRING) return 0;
    x->sval.assign(raw_s, raw_e - raw_s);
    store_string(x, 0, i, x->sval);
    return 1;
  }
  // anything else cannot start a JSON value: raw text for STRING
  if (t != FT_STRING) return 0;
  x->sval.assign(raw_s, raw_e - raw_s);
  store_string(x, 0, i, x->sval);
  return 1;
}

// ------------------------------------------------------ mode 2: DELIMITED

// DelimitedFormat._split bit-exactly: stateful quote-aware scan with
// doubled-quote escapes; a split never fails (unterminated quotes just
// consume to end-of-payload, like the Python parser)
static void delim_split(const char* p, const char* end, char delim,
                        std::vector<std::string>* out) {
  out->clear();
  std::string cur;
  bool in_quotes = false;
  while (p < end) {
    char ch = *p;
    if (in_quotes) {
      if (ch == '"') {
        if (p + 1 < end && p[1] == '"') {
          cur.push_back('"');
          p += 2;
          continue;
        }
        in_quotes = false;
      } else {
        cur.push_back(ch);
      }
    } else if (ch == '"') {
      in_quotes = true;
    } else if (ch == delim) {
      out->push_back(cur);
      cur.clear();
    } else {
      cur.push_back(ch);
    }
    p++;
  }
  out->push_back(cur);
}

static bool all_ascii(const std::string& s) {
  for (char ch : s) {
    if ((unsigned char)ch >= 0x80) return false;
  }
  return true;
}

// the ASCII whitespace int()/float() accept around a numeric literal
static inline bool ascii_ws(char ch) {
  return ch == ' ' || ch == '\t' || ch == '\n' || ch == '\r' || ch == '\v' ||
         ch == '\f';
}

// str.strip()'s ASCII whitespace is wider: \x1c-\x1f are Unicode
// whitespace (separator controls) that int()/float() reject
static inline bool strip_ws(char ch) {
  return ascii_ws(ch) || ((unsigned char)ch >= 0x1c && (unsigned char)ch <= 0x1f);
}

// Python int(raw): optional surrounding whitespace, [+-]?digits.  The
// grammar here is strictly narrower (no underscores, no unicode digits) —
// anything else defers to the fallback, which reproduces int()'s full
// behavior including its ValueError.
static int parse_delim_int(const std::string& s, long long* out) {
  size_t a = 0, b = s.size();
  while (a < b && ascii_ws(s[a])) a++;
  while (b > a && ascii_ws(s[b - 1])) b--;
  if (a >= b) return 0;
  size_t q = a;
  if (s[q] == '+' || s[q] == '-') q++;
  size_t digs = q;
  while (q < b && s[q] >= '0' && s[q] <= '9') q++;
  if (q != b || q == digs) return 0;
  std::string tok(s, a, b - a);
  errno = 0;
  long long v = strtoll(tok.c_str(), nullptr, 10);
  if (errno == ERANGE) return 0;
  *out = v;
  return 1;
}

// Python float(raw) over the plain-decimal grammar ("1.", ".5", "1e3");
// inf/nan/underscored literals defer to the fallback
static int parse_delim_double(const std::string& s, double* out) {
  size_t a = 0, b = s.size();
  while (a < b && ascii_ws(s[a])) a++;
  while (b > a && ascii_ws(s[b - 1])) b--;
  if (a >= b) return 0;
  size_t q = a;
  if (s[q] == '+' || s[q] == '-') q++;
  size_t int_digs = 0, frac_digs = 0;
  while (q < b && s[q] >= '0' && s[q] <= '9') { q++; int_digs++; }
  if (q < b && s[q] == '.') {
    q++;
    while (q < b && s[q] >= '0' && s[q] <= '9') { q++; frac_digs++; }
  }
  if (int_digs + frac_digs == 0) return 0;
  if (q < b && (s[q] == 'e' || s[q] == 'E')) {
    q++;
    if (q < b && (s[q] == '+' || s[q] == '-')) q++;
    size_t ex = q;
    while (q < b && s[q] >= '0' && s[q] <= '9') q++;
    if (q == ex) return 0;
  }
  if (q != b) return 0;
  std::string tok(s, a, b - a);
  *out = strtod(tok.c_str(), nullptr);
  return 1;
}

static int parse_row_delimited(ParseCtx* x, Cursor c, int i, char delim) {
  delim_split(c.p, c.end, delim, &x->fields);
  if ((int)x->fields.size() != x->nf) {
    return 0;  // count mismatch: Python raises SerdeException (error-logged)
  }
  for (int f = 0; f < x->nf; f++) {
    const std::string& raw = x->fields[f];
    if (raw.empty()) continue;  // "" -> NULL (valid stays 0)
    switch (x->types[f]) {
      case FT_STRING:
        store_string(x, f, i, raw);
        break;
      case FT_BOOLEAN: {
        // raw.strip().lower() == "true"; non-ASCII bytes could be unicode
        // whitespace under Python's strip -> defer
        if (!all_ascii(raw)) return 0;
        size_t a = 0, b = raw.size();
        while (a < b && strip_ws(raw[a])) a++;
        while (b > a && strip_ws(raw[b - 1])) b--;
        bool t = (b - a) == 4;
        static const char* lit = "true";
        for (size_t j = 0; t && j < 4; j++) {
          char ch = raw[a + j];
          if (ch >= 'A' && ch <= 'Z') ch += 32;
          if (ch != lit[j]) t = false;
        }
        ((uint8_t*)x->out_data[f])[i] = t ? 1 : 0;
        x->out_valid[f][i] = 1;
        break;
      }
      case FT_DOUBLE: {
        if (!all_ascii(raw)) return 0;
        double v;
        if (!parse_delim_double(raw, &v)) return 0;
        ((double*)x->out_data[f])[i] = v;
        x->out_valid[f][i] = 1;
        break;
      }
      default: {  // FT_BIGINT / FT_INT
        if (!all_ascii(raw)) return 0;
        long long v;
        if (!parse_delim_int(raw, &v)) return 0;
        if (x->types[f] == FT_BIGINT) {
          ((int64_t*)x->out_data[f])[i] = (int64_t)v;
        } else {
          if (v < INT32_MIN || v > INT32_MAX) return 0;
          ((int32_t*)x->out_data[f])[i] = (int32_t)v;
        }
        x->out_valid[f][i] = 1;
        break;
      }
    }
  }
  return 1;
}

}  // namespace

extern "C" {

// Parse n payloads into columns.
//
//   buf/offsets: payload i is buf[offsets[i] .. offsets[i+1])
//   nf fields: names (concatenated, name_offsets), types[nf]
//   out_data[f]: int64*/int32*/double*/uint8* per type, length n
//   out_valid[f]: uint8* length n
//   row_ok: uint8* length n — 0 where the payload failed to parse (caller
//           falls back to the Python decoder for those rows)
//   mode: ParseMode; delim: field separator for MODE_DELIMITED
//
// Returns an opaque StringArena* holding this batch's unique strings (fetch
// with ingest_arena_*; free with ingest_free_arena), or nullptr when no
// string fields were requested.
void* ingest_parse_batch2(const char* buf, const int64_t* offsets, int n,
                          int nf, const char* names,
                          const int64_t* name_offsets, const int32_t* types,
                          void** out_data, uint8_t** out_valid,
                          uint8_t* row_ok, int32_t mode, char delim) {
  ParseCtx x;
  x.nf = nf;
  x.types = types;
  x.out_data = out_data;
  x.out_valid = out_valid;
  x.arena = nullptr;
  for (int f = 0; f < nf; f++) {
    if (types[f] == FT_STRING && x.arena == nullptr) {
      x.arena = new StringArena();
    }
  }
  x.fnames.resize(nf);
  for (int f = 0; f < nf; f++) {
    x.fnames[f].assign(names + name_offsets[f], names + name_offsets[f + 1]);
  }
  for (int i = 0; i < n; i++) {
    for (int f = 0; f < nf; f++) out_valid[f][i] = 0;
    Cursor c{buf + offsets[i], buf + offsets[i + 1]};
    int ok;
    switch (mode) {
      case MODE_JSON_UNWRAPPED:
        ok = parse_row_single(&x, c, i);
        break;
      case MODE_DELIMITED:
        ok = parse_row_delimited(&x, c, i, delim);
        break;
      default:
        ok = parse_row_object(&x, c, i);
        break;
    }
    row_ok[i] = ok ? 1 : 0;
    if (!ok) {
      for (int f = 0; f < nf; f++) out_valid[f][i] = 0;
    }
  }
  return x.arena;
}

// legacy entry: wrapped-JSON objects only
void* ingest_parse_batch(const char* buf, const int64_t* offsets, int n,
                         int nf, const char* names, const int64_t* name_offsets,
                         const int32_t* types, void** out_data,
                         uint8_t** out_valid, uint8_t* row_ok) {
  return ingest_parse_batch2(buf, offsets, n, nf, names, name_offsets, types,
                             out_data, out_valid, row_ok, MODE_JSON_WRAPPED,
                             ',');
}

int64_t ingest_arena_count(void* arena) {
  return arena ? (int64_t)((StringArena*)arena)->hashes.size() : 0;
}

int64_t ingest_arena_bytes_len(void* arena) {
  return arena ? (int64_t)((StringArena*)arena)->bytes.size() : 0;
}

void ingest_arena_fetch(void* arena, int64_t* hashes, int64_t* ends,
                        char* bytes) {
  if (!arena) return;
  StringArena* a = (StringArena*)arena;
  memcpy(hashes, a->hashes.data(), a->hashes.size() * 8);
  memcpy(ends, a->offsets.data(), a->offsets.size() * 8);
  memcpy(bytes, a->bytes.data(), a->bytes.size());
}

void ingest_free_arena(void* arena) {
  delete (StringArena*)arena;
}

int64_t ingest_hash_string(const char* s, int64_t len) {
  return hash_string(s, (size_t)len);
}

}  // extern "C"
