"""Native (C++) ingest tier: batch payloads -> columnar arrays.

The runtime-native component prescribed by SURVEY §2.2 — the reference's
hot host path is native (Kafka client codecs, RocksDB JNI); ours is a
columnar batch decoder (ingest.cc) that turns a micro-batch of payloads
into device-ready arrays in one call, including stable-hash64 string
codes bit-identical to the Python dictionary encoder.  Three payload
modes are supported (MODE_JSON / MODE_JSON_SINGLE / MODE_DELIMITED);
rows the native grammar cannot decode bit-identically to the Python
serde come back with ``row_ok`` False and the caller replays them.

The shared library builds on first use with g++ (no external deps) and is
cached next to the source; every consumer falls back to the pure-Python
decode path when the toolchain or build is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "ingest.cc")
_LIB = os.path.join(_DIR, "_libingest.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_failed = False

# field type codes (mirror ingest.cc FieldType)
FT_BIGINT, FT_INT, FT_DOUBLE, FT_BOOLEAN, FT_STRING = 0, 1, 2, 3, 4

# payload modes (mirror ingest.cc ParseMode)
MODE_JSON = 0         # one JSON object per payload (wrapped values)
MODE_JSON_SINGLE = 1  # one bare JSON scalar per payload (unwrapped single)
MODE_DELIMITED = 2    # commons-csv minimal-quote row per payload

_NP_OF = {
    FT_BIGINT: np.int64,
    FT_INT: np.int32,
    FT_DOUBLE: np.float64,
    FT_BOOLEAN: np.uint8,
    FT_STRING: np.int64,
}


def _build() -> Optional[ctypes.CDLL]:
    if not os.path.exists(_LIB) or (
        os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
    ):
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _LIB]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    lib = ctypes.CDLL(_LIB)
    lib.ingest_parse_batch.restype = ctypes.c_void_p
    lib.ingest_parse_batch.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.ingest_parse_batch2.restype = ctypes.c_void_p
    lib.ingest_parse_batch2.argtypes = lib.ingest_parse_batch.argtypes + [
        ctypes.c_int32, ctypes.c_char,
    ]
    lib.ingest_arena_count.restype = ctypes.c_int64
    lib.ingest_arena_count.argtypes = [ctypes.c_void_p]
    lib.ingest_arena_bytes_len.restype = ctypes.c_int64
    lib.ingest_arena_bytes_len.argtypes = [ctypes.c_void_p]
    lib.ingest_arena_fetch.restype = None
    lib.ingest_arena_fetch.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p,
    ]
    lib.ingest_free_arena.restype = None
    lib.ingest_free_arena.argtypes = [ctypes.c_void_p]
    lib.ingest_hash_string.restype = ctypes.c_int64
    lib.ingest_hash_string.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None when the
    toolchain is unavailable (callers use the Python path)."""
    global _lib, _failed
    if _lib is not None or _failed:
        return _lib
    with _lock:
        if _lib is not None or _failed:
            return _lib
        try:
            _lib = _build()
        except Exception:  # noqa: BLE001 — no compiler / bad env: fall back
            _failed = True
    return _lib


def available() -> bool:
    return get_lib() is not None


def parse_json_batch(
    payloads: Sequence[Any],
    fields: Sequence[Tuple[str, int]],
    mode: int = MODE_JSON,
    delimiter: str = ",",
) -> Optional[Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray],
                    np.ndarray, List[Tuple[int, str]]]]:
    """Parse a batch of payloads into columns.

    Returns (data, valid, row_ok, learned) — ``learned`` is this batch's
    unique (hash, string) pairs for dictionary learning — or None when the
    native library is unavailable.  Rows with ``row_ok`` False must be
    decoded by the Python fallback.
    """
    lib = get_lib()
    if lib is None:
        return None
    n = len(payloads)
    enc: List[bytes] = []
    offs = np.zeros(n + 1, np.int64)
    for i, p in enumerate(payloads):
        b = p if isinstance(p, bytes) else str(p).encode("utf-8")
        enc.append(b)
        offs[i + 1] = offs[i] + len(b)
    buf = b"".join(enc)
    names = b""
    name_offs = np.zeros(len(fields) + 1, np.int64)
    types = np.zeros(len(fields), np.int32)
    for f, (name, code) in enumerate(fields):
        nb = name.encode("utf-8")
        names += nb
        name_offs[f + 1] = name_offs[f] + len(nb)
        types[f] = code
    data: Dict[str, np.ndarray] = {}
    valid: Dict[str, np.ndarray] = {}
    dptrs = (ctypes.c_void_p * len(fields))()
    vptrs = (ctypes.c_void_p * len(fields))()
    for f, (name, code) in enumerate(fields):
        d = np.zeros(n, _NP_OF[code])
        v = np.zeros(n, np.uint8)
        data[name] = d
        valid[name] = v
        dptrs[f] = d.ctypes.data_as(ctypes.c_void_p)
        vptrs[f] = v.ctypes.data_as(ctypes.c_void_p)
    row_ok = np.zeros(n, np.uint8)
    arena = lib.ingest_parse_batch2(
        buf,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        len(fields),
        names,
        name_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        types.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.cast(dptrs, ctypes.POINTER(ctypes.c_void_p)),
        ctypes.cast(vptrs, ctypes.POINTER(ctypes.c_void_p)),
        row_ok.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        mode,
        delimiter.encode("ascii"),
    )
    learned: List[Tuple[int, str]] = []
    if arena:
        try:  # a failed fetch/decode must still free the arena
            cnt = lib.ingest_arena_count(arena)
            blen = lib.ingest_arena_bytes_len(arena)
            if cnt:
                hashes = np.zeros(cnt, np.int64)
                ends = np.zeros(cnt, np.int64)
                bbuf = ctypes.create_string_buffer(int(blen))
                lib.ingest_arena_fetch(
                    arena,
                    hashes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    bbuf,
                )
                raw = bbuf.raw
                start = 0
                for h, end in zip(hashes.tolist(), ends.tolist()):
                    learned.append((h, raw[start:end].decode("utf-8")))
                    start = end
        finally:
            lib.ingest_free_arena(arena)
    return data, {k: v.astype(bool) for k, v in valid.items()}, row_ok.astype(bool), learned


def parse_batch(payloads: Sequence[Any], spec: Dict[str, Any]):
    """Parse a batch against a ``native_ingest_fields`` spec dict
    ({"mode", "fields", "delimiter", ...})."""
    return parse_json_batch(
        payloads,
        spec["fields"],
        mode=spec.get("mode", MODE_JSON),
        delimiter=spec.get("delimiter", ","),
    )
