"""Columnar expression compiler: SQL expression tree → traced JAX ops.

The XLA analog of the reference's Janino codegen (ksqldb-execution/.../codegen/
CodeGenRunner.java:62-66, SqlToJavaVisitor.java:131): where the reference
compiles each expression to JVM bytecode evaluated per row, we trace the
expression once into the enclosing jit so XLA fuses the whole row transform
into the surrounding kernel — per-*batch* compilation instead of per-row
interpretation.

Value representation: every sub-expression evaluates to a :class:`DCol` —
``(data, valid)`` arrays over the batch (SQL three-valued logic rides the
``valid`` mask).  STRING/BYTES columns are hash-encoded (see
runtime/device.py): ``data`` is the stable 64-bit hash, so equality,
IN-lists, CASE and GROUP BY work on device; ordering/concat on strings does
not — those expressions raise :class:`DeviceUnsupported` and the query falls
back to the row oracle, mirroring how the reference falls back from codegen
to its interpreter (InterpretedExpressionFactory).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ksql_tpu.common import types as T
from ksql_tpu.common.batch import stable_hash64
from ksql_tpu.common.types import SqlBaseType, SqlType
from ksql_tpu.execution import expressions as ex


class DeviceUnsupported(Exception):
    """Expression/step cannot run on the device path; caller falls back to
    the row oracle."""


# hash-encoded on device: data column holds stable_hash64 of the value
_HASHED = (
    SqlBaseType.STRING, SqlBaseType.BYTES,
    # nested values ride as opaque dictionary codes (passthrough/equality/
    # grouping); structural expressions over them stay DeviceUnsupported
    SqlBaseType.ARRAY, SqlBaseType.MAP, SqlBaseType.STRUCT,
)
# numeric promotion order (SqlBaseType.canImplicitlyCast)
_NUM_ORDER = [
    SqlBaseType.INTEGER,
    SqlBaseType.BIGINT,
    SqlBaseType.DECIMAL,
    SqlBaseType.DOUBLE,
]


@dataclasses.dataclass
class DCol:
    """A device column: fixed-width data + validity, typed.

    Vector-state aggregate outputs (collect/topk) carry 2-D ``data``
    ((rows, K)) with ``valid`` marking present entries and ``elem_valid``
    marking non-null entries; such columns pass through to the sink only."""

    data: jnp.ndarray
    valid: jnp.ndarray  # bool, same shape
    sql_type: SqlType
    elem_valid: Optional[jnp.ndarray] = None
    # companion per-element payload (histogram counts): decoded as the MAP
    # values parallel to ``data``'s keys
    aux: Optional[jnp.ndarray] = None

    @property
    def hashed(self) -> bool:
        return self.sql_type.base in _HASHED


def deref_root(e: "ex.Dereference"):
    """The base expression under a Dereference chain."""
    cur = e
    while isinstance(cur, ex.Dereference):
        cur = cur.base
    return cur


def deref_fields(e: "ex.Dereference"):
    """Field path of a Dereference chain, outermost-last."""
    chain = []
    cur = e
    while isinstance(cur, ex.Dereference):
        chain.append(cur.field)
        cur = cur.base
    return tuple(reversed(chain))


def deref_synth_name(root: str, fields) -> str:
    """The flattened path column's name (shared by the batch layout that
    extracts it and the compiler that resolves it)."""
    return f"{root}->" + ".".join(fields)


def _dtype_for(t: SqlType):
    if t.base in _HASHED:
        return jnp.int64
    return t.device_dtype()


def const_col(value, sql_type: SqlType, n: int) -> DCol:
    """Broadcast a Python literal to a batch column."""
    if value is None:
        return DCol(jnp.zeros(n, _dtype_for(sql_type)), jnp.zeros(n, bool), sql_type)
    if sql_type.base in _HASHED:
        value = stable_hash64(value)
    return DCol(
        jnp.full(n, value, _dtype_for(sql_type)), jnp.ones(n, bool), sql_type
    )


def _promote(a: DCol, b: DCol) -> tuple:
    """Numeric promotion for binary ops; returns (a', b', result_type)."""
    ta, tb = a.sql_type.base, b.sql_type.base
    if ta not in _NUM_ORDER or tb not in _NUM_ORDER:
        raise DeviceUnsupported(f"arithmetic on {ta}/{tb}")
    out = _NUM_ORDER[max(_NUM_ORDER.index(ta), _NUM_ORDER.index(tb))]
    if out == SqlBaseType.DECIMAL:
        out = SqlBaseType.DOUBLE  # device DECIMAL = f64 (documented deviation)
    t = SqlType.of(out)
    dt = t.device_dtype()
    return a.data.astype(dt), b.data.astype(dt), t


class JaxExprCompiler:
    """Compiles expressions against an environment of named DCols.

    ``env`` maps column name → DCol (pseudocolumns ROWTIME/WINDOWSTART/...
    included by the lowering when available).
    """

    def __init__(self, env: Dict[str, DCol], n: int, dictionary=None):
        self.env = env
        self.n = n
        # host-side hash->value reverse map; string/bytes literals must be
        # learned here or emitted constants decode to null
        self.dictionary = dictionary

    # ------------------------------------------------------------- dispatch
    def compile(self, e: ex.Expression) -> DCol:
        m = getattr(self, "_c_" + type(e).__name__, None)
        if m is None:
            raise DeviceUnsupported(f"expression {type(e).__name__}")
        return m(e)

    # -------------------------------------------------------------- leaves
    def _c_NullLiteral(self, e) -> DCol:
        return const_col(None, T.STRING, self.n)

    def _c_BooleanLiteral(self, e) -> DCol:
        return const_col(e.value, T.BOOLEAN, self.n)

    def _c_IntegerLiteral(self, e) -> DCol:
        return const_col(e.value, T.INTEGER, self.n)

    def _c_LongLiteral(self, e) -> DCol:
        return const_col(e.value, T.BIGINT, self.n)

    def _c_DoubleLiteral(self, e) -> DCol:
        return const_col(e.value, T.DOUBLE, self.n)

    def _c_DecimalLiteral(self, e) -> DCol:
        return const_col(float(e.text), T.DOUBLE, self.n)

    def _c_StringLiteral(self, e) -> DCol:
        if self.dictionary is not None and e.value is not None:
            self.dictionary.learn_value(e.value)
        return const_col(e.value, T.STRING, self.n)

    def _c_BytesLiteral(self, e) -> DCol:
        if self.dictionary is not None and e.value is not None:
            self.dictionary.learn_value(e.value)
        return const_col(e.value, T.BYTES, self.n)

    def _c_ColumnRef(self, e) -> DCol:
        col = self.env.get(e.name)
        if col is None and e.source:
            col = self.env.get(f"{e.source}.{e.name}")
        if col is None:
            raise DeviceUnsupported(f"column {e.name} not on device")
        return col

    # ---------------------------------------------------------- arithmetic
    def _c_Dereference(self, e) -> DCol:
        """Struct field access resolves to the flattened path column the
        layout extracted at encode (``ROOT->F.G``)."""
        root = deref_root(e)
        if isinstance(root, ex.ColumnRef):
            d = self.env.get(deref_synth_name(root.name, deref_fields(e)))
            if d is not None:
                return d
        raise DeviceUnsupported("struct dereference without a path column")

    def _c_ArithmeticBinary(self, e) -> DCol:
        a, b = self.compile(e.left), self.compile(e.right)
        da, db, t = _promote(a, b)
        valid = a.valid & b.valid
        op = e.op
        if op == ex.ArithOp.ADD:
            out = da + db
        elif op == ex.ArithOp.SUBTRACT:
            out = da - db
        elif op == ex.ArithOp.MULTIPLY:
            out = da * db
        elif op == ex.ArithOp.DIVIDE:
            decimal_op = (
                a.sql_type.base == SqlBaseType.DECIMAL
                and b.sql_type.base == SqlBaseType.DECIMAL
            )
            if jnp.issubdtype(da.dtype, jnp.integer) or decimal_op:
                # Java int division truncates toward zero; /0 → error →
                # null.  DECIMAL/0 is an ArithmeticException → null too
                # (double division keeps IEEE inf)
                zero = db == 0
                one = jnp.asarray(1, da.dtype)
                safe = jnp.where(zero, one, db)
                out = (
                    jax.lax.div(da, safe)
                    if jnp.issubdtype(da.dtype, jnp.integer)
                    else da / safe
                )
                valid = valid & ~zero
            else:
                out = da / db  # IEEE: inf/nan, stays valid (Java double)
        elif op == ex.ArithOp.MODULUS:
            decimal_op = (
                a.sql_type.base == SqlBaseType.DECIMAL
                and b.sql_type.base == SqlBaseType.DECIMAL
            )
            if jnp.issubdtype(da.dtype, jnp.integer) or decimal_op:
                zero = db == 0
                one = jnp.asarray(1, da.dtype)
                out = jax.lax.rem(da, jnp.where(zero, one, db))
                valid = valid & ~zero
            else:
                out = jnp.where(db != 0, jax.lax.rem(da, jnp.where(db == 0, 1.0, db)), jnp.nan)
        else:  # pragma: no cover
            raise DeviceUnsupported(f"arith op {op}")
        return DCol(out, valid, t)

    def _c_ArithmeticUnary(self, e) -> DCol:
        v = self.compile(e.operand)
        if not v.sql_type.is_numeric():
            raise DeviceUnsupported("unary arith on non-numeric")
        data = -v.data if e.op == ex.ArithOp.SUBTRACT else v.data
        return DCol(data, v.valid, v.sql_type)

    # ---------------------------------------------------------- comparison
    def _c_Comparison(self, e) -> DCol:
        a, b = self.compile(e.left), self.compile(e.right)
        op = e.op
        ta, tb = a.sql_type.base, b.sql_type.base
        if ta in _HASHED or tb in _HASHED:
            if ta != tb:
                raise DeviceUnsupported(f"compare {ta} vs {tb}")
            if op not in (
                ex.CompareOp.EQ,
                ex.CompareOp.NEQ,
                ex.CompareOp.IS_DISTINCT_FROM,
                ex.CompareOp.IS_NOT_DISTINCT_FROM,
            ):
                raise DeviceUnsupported("string ordering on device")
            da, db = a.data, b.data
        elif ta == SqlBaseType.BOOLEAN and tb == SqlBaseType.BOOLEAN:
            da, db = a.data, b.data
        elif a.sql_type.is_numeric() and b.sql_type.is_numeric():
            da, db, _ = _promote(a, b)
        elif ta == tb:  # TIME/DATE/TIMESTAMP
            da, db = a.data, b.data
        else:
            raise DeviceUnsupported(f"compare {ta} vs {tb}")
        valid = a.valid & b.valid
        if op in (ex.CompareOp.EQ, ex.CompareOp.IS_NOT_DISTINCT_FROM):
            out = da == db
        elif op in (ex.CompareOp.NEQ, ex.CompareOp.IS_DISTINCT_FROM):
            out = da != db
        elif op == ex.CompareOp.LT:
            out = da < db
        elif op == ex.CompareOp.LTE:
            out = da <= db
        elif op == ex.CompareOp.GT:
            out = da > db
        else:
            out = da >= db
        if op == ex.CompareOp.IS_DISTINCT_FROM:
            # null-safe: NULL is distinct from non-NULL, not from NULL
            out = jnp.where(
                a.valid & b.valid, out, a.valid != b.valid
            )
        elif op == ex.CompareOp.IS_NOT_DISTINCT_FROM:
            out = jnp.where(a.valid & b.valid, out, a.valid == b.valid)
        else:
            # NULL operand -> false, not NULL (SqlToJavaVisitor.nullCheckPrefix:621)
            out = jnp.where(valid, out, False)
        return DCol(out, jnp.ones_like(valid), T.BOOLEAN)

    # ------------------------------------------------------------- logical
    def _c_LogicalBinary(self, e) -> DCol:
        a, b = self.compile(e.left), self.compile(e.right)
        av = a.valid & a.data.astype(bool)
        bv = b.valid & b.data.astype(bool)
        af = a.valid & ~a.data.astype(bool)
        bf = b.valid & ~b.data.astype(bool)
        if e.op == ex.LogicOp.AND:
            out = av & bv
            valid = (a.valid & b.valid) | af | bf
        else:
            out = av | bv
            valid = (a.valid & b.valid) | av | bv
        return DCol(out, valid, T.BOOLEAN)

    def _c_Not(self, e) -> DCol:
        v = self.compile(e.operand)
        return DCol(~v.data.astype(bool), v.valid, T.BOOLEAN)

    def _c_IsNull(self, e) -> DCol:
        v = self.compile(e.operand)
        return DCol(~v.valid, jnp.ones(self.n, bool), T.BOOLEAN)

    def _c_IsNotNull(self, e) -> DCol:
        v = self.compile(e.operand)
        return DCol(v.valid, jnp.ones(self.n, bool), T.BOOLEAN)

    def _c_Between(self, e) -> DCol:
        lo = ex.Comparison(ex.CompareOp.GTE, e.value, e.lower)
        hi = ex.Comparison(ex.CompareOp.LTE, e.value, e.upper)
        both = ex.LogicalBinary(ex.LogicOp.AND, lo, hi)
        out = self.compile(ex.Not(both) if e.negated else both)
        return out

    def _c_InList(self, e) -> DCol:
        v = self.compile(e.value)
        hit = None
        for item in e.items:
            c = self.compile(ex.Comparison(ex.CompareOp.EQ, e.value, item))
            hit = c if hit is None else self._or(hit, c)
        if hit is None:
            return const_col(False, T.BOOLEAN, self.n)
        if e.negated:
            hit = DCol(~hit.data, hit.valid, T.BOOLEAN)
        return hit

    def _or(self, a: DCol, b: DCol) -> DCol:
        av = a.valid & a.data
        bv = b.valid & b.data
        return DCol(av | bv, (a.valid & b.valid) | av | bv, T.BOOLEAN)

    # ---------------------------------------------------------------- cast
    def _c_Cast(self, e) -> DCol:
        v = self.compile(e.operand)
        src, dst = v.sql_type.base, e.target.base
        _nested = (SqlBaseType.ARRAY, SqlBaseType.MAP, SqlBaseType.STRUCT)
        if (src in _nested or dst in _nested) and v.sql_type != e.target:
            # nested values are opaque codes: a schema-changing cast needs
            # element coercion — host-computed, not a code passthrough
            raise DeviceUnsupported(f"CAST {src} AS {dst} on device")
        if src == dst and src == SqlBaseType.DECIMAL and v.sql_type != e.target:
            # DECIMAL(p,s) re-scaling needs exact arithmetic
            raise DeviceUnsupported("DECIMAL rescale on device")
        if src == dst:
            return DCol(v.data, v.valid, e.target)
        if v.sql_type.is_numeric() and e.target.is_numeric():
            dt = (
                jnp.float64
                if dst == SqlBaseType.DECIMAL
                else e.target.device_dtype()
            )
            data = v.data
            if jnp.issubdtype(data.dtype, jnp.floating) and jnp.issubdtype(
                dt, jnp.integer
            ):
                data = jnp.trunc(data)  # Java narrowing truncates toward zero
            out = data.astype(dt)
            valid = v.valid
            if dst == SqlBaseType.DECIMAL and e.target.scale is not None:
                # device decimals are f64 rounded to scale (HALF_UP);
                # values exceeding precision null out (ArithmeticException
                # -> null in the reference's cast)
                f = 10.0 ** e.target.scale
                out = jnp.where(out >= 0, jnp.floor(out * f + 0.5), jnp.ceil(out * f - 0.5)) / f
                if e.target.precision is not None:
                    limit = 10.0 ** (e.target.precision - e.target.scale)
                    valid = valid & (jnp.abs(out) < limit)
            return DCol(out, valid, e.target)
        if dst in (SqlBaseType.TIMESTAMP, SqlBaseType.TIME, SqlBaseType.DATE) and src in (
            SqlBaseType.INTEGER,
            SqlBaseType.BIGINT,
        ):
            return DCol(v.data.astype(e.target.device_dtype()), v.valid, e.target)
        if dst == SqlBaseType.TIMESTAMP and src == SqlBaseType.TIME:
            return DCol(v.data.astype(e.target.device_dtype()), v.valid, e.target)
        if dst == SqlBaseType.TIMESTAMP and src == SqlBaseType.DATE:
            # DATE carries epoch days -> midnight ms
            return DCol(
                v.data.astype(jnp.int64) * jnp.asarray(86_400_000, jnp.int64),
                v.valid, e.target,
            )
        if dst == SqlBaseType.DATE and src == SqlBaseType.TIMESTAMP:
            # DATE carries epoch DAYS (floor toward -inf for pre-epoch)
            day = jnp.asarray(86_400_000, jnp.int64)
            return DCol(
                v.data.astype(jnp.int64) // day, v.valid, e.target
            )
        if dst == SqlBaseType.TIME and src == SqlBaseType.TIMESTAMP:
            # time-of-day millis; negative timestamps floor toward -inf
            day = jnp.asarray(86_400_000, jnp.int64)
            return DCol(
                v.data.astype(jnp.int64) - (v.data.astype(jnp.int64) // day) * day,
                v.valid, e.target,
            )
        raise DeviceUnsupported(f"CAST {src} AS {dst} on device")

    # --------------------------------------------------------- conditionals
    def _c_SearchedCase(self, e) -> DCol:
        results = [self.compile(w.result) for w in e.when_clauses]
        default = (
            self.compile(e.default)
            if e.default is not None
            else None
        )
        t = self._common_type([r.sql_type for r in results] + ([default.sql_type] if default else []))
        dt = _dtype_for(t)
        out = default.data.astype(dt) if default is not None else jnp.zeros(self.n, dt)
        valid = default.valid if default is not None else jnp.zeros(self.n, bool)
        taken = jnp.zeros(self.n, bool)
        for w, r in zip(e.when_clauses, results):
            c = self.compile(w.condition)
            fire = ~taken & c.valid & c.data.astype(bool)
            out = jnp.where(fire, r.data.astype(dt), out)
            valid = jnp.where(fire, r.valid, valid)
            taken = taken | fire
        return DCol(out, valid, t)

    def _c_SimpleCase(self, e) -> DCol:
        whens = tuple(
            ex.WhenClause(
                ex.Comparison(ex.CompareOp.EQ, e.operand, w.condition), w.result
            )
            for w in e.when_clauses
        )
        return self._c_SearchedCase(ex.SearchedCase(whens, e.default))

    def _common_type(self, types) -> SqlType:
        types = [t for t in types if t is not None]
        if not types:
            return T.STRING
        out = types[0]
        for t in types[1:]:
            if t.base == out.base:
                continue
            if out.base in _NUM_ORDER and t.base in _NUM_ORDER:
                nb = _NUM_ORDER[max(_NUM_ORDER.index(out.base), _NUM_ORDER.index(t.base))]
                out = T.DOUBLE if nb == SqlBaseType.DECIMAL else SqlType.of(nb)
            else:
                raise DeviceUnsupported(f"mixed CASE types {out}/{t}")
        return out

    # ------------------------------------------------------------ functions
    def _c_FunctionCall(self, e) -> DCol:
        fn = _DEVICE_FUNCTIONS.get(e.name.upper())
        if fn is None:
            raise DeviceUnsupported(f"function {e.name} on device")
        args = [self.compile(a) for a in e.args]
        return fn(self, args)


# ----------------------------------------------------- device function lib


def _f_abs(c, args):
    (v,) = args
    return DCol(jnp.abs(v.data), v.valid, v.sql_type)


def _f_round(c, args):
    # floor(x + 0.5): Java Math.round — -1.5 rounds UP to -1, and the
    # result of rounding a negative fraction is +0.0 (oracle _round0)
    v = args[0]
    if len(args) == 1:
        if jnp.issubdtype(v.data.dtype, jnp.integer):
            # Java ROUND of an integral is identity (no f64 round-trip,
            # which would lose precision above 2^53)
            return DCol(v.data.astype(jnp.int64), v.valid, T.BIGINT)
        d = v.data.astype(jnp.float64)
        out = jnp.floor(d + 0.5)
        return DCol(out.astype(jnp.int64), v.valid, T.BIGINT)
    s = args[1]
    f = 10.0 ** s.data.astype(jnp.float64)
    d = v.data.astype(jnp.float64) * f
    out = jnp.floor(d + 0.5) / f
    return DCol(out, v.valid & s.valid, T.DOUBLE)


def _f_floor(c, args):
    (v,) = args
    return DCol(jnp.floor(v.data.astype(jnp.float64)), v.valid, T.DOUBLE)


def _f_ceil(c, args):
    (v,) = args
    return DCol(jnp.ceil(v.data.astype(jnp.float64)), v.valid, T.DOUBLE)


def _unary_f64(op):
    def f(c, args):
        (v,) = args
        return DCol(op(v.data.astype(jnp.float64)), v.valid, T.DOUBLE)

    return f


def _f_sign(c, args):
    (v,) = args
    return DCol(jnp.sign(v.data).astype(jnp.int32), v.valid, T.INTEGER)


def _f_greatest(c, args):
    out = args[0]
    for v in args[1:]:
        da, db, t = _promote(out, v)
        out = DCol(jnp.maximum(da, db), out.valid & v.valid, t)
    return out


def _f_least(c, args):
    out = args[0]
    for v in args[1:]:
        da, db, t = _promote(out, v)
        out = DCol(jnp.minimum(da, db), out.valid & v.valid, t)
    return out


def _f_coalesce(c, args):
    t = c._common_type([a.sql_type for a in args])
    dt = _dtype_for(t)
    out = jnp.zeros(c.n, dt)
    valid = jnp.zeros(c.n, bool)
    for v in args:
        take = ~valid & v.valid
        out = jnp.where(take, v.data.astype(dt), out)
        valid = valid | v.valid
    return DCol(out, valid, t)


def _f_ifnull(c, args):
    return _f_coalesce(c, args)


_DEVICE_FUNCTIONS: Dict[str, Callable] = {
    "AS_VALUE": lambda c, args: args[0],  # key->value copy marker: identity
    "ABS": _f_abs,
    "ROUND": _f_round,
    "FLOOR": _f_floor,
    "CEIL": _f_ceil,
    "EXP": _unary_f64(jnp.exp),
    "LN": _unary_f64(jnp.log),
    "SQRT": _unary_f64(jnp.sqrt),
    "SIGN": _f_sign,
    "GREATEST": _f_greatest,
    "LEAST": _f_least,
    "COALESCE": _f_coalesce,
    "IFNULL": _f_ifnull,
}
