"""Zombie-fence rule: handle mutations inside supervised tick bodies.

The PR-5 tick-deadline watchdog abandons a hung worker thread but cannot
kill it: the zombie keeps running with references to the query's
``handle``.  The fence contract (engine._poll_query) is that the tick
body identity-binds its consumer (``consumer = handle.consumer``) and
defines ``def alive(): return handle.consumer is consumer`` — and every
``handle`` mutation AFTER that point must be guarded by ``alive()``, or a
woken zombie overwrites state the restarted query now owns (stale
offsets, poison markers, restart counters).

Scope: only functions that define a local ``alive`` fence (that is the
marker that this body can be abandoned mid-flight).  Inside one, a
mutation of ``handle.<attr>`` — assignment, augmented assignment,
subscript store, or a mutating method call (add/discard/update/...) — is
flagged unless it is

* on an ``if`` branch where ``alive()`` is known truthy: the body of a
  positive test (``if alive():``, ``if cond and alive():``, ``if alive
  is None or alive():``) or the else of a negated one (``if not
  alive(): ... else:``) — the body of ``if not alive():`` is exactly
  the zombie path and stays flagged — or
* sequentially dominated by an early bail-out ``if not alive(): return/
  continue/raise`` earlier in the same (or an enclosing) block.

Mutations that must run unconditionally (e.g. binding the tick's commit
dict at tick START, before the worker can possibly be abandoned) carry
the escape hatch with a justification.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from ksql_tpu.analysis.lint import Finding, LintModule, Rule

_MUTATORS = {
    "add", "append", "clear", "discard", "extend", "insert", "pop",
    "popitem", "remove", "setdefault", "update",
}


def _calls_name(expr: ast.AST, name: str) -> bool:
    for n in ast.walk(expr):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == name):
            return True
    return False


def _mentions_with_polarity(test: ast.AST, fence: str, want_neg: bool) -> bool:
    """True when the test mentions a ``fence()`` call under the given
    negation polarity (tracking ``not`` through BoolOps), so ``if not
    alive():`` guards its ELSE branch, never its body."""
    def walk(n: ast.AST, neg: bool) -> bool:
        if isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.Not):
            return walk(n.operand, not neg)
        if isinstance(n, ast.BoolOp):
            return any(walk(v, neg) for v in n.values)
        return neg == want_neg and _calls_name(n, fence)
    return walk(test, False)


def _is_bailout(stmt: ast.stmt, fence: str) -> bool:
    """``if not alive(): return/continue/raise`` (possibly with more in the
    body, as long as it ends the flow)."""
    if not isinstance(stmt, ast.If) or stmt.orelse:
        return False
    test = stmt.test
    neg = isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
        and _calls_name(test.operand, fence)
    if not neg:
        return False
    last = stmt.body[-1]
    return isinstance(last, (ast.Return, ast.Continue, ast.Break, ast.Raise))


class UnfencedHandleMutationRule(Rule):
    name = "unfenced-handle-mutation"
    doc = ("handle mutations in a tick body that defines an alive() fence "
           "must be guarded by it (zombie-worker discipline)")

    #: the fence function name the PR-5 contract uses
    fence = "alive"
    #: the object whose mutations the fence protects
    subject = "handle"

    def check(self, module: LintModule) -> Iterable[Finding]:
        out: List[Finding] = []
        for fn in module.functions():
            if not self._defines_fence(fn):
                continue
            out.extend(self._check_fn(module, fn))
        return out

    def _defines_fence(self, fn: ast.FunctionDef) -> bool:
        return any(
            isinstance(s, ast.FunctionDef) and s.name == self.fence
            for s in ast.walk(fn)
        )

    # ------------------------------------------------------------ guarding
    def _guarded(self, module: LintModule, fn: ast.FunctionDef,
                 node: ast.AST) -> bool:
        # (a) an enclosing if-branch on which alive() is known truthy:
        # the body of a positive test, or the else of a negated one —
        # mutations under `if not alive():` are exactly the zombie write
        cur: Optional[ast.AST] = node
        while cur is not None and cur is not fn:
            parent = module.parent(cur)
            if isinstance(parent, ast.If):
                if cur in parent.body and _mentions_with_polarity(
                    parent.test, self.fence, want_neg=False
                ):
                    return True
                if cur in parent.orelse and _mentions_with_polarity(
                    parent.test, self.fence, want_neg=True
                ):
                    return True
            cur = parent
        # (b) an earlier bail-out in the statement's own or an enclosing block
        cur = node
        while cur is not None and cur is not fn:
            parent = module.parent(cur)
            for field in ("body", "orelse", "finalbody"):
                block = getattr(parent, field, None)
                if isinstance(block, list) and cur in block:
                    idx = block.index(cur)
                    if any(_is_bailout(s, self.fence) for s in block[:idx]):
                        return True
            cur = parent
        return False

    def _mutations(self, fn: ast.FunctionDef):
        for node in ast.walk(fn):
            # skip the fence body itself and nested defs other than the
            # tick body (closures like note_durable operate on locals)
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if self._is_subject_store(t):
                        yield node, t
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if self._is_subject_store(node.target):
                    yield node, node.target
            elif isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute) and f.attr in _MUTATORS
                        and self._is_subject_attr(f.value)):
                    yield node, f

    def _is_subject_store(self, target: ast.AST) -> bool:
        # handle.x = ... / handle.x[...] = ...
        if isinstance(target, ast.Attribute):
            return self._is_subject(target.value)
        if isinstance(target, ast.Subscript):
            return self._is_subject_attr(target.value)
        return False

    def _is_subject_attr(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Attribute) and self._is_subject(node.value)

    def _is_subject(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Name) and node.id == self.subject

    def _check_fn(self, module: LintModule, fn: ast.FunctionDef) -> List[Finding]:
        out: List[Finding] = []
        seen: Set[int] = set()
        for stmt, target in self._mutations(fn):
            if stmt.lineno in seen:
                continue
            if self._guarded(module, fn, stmt):
                continue
            seen.add(stmt.lineno)
            if isinstance(target, ast.Attribute) and isinstance(
                target.value, ast.Attribute
            ):
                desc = f"{target.value.attr}.{target.attr}(...)"  # method call
            elif isinstance(target, ast.Attribute):
                desc = target.attr
            else:
                desc = "?"
            out.append(Finding(
                self.name, module.path, stmt.lineno, stmt.col_offset,
                f"unfenced mutation of handle.{desc} inside a tick body "
                f"that defines an {self.fence}() fence — guard with "
                f"'if {self.fence}():' or it races the zombie-worker "
                "restart (PR-5 contract)",
            ))
        return out
