"""graftmem — ahead-of-time device-memory footprint model.

Every sharded store, slice ring, ss-join buffer and join-table tier lives
wholly in device HBM with power-of-two capacities, yet until this module
a plan's footprint was discovered only when XLA OOMed or the store-growth
ladder doubled past what the chip holds.  ROADMAP direction #2 (tiered
state) and #4 (cost-based multi-query optimizer) both need a trustworthy
static memory model before any spill or sharing decision can be priced.

The model is the PR-6 discipline applied to memory: a static analyzer
pinned byte-exact against the real runtime over the golden-plan corpus.
:func:`footprint_of` walks the allocation *template* of a lowering probe —
``jax.eval_shape(dev.init_state)``, the same abstract-interpretation seam
the backend classifier and reshard-on-restore already trust (no device
allocation, no data, works on ``analyze_only`` probes) — and groups every
state array into a named component:

==================  =====================================================
component           state keys
==================  =====================================================
``store``           hash-store slot bookkeeping: occ/grave/khash/wstart/
                    knull/dirty/key<i> (+ suppress/session/having flags)
``agg.state``       per-slot aggregate columns ``a<j>`` (scalar widths)
``slice.ring``      sliced hopping: ``a<j>`` at ring width (the family
                    re-gcd ring), plus ``slice_id`` / ``slast``
``join.table[i]``   stream-table probe i's device table store
``tt.store``        table-table join two-sided store
``fk.store.{l,r}``  foreign-key join side stores
``ss.buffer.{l,r}`` stream-stream join ring buffers
==================  =====================================================

plus *transient* components that are not part of the persistent state
pytree (excluded from the :meth:`CompiledDeviceQuery.device_state_bytes`
parity seam, reported for sizing): per-shard ``exchange.lanes`` (the
all-to-all payload buckets under ``ksql.device.shards``), the batched
``pipeline.buffer`` emission double-buffer, and the fused tap-kernel
``tap.lanes`` floor tier for push-shareable shapes.

Three report points:

* **at-creation** — bytes the state pytree allocates at construction
  (byte-exact: the parity test pins it against live array ``nbytes``);
* **at-growth-cap** — bytes once every growable store (hash store, join
  tables, tt/fk stores — each doubles on occupancy) reaches its ceiling:
  the largest power-of-two capacity whose group footprint stays within
  the growth budget (``ksql.analysis.memory.budget.bytes`` when set,
  else the same 256 MiB vec-state budget construction itself uses);
* **per-shard / at-mesh(M)** — distributed state is broadcast with a
  leading ``[n_shards]`` axis (every shard holds full-capacity arrays
  owning its key hash-range), so per-shard state bytes equal the
  single-device footprint and total = M x (per-shard + exchange lanes).

The admission gate (engine ``ksql.analysis.memory.budget.bytes`` +
``.strict``), EXPLAIN's ``Device memory (static)`` table, the
``ksql_query_estimated_hbm_bytes{point}`` gauge and the rescale
controller's shrink refusal all read this one model; scripts/memcheck.py
sweeps it over the golden-plan corpus.  The multi-query optimizer
(planner/mqo.py) additionally prices a prospective window-family attach
at its MARGINAL bytes — the shared slice ring re-priced at the post-gcd
width/ring with the union partial set (:func:`family_attach_marginal`)
— so the admission gate charges an attach what it actually allocates,
not a phantom standalone store.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

#: mirrors lowering._VEC_STATE_BUDGET_BYTES — the HBM budget construction
#: already uses to size wide vector stores; the growth ladder's modeled
#: ceiling when no explicit ksql.analysis.memory.budget.bytes is set
DEFAULT_GROWTH_BUDGET_BYTES = 256 << 20

#: report points (the {point} label of ksql_query_estimated_hbm_bytes)
POINT_CREATION = "at_creation"
POINT_GROWTH_CAP = "at_growth_cap"
POINT_PER_SHARD = "per_shard"


# ------------------------------------------------------ component naming
#
# The ONE key->component classification, shared with the runtime seam
# (CompiledDeviceQuery.device_state_bytes imports these), so the static
# report and the live measurement can never group differently.


def component_of_nested(outer: str) -> str:
    """Component name of a nested (dict-valued) state entry."""
    if outer == "jtab":
        return "join.table"
    if outer.startswith("jtab"):
        return f"join.table{outer[len('jtab'):]}"
    if outer == "ttab":
        return "tt.store"
    if outer == "fkl":
        return "fk.store.l"
    if outer == "fkr":
        return "fk.store.r"
    return outer  # unknown nested store: its own component, never hidden


def component_of_key(key: str, sliced: bool = False) -> str:
    """Component name of a flat state key (see module table)."""
    if key.startswith("ssl_"):
        return "ss.buffer.l"
    if key.startswith("ssr_"):
        return "ss.buffer.r"
    if key in ("slice_id", "slast"):
        return "slice.ring"
    if key.startswith("a") and key[1:].isdigit():
        # sliced hopping folds per-(key, slice) partials: the aggregate
        # columns ARE the ring (width = retention / re-gcd slice width)
        return "slice.ring" if sliced else "agg.state"
    return "store"


def measure_state_bytes(state: Dict[str, Any],
                        sliced: bool = False) -> Dict[str, int]:
    """Live per-component bytes of a state pytree — the ONE measurement
    loop behind every ``device_state_bytes()`` seam (single-device and
    distributed), summing each array's ``nbytes`` (metadata only, no
    device sync) under the model's key->component classification."""
    out: Dict[str, int] = {}
    for k, v in state.items():
        if isinstance(v, dict):
            comp = component_of_nested(k)
            b = sum(int(a.nbytes) for a in v.values())
        else:
            comp = component_of_key(k, sliced=sliced)
            b = int(v.nbytes)
        out[comp] = out.get(comp, 0) + b
    return out


# ------------------------------------------------------------ the report


@dataclasses.dataclass(frozen=True)
class ComponentBytes:
    """One component's modeled footprint (bytes, per shard)."""

    name: str
    at_creation: int
    at_growth_cap: int
    arrays: int
    #: capacity (slot count) backing the scaling group, 0 = unsized
    capacity: int = 0
    #: capacity at the growth-cap point (== capacity when not growable)
    growth_cap_capacity: int = 0
    #: True = not part of the persistent state pytree (exchange lanes,
    #: double-buffers, tap-kernel lanes) — excluded from the
    #: device_state_bytes() parity seam, reported for sizing only
    transient: bool = False


@dataclasses.dataclass(frozen=True)
class MemoryReport:
    """Per-component static footprint of one lowered plan."""

    components: Tuple[ComponentBytes, ...]
    n_shards: int = 1
    growth_budget_bytes: int = DEFAULT_GROWTH_BUDGET_BYTES

    # ------------------------------------------------------------ totals
    def per_shard_bytes(self, point: str = POINT_CREATION,
                        include_transient: bool = True) -> int:
        grow = point == POINT_GROWTH_CAP
        return sum(
            (c.at_growth_cap if grow else c.at_creation)
            for c in self.components
            if include_transient or not c.transient
        )

    def total_bytes(self, point: str = POINT_CREATION) -> int:
        return self.n_shards * self.per_shard_bytes(point)

    def at_mesh(self, n_shards: int) -> "MemoryReport":
        """The same footprint under a different mesh size (per-shard
        state bytes are mesh-invariant — state is broadcast with a
        leading shard axis — only the report's multiplier changes)."""
        return dataclasses.replace(self, n_shards=max(1, int(n_shards)))

    def state_bytes(self) -> Dict[str, int]:
        """Per-component at-creation bytes of the persistent state pytree
        only — the shape device_state_bytes() measures."""
        return {
            c.name: c.at_creation for c in self.components if not c.transient
        }

    def dominant(self, point: str = POINT_CREATION,
                 include_transient: bool = False) -> Optional[ComponentBytes]:
        grow = point == POINT_GROWTH_CAP
        cands = [
            c for c in self.components if include_transient or not c.transient
        ]
        if not cands:
            return None
        return max(
            cands, key=lambda c: c.at_growth_cap if grow else c.at_creation
        )

    # --------------------------------------------------------- rendering
    def format_table(self) -> str:
        """The EXPLAIN component table (one header line + one line per
        component, largest first)."""
        shards = (
            f", shards={self.n_shards} "
            f"(total {_fmt_bytes(self.total_bytes(POINT_CREATION))})"
            if self.n_shards > 1 else ""
        )
        lines = [
            "Device memory (static): "
            f"{_fmt_bytes(self.per_shard_bytes(POINT_CREATION))} at-creation"
            f", {_fmt_bytes(self.per_shard_bytes(POINT_GROWTH_CAP))} "
            f"at-growth-cap per shard{shards}"
        ]
        for c in sorted(
            self.components, key=lambda c: -c.at_creation
        ):
            cap = f" cap={c.capacity}" if c.capacity else ""
            gcap = (
                f" -> {c.growth_cap_capacity}"
                if c.growth_cap_capacity > c.capacity else ""
            )
            star = "*" if c.transient else ""
            lines.append(
                f"  {c.name + star:<18} {_fmt_bytes(c.at_creation):>10}  "
                f"{_fmt_bytes(c.at_growth_cap):>10} at-cap"
                f"{cap}{gcap}"
            )
        if any(c.transient for c in self.components):
            lines.append("  (* transient: not part of checkpointed state)")
        return "\n".join(lines)

    def to_json(self) -> Dict[str, Any]:
        return {
            "nShards": self.n_shards,
            "growthBudgetBytes": self.growth_budget_bytes,
            "perShardBytes": {
                POINT_CREATION: self.per_shard_bytes(POINT_CREATION),
                POINT_GROWTH_CAP: self.per_shard_bytes(POINT_GROWTH_CAP),
            },
            "totalBytes": {
                POINT_CREATION: self.total_bytes(POINT_CREATION),
                POINT_GROWTH_CAP: self.total_bytes(POINT_GROWTH_CAP),
            },
            "components": [dataclasses.asdict(c) for c in self.components],
        }


def _fmt_bytes(n: int) -> str:
    f = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if f < 1024 or unit == "GiB":
            return f"{f:.1f} {unit}" if unit != "B" else f"{int(f)} B"
        f /= 1024
    return f"{int(n)} B"  # pragma: no cover — unreachable


# ----------------------------------------------------------- the analyzer


@dataclasses.dataclass
class _Group:
    """One scaling group: arrays whose leading dim is ``capacity + 1`` of
    one growable store — the whole group doubles together."""

    capacity: int
    growable: bool
    fixed: Dict[str, int] = dataclasses.field(default_factory=dict)
    per_slot: Dict[str, int] = dataclasses.field(default_factory=dict)
    arrays: Dict[str, int] = dataclasses.field(default_factory=dict)

    def bytes_at(self, capacity: int) -> Dict[str, int]:
        out = dict(self.fixed)
        for comp, unit in self.per_slot.items():
            out[comp] = out.get(comp, 0) + unit * (capacity + 1)
        return out

    def total_at(self, capacity: int) -> int:
        return sum(self.bytes_at(capacity).values())

    def growth_cap(self, budget: int) -> int:
        """Largest power-of-two capacity whose group total stays within
        ``budget`` — at least the current capacity (a store already past
        the budget cannot un-grow; the report shows it saturated)."""
        if not self.growable or not self.per_slot:
            return self.capacity
        cap = self.capacity
        while self.total_at(cap * 2) <= budget:
            cap *= 2
        return cap


def _add_array(group: _Group, comp: str, shape, itemsize: int) -> None:
    n = itemsize
    for d in shape:
        n *= int(d)
    c1 = group.capacity + 1
    if shape and int(shape[0]) == c1 and group.capacity:
        # per-slot array: scales with the store's capacity (row bytes =
        # total / (capacity + 1) — exact, shapes are (c1, ...) )
        group.per_slot[comp] = group.per_slot.get(comp, 0) + n // c1
    else:
        group.fixed[comp] = group.fixed.get(comp, 0) + n
    group.arrays[comp] = group.arrays.get(comp, 0) + 1


def footprint_of(
    dev: Any,
    n_shards: int = 1,
    growth_budget_bytes: Optional[int] = None,
) -> MemoryReport:
    """Model the device-memory footprint of a lowering (``analyze_only``
    probes included — nothing here allocates device memory).

    ``dev`` is a :class:`~ksql_tpu.runtime.lowering.CompiledDeviceQuery`;
    the state template comes from ``jax.eval_shape(dev.init_state)`` —
    abstract shapes only, the exact arrays ``init_state`` would build.
    """
    import jax

    budget = int(growth_budget_bytes or 0) or DEFAULT_GROWTH_BUDGET_BYTES
    template = jax.eval_shape(dev.init_state)
    sliced = bool(getattr(dev, "sliced", False))

    # scaling groups: the main store + one per nested keyed sub-store
    has_store = getattr(dev, "store_layout", None) is not None
    store_group = _Group(
        capacity=(
            int(getattr(dev, "store_capacity", 0) or 0) if has_store else 0
        ),
        growable=has_store,
    )
    groups: List[_Group] = [store_group]
    for key, tmpl in template.items():
        if isinstance(tmpl, dict):
            comp = component_of_nested(key)
            cap = int(tmpl["occ"].shape[0]) - 1 if "occ" in tmpl else 0
            # ss buffers never grow (restart-sized); every keyed nested
            # store (join tables, tt, fk) doubles on occupancy
            g = _Group(capacity=cap, growable=True)
            groups.append(g)
            for sub, t in tmpl.items():
                _add_array(g, comp, t.shape, t.dtype.itemsize)
            continue
        comp = component_of_key(key, sliced=sliced)
        if comp.startswith("ss.buffer"):
            # flat ss keys form their own fixed-capacity group so their
            # bytes never fold into the store's growth scaling
            _add_array(
                _ss_group(groups, dev), comp, tmpl.shape, tmpl.dtype.itemsize
            )
            continue
        _add_array(store_group, comp, tmpl.shape, tmpl.dtype.itemsize)

    # fold groups into per-component creation/growth-cap bytes
    creation: Dict[str, int] = {}
    at_cap: Dict[str, int] = {}
    caps: Dict[str, Tuple[int, int]] = {}
    arrays: Dict[str, int] = {}
    for g in groups:
        cap_capacity = g.growth_cap(budget)
        for comp, b in g.bytes_at(g.capacity).items():
            creation[comp] = creation.get(comp, 0) + b
        for comp, b in g.bytes_at(cap_capacity).items():
            at_cap[comp] = at_cap.get(comp, 0) + b
        for comp, n in g.arrays.items():
            arrays[comp] = arrays.get(comp, 0) + n
            caps[comp] = (g.capacity, cap_capacity)

    components = [
        ComponentBytes(
            name=comp,
            at_creation=creation[comp],
            at_growth_cap=at_cap.get(comp, creation[comp]),
            arrays=arrays.get(comp, 0),
            capacity=caps.get(comp, (0, 0))[0],
            growth_cap_capacity=caps.get(comp, (0, 0))[1],
        )
        for comp in sorted(creation)
    ]
    components.extend(_transient_components(dev, n_shards))
    return MemoryReport(
        components=tuple(components),
        n_shards=max(1, int(n_shards)),
        growth_budget_bytes=budget,
    )


def _ss_group(groups: List[_Group], dev: Any) -> _Group:
    """The (single, lazily-created) fixed-capacity group holding both ss
    ring buffers — capacity is ``ss_capacity`` and never grows (the
    runtime's posture: overflow says 'restart with a larger
    ss_buffer_capacity')."""
    for g in groups:
        if getattr(g, "_is_ss", False):
            return g
    g = _Group(capacity=int(getattr(dev, "ss_capacity", 0) or 0),
               growable=False)
    g._is_ss = True  # type: ignore[attr-defined]
    groups.append(g)
    return g


def _transient_components(dev: Any, n_shards: int) -> List[ComponentBytes]:
    """Per-shard working-set components outside the state pytree."""
    out: List[ComponentBytes] = []
    capacity = int(getattr(dev, "capacity", 0) or 0)
    expansion = int(getattr(dev, "expansion", 1) or 1)
    layout = getattr(dev, "layout", None)
    n_cols = len(getattr(layout, "specs", ()) or ()) if layout else 0
    if n_shards > 1 and capacity:
        # all-to-all exchange buckets (distributed.DistributedDeviceQuery):
        # bucket_capacity = capacity x window expansion rows per shard at
        # the wire estimate of 9 bytes per layout column + 24 fixed lanes
        bucket = capacity * expansion
        b = bucket * (9 * n_cols + 24)
        out.append(ComponentBytes(
            name="exchange.lanes", at_creation=b, at_growth_cap=b,
            arrays=0, capacity=bucket, growth_cap_capacity=bucket,
            transient=True,
        ))
    if capacity and not getattr(dev, "suppress", False):
        # batched-mode emission double-buffer: decode lags one batch, so
        # one batch worth of emit arrays stays device-resident (estimate
        # at the ingress column-count wire rate)
        b = capacity * expansion * (9 * max(n_cols, 1) + 24)
        out.append(ComponentBytes(
            name="pipeline.buffer", at_creation=b, at_growth_cap=b,
            arrays=0, capacity=capacity * expansion,
            growth_cap_capacity=capacity * expansion, transient=True,
        ))
    if _push_shareable(dev) and capacity:
        # fused tap-kernel floor tier (server/tap_kernel.py): the minimum
        # lane capacity x minimum row bucket — bitmask + per-lane params.
        # Growth doubles lanes toward ksql.push.registry.fused.capacity.max
        # per predicate family; the floor is what plan admission can know.
        lanes, rows = 8, 256
        b = lanes * rows + lanes * (2 * 8 + 1) + lanes * 8
        out.append(ComponentBytes(
            name="tap.lanes", at_creation=b, at_growth_cap=b,
            arrays=0, capacity=lanes, growth_cap_capacity=lanes,
            transient=True,
        ))
    return out


def _push_shareable(dev: Any) -> bool:
    """A bare source->filter/select->sink pipeline is what the push
    registry multiplexes as tap lanes (push_registry shareability)."""
    return (
        getattr(dev, "agg", None) is None
        and getattr(dev, "join", None) is None
        and not getattr(dev, "join_chain", ())
        and getattr(dev, "ss_join", None) is None
        and getattr(dev, "tt_join", None) is None
        and getattr(dev, "fk_join", None) is None
        and getattr(dev, "flatmap", None) is None
    )


# --------------------------------------------------------- plan-level API


def analyze_plan_memory(
    plan: Any,
    registry: Any,
    capacity: int = 8192,
    store_capacity: int = 1 << 17,
    n_shards: int = 1,
    sliced: Optional[bool] = None,
    slice_ring_max: int = 512,
    growth_budget_bytes: Optional[int] = None,
) -> MemoryReport:
    """Footprint of an ExecutionStep plan under the given lowering
    parameters: builds the construction-free ``analyze_only`` probe (the
    classifier's seam) and models it.  Raises ``DeviceUnsupported`` when
    the plan does not lower — such plans hold no device memory."""
    from ksql_tpu.runtime.lowering import CompiledDeviceQuery

    probe = CompiledDeviceQuery(
        plan, registry, capacity=capacity, store_capacity=store_capacity,
        analyze_only=True, sliced=sliced, slice_ring_max=slice_ring_max,
    )
    return footprint_of(
        probe, n_shards=n_shards, growth_budget_bytes=growth_budget_bytes
    )


# ------------------------------------------- family attach (MQO) pricing


def slice_ring_bytes(store_capacity: int, components, ring: int) -> int:
    """Bytes of a sliced store's ring tier at ``ring`` cells per key
    slot: every aggregate component column at (capacity+1, ring) plus
    the int64 ``slice_id`` map and the per-slot ``slast`` clock — the
    slice.ring component priced at an arbitrary width/ring instead of
    the probe's current one."""
    import numpy as np

    c1 = int(store_capacity) + 1
    per_cell = sum(int(np.dtype(c.dtype).itemsize) for c in components)
    return (per_cell + 8) * ring * c1 + 8 * c1


def family_attach_marginal(primary_dev: Any, new_ring: int,
                           new_specs=()) -> int:
    """MARGINAL device bytes of attaching one more member to
    ``primary_dev``'s shared sliced pipeline: the slice ring re-priced at
    the post-gcd ring span with the union partial set (existing
    components plus the attach's genuinely new aggregate components),
    minus the ring already allocated.  This — not a phantom standalone
    store — is what the admission gate and the cost model
    (planner/mqo.py) charge a shared attach."""
    comps = list(primary_dev.store_layout.components)
    before = slice_ring_bytes(
        primary_dev.store_capacity, comps, primary_dev.slice_ring
    )
    union = comps + [
        c for spec in new_specs for c in spec.device.components
    ]
    after = slice_ring_bytes(
        primary_dev.store_capacity, union, max(int(new_ring), 1)
    )
    return max(after - before, 0)


# ------------------------------------------------- rescale shrink pricing


def shrink_store_capacity(
    store_capacity: int, live_keys: int, target_shards: int
) -> int:
    """The per-shard store capacity a shrink to ``target_shards`` lands
    at: reshard-on-restore grows the fullest target shard's capacity
    until it sits at <= 50% load (checkpoint._prepare_reshard), with the
    static model assuming balanced key routing (splitmix-mixed hashes)."""
    target = max(1, int(target_shards))
    per_shard = -(-max(0, int(live_keys)) // target)  # ceil
    cap = max(1, int(store_capacity))
    while per_shard > cap // 2:
        cap *= 2
    return cap


def shrink_footprint(
    dev: Any,
    live_keys: int,
    target_shards: int,
    growth_budget_bytes: Optional[int] = None,
) -> MemoryReport:
    """Projected per-shard footprint after shrinking ``dev`` (a
    CompiledDeviceQuery or the ``.c`` of a DistributedDeviceQuery) to
    ``target_shards``, accounting for the reshard capacity growth that
    key concentration forces.  The projection scales the main store
    group's per-slot bytes to the projected capacity; every other
    component keeps its creation size."""
    base = footprint_of(
        dev, n_shards=target_shards, growth_budget_bytes=growth_budget_bytes
    )
    cur_cap = int(getattr(dev, "store_capacity", 0) or 0)
    if not cur_cap:
        return base
    new_cap = shrink_store_capacity(cur_cap, live_keys, target_shards)
    if new_cap == cur_cap:
        return base
    scale_comps = {"store", "agg.state", "slice.ring"}
    scaled = []
    for c in base.components:
        if c.name in scale_comps and c.capacity == cur_cap:
            unit = c.at_creation // (cur_cap + 1)
            fixed = c.at_creation - unit * (cur_cap + 1)
            scaled.append(dataclasses.replace(
                c,
                at_creation=fixed + unit * (new_cap + 1),
                at_growth_cap=max(c.at_growth_cap,
                                  fixed + unit * (new_cap + 1)),
                capacity=new_cap,
                growth_cap_capacity=max(c.growth_cap_capacity, new_cap),
            ))
        else:
            scaled.append(c)
    return dataclasses.replace(base, components=tuple(scaled))
