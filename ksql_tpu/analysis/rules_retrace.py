"""jit-retrace rule: patterns that force XLA recompiles or per-call traces.

Every perf direction in ROADMAP (sliced windows, distributed parity, the
bench regression gate) lives or dies on avoiding silent recompilation —
and until PR 8 the only signal was the ``jit_miss`` counter AFTER the
throughput had already collapsed.  This rule shifts the bug class left,
flagging inside the jit-traced call tree (``_trace_*`` functions, ``@jit``
-decorated defs, and the module-local helpers they call, with parameter
taint propagated call-site -> callee to a bounded depth):

* **branch-on-tracer** — a Python ``if``/``while`` whose test derives
  from traced values: either a trace error at runtime or, with shape
  polymorphism, a silent retrace per branch flip.  ``x is None`` /
  ``isinstance`` tests are exempt (Optional plumbing is resolved at trace
  time).
* **concretization** — ``int()`` / ``float()`` / ``bool()`` / ``.item()``
  / ``.tolist()`` on traced values: forces a host sync (or a trace
  error), and as a ``jax.jit`` static argument it recompiles per value.
* **host-string of tracer** — f-strings / ``str()`` / ``repr()`` over
  traced values bake the trace-time abstract value into a string.
* **mutable-host capture** — a traced body reading ``self.<attr>`` that
  some host-side method mutates WITHOUT triggering a recompile (the
  mutator neither runs at construction time nor reaches a
  ``*compile*`` call): the trace keeps the stale snapshot forever.
  Mutators that recompile (``_resize_ring`` -> ``_compile_steps``) are
  the repo's sanctioned pattern and stay silent.
* **per-batch static arg** — a call to a ``jax.jit(...,
  static_argnums=...)`` binding passing, at a static position, an
  unhashable literal (TypeError at call time), an f-string, or a value
  derived from the calling function's own parameters (``len(rows)``,
  ``arr.shape[0]``): a new compile cache entry per distinct batch.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ksql_tpu.analysis.lint import (
    Finding,
    LintModule,
    Rule,
    call_name,
    dotted_name,
)

_JIT_NAMES = ("jax.jit", "jit")
_CONCRETIZERS = {"int", "float", "bool"}
_CONCRETIZER_METHODS = {"item", "tolist"}
_STRINGIFIERS = {"str", "repr", "format"}
_TRACE_DEPTH = 3
#: mutator functions containing/reaching these name fragments are the
#: sanctioned mutate-then-recompile pattern, not a stale capture
_RECOMPILE_MARKERS = ("compile", "build_steps", "rebuild")


def _decorated_jit(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        name = dotted_name(dec)
        if name in _JIT_NAMES:
            return True
        if isinstance(dec, ast.Call):
            cname = call_name(dec)
            if cname in _JIT_NAMES:
                return True
            if cname in ("partial", "functools.partial") and dec.args:
                if dotted_name(dec.args[0]) in _JIT_NAMES:
                    return True
    return False


def _static_positions(call: ast.Call) -> Set[int]:
    """Literal static_argnums positions only.  Anything unparseable —
    static_argnames (string-keyed, no position mapping without the
    callee's signature), a variable, a computed tuple — yields NO
    positions: guessing {0} would flag correct code, and this rule's
    contract is that resolution failures cost recall, never precision."""
    for kw in call.keywords:
        if kw.arg != "static_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return {v.value}
        if isinstance(v, ast.Tuple):
            return {
                e.value for e in v.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)
            }
        return set()
    return set()


class _ModuleView:
    """Traced-set discovery + light parameter taint for one module."""

    def __init__(self, module: LintModule):
        self.module = module
        self.fns = module.functions()
        self.by_name: Dict[str, List[ast.FunctionDef]] = {}
        for fn in self.fns:
            self.by_name.setdefault(fn.name, []).append(fn)
        #: jitted binding name ("self._step", "_step") -> static positions
        self.static_bindings: Dict[str, Set[int]] = {}
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                key = dotted_name(target)
                if key is None:
                    continue
                for call in ast.walk(node.value):
                    if isinstance(call, ast.Call) \
                            and call_name(call) in _JIT_NAMES:
                        pos = _static_positions(call)
                        if pos:
                            self.static_bindings[key] = pos
        #: fn id -> set of tainted (tracer-carrying) parameter names
        self.tainted_params: Dict[int, Set[str]] = {}
        self.traced: List[ast.FunctionDef] = []
        self._discover()
        self._init_reach = self._reach_from_inits()

    # ------------------------------------------------------------ traced
    def _roots(self) -> List[ast.FunctionDef]:
        return [
            fn for fn in self.fns
            if fn.name.startswith("_trace_") or _decorated_jit(fn)
        ]

    def _local_callee(self, fn: ast.FunctionDef,
                      name: str) -> Optional[ast.FunctionDef]:
        parts = name.split(".")
        if len(parts) > 2 or (len(parts) == 2
                              and parts[0] not in ("self", "cls")):
            return None
        cands = self.by_name.get(parts[-1], [])
        return cands[0] if cands else None

    def _discover(self) -> None:
        """Traced set = roots + local callees to depth 3, with parameter
        taint pushed call-site -> callee (two passes settle chains)."""
        traced: Dict[int, ast.FunctionDef] = {}
        for fn in self._roots():
            traced[id(fn)] = fn
            self.tainted_params[id(fn)] = {
                a.arg for a in fn.args.args
                if a.arg not in ("self", "cls")
                and not _static_param(fn, a)
            }
        for _ in range(2):
            frontier = list(traced.values())
            for _depth in range(_TRACE_DEPTH):
                nxt: List[ast.FunctionDef] = []
                for fn in frontier:
                    env = self.tainted_params.get(id(fn), set())
                    for node in ast.walk(fn):
                        if not isinstance(node, ast.Call):
                            continue
                        name = call_name(node)
                        if name is None:
                            continue
                        callee = self._local_callee(fn, name)
                        if callee is None or callee.name.startswith(
                            "__"
                        ):
                            continue
                        shift = 1 if callee.args.args and \
                            callee.args.args[0].arg in ("self", "cls") \
                            and "." in name else 0
                        tp = self.tainted_params.setdefault(
                            id(callee), set()
                        )
                        for i, arg in enumerate(node.args):
                            pi = i + shift
                            if pi < len(callee.args.args) and \
                                    _expr_tainted(arg, env):
                                tp.add(callee.args.args[pi].arg)
                        if id(callee) not in traced:
                            traced[id(callee)] = callee
                            nxt.append(callee)
                frontier = nxt
        self.traced = list(traced.values())

    # ----------------------------------------------- construction excusal
    def _reach_from_inits(self) -> Set[int]:
        seen: Set[int] = set()
        frontier = [fn for fn in self.fns if fn.name == "__init__"]
        seen |= {id(fn) for fn in frontier}
        for _ in range(_TRACE_DEPTH):
            nxt = []
            for fn in frontier:
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        name = call_name(node)
                        callee = (
                            self._local_callee(fn, name)
                            if name is not None else None
                        )
                        if callee is not None and id(callee) not in seen:
                            seen.add(id(callee))
                            nxt.append(callee)
            frontier = nxt
        return seen

    def _triggers_recompile(self, fn: ast.FunctionDef,
                            depth: int = _TRACE_DEPTH) -> bool:
        if any(m in fn.name.lower() for m in _RECOMPILE_MARKERS):
            return True
        if depth <= 0:
            return False
        for node in ast.walk(fn):
            if isinstance(node, ast.Delete):
                # `del self._fk_steps`: the lazy-rebuild recompile idiom —
                # dropping the compiled-steps cache forces a fresh trace
                # on next use
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and "step" in t.attr:
                        return True
                continue
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            if any(m in name.lower() for m in _RECOMPILE_MARKERS):
                return True
            if name in _JIT_NAMES:
                return True  # re-jits the step in place: a fresh trace
            callee = self._local_callee(fn, name)
            if callee is not None and callee is not fn \
                    and self._triggers_recompile(callee, depth - 1):
                return True
        return False

    def stale_capture_attrs(self) -> Set[str]:
        """self attributes some host-side method mutates without either
        running at construction time or triggering a recompile — reading
        one inside the traced tree captures a stale snapshot."""
        traced_ids = {id(fn) for fn in self.traced}
        out: Set[str] = set()
        for fn in self.fns:
            if id(fn) in traced_ids or id(fn) in self._init_reach:
                continue
            if fn.name.startswith("__") or self._triggers_recompile(fn):
                continue
            for node in ast.walk(fn):
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets = list(node.targets)
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        out.add(t.attr)
        return out


def _static_param(fn: ast.FunctionDef, arg: ast.arg) -> bool:
    """Trace-root parameters that are trace-time STATICS by this repo's
    binding idiom: scalar-annotated (``side: str`` / ``idx: int`` bound
    via closure defaults in _compile_steps lambdas) or carrying a scalar
    constant default."""
    ann = arg.annotation
    if isinstance(ann, ast.Name) and ann.id in (
        "int", "str", "bool", "float"
    ):
        return True
    args = fn.args
    defaults = args.defaults
    if defaults:
        offset = len(args.args) - len(defaults)
        try:
            i = args.args.index(arg)
        except ValueError:
            return False
        if i >= offset and isinstance(defaults[i - offset], ast.Constant):
            return True
    return False


def _expr_tainted(expr: ast.AST, env: Set[str]) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in env:
            return True
    return False


def _test_exempt(test: ast.AST) -> bool:
    """Tests resolved at trace time even over traced operands: identity
    against None, isinstance, and boolean combinations thereof."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _test_exempt(test.operand)
    if isinstance(test, ast.BoolOp):
        return all(_test_exempt(v) for v in test.values)
    if isinstance(test, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return True
        # `"key" in store`: pytree STRUCTURE membership, fixed at trace
        # time (tracers live in the values, the key set is static)
        return (
            all(isinstance(op, (ast.In, ast.NotIn)) for op in test.ops)
            and isinstance(test.left, ast.Constant)
        )
    if isinstance(test, ast.Call):
        return call_name(test) in ("isinstance", "hasattr", "len")
    if isinstance(test, ast.Attribute) or isinstance(test, ast.Constant):
        return True  # self.flag / literal: trace-time static
    return False


class JitRetraceRule(Rule):
    name = "jit-retrace"
    doc = ("no Python branches/concretization/f-strings on traced values, "
           "no stale mutable-host capture, no per-batch static args — "
           "each forces an XLA recompile or per-call retrace")

    def check(self, module: LintModule) -> Iterable[Finding]:
        view = _ModuleView(module)
        out: List[Finding] = []
        if view.traced:
            stale = view.stale_capture_attrs()
            for fn in view.traced:
                out.extend(self._check_traced(module, view, fn, stale))
        if view.static_bindings:
            out.extend(self._check_static_calls(module, view))
        # deduplicate across overlapping traced walks
        seen: Set[Tuple[int, int, str]] = set()
        uniq = []
        for f in out:
            k = (f.line, f.col, f.message)
            if k not in seen:
                seen.add(k)
                uniq.append(f)
        return uniq

    def _finding(self, module: LintModule, node: ast.AST,
                 msg: str) -> Finding:
        return Finding(self.name, module.path, node.lineno,
                       node.col_offset, msg)

    # ------------------------------------------------------- traced body
    def _check_traced(self, module: LintModule, view: _ModuleView,
                      fn: ast.FunctionDef, stale: Set[str]) -> List[Finding]:
        out: List[Finding] = []
        env = set(view.tainted_params.get(id(fn), set()))
        # forward pass: taint assignments derived from tainted names.
        # Only the target ROOT is tainted — `jt[f"v_{col.name}"] = x`
        # taints jt, never the index expression's names
        def roots(t: ast.AST) -> Iterable[str]:
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    yield from roots(e)
                return
            while isinstance(t, (ast.Subscript, ast.Attribute, ast.Starred)):
                t = t.value
            if isinstance(t, ast.Name):
                yield t.id

        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _expr_tainted(
                node.value, env
            ):
                for t in node.targets:
                    env.update(roots(t))
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                if _expr_tainted(node.test, env) \
                        and not _test_exempt(node.test):
                    out.append(self._finding(
                        module, node,
                        f"Python branch on a traced value in {fn.name}: "
                        "tracer boolean coercion fails or silently "
                        "retraces per flip — use jnp.where/lax.cond",
                    ))
            elif isinstance(node, ast.Call):
                name = call_name(node)
                if name in _CONCRETIZERS and node.args and _expr_tainted(
                    node.args[0], env
                ):
                    out.append(self._finding(
                        module, node,
                        f"{name}() concretizes a traced value in "
                        f"{fn.name}: host sync / trace error — and as a "
                        "static arg it recompiles per value",
                    ))
                elif name in _STRINGIFIERS and node.args \
                        and _expr_tainted(node.args[0], env):
                    out.append(self._finding(
                        module, node,
                        f"{name}() over a traced value in {fn.name} "
                        "bakes the trace-time abstract value into a "
                        "string",
                    ))
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _CONCRETIZER_METHODS \
                        and _expr_tainted(node.func.value, env):
                    out.append(self._finding(
                        module, node,
                        f".{node.func.attr}() on a traced value in "
                        f"{fn.name}: forces a device sync per call (or "
                        "fails under jit)",
                    ))
            elif isinstance(node, ast.JoinedStr):
                for v in node.values:
                    if isinstance(v, ast.FormattedValue) \
                            and _expr_tainted(v.value, env):
                        out.append(self._finding(
                            module, node,
                            f"f-string over a traced value in {fn.name}: "
                            "bakes the trace-time abstract value into a "
                            "string (shape-derived strings vary per "
                            "batch and force retraces as static args)",
                        ))
                        break
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self" \
                    and node.attr in stale:
                out.append(self._finding(
                    module, node,
                    f"traced {fn.name} reads mutable host state "
                    f"'self.{node.attr}' (mutated by a non-recompiling "
                    "host path): the compiled step keeps the trace-time "
                    "snapshot forever — pass it as an argument or "
                    "recompile on mutation",
                ))
        return out

    # -------------------------------------------------- static-arg calls
    def _check_static_calls(self, module: LintModule,
                            view: _ModuleView) -> List[Finding]:
        out: List[Finding] = []
        for fn in module.functions():
            params = {
                a.arg for a in fn.args.args if a.arg not in ("self", "cls")
            }
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                positions = None
                if name is not None:
                    positions = view.static_bindings.get(name)
                    if positions is None and name.startswith("self."):
                        positions = view.static_bindings.get(
                            name.split(".", 1)[1]
                        )
                if not positions:
                    continue
                for pos in sorted(positions):
                    if pos >= len(node.args):
                        continue
                    arg = node.args[pos]
                    if isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                        out.append(self._finding(
                            module, node,
                            f"unhashable literal at static position "
                            f"{pos} of jitted '{name}': TypeError at "
                            "call time — static args must be hashable",
                        ))
                    elif any(isinstance(n, ast.JoinedStr)
                             for n in ast.walk(arg)):
                        out.append(self._finding(
                            module, node,
                            f"f-string at static position {pos} of "
                            f"jitted '{name}': a distinct string per "
                            "call means a silent recompile per call",
                        ))
                    elif _expr_tainted(arg, params):
                        out.append(self._finding(
                            module, node,
                            f"static position {pos} of jitted '{name}' "
                            "derives from the caller's per-batch data: "
                            "every distinct value compiles a new XLA "
                            "program (the jit_miss counter you see "
                            "after the fact)",
                        ))
        return out
