"""Trace-safety rule: ``_trace_*`` functions are jit-traced and must be pure.

Every function named ``_trace_*`` in this repo is handed to ``jax.jit``
(lowering._compile_steps, parallel/distributed._build_steps) — its Python
body runs ONCE per compilation, not per step.  A wall-clock read, RNG
draw, or Python-level mutation inside one silently bakes trace-time
values into the compiled program (or mutates host state once instead of
per batch) — a bug class that survives every unit test whose first run
compiles and asserts in the same breath.

Flags, inside any ``def _trace_*``:

* host-time / RNG / IO calls: ``time.*``, ``random.*``, ``np.random.*``,
  ``datetime.now``, ``print``, ``open``, ``input``;
* fault-injection seams (``faults.fault_point``) — they would fire at
  trace time only;
* Python-level mutation of the enclosing object: ``self.x = ...``,
  ``self.x += ...``, and mutating method calls on ``self`` attributes
  (append/add/update/...).

Reads of ``self`` (capacities, layouts, specs) are fine — they are
trace-time statics by design.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ksql_tpu.analysis.lint import Finding, LintModule, Rule, call_name

_BANNED_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "time.sleep",
    "datetime.now", "datetime.datetime.now", "datetime.utcnow",
    "print", "open", "input",
    "faults.fault_point",
}
_BANNED_PREFIXES = ("random.", "np.random.", "numpy.random.", "_random.")
_MUTATORS = {
    "append", "add", "update", "clear", "discard", "extend", "insert",
    "pop", "popitem", "remove", "setdefault", "write",
}


class TraceUnsafeRule(Rule):
    name = "trace-unsafe"
    doc = ("_trace_* functions are jit-traced: no wall-clock/RNG/IO calls, "
           "no Python-level mutation of self")

    def check(self, module: LintModule) -> Iterable[Finding]:
        out: List[Finding] = []
        for fn in module.functions():
            if not fn.name.startswith("_trace_"):
                continue
            out.extend(self._check_fn(module, fn))
        return out

    def _finding(self, module: LintModule, node: ast.AST, msg: str) -> Finding:
        return Finding(self.name, module.path, node.lineno, node.col_offset, msg)

    def _check_fn(self, module: LintModule, fn: ast.FunctionDef) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name is None:
                    continue
                if name in _BANNED_CALLS or name.startswith(_BANNED_PREFIXES):
                    out.append(self._finding(
                        module, node,
                        f"'{name}' inside jit-traced {fn.name}: runs at "
                        "trace time only, baking one value into the "
                        "compiled step",
                    ))
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATORS
                    and self._roots_at_self(node.func.value)
                ):
                    out.append(self._finding(
                        module, node,
                        f"Python-level mutation '.{node.func.attr}(...)' of "
                        f"self state inside jit-traced {fn.name}: happens "
                        "once at trace time, not per step",
                    ))
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Attribute) and self._roots_at_self(t):
                        out.append(self._finding(
                            module, t,
                            f"assignment to 'self.{t.attr}' inside "
                            f"jit-traced {fn.name}: a trace-time side "
                            "effect (runs once per compilation, not per "
                            "step)",
                        ))
                    elif (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and self._roots_at_self(t.value)
                    ):
                        out.append(self._finding(
                            module, t,
                            f"element store into a self attribute inside "
                            f"jit-traced {fn.name}: a trace-time side effect",
                        ))
        return out

    @staticmethod
    def _roots_at_self(node: ast.AST) -> bool:
        cur = node
        while isinstance(cur, (ast.Attribute, ast.Subscript)):
            cur = cur.value
        return isinstance(cur, ast.Name) and cur.id == "self"
