"""Unregistered-config-key rule.

Every ``ksql.*`` key the code READS must be registered with a typed
default and a one-line doc in :mod:`ksql_tpu.common.config` — that is
what makes SET / LIST PROPERTIES / server-config round-trips, docs, and
default discovery work (the reference's KsqlConfig ConfigDef discipline).
A read of an unregistered key silently returns the caller's fallback and
never shows up in ``KsqlConfig.defs()``.

Flags string-literal keys starting ``ksql.`` passed as the first argument
to the config read surface: ``.get(...)`` / ``.get_int/.get_bool/
.get_str`` / ``.explicit(...)`` / ``effective_property(...)``.  Writes
(``SET``, constructor dicts) stay unchecked — unknown keys are tolerated
there exactly like AbstractConfig.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set

from ksql_tpu.analysis.lint import Finding, LintModule, Rule

_READS = {"get", "get_int", "get_bool", "get_str", "explicit",
          "effective_property"}


def registered_keys() -> Set[str]:
    """The ``ksql.*`` keys defined in common/config.py, read from source so
    the rule needs no jax-capable import of the engine tree."""
    import ksql_tpu.common.config as cfgmod

    try:
        return set(getattr(cfgmod, "_DEFS").keys())
    except Exception:  # pragma: no cover — fall back to a source scan
        with open(cfgmod.__file__, encoding="utf-8") as f:
            src = f.read()
        return set(re.findall(r'_define\(\s*"(ksql\.[^"]+)"', src))


class UnregisteredConfigKeyRule(Rule):
    name = "unregistered-config-key"
    doc = ("ksql.* keys read via config.get/effective_property must be "
           "registered (default + doc) in ksql_tpu.common.config")

    def __init__(self, keys: Optional[Set[str]] = None):
        self._keys = keys

    @property
    def keys(self) -> Set[str]:
        if self._keys is None:
            self._keys = registered_keys()
        return self._keys

    def check(self, module: LintModule) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and fn.attr in _READS):
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
                continue
            key = arg.value
            if key.startswith("ksql.") and key not in self.keys:
                out.append(Finding(
                    self.name, module.path, arg.lineno, arg.col_offset,
                    f"config key '{key}' is read but not registered in "
                    "ksql_tpu.common.config — add a _define(...) with a "
                    "typed default and one-line doc",
                ))
        return out
