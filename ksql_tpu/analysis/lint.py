"""graftlint — AST lint framework with repo-specific rules.

Why a bespoke linter: the invariants that have actually bitten this
codebase are not stylistic, they are semantic contracts between layers —
numpy host buffers must never zero-copy-alias into jit state that a step
later donates to XLA (the PR-2 heap corruption), ``_trace_*`` functions
are jit-traced and must stay side-effect free, every ``ksql.*`` key read
must be registered in :mod:`ksql_tpu.common.config` so SET/docs/defaults
round-trip, and ``handle`` mutations inside deadline-supervised tick
bodies must go through the PR-5 zombie-worker fence.  Generic linters
cannot express any of these; each is a :class:`Rule` here.

Suppression (the escape hatch): append ``# graftlint: disable=<rule>`` to
the flagged line (or put it on its own line directly above), or disable a
rule for a whole file with ``# graftlint: disable-file=<rule>``.  Several
rules separate with commas.  Use it with a justification comment — the
escape hatch records a reviewed decision, it does not waive the review.

Whole-program mode (PR 8): every lint entry point parses ALL files into a
:class:`~ksql_tpu.analysis.program.Program` and hands it to each rule's
:meth:`Rule.prepare` before the per-module checks run, so rules can build
interprocedural summaries (donated-aliasing taint through helper chains
and cross-module handoffs) and concurrency maps (shared-state-race).
Two more annotations ride the same comment syntax:

* ``# graftlint: entrypoint=<label>`` on (or directly above) a ``def``
  declares the function a thread entrypoint the race rule cannot discover
  syntactically (callback-driven: family delivery, push-session polls);
* ``# graftlint: owner=<label>`` on a mutation line records a reviewed
  single-writer claim — only the named entrypoint ever executes this
  write — which the race rule validates against its reachability map.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set

_DISABLE = "graftlint:"


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


class Rule:
    """One lint rule: a name, a one-line doc, and a check over a module.

    ``prepare`` runs once per lint invocation with the whole
    :class:`~ksql_tpu.analysis.program.Program` before any ``check``;
    interprocedural rules build their cross-module summaries there.
    Per-module-only rules just ignore it."""

    name: str = ""
    doc: str = ""

    def prepare(self, program) -> None:
        pass

    def check(self, module: "LintModule") -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


class LintModule:
    """A parsed source file plus the suppression map rules consult."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        # parent links: rules reason about enclosing statements/guards
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._graftlint_parent = node  # type: ignore[attr-defined]
        self._line_disabled: Dict[int, Set[str]] = {}
        self._file_disabled: Set[str] = set()
        #: line -> single-writer owner label (# graftlint: owner=<label>)
        self.owner_marks: Dict[int, str] = {}
        #: line -> declared thread-entrypoint label (# graftlint:
        #: entrypoint=<label> on or directly above a def)
        self.entrypoint_marks: Dict[int, str] = {}
        self._parse_disables()

    # ------------------------------------------------------------ disables
    def _parse_disables(self) -> None:
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(self.source).readline))
        except tokenize.TokenError:  # pragma: no cover — ast.parse passed
            return
        for tok in tokens:
            if tok.type != tokenize.COMMENT or _DISABLE not in tok.string:
                continue
            body = tok.string.split(_DISABLE, 1)[1].strip()
            file_wide = body.startswith("disable-file=")
            line = tok.start[0]
            standalone = self.source.splitlines()[line - 1].lstrip().startswith("#")
            if body.startswith(("owner=", "entrypoint=")):
                marks = (
                    self.owner_marks if body.startswith("owner=")
                    else self.entrypoint_marks
                )
                label = body.split("=", 1)[1].split(",")[0].strip()
                if label:
                    marks[line] = label
                    if standalone:
                        marks[line + 1] = label
                    else:
                        start = self._innermost_stmt_start(line)
                        if start is not None:
                            marks.setdefault(start, label)
                continue
            if not (file_wide or body.startswith("disable=")):
                continue
            rules = {r.strip() for r in body.split("=", 1)[1].split(",") if r.strip()}
            if file_wide:
                self._file_disabled |= rules
                continue
            self._line_disabled.setdefault(line, set()).update(rules)
            if standalone:
                # a standalone disable comment covers the next line too
                self._line_disabled.setdefault(line + 1, set()).update(rules)
            else:
                # a trailing comment on a CONTINUATION line covers the
                # multi-line statement it annotates (findings anchor at the
                # statement's first line) — the INNERMOST one only, never
                # the enclosing for/if/def headers whose span also covers it
                start = self._innermost_stmt_start(line)
                if start is not None:
                    self._line_disabled.setdefault(start, set()).update(rules)

    def _innermost_stmt_start(self, line: int) -> Optional[int]:
        best: Optional[ast.stmt] = None
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.stmt):
                continue
            end = getattr(node, "end_lineno", node.lineno)
            if not (node.lineno <= line <= end):
                continue
            if best is None or (node.lineno, -(end - node.lineno)) > (
                best.lineno, -(getattr(best, "end_lineno", best.lineno)
                               - best.lineno)
            ):
                best = node
        return best.lineno if best is not None else None

    def disabled(self, rule: str, line: int) -> bool:
        if rule in self._file_disabled:
            return True
        return rule in self._line_disabled.get(line, ())

    # ------------------------------------------------------------- helpers
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return getattr(node, "_graftlint_parent", None)

    def functions(self) -> List[ast.FunctionDef]:
        cached = getattr(self, "_functions", None)
        if cached is None:
            cached = self._functions = [
                n for n in ast.walk(self.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
        return cached


def default_rules() -> List[Rule]:
    from ksql_tpu.analysis.rules_aliasing import DonatedAliasingRule
    from ksql_tpu.analysis.rules_blocking import BlockingUnderLockRule
    from ksql_tpu.analysis.rules_config import UnregisteredConfigKeyRule
    from ksql_tpu.analysis.rules_fence import UnfencedHandleMutationRule
    from ksql_tpu.analysis.rules_race import SharedStateRaceRule
    from ksql_tpu.analysis.rules_retrace import JitRetraceRule
    from ksql_tpu.analysis.rules_trace import TraceUnsafeRule

    return [
        DonatedAliasingRule(),
        TraceUnsafeRule(),
        UnregisteredConfigKeyRule(),
        UnfencedHandleMutationRule(),
        SharedStateRaceRule(),
        JitRetraceRule(),
        BlockingUnderLockRule(),
    ]


def lint_modules(
    modules: Sequence[LintModule], rules: Optional[Sequence[Rule]] = None
) -> List[Finding]:
    """The core pass: one Program over all modules, rules prepared once,
    then checked per module.  Every public entry point funnels here so
    interprocedural rules always see the full file set they were given."""
    from ksql_tpu.analysis.program import Program

    rules = list(rules) if rules is not None else default_rules()
    program = Program(modules)
    for rule in rules:
        rule.prepare(program)
    out: List[Finding] = []
    for module in modules:
        for rule in rules:
            for f in rule.check(module):
                if not module.disabled(f.rule, f.line):
                    out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def lint_source(
    source: str, path: str = "<string>", rules: Optional[Sequence[Rule]] = None
) -> List[Finding]:
    return lint_modules([LintModule(path, source)], rules)


def lint_file(path: str, rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_modules([LintModule(path, f.read())], rules)


def expand_lint_paths(paths: Sequence[str]) -> List[str]:
    """Files and directory trees -> the ordered file list (``__pycache__``
    skipped) — shared by lint_paths and the CLI's --jobs scheduler."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                files.extend(
                    os.path.join(root, n) for n in sorted(names) if n.endswith(".py")
                )
        else:
            files.append(p)
    return files


def load_modules(files: Sequence[str]) -> List[LintModule]:
    modules = []
    for path in files:
        with open(path, encoding="utf-8") as f:
            modules.append(LintModule(path, f.read()))
    return modules


def lint_paths(
    paths: Sequence[str], rules: Optional[Sequence[Rule]] = None
) -> List[Finding]:
    """Lint files and directory trees as ONE program: cross-module taint
    and entrypoint maps span everything passed in a single call."""
    return lint_modules(load_modules(expand_lint_paths(paths)), rules)


# --------------------------------------------------------- shared AST utils


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)
