"""Static analysis: graftlint (repo-specific AST lint) + the plan verifier.

Two halves, one motivation — move failure discovery from runtime to
analysis time:

* :mod:`ksql_tpu.analysis.lint` is a WHOLE-PROGRAM AST lint framework
  (every linted file parsed into one :class:`Program`; rules build
  interprocedural summaries before per-module checks) whose rules encode
  this repo's hard-won invariants: the PR-2 donated-buffer aliasing
  corruption class tracked across helper chains and modules, jit trace
  purity, config-key registration, the PR-5 zombie-worker fence
  discipline, thread-shared-state mutation discipline
  (``shared-state-race`` + the ``--threads`` entrypoint map), and
  XLA-recompile forcers (``jit-retrace``).  ``scripts/lint.py`` is the
  CLI (``--jobs``/``--baseline``/``--threads``);
  tests/test_analysis.py gates the tree in tier-1.
* :mod:`ksql_tpu.analysis.plan_verifier` walks the serialized
  ``ExecutionStep`` DAG before lowering — schema propagation, key
  consistency across repartitions, window/serde invariants — and
  classifies each plan's backend (distributed / device / oracle) ahead of
  time with the same reason strings the runtime fallback ladder counts in
  ``engine.fallback_reasons``, surfaced through ``EXPLAIN``.
* :mod:`ksql_tpu.analysis.mem_model` (graftmem) models a device plan's
  HBM footprint ahead of time — per-component bytes at-creation /
  at-growth-cap / per-shard, pinned byte-exact against the runtime's
  ``device_state_bytes()`` seam over the golden-plan corpus — feeding
  the ``ksql.analysis.memory.budget.bytes`` admission gate, EXPLAIN's
  ``Device memory (static)`` table, the
  ``ksql_query_estimated_hbm_bytes`` gauge, the rescale controller's
  shrink refusal, and ``scripts/memcheck.py``.
"""

from ksql_tpu.analysis.lint import (  # noqa: F401
    Finding,
    LintModule,
    Rule,
    default_rules,
    expand_lint_paths,
    lint_file,
    lint_modules,
    lint_paths,
    lint_source,
    load_modules,
)
from ksql_tpu.analysis.program import Program  # noqa: F401
from ksql_tpu.analysis.rules_race import RaceAnalysis  # noqa: F401
from ksql_tpu.analysis.plan_verifier import (  # noqa: F401
    BackendDecision,
    PlanViolation,
    classify_plan,
    verify_plan,
)
from ksql_tpu.analysis.mem_model import (  # noqa: F401
    ComponentBytes,
    MemoryReport,
    analyze_plan_memory,
    footprint_of,
    shrink_footprint,
)
