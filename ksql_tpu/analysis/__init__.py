"""Static analysis: graftlint (repo-specific AST lint) + the plan verifier.

Two halves, one motivation — move failure discovery from runtime to
analysis time:

* :mod:`ksql_tpu.analysis.lint` is an AST-based lint framework whose rules
  encode this repo's hard-won invariants (the PR-2 donated-buffer aliasing
  corruption class, jit trace purity, config-key registration, the PR-5
  zombie-worker fence discipline).  ``scripts/lint.py`` is the CLI;
  tests/test_analysis.py gates the tree in tier-1.
* :mod:`ksql_tpu.analysis.plan_verifier` walks the serialized
  ``ExecutionStep`` DAG before lowering — schema propagation, key
  consistency across repartitions, window/serde invariants — and
  classifies each plan's backend (distributed / device / oracle) ahead of
  time with the same reason strings the runtime fallback ladder counts in
  ``engine.fallback_reasons``, surfaced through ``EXPLAIN``.
"""

from ksql_tpu.analysis.lint import (  # noqa: F401
    Finding,
    LintModule,
    Rule,
    default_rules,
    lint_file,
    lint_paths,
    lint_source,
)
from ksql_tpu.analysis.plan_verifier import (  # noqa: F401
    BackendDecision,
    PlanViolation,
    classify_plan,
    verify_plan,
)
