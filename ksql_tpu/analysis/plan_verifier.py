"""Static plan verifier + ahead-of-time backend classification.

ksqlDB's architecture validates the serializable ExecutionStep IR *before*
lowering (PAPER.md layers 5-6: StepSchemaResolver, PlanInfo); this module
is that seam for the XLA reproduction.  Two services:

* :func:`verify_plan` — walk the step DAG and check the invariants every
  backend assumes: expression column references resolve against the
  child's schema scope, projections produce exactly their declared value
  columns, re-keying steps declare as many key columns as key
  expressions, key schema stays consistent across non-rekeying steps,
  join keys are type-compatible across sides, window parameters are
  sane (HOPPING advance ≤ size, SESSION gap > 0, retention ≥ size),
  and serde formats are known / representable (DELIMITED cannot carry
  nested types).  Violations are returned, not raised — the engine logs
  them (``ksql.analysis.verify.plans``) and optionally rejects
  (``ksql.analysis.verify.strict``).

* :func:`classify_plan` — decide the backend (distributed / device /
  oracle) a plan will run on BEFORE any executor is built, replaying the
  engine's fallback ladder (engine._build_executor) against a
  construction-free lowering probe (``CompiledDeviceQuery(...,
  analyze_only=True)``: full structural analysis + agg-spec/layout
  checks, no jit wrappers, no abstract tracing, no allocation).  Reason
  strings are the exact ``DeviceUnsupported`` messages the runtime counts
  in ``engine.fallback_reasons``, which is what makes the decision
  testable against the live ladder.  ``EXPLAIN`` surfaces both.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

from ksql_tpu.common.schema import (
    PSEUDOCOLUMNS,
    WINDOW_BOUNDS,
    LogicalSchema,
)
from ksql_tpu.common.types import SqlBaseType
from ksql_tpu.execution import expressions as ex
from ksql_tpu.execution import steps as st

# ----------------------------------------------------------------- verifier

#: formats the serde layer implements (ksql_tpu/serde/)
KNOWN_FORMATS = {
    "KAFKA", "JSON", "JSON_SR", "AVRO", "PROTOBUF", "PROTOBUF_NOSR",
    "DELIMITED", "NONE",
}
_NESTED = (SqlBaseType.ARRAY, SqlBaseType.MAP, SqlBaseType.STRUCT)
_NUMERIC = (
    SqlBaseType.INTEGER, SqlBaseType.BIGINT, SqlBaseType.DOUBLE,
    SqlBaseType.DECIMAL,
)


@dataclasses.dataclass(frozen=True)
class PlanViolation:
    step_ctx: str
    step_type: str
    rule: str
    message: str

    def format(self) -> str:
        return f"[{self.rule}] {self.step_type}/{self.step_ctx}: {self.message}"


def _scope_names(schema: LogicalSchema) -> set:
    """Column names expressions over this schema may reference (the
    reference resolves against withPseudoAndKeyColsInValue; window bounds
    are always admitted — windowed-ness is a runtime property the verifier
    must not guess stricter than the planner)."""
    names = {c.name for c in schema.columns()}
    names |= set(PSEUDOCOLUMNS) | set(WINDOW_BOUNDS)
    return names


def _free_columns(expr: Any, bound: frozenset = frozenset()):
    """Column references NOT bound by an enclosing lambda — plans encode
    lambda variables as ColumnRef inside the lambda body (resolution is
    the interpreter's job), so a plain referenced_columns walk would flag
    every TRANSFORM/REDUCE/FILTER lambda parameter."""
    import dataclasses as _dc

    if isinstance(expr, ex.LambdaExpression):
        yield from _free_columns(expr.body, bound | set(expr.params))
        return
    if isinstance(expr, ex.ColumnRef):
        if expr.name not in bound:
            yield expr.name
        return
    if isinstance(expr, ex.Expression):
        for f in _dc.fields(expr):
            yield from _free_columns(getattr(expr, f.name), bound)
    elif isinstance(expr, (list, tuple)):
        for item in expr:
            yield from _free_columns(item, bound)


def _expr_refs_ok(out: List[PlanViolation], step: st.ExecutionStep,
                  exprs: Sequence[Any], schema: LogicalSchema,
                  what: str) -> None:
    scope = _scope_names(schema)
    for e in exprs:
        for name in _free_columns(e):
            if name not in scope:
                out.append(PlanViolation(
                    step.ctx, type(step).__name__, "schema-propagation",
                    f"{what} references column '{name}' absent from the "
                    f"child schema [{', '.join(sorted(scope - set(PSEUDOCOLUMNS) - set(WINDOW_BOUNDS)))}]",
                ))


def _key_types(schema: LogicalSchema) -> Tuple:
    return tuple(c.type.base for c in schema.key_columns)


def _types_joinable(a, b) -> bool:
    if a == b:
        return True
    return a in _NUMERIC and b in _NUMERIC  # numeric keys coerce


def _check_window(out: List[PlanViolation], step: st.ExecutionStep,
                  window) -> None:
    from ksql_tpu.parser.ast_nodes import WindowType

    name = type(step).__name__
    wt = window.window_type
    if wt in (WindowType.TUMBLING, WindowType.HOPPING):
        if not window.size_ms or window.size_ms <= 0:
            out.append(PlanViolation(
                step.ctx, name, "window-invariant",
                f"{wt.value} window requires SIZE > 0 (got {window.size_ms})",
            ))
        if wt == WindowType.HOPPING:
            adv = window.advance_ms
            if not adv or adv <= 0:
                out.append(PlanViolation(
                    step.ctx, name, "window-invariant",
                    f"HOPPING window requires ADVANCE BY > 0 (got {adv})",
                ))
            elif window.size_ms and adv > window.size_ms:
                out.append(PlanViolation(
                    step.ctx, name, "window-invariant",
                    f"HOPPING ADVANCE ({adv}ms) must not exceed SIZE "
                    f"({window.size_ms}ms) — gaps would drop records",
                ))
    elif wt == WindowType.SESSION:
        if not window.gap_ms or window.gap_ms <= 0:
            out.append(PlanViolation(
                step.ctx, name, "window-invariant",
                f"SESSION window requires GAP > 0 (got {window.gap_ms})",
            ))
    if window.grace_ms is not None and window.grace_ms < 0:
        out.append(PlanViolation(
            step.ctx, name, "window-invariant",
            f"GRACE PERIOD must be >= 0 (got {window.grace_ms})",
        ))
    if (
        window.retention_ms is not None and window.size_ms
        and window.retention_ms < window.size_ms
    ):
        out.append(PlanViolation(
            step.ctx, name, "window-invariant",
            f"RETENTION ({window.retention_ms}ms) must be >= window SIZE "
            f"({window.size_ms}ms)",
        ))


def _check_formats(out: List[PlanViolation], step: st.ExecutionStep) -> None:
    fmts = getattr(step, "formats", None)
    if fmts is None:
        return
    name = type(step).__name__
    for side, fmt in (("key", fmts.key_format), ("value", fmts.value_format)):
        if str(fmt).upper() not in KNOWN_FORMATS:
            out.append(PlanViolation(
                step.ctx, name, "serde-invariant",
                f"unknown {side} format '{fmt}' (known: "
                f"{', '.join(sorted(KNOWN_FORMATS))})",
            ))
    if str(fmts.value_format).upper() == "DELIMITED":
        schema = getattr(step, "schema", None)
        if schema is not None:
            for c in schema.value_columns:
                if c.type.base in _NESTED:
                    out.append(PlanViolation(
                        step.ctx, name, "serde-invariant",
                        f"DELIMITED value format cannot represent nested "
                        f"column '{c.name}' ({c.type.base.name})",
                    ))


def _verify_step(out: List[PlanViolation], step: st.ExecutionStep) -> None:
    name = type(step).__name__
    src = getattr(step, "source", None)
    src_schema = src.schema if isinstance(src, st.ExecutionStep) else None

    _check_formats(out, step)

    if isinstance(step, (st.StreamFilter, st.TableFilter)) and src_schema:
        _expr_refs_ok(out, step, [step.predicate], src_schema, "filter predicate")
        # a filter passes rows through unchanged
        if _key_types(step.schema) != _key_types(src_schema):
            out.append(PlanViolation(
                step.ctx, name, "key-consistency",
                "filter must preserve its child's key schema "
                f"({_key_types(src_schema)} -> {_key_types(step.schema)})",
            ))

    elif isinstance(step, (st.StreamSelect, st.TableSelect)) and src_schema:
        _expr_refs_ok(out, step, [e for _, e in step.selects], src_schema,
                      "projection expression")
        aliases = [a for a, _ in step.selects]
        declared = [c.name for c in step.schema.value_columns]
        if aliases != declared:
            out.append(PlanViolation(
                step.ctx, name, "schema-propagation",
                f"projection aliases {aliases} do not match the declared "
                f"value columns {declared}",
            ))
        if len(step.schema.key_columns) > len(src_schema.key_columns):
            # fewer is legal (ksql.new.query.planner.enabled drops
            # unprojected keys); a projection INVENTING key columns is not
            out.append(PlanViolation(
                step.ctx, name, "key-consistency",
                "projection cannot add key columns "
                f"({len(src_schema.key_columns)} -> "
                f"{len(step.schema.key_columns)}); re-key with PARTITION BY",
            ))

    elif isinstance(step, (st.StreamSelectKey, st.TableSelectKey)) and src_schema:
        _expr_refs_ok(out, step, step.key_expressions, src_schema,
                      "PARTITION BY expression")
        if len(step.key_expressions) != len(step.schema.key_columns):
            out.append(PlanViolation(
                step.ctx, name, "key-consistency",
                f"{len(step.key_expressions)} key expression(s) but "
                f"{len(step.schema.key_columns)} declared key column(s) — "
                "the repartition would mis-route rows",
            ))

    elif isinstance(step, (st.StreamGroupBy, st.TableGroupBy)) and src_schema:
        # NOTE: a GroupBy step's schema is the PRE-grouping schema (pass-
        # through); the grouped key appears on the Aggregate above it
        _expr_refs_ok(out, step, step.group_by_expressions, src_schema,
                      "GROUP BY expression")
        if not step.group_by_expressions:
            out.append(PlanViolation(
                step.ctx, name, "key-consistency",
                "GROUP BY step with no grouping expressions",
            ))

    elif isinstance(step, (st.StreamAggregate, st.StreamWindowedAggregate,
                           st.TableAggregate)) and src_schema:
        _expr_refs_ok(
            out, step,
            [a for call in step.aggregations for a in call.args],
            src_schema, "aggregate argument",
        )
        # non-agg columns are the group-key columns carried through: they
        # resolve against the aggregate's OWN key schema or the child scope
        scope = _scope_names(src_schema) | {
            c.name for c in step.schema.key_columns
        }
        for col in step.non_agg_columns:
            if col not in scope:
                out.append(PlanViolation(
                    step.ctx, name, "schema-propagation",
                    f"non-aggregate column '{col}' is neither a group-key "
                    "column nor in the pre-aggregation schema",
                ))
        # each aggregation call produces exactly one value column; non-agg
        # key columns live in the key schema, riding into the value only
        # when declared there
        declared = len(step.schema.value_columns)
        produced = len(step.aggregations) + sum(
            1 for c in step.non_agg_columns
            if step.schema.find_value_column(c) is not None
        )
        if declared != produced:
            out.append(PlanViolation(
                step.ctx, name, "schema-propagation",
                f"aggregate produces {produced} value column(s) "
                f"({len(step.aggregations)} aggregation(s) + carried "
                "group-key columns) but declares "
                f"{declared}",
            ))
        # the grouped key arity must match the grouping expressions below
        group = step.source
        if isinstance(group, (st.StreamGroupBy, st.TableGroupBy)):
            n_exprs = len(group.group_by_expressions)
            if n_exprs != len(step.schema.key_columns):
                out.append(PlanViolation(
                    step.ctx, name, "key-consistency",
                    f"{n_exprs} grouping expression(s) below but "
                    f"{len(step.schema.key_columns)} aggregate key "
                    "column(s) — repartition and store key would disagree",
                ))
        window = getattr(step, "window", None)
        if window is not None:
            _check_window(out, step, window)

    elif isinstance(step, (st.StreamStreamJoin, st.StreamTableJoin,
                           st.TableTableJoin)):
        for side, key_expr, child in (
            ("left", step.left_key, step.left),
            ("right", step.right_key, step.right),
        ):
            _expr_refs_ok(out, step, [key_expr], child.schema,
                          f"{side} join key")
        lt = _join_key_type(step.left_key, step.left.schema)
        rt = _join_key_type(step.right_key, step.right.schema)
        if lt is not None and rt is not None and not _types_joinable(lt, rt):
            out.append(PlanViolation(
                step.ctx, name, "key-consistency",
                f"join key types are incompatible: left {lt.name} vs "
                f"right {rt.name} — co-partitioning by key hash would "
                "never match",
            ))
        if isinstance(step, st.StreamStreamJoin):
            if step.before_ms < 0 or step.after_ms < 0:
                out.append(PlanViolation(
                    step.ctx, name, "window-invariant",
                    f"WITHIN bounds must be >= 0 (before={step.before_ms}, "
                    f"after={step.after_ms})",
                ))
            if step.grace_ms is not None and step.grace_ms < 0:
                out.append(PlanViolation(
                    step.ctx, name, "window-invariant",
                    f"join GRACE must be >= 0 (got {step.grace_ms})",
                ))

    elif isinstance(step, st.ForeignKeyTableTableJoin):
        _expr_refs_ok(out, step, [step.foreign_key_expression],
                      step.left.schema, "foreign-key expression")

    elif isinstance(step, (st.WindowedStreamSource, st.WindowedTableSource)):
        if str(step.window_type).upper() != "SESSION" and not step.window_size_ms:
            out.append(PlanViolation(
                step.ctx, name, "window-invariant",
                f"windowed source of type {step.window_type} requires "
                "WINDOW_SIZE",
            ))

    elif isinstance(step, (st.StreamSink, st.TableSink)) and src_schema:
        defaults = {n for n, _ in getattr(step, "value_defaults", ())}
        src_cols = {c.name for c in src_schema.columns()} | defaults
        for c in step.schema.value_columns:
            if c.name not in src_cols:
                out.append(PlanViolation(
                    step.ctx, name, "schema-propagation",
                    f"sink declares value column '{c.name}' that the query "
                    "does not produce (and no write-default is attached)",
                ))


def _join_key_type(key_expr, schema: LogicalSchema):
    """Base SQL type of a join key when it is a plain column reference;
    None for computed keys (typing those is the interpreter's job)."""
    if isinstance(key_expr, ex.ColumnRef):
        col = schema.find_column(key_expr.name)
        return col.type.base if col is not None else None
    return None


def verify_plan(plan: st.QueryPlan) -> List[PlanViolation]:
    """Every invariant violation in the plan's step DAG (empty = clean)."""
    out: List[PlanViolation] = []
    root = plan.physical_plan
    if plan.sink_name is not None and not isinstance(
        root, (st.StreamSink, st.TableSink)
    ):
        # transient (push/pull) plans legitimately have no sink step; only
        # a persistent query that DECLARES a sink must be rooted at one
        out.append(PlanViolation(
            getattr(root, "ctx", "?"), type(root).__name__,
            "plan-shape", "physical plan must be rooted at a sink step",
        ))
    for step in st.walk_steps(root):
        _verify_step(out, step)
    return out


# ----------------------------------------------------- backend classification


@dataclasses.dataclass(frozen=True)
class BackendDecision:
    """Ahead-of-time backend placement: where the plan will run and, for
    every rung it fell through, the exact DeviceUnsupported reason the
    runtime ladder would count in ``engine.fallback_reasons``.

    ``windowing`` is the device backend's windowing shape for HOPPING
    aggregations — ``sliced (width=..., ring=..., k=...)`` when the
    per-slice partial-aggregation path applies, or ``expansion (k=...):
    <reason>`` when the query keeps the k-fold expansion (the reason is
    the same windowing-shape fallback string the engine counts in
    ``fallback_reasons``).  None for plans with no hopping aggregation or
    plans that never reach the device."""

    backend: str  # "distributed" | "device" | "oracle"
    reasons: Tuple[Tuple[str, str], ...] = ()  # (rung, reason)
    windowing: Optional[str] = None
    #: environment-dependent caveats about the CHOSEN backend (e.g. the
    #: native C++ ingest tier being bypassed in distributed mode) — shown
    #: in EXPLAIN, deliberately NOT pinned in the committed snapshot
    #: (native availability varies per container)
    notes: Tuple[str, ...] = ()

    def reason_strings(self) -> List[str]:
        return [r for _, r in self.reasons]

    def format(self) -> str:
        lines = [f"Backend (static): {self.backend}"]
        if self.windowing:
            lines.append(f"Windowing: {self.windowing}")
        for note in self.notes:
            lines.append(f"  note: {note}")
        for rung, reason in self.reasons:
            lines.append(f"  fell through {rung}: {reason}")
        return "\n".join(lines)


def _device_probe(plan: st.QueryPlan, registry, capacity: int,
                  store_capacity: int, deep: bool,
                  sliced: Optional[bool] = None, slice_ring_max: int = 512):
    """Lowering analysis without construction side effects.  analyze_only
    runs the full structural/agg/layout analysis (every plan-derivable
    DeviceUnsupported) but skips jit wrapping and abstract tracing;
    deep=True runs the real constructor (eval_shape included) for
    expression-level exactness at EXPLAIN cost."""
    from ksql_tpu.runtime.lowering import CompiledDeviceQuery

    return CompiledDeviceQuery(
        plan, registry, capacity=capacity, store_capacity=store_capacity,
        analyze_only=not deep,
        sliced=sliced, slice_ring_max=slice_ring_max,
    )


def _windowing_of(c) -> Optional[str]:
    """The probe's windowing-shape classification (see BackendDecision)."""
    if getattr(c, "sliced", False):
        return (
            f"sliced (width={c.slice_width}ms, ring={c.slice_ring}, "
            f"k={c.hop_k})"
        )
    wf = getattr(c, "windowing_fallback", None)
    if wf:
        return f"expansion (k={getattr(c, 'hop_k', 1)}): {wf}"
    return None


def classify_plan(
    plan: st.QueryPlan,
    registry,
    backend: str = "device",
    per_record: bool = False,
    capacity: int = 8192,
    store_capacity: int = 1 << 17,
    deep: bool = False,
    sliced: Optional[bool] = None,
    slice_ring_max: int = 512,
) -> BackendDecision:
    """Replay the engine's fallback ladder statically.

    Mirrors engine._build_executor rung for rung: (1) under
    ``backend=distributed``, the DistributedDeviceExecutor plan rejects
    (per-record cadence, fk/self joins, tt/fk joins, EMIT FINAL, n-way
    chains, table transforms) then the lowering probe then the
    DistributedDeviceQuery gaps (EARLIEST/LATEST arrival sequencing);
    (2) the single-device lowering probe; (3) the row oracle, which runs
    everything.  ``device-only`` probes like ``device`` but a failed probe
    classifies as ``rejected (device-only)`` — the runtime raises
    KsqlException there instead of degrading to the oracle."""
    from ksql_tpu.compiler.jax_expr import DeviceUnsupported
    from ksql_tpu.runtime.device_executor import (
        _is_suppress,
        _needs_per_record,
        _reject_undistributable_plan,
    )

    backend = (backend or "device").lower()
    reasons: List[Tuple[str, str]] = []
    if backend == "oracle":
        return BackendDecision("oracle", (("configured", "ksql.runtime.backend=oracle"),))

    probe = None
    probe_err: Optional[Exception] = None

    def get_probe():
        nonlocal probe, probe_err
        if probe is None and probe_err is None:
            try:
                probe = _device_probe(plan, registry, capacity,
                                      store_capacity, deep,
                                      sliced=sliced,
                                      slice_ring_max=slice_ring_max)
            except Exception as e:  # noqa: BLE001 — classification datum
                probe_err = e
        return probe

    if backend == "distributed":
        try:
            # same order as DistributedDeviceExecutor.__init__
            if per_record:
                raise DeviceUnsupported(
                    "per-record emission cadence is not distributed "
                    "(micro-batch lanes are the unit of mesh parallelism); "
                    "run single-device"
                )
            if _needs_per_record(plan):
                raise DeviceUnsupported(
                    "plan requires per-record stepping (fk join / self "
                    "join); not distributed — run single-device"
                )
            _reject_undistributable_plan(plan)
            c = get_probe()
            if c is None:
                raise probe_err  # type: ignore[misc]
            # DistributedDeviceQuery constructor gaps not already covered
            # by the plan-level rejects
            if getattr(c, "_needs_seq", False):
                raise DeviceUnsupported(
                    "distributed EARLIEST/LATEST pending (needs a global "
                    "arrival sequence across shards); run them single-device"
                )
            notes: Tuple[str, ...] = ()
            try:
                # the ONE wording, shared with the engine constant — the
                # mesh-aware lane split keeps the C++ tier engaged on the
                # mesh, so EXPLAIN now surfaces engagement rather than the
                # historical bypass (lazy import: no module-level cycle)
                from ksql_tpu.engine.engine import (
                    NATIVE_INGEST_ENGAGED_NOTE,
                )
                from ksql_tpu.runtime.device_executor import (
                    native_ingest_fields,
                )

                if native_ingest_fields(c) is not None:
                    notes = (NATIVE_INGEST_ENGAGED_NOTE,)
            except Exception:  # noqa: BLE001 — a probe without a layout
                pass  # (analyze-only edge) just omits the note
            return BackendDecision("distributed", (),
                                   windowing=_windowing_of(c),
                                   notes=notes)
        except DeviceUnsupported as e:
            reasons.append(("distributed", str(e)))
        except Exception as e:  # noqa: BLE001 — engine degrades to rung 2
            reasons.append(("distributed", f"construction failed: {e}"))

    c = get_probe()
    if c is not None:
        # DeviceExecutor-level reject the lowering probe cannot see: a
        # same-topic (self) join normally runs per-record (capacity 1),
        # but EMIT FINAL forces batched mode, and batched self-joins
        # break record-interleaved side semantics (device_executor.py).
        # Mirror the runtime condition exactly: the executor constructs
        # its device with capacity 1 when per-record (suppress excepted)
        # and rejects only when that effective capacity exceeds 1
        per_record_eff = per_record or _needs_per_record(plan)
        eff_capacity = (
            1 if (per_record_eff and not _is_suppress(plan)) else capacity
        )
        if (
            getattr(c, "right_source", None) is not None
            and getattr(c, "source", None) is not None
            and c.right_source.topic == c.source.topic
            and eff_capacity > 1
        ):
            reasons.append(("device", "batched self-join on device"))
            if backend == "device-only":
                # same contract as the probe-failure path below: the
                # runtime escalates to KsqlException, it never degrades
                return BackendDecision(
                    "rejected (device-only)", tuple(reasons)
                )
            return BackendDecision("oracle", tuple(reasons))
        return BackendDecision("device", tuple(reasons),
                               windowing=_windowing_of(c))
    if isinstance(probe_err, DeviceUnsupported):
        reasons.append(("device", str(probe_err)))
    else:
        reasons.append(("device", f"construction failed: {probe_err}"))
    if backend == "device-only":
        # the runtime raises KsqlException here instead of degrading, so
        # advertising "oracle" would promise a backend the statement can
        # never run on
        return BackendDecision("rejected (device-only)", tuple(reasons))
    return BackendDecision("oracle", tuple(reasons))
