"""Donated-buffer aliasing detector — the PR-2 memory-corruption class.

The bug this rule exists for: ``jnp.asarray(host_numpy_array)`` on the CPU
backend returns a ZERO-COPY view over the numpy buffer.  If that view is
stored into the state pytree that a ``jax.jit(..., donate_argnums=...)``
step later consumes, XLA treats the buffer as donated scratch and recycles
memory numpy (or pickle, or a rebuild temp) still owns — intermittent
SIGSEGV/SIGABRT far from the cause (see ROADMAP "environment hazard":
this masqueraded as platform flakiness for two PRs).

Detection is a per-function forward dataflow over a three-value taint
lattice (HOST > UNKNOWN > SAFE):

* taint sources (HOST — a live numpy host buffer): any ``np.*`` /
  ``numpy.*`` call, ``jax.device_get(...)``, element reads / methods /
  arithmetic over HOST values, ``jnp.asarray(HOST)`` (zero-copy keeps the
  alias), comprehensions iterating HOST containers;
* sanitizers (SAFE — a fresh device buffer): ``jnp.array`` and every other
  ``jnp.*`` constructor/op, ``jax.device_put``;
* sinks (donated state): stores into ``*.state`` / ``*._state`` attributes
  or into local names aliasing them, and arguments in donated positions of
  callables wrapped by ``jax.jit(..., donate_argnums=...)`` in the module.

Calls are resolved through interprocedural summaries (PR 8): every
function in the linted program gets ``(returns taint, param->return
dependence, param->sink set)`` computed in two global passes, so
``dev.state = _unflatten_state(...)`` is judged by what
``_unflatten_state`` actually builds, ``tree_map(lambda v: ..., x)`` by
the lambda body, ``helper(np_buf)`` is flagged AT THE CALL SITE when the
helper (transitively, to the two-pass depth) stores its parameter into
donated state — and all of it follows imports across modules (store
grow/rebuild -> lowering, checkpoint restore -> executor, family
``attach_member`` re-gcd), the handoffs ROADMAP used to say to audit by
hand.  ``DonatedAliasingRule(interprocedural=False)`` is the frozen PR-6
per-function pass, kept so tests can pin that its result is a subset of
the whole-program result.  Unknown stays unflagged: resolution failures
cost recall, never precision.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from ksql_tpu.analysis.lint import Finding, LintModule, Rule, call_name, dotted_name

SAFE, UNKNOWN, HOST = 0, 1, 2

_NP_ROOTS = {"np", "numpy"}
_JNP_ROOTS = {"jnp"}
#: state-pytree attribute names treated as donated roots repo-wide: the
#: compiled query's ``state``/``_state`` is THE donated jit argument
#: (lowering._compile_steps), including when another module reaches it
#: through ``dev.state`` / ``dist.c.state``
_STATE_ATTRS = {"state", "_state"}
_TREE_MAP = {
    "jax.tree_map", "jtu.tree_map", "jax.tree_util.tree_map", "jax.tree.map",
    "tree_map",
}
_DEVICE_GET = {"jax.device_get"}
_SANITIZERS = {"jax.device_put"}
#: calls that hand back host-owned buffers (the checkpoint-restore source)
_HOST_SOURCES = {"pickle.load", "pickle.loads", "np.load", "numpy.load"}


@dataclasses.dataclass(frozen=True)
class Summary:
    """Interprocedural taint summary of one function.

    ``base``: return taint with every parameter UNKNOWN.  ``param_dep``:
    a HOST argument at the call site makes the return HOST (the
    returns-asarray-of-its-argument shape).  ``sink_params``: positions in
    the def's parameter list (``self`` included in the numbering) whose
    HOST-ness reaches a donated-state sink inside the callee — directly or
    through further calls, to the two-global-pass depth.  ``has_self``
    lets call sites shift receiver-call arguments into parameter
    positions."""

    base: int
    param_dep: bool
    sink_params: frozenset = frozenset()
    has_self: bool = False


def _is_np_call(name: str) -> bool:
    root = name.split(".", 1)[0]
    return root in _NP_ROOTS


def _is_jnp(name: str) -> bool:
    return name.split(".", 1)[0] in _JNP_ROOTS or name.startswith("jax.numpy.")


class _DonatedCallables:
    """Module scan: names/attributes bound to jax.jit(..., donate_argnums=ns)
    with a non-empty ns, and the donated positions."""

    def __init__(self, module: LintModule):
        #: callee key ("self._step", "_step", ...) -> donated positions
        self.donated: Dict[str, Set[int]] = {}
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            for target, value in self._jit_bindings(node):
                positions = self._donated_positions(value)
                if positions:
                    self.donated[target] = positions

    @staticmethod
    def _jit_calls(value: ast.AST) -> List[ast.Call]:
        calls = []
        for n in ast.walk(value):
            if isinstance(n, ast.Call) and call_name(n) in ("jax.jit", "jit"):
                calls.append(n)
        return calls

    def _jit_bindings(self, assign: ast.Assign) -> List[Tuple[str, ast.Call]]:
        out: List[Tuple[str, ast.Call]] = []
        for target in assign.targets:
            key = dotted_name(target)
            if key is None:
                continue
            # direct binding, or a dict of jitted steps ({...: jax.jit(...)})
            for call in self._jit_calls(assign.value):
                out.append((key, call))
        return out

    @staticmethod
    def _donated_positions(call: ast.Call) -> Set[int]:
        for kw in call.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            if isinstance(v, ast.Tuple):
                if not v.elts:
                    return set()
                out = set()
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, int):
                        out.add(e.value)
                    else:
                        return {0}  # non-literal element: assume position 0
                return out
            # non-literal (e.g. `() if session else (0,)`): conservatively
            # treat as donating position 0 — matches every use in-tree
            return {0}
        return set()


class _FunctionAnalysis:
    """Forward taint pass over one function body.

    ``summaries`` maps a module-local function name to its
    :class:`Summary`; ``global_lookup`` (interprocedural mode) resolves
    any other call name — imports, module aliases, unique methods —
    to a summary from anywhere in the program.  ``param_taints`` pins
    individual parameters (the per-param sink-discovery runs);
    ``param_taint`` is the uniform default."""

    def __init__(self, rule: "DonatedAliasingRule", module: LintModule,
                 fn: ast.FunctionDef, donated: _DonatedCallables,
                 summaries: Dict[str, Summary],
                 param_taint: int = UNKNOWN,
                 global_lookup: Optional[
                     Callable[[str], Optional[Summary]]] = None,
                 param_taints: Optional[Dict[str, int]] = None):
        self.rule = rule
        self.module = module
        self.fn = fn
        self.donated = donated
        self.summaries = summaries
        self.global_lookup = global_lookup
        self.param_taints = param_taints
        self.param_taint = param_taint
        self.env: Dict[str, int] = {}
        self.findings: List[Finding] = []
        self.return_taint = SAFE
        # names aliasing donated state: assigned FROM a state attribute, or
        # (anywhere in the function) assigned INTO one — stores into their
        # elements are sink stores.  Cached on the node: the same function
        # is analyzed many times (summary passes, per-param runs, check)
        aliases = getattr(fn, "_graftlint_state_aliases", None)
        if aliases is None:
            aliases = fn._graftlint_state_aliases = (
                self._collect_state_aliases()
            )
        self.state_aliases: Set[str] = aliases

    # ----------------------------------------------------------- pre-pass
    def _is_state_attr(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Attribute) and node.attr in _STATE_ATTRS

    def _collect_state_aliases(self) -> Set[str]:
        aliases: Set[str] = set()
        for node in ast.walk(self.fn):
            if not isinstance(node, ast.Assign):
                continue
            value, targets = node.value, node.targets
            # x = self.state / x = dict(self.state)
            src = value
            if isinstance(src, ast.Call) and call_name(src) == "dict" and src.args:
                src = src.args[0]
            if self._is_state_attr(src):
                for t in targets:
                    if isinstance(t, ast.Name):
                        aliases.add(t.id)
            # self.state = x  → x's element stores are sink stores
            for t in targets:
                if self._is_state_attr(t) and isinstance(value, ast.Name):
                    aliases.add(value.id)
        # a parameter named "state" is the donated pytree in step helpers
        for arg in self.fn.args.args:
            if arg.arg in ("state", "new_state"):
                aliases.add(arg.arg)
        return aliases

    # ------------------------------------------------------------- lattice
    def taint_of(self, node: ast.AST) -> int:
        if isinstance(node, ast.Constant):
            return SAFE
        if isinstance(node, ast.Name):
            return self.env.get(node.id, UNKNOWN)
        if isinstance(node, ast.Call):
            return self._taint_call(node)
        if isinstance(node, ast.Subscript):
            return self.taint_of(node.value)
        if isinstance(node, ast.Attribute):
            # .T / .flat are numpy views; other attribute reads (.size,
            # .dtype, object fields) lose arrayness
            if node.attr in ("T", "flat"):
                return self.taint_of(node.value)
            return UNKNOWN
        if isinstance(node, (ast.BinOp, ast.BoolOp, ast.UnaryOp, ast.Compare)):
            return max(
                (self.taint_of(c) for c in ast.iter_child_nodes(node)
                 if isinstance(c, ast.expr)),
                default=SAFE,
            )
        if isinstance(node, ast.IfExp):
            return max(self.taint_of(node.body), self.taint_of(node.orelse))
        if isinstance(node, (ast.Dict,)):
            return max((self.taint_of(v) for v in node.values if v is not None),
                       default=SAFE)
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            return max((self.taint_of(v) for v in node.elts), default=SAFE)
        if isinstance(node, (ast.DictComp, ast.ListComp, ast.SetComp,
                             ast.GeneratorExp)):
            return self._taint_comp(node)
        if isinstance(node, ast.Starred):
            return self.taint_of(node.value)
        return UNKNOWN

    def _bind_comp_targets(self, comp: ast.comprehension, taint: int) -> None:
        for t in ast.walk(comp.target):
            if isinstance(t, ast.Name):
                self.env[t.id] = taint

    def _taint_comp(self, node: ast.AST) -> int:
        saved = dict(self.env)
        try:
            for comp in node.generators:
                src = self.taint_of(comp.iter)
                # iterating a HOST container (old.items(), zip(host, ...))
                # yields HOST elements
                self._bind_comp_targets(comp, src)
            if isinstance(node, ast.DictComp):
                # keys are hashables (strings), never stored buffers
                return self.taint_of(node.value)
            return self.taint_of(node.elt)  # type: ignore[attr-defined]
        finally:
            self.env = saved

    def _taint_call(self, node: ast.Call) -> int:
        name = call_name(node)
        if name is None:
            # method call on an expression; fall through to receiver below
            if isinstance(node.func, ast.Attribute):
                return self.taint_of(node.func.value)
            return UNKNOWN
        if name in _DEVICE_GET or name in _HOST_SOURCES:
            return HOST
        if name in _SANITIZERS:
            return SAFE
        if _is_np_call(name):
            return HOST
        if name == "jnp.asarray" or name == "jax.numpy.asarray":
            # zero-copy: the alias survives
            return self.taint_of(node.args[0]) if node.args else UNKNOWN
        if _is_jnp(name):
            return SAFE  # jnp.array / jnp.zeros / jnp ops build device values
        if name in _TREE_MAP and node.args:
            return self._taint_tree_map(node)
        if name == "dict" and node.args:
            return self.taint_of(node.args[0])
        if name in ("list", "tuple", "sorted", "reversed") and node.args:
            return self.taint_of(node.args[0])
        summary = self._local_summary(name)
        if summary is not None:
            if summary.param_dep and any(
                self.taint_of(a) == HOST for a in node.args
            ):
                return HOST
            return summary.base
        # method calls on a tainted receiver keep the taint (.astype, .copy,
        # .reshape, ... return numpy when the receiver is numpy)
        if isinstance(node.func, ast.Attribute):
            recv = self.taint_of(node.func.value)
            if recv == HOST:
                return HOST
        # interprocedural: imports / module aliases / unique methods —
        # consulted LAST so the per-function results above are preserved
        # verbatim (whole-program findings are a superset by construction)
        if self.global_lookup is not None:
            summary = self.global_lookup(name)
            if summary is not None:
                if summary.param_dep and any(
                    self.taint_of(a) == HOST for a in node.args
                ):
                    return HOST
                return summary.base
        return UNKNOWN

    def _local_summary(self, name: str) -> Optional[Summary]:
        if "." not in name and name in self.summaries:
            return self.summaries[name]
        if name.startswith("self.") and name.split(".", 1)[1] in self.summaries:
            return self.summaries[name.split(".", 1)[1]]
        return None

    def _taint_tree_map(self, node: ast.Call) -> int:
        f = node.args[0]
        operand = max((self.taint_of(a) for a in node.args[1:]), default=UNKNOWN)
        if isinstance(f, ast.Lambda):
            saved = dict(self.env)
            try:
                for a in f.args.args:
                    self.env[a.arg] = operand if operand == HOST else UNKNOWN
                return self.taint_of(f.body)
            finally:
                self.env = saved
        fname = dotted_name(f)
        if fname in ("jnp.asarray", "jax.numpy.asarray"):
            return operand
        if fname and (_is_jnp(fname) or fname in _SANITIZERS):
            return SAFE
        return UNKNOWN

    # --------------------------------------------------------------- walk
    def run(self) -> None:
        for arg in self.fn.args.args:
            if arg.arg != "self":
                if self.param_taints is not None:
                    self.env.setdefault(
                        arg.arg, self.param_taints.get(arg.arg, UNKNOWN)
                    )
                else:
                    self.env.setdefault(arg.arg, self.param_taint)
        self._walk(self.fn.body)

    def _walk(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign):
            self._check_calls(stmt.value)
            taint = self.taint_of(stmt.value)
            for target in stmt.targets:
                self._store(target, taint, stmt)
            return
        if isinstance(stmt, ast.AugAssign):
            self._check_calls(stmt.value)
            self._store(stmt.target, self.taint_of(stmt.value), stmt)
            return
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._check_calls(stmt.value)
            self._store(stmt.target, self.taint_of(stmt.value), stmt)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._check_calls(stmt.value)
                self.return_taint = max(self.return_taint,
                                        self.taint_of(stmt.value))
            return
        if isinstance(stmt, ast.Expr):
            self._check_calls(stmt.value)
            return
        if isinstance(stmt, (ast.If,)):
            self._check_calls(stmt.test)
            before = dict(self.env)
            self._walk(stmt.body)
            env_then = self.env
            self.env = before
            self._walk(stmt.orelse)
            for k, v in env_then.items():
                self.env[k] = max(self.env.get(k, SAFE), v)
            return
        if isinstance(stmt, (ast.For, ast.While)):
            if isinstance(stmt, ast.For):
                self._bind_for_target(stmt)
            # two passes so taint introduced late in the body reaches
            # earlier statements on the notional next iteration
            self._walk(stmt.body)
            if isinstance(stmt, ast.For):
                self._bind_for_target(stmt)
            self._walk(stmt.body)
            self._walk(stmt.orelse)
            return
        if isinstance(stmt, (ast.With,)):
            for item in stmt.items:
                self._check_calls(item.context_expr)
            self._walk(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self._walk(stmt.body)
            for h in stmt.handlers:
                self._walk(h.body)
            self._walk(stmt.orelse)
            self._walk(stmt.finalbody)
            return
        # nested defs analyzed separately; everything else: scan its calls
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call):
                    self._check_donated_call(n)

    def _bind_for_target(self, stmt: ast.For) -> None:
        src = self.taint_of(stmt.iter)
        for t in ast.walk(stmt.target):
            if isinstance(t, ast.Name):
                self.env[t.id] = src

    def _check_calls(self, expr: ast.expr) -> None:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                self._check_donated_call(n)

    # -------------------------------------------------------------- sinks
    def _store(self, target: ast.AST, taint: int, stmt: ast.stmt) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._store(e, UNKNOWN if taint != HOST else HOST, stmt)
            return
        if isinstance(target, ast.Name):
            self.env[target.id] = taint
            if taint == HOST and target.id in self.state_aliases:
                # the alias itself becomes host-backed wholesale
                self._flag(stmt, target.id)
            return
        sink = False
        if isinstance(target, ast.Attribute) and target.attr in _STATE_ATTRS:
            sink = True
        if isinstance(target, ast.Subscript):
            base = target.value
            if isinstance(base, ast.Name):
                if base.id in self.state_aliases:
                    sink = True
                # an element store raises the container's own taint (a dict
                # holding one host buffer is host-tainted when returned)
                self.env[base.id] = max(self.env.get(base.id, UNKNOWN), taint)
            if isinstance(base, ast.Attribute) and base.attr in _STATE_ATTRS:
                sink = True
        if sink and taint == HOST:
            self._flag(stmt, ast.unparse(target) if hasattr(ast, "unparse")
                       else "state")

    def _check_donated_call(self, node: ast.Call) -> None:
        name = call_name(node)
        if name is not None:
            self._check_sink_call(node, name)
        key = None
        if name is not None and name in self.donated.donated:
            key = name
        elif isinstance(node.func, ast.Subscript):
            # self._table_steps[idx](state, ...)
            base = dotted_name(node.func.value)
            if base in self.donated.donated:
                key = base
        if key is None:
            return
        for pos in self.donated.donated[key]:
            if pos < len(node.args) and self.taint_of(node.args[pos]) == HOST:
                self.findings.append(Finding(
                    rule=DonatedAliasingRule.name,
                    path=self.module.path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"numpy host buffer passed at donated position {pos} "
                        f"of jitted '{key}' — XLA will recycle memory the "
                        "host still owns; copy with jnp.array first"
                    ),
                ))

    def _check_sink_call(self, node: ast.Call, name: str) -> None:
        """Call-site check against the callee's param->sink summary: a
        HOST argument whose parameter reaches donated state inside the
        callee is the cross-function aliasing handoff the per-function
        pass provably missed (taint died at this boundary)."""
        summary = self._local_summary(name)
        if (summary is None or not summary.sink_params) \
                and self.global_lookup is not None:
            resolved = self.global_lookup(name)
            if resolved is not None and resolved.sink_params:
                summary = resolved
        if summary is None or not summary.sink_params:
            return
        # receiver calls (obj.m / self.m) drop the self slot from the
        # argument numbering
        shift = (
            1 if summary.has_self and isinstance(node.func, ast.Attribute)
            else 0
        )
        for pos in sorted(summary.sink_params):
            ai = pos - shift
            if 0 <= ai < len(node.args) \
                    and self.taint_of(node.args[ai]) == HOST:
                self.findings.append(Finding(
                    rule=DonatedAliasingRule.name,
                    path=self.module.path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"numpy host buffer passed to '{name}' reaches "
                        "donated jit state inside it (interprocedural "
                        f"taint, parameter #{pos}) — copy with jnp.array "
                        "before the handoff"
                    ),
                ))

    def _flag(self, stmt: ast.stmt, target: str) -> None:
        self.findings.append(Finding(
            rule=DonatedAliasingRule.name,
            path=self.module.path,
            line=stmt.lineno,
            col=stmt.col_offset,
            message=(
                f"numpy host buffer stored into donated jit state "
                f"('{target}') via a zero-copy path — use jnp.array (copy), "
                "not jnp.asarray: XLA donation recycles memory the host "
                "still owns (the PR-2 corruption class)"
            ),
        ))


class DonatedAliasingRule(Rule):
    name = "donated-aliasing"
    doc = ("numpy buffers must not zero-copy alias into jit state that a "
           "donate_argnums step consumes (use jnp.array copies) — tracked "
           "interprocedurally across helper chains and modules")

    #: fixpoint bound for the global summary passes — deep enough for any
    #: real helper chain, finite under mutual recursion
    MAX_PASSES = 6

    def __init__(self, interprocedural: bool = True):
        #: False = the frozen PR-6 per-function pass (module-local
        #: returns-taint only); tests pin that its findings are a subset
        #: of the whole-program pass
        self.interprocedural = interprocedural
        #: (module path, name) -> (target path, name), or None — injected
        #: by prepare() (in-process Program) or prime() (--jobs workers)
        self._resolver = None
        self._donated: Dict[str, _DonatedCallables] = {}
        self._summaries: Dict[Tuple[str, str], Summary] = {}
        #: per-module view of the same table, so the fixpoint passes and
        #: check() never rescan the whole flat dict per module
        self._by_module: Dict[str, Dict[str, Summary]] = {}
        self._prepared_paths: Set[str] = set()

    # ------------------------------------------------ program-level pass
    def prepare(self, program) -> None:
        if not self.interprocedural:
            return
        self._resolver = program.resolve_call
        self._donated = {}
        self._summaries = {}
        self._by_module = {}
        self._prepared_paths = {m.path for m in program.modules}
        # global passes to a bounded fixpoint: pass 1 summarizes every
        # function with the (partially empty) table; further passes
        # re-summarize with every callee visible and stop as soon as the
        # table is stable, so helper-chain depth does not depend on file
        # order (a<-b<-c<-d with the caller summarized first still
        # converges).  MAX_PASSES bounds pathological mutual recursion.
        for _pass in range(self.MAX_PASSES):
            before = dict(self._summaries)
            for m in program.modules:
                self.summarize_module(m)
            if _pass >= 1 and self._summaries == before:
                break

    def prime(self, resolver, summaries: Dict[Tuple[str, str], Summary],
              paths) -> None:
        """--jobs worker entry: adopt a merged cross-chunk summary table
        and a :class:`~ksql_tpu.analysis.program.ResolverTables`-backed
        resolver instead of running prepare() over a full Program."""
        self._resolver = resolver
        self._summaries = dict(summaries)
        self._by_module = {}
        for (path, name), s in self._summaries.items():
            self._by_module.setdefault(path, {})[name] = s
        self._prepared_paths = set(paths)

    def summarize_module(
        self, module: LintModule
    ) -> Dict[Tuple[str, str], Summary]:
        """One summary pass over one module against the CURRENT global
        table; updates and returns the module's slice.  --jobs workers
        call this directly (pass 1 chunk-local, pass 2 with the merged
        table primed)."""
        donated = self._donated.get(module.path)
        if donated is None:
            donated = self._donated[module.path] = _DonatedCallables(module)
        local = self._module_summaries(module)
        lookup = self._global_lookup(module)
        out: Dict[Tuple[str, str], Summary] = {}
        for fn in module.functions():
            s = self._summarize(module, fn, donated, local, lookup)
            self._summaries[(module.path, fn.name)] = s
            out[(module.path, fn.name)] = s
            local[fn.name] = s  # visible to later fns this pass (local
            # IS the _by_module entry, so this also updates the index)
        return out

    def _global_lookup(self, module: LintModule):
        if self._resolver is None:
            return None
        resolver = self._resolver

        def lookup(name: str) -> Optional[Summary]:
            ref = resolver(module.path, name)
            return self._summaries.get(ref) if ref is not None else None

        return lookup

    def _module_summaries(self, module: LintModule) -> Dict[str, Summary]:
        return self._by_module.setdefault(module.path, {})

    def _summarize(self, module: LintModule, fn: ast.FunctionDef,
                   donated: _DonatedCallables, local: Dict[str, Summary],
                   lookup) -> Summary:
        # a function with no value-returning `return` has SAFE return
        # taint by construction: skip the base run entirely (about half
        # the tree is procedures — this halves the summary pass).  The
        # worst run still executes: it doubles as the sink detector.
        returns_value = getattr(fn, "_graftlint_returns_value", None)
        if returns_value is None:
            returns_value = fn._graftlint_returns_value = any(
                isinstance(n, ast.Return) and n.value is not None
                for n in ast.walk(fn)
            )
        base_fa = None
        if returns_value:
            base_fa = _FunctionAnalysis(self, module, fn, donated, local,
                                        global_lookup=lookup)
            base_fa.run()
            base = base_fa.return_taint
        else:
            base = SAFE
        worst = _FunctionAnalysis(self, module, fn, donated, local,
                                  param_taint=HOST, global_lookup=lookup)
        worst.run()

        def live_keys(fa) -> Set[Tuple[int, int, str]]:
            # suppression-filtered: a justified-disabled internal finding
            # must not poison the summary
            return {
                (f.line, f.col, f.message) for f in fa.findings
                if not module.disabled(f.rule, f.line)
            }

        sink_params: Set[int] = set()
        worst_keys = live_keys(worst)
        if worst_keys:
            # attribution must be DIFFERENTIAL: findings the function
            # produces with every parameter UNKNOWN are param-independent
            # (an internal host store) and must not mark any parameter as
            # a sink — only findings that APPEAR when a parameter turns
            # HOST attribute to it
            if base_fa is None:
                base_fa = _FunctionAnalysis(self, module, fn, donated,
                                            local, global_lookup=lookup)
                base_fa.run()
            baseline = live_keys(base_fa)
            if worst_keys - baseline:
                for i, arg in enumerate(fn.args.args):
                    if arg.arg in ("self", "cls"):
                        continue
                    fa = _FunctionAnalysis(
                        self, module, fn, donated, local,
                        global_lookup=lookup,
                        param_taints={arg.arg: HOST},
                    )
                    fa.run()
                    if live_keys(fa) - baseline:
                        sink_params.add(i)
        has_self = bool(fn.args.args) and fn.args.args[0].arg in (
            "self", "cls"
        )
        return Summary(
            base=base,
            param_dep=worst.return_taint == HOST and base != HOST,
            sink_params=frozenset(sink_params),
            has_self=has_self,
        )

    # ------------------------------------------------------ per-module
    def check(self, module: LintModule) -> Iterable[Finding]:
        fns = module.functions()
        if self.interprocedural and self._resolver is not None \
                and module.path in self._prepared_paths:
            donated = self._donated.get(module.path)
            if donated is None:
                donated = self._donated[module.path] = _DonatedCallables(
                    module
                )
            summaries = self._module_summaries(module)
            lookup = self._global_lookup(module)
        else:
            # the PR-6 per-function pass: module-local returns-taint
            # summaries (no param->sink, no cross-module), two passes so
            # call-before-def and simple chains settle
            donated = _DonatedCallables(module)
            summaries = {}
            lookup = None
            for _ in range(2):
                for fn in fns:
                    fa = _FunctionAnalysis(self, module, fn, donated,
                                           summaries)
                    fa.run()
                    base = fa.return_taint
                    worst_fa = _FunctionAnalysis(self, module, fn, donated,
                                                 summaries, param_taint=HOST)
                    worst_fa.run()
                    summaries[fn.name] = Summary(
                        base=base,
                        param_dep=worst_fa.return_taint == HOST
                        and base != HOST,
                    )
        findings: List[Finding] = []
        for fn in fns:
            fa = _FunctionAnalysis(self, module, fn, donated, summaries,
                                   global_lookup=lookup)
            fa.run()
            findings.extend(fa.findings)
        # deduplicate (loops walk bodies twice)
        seen: Set[Tuple[int, int, str]] = set()
        out = []
        for f in findings:
            k = (f.line, f.col, f.message)
            if k not in seen:
                seen.add(k)
                out.append(f)
        return out
