"""--jobs worker functions for scripts/lint.py (module-level: picklable).

The interprocedural pass cannot simply shard files across processes —
cross-module taint needs every module's summaries.  The scheme here keeps
the workers independent while still converging to the same result as the
in-process two-pass prepare:

1. **pass 1** (parallel): each worker parses its chunk and summarizes it
   with CHUNK-LOCAL resolution, returning the picklable resolution
   metadata (:func:`~ksql_tpu.analysis.program.module_meta`) and summary
   slice.
2. The parent merges all metadata + summaries into one
   :class:`~ksql_tpu.analysis.program.ResolverTables` input.
3. **pass 2** (parallel, iterated): workers re-summarize their chunk
   against the MERGED table; the parent repeats the pass until the table
   is stable (bounded by ``DonatedAliasingRule.MAX_PASSES``), so a taint
   chain whose hops live in different chunks propagates one hop per
   merged pass — converging to the same fixpoint as the serial path.
4. **check** (parallel): workers run every requested rule per module with
   the aliasing rule primed on the final table, returning findings.

Each worker process caches its parsed modules, so the three phases parse
each file once per process (ProcessPoolExecutor reuses workers)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

#: per-worker-process parse cache: path -> LintModule
_CACHE: Dict[str, object] = {}


def _modules(paths: Sequence[str]) -> List:
    from ksql_tpu.analysis.lint import LintModule

    out = []
    for p in paths:
        m = _CACHE.get(p)
        if m is None:
            with open(p, encoding="utf-8") as f:
                m = LintModule(p, f.read())
            _CACHE[p] = m
        out.append(m)
    return out


def _primed_aliasing(meta_all: Dict, summaries: Dict):
    from ksql_tpu.analysis.program import ResolverTables
    from ksql_tpu.analysis.rules_aliasing import DonatedAliasingRule

    rule = DonatedAliasingRule()
    tables = ResolverTables(meta_all)
    rule.prime(tables.resolve, summaries, set(meta_all))
    return rule


def summarize_pass1(paths: Sequence[str]) -> Tuple[Dict, Dict]:
    """Chunk-local summaries + resolution metadata."""
    from ksql_tpu.analysis.program import module_meta

    mods = _modules(paths)
    meta = {m.path: module_meta(m) for m in mods}
    rule = _primed_aliasing(meta, {})
    for _ in range(2):
        for m in mods:
            rule.summarize_module(m)
    return meta, rule._summaries


def summarize_pass2(paths: Sequence[str], meta_all: Dict,
                    summaries: Dict) -> Dict:
    """Re-summarize the chunk against the merged global table."""
    mods = _modules(paths)
    rule = _primed_aliasing(meta_all, summaries)
    out: Dict = {}
    for m in mods:
        out.update(rule.summarize_module(m))
    return out


def check_chunk(paths: Sequence[str], meta_all: Dict, summaries: Dict,
                rule_names: Optional[Sequence[str]]) -> List:
    """Run the requested rules over the chunk's modules with the final
    summary table; returns suppression-filtered findings."""
    from ksql_tpu.analysis.lint import Rule, default_rules
    from ksql_tpu.analysis.program import Program
    from ksql_tpu.analysis.rules_aliasing import DonatedAliasingRule

    mods = _modules(paths)
    rules = default_rules()
    if rule_names is not None:
        rules = [r for r in rules if r.name in set(rule_names)]
    chunk_program = None
    for i, r in enumerate(rules):
        if isinstance(r, DonatedAliasingRule):
            # whole-program context arrives via the merged tables, not
            # prepare() — the one rule with a cross-chunk prime path
            rules[i] = _primed_aliasing(meta_all, summaries)
        elif type(r).prepare is not Rule.prepare:
            # honor the Rule.prepare contract for any OTHER prepare-aware
            # rule with a chunk-scoped Program.  NOTE: that context is
            # chunk-local — a future rule needing genuinely cross-module
            # state must grow a prime() path like the aliasing rule, or
            # --jobs would silently diverge from the serial sweep
            if chunk_program is None:
                chunk_program = Program(mods)
            r.prepare(chunk_program)
    out = []
    for m in mods:
        for r in rules:
            for f in r.check(m):
                if not m.disabled(f.rule, f.line):
                    out.append(f)
    return out
