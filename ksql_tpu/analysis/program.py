"""Whole-program context for graftlint — call resolution across modules.

PR 6's rules were per-function with module-local returns-taint summaries:
taint died at every call boundary, so the donated-aliasing rule could not
follow a numpy buffer through a helper chain (``restore -> _unflatten ->
state store``) or a cross-module handoff (checkpoint restore building
arrays that an executor method installs, the family ``attach_member``
re-gcd calling into lowering).  ROADMAP literally instructed debuggers to
"audit the handoff by hand".

:class:`Program` is the shared substrate that upgrades the rules to a
whole-program pass: every linted module parsed together, per-module
import maps, a flat function index, and a bounded call resolver that maps
a dotted call name seen in one module to the function definition it
denotes — possibly in another module.  Rules receive the program once via
:meth:`Rule.prepare` and build their own interprocedural summaries on top
(two global passes, so chains settle to a bounded depth instead of
requiring a fixpoint).

Resolution is deliberately pragmatic, tuned for this tree:

* bare names: local function first, then ``from x import f`` imports;
* ``self.m`` / ``cls.m``: a method named ``m`` in the same module (flat —
  matches the PR-6 summary keying), then a program-wide unique method;
* ``z.f`` where ``z`` aliases an imported module: function ``f`` there;
* ``obj.m`` on an arbitrary receiver: resolved only when exactly ONE
  function named ``m`` exists program-wide (unique-name matching) —
  ambiguity degrades to "unknown", never to a guess.

Unknown stays unflagged everywhere, so resolution failures cost recall,
not precision.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple, TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover — import cycle with lint.py
    from ksql_tpu.analysis.lint import LintModule

#: method names too generic for unique-name fallback resolution even when
#: a single definition exists in the linted set (stdlib/numpy methods of
#: the same name would be misattributed to it)
_GENERIC_NAMES = {
    "get", "put", "add", "pop", "run", "read", "write", "close", "open",
    "send", "recv", "poll", "process", "update", "append", "clear",
    "copy", "items", "keys", "values", "format", "join", "split",
}


def module_dotted_name(path: str) -> str:
    """Dotted python name for a source path, walking up while __init__.py
    exists (``.../ksql_tpu/runtime/lowering.py`` -> ``ksql_tpu.runtime.
    lowering``).  A file outside any package is just its stem, which still
    lets single-file fixtures resolve their own locals."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        parent = os.path.dirname(d)
        if parent == d:  # pragma: no cover — filesystem root
            break
        d = parent
    name = ".".join(reversed(parts))
    return name[: -len(".__init__")] if name.endswith(".__init__") else name


class ModuleIndex:
    """Per-module view: flat function table + import maps."""

    def __init__(self, module: "LintModule"):
        self.module = module
        self.dotted = module_dotted_name(module.path)
        #: bare function name -> FIRST definition (flat across classes and
        #: nesting — the same keying the PR-6 module-local summaries used)
        self.functions: Dict[str, ast.FunctionDef] = {}
        for fn in module.functions():
            self.functions.setdefault(fn.name, fn)
        #: local alias -> dotted module name (``import x.y as z``)
        self.module_aliases: Dict[str, str] = {}
        #: local name -> (dotted module, original name) (``from x import f``)
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self._scan_imports()

    def _scan_imports(self) -> None:
        pkg = self.dotted.rsplit(".", 1)[0] if "." in self.dotted else ""
        for node in ast.walk(self.module.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.module_aliases[alias.asname or alias.name] = alias.name
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    # relative import: climb level-1 packages from here
                    anchor = pkg.split(".") if pkg else []
                    anchor = anchor[: len(anchor) - (node.level - 1)]
                    base = ".".join(anchor + ([base] if base else []))
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = (
                        base, alias.name
                    )


class ResolverTables:
    """The call resolver over PLAIN-DICT module metadata.

    Exists so ``--jobs`` workers can resolve cross-module calls without
    holding every parsed AST: the parent merges each worker's
    :func:`module_meta` and ships these picklable tables back.  Program
    (the in-process path) builds the same tables from its ModuleIndexes,
    so there is exactly one resolution algorithm."""

    def __init__(self, meta: Dict[str, Dict[str, object]]):
        #: path -> {"dotted", "functions" (set), "aliases", "from_imports"}
        self.meta = meta
        self.by_dotted: Dict[str, str] = {}
        self.name_index: Dict[str, List[str]] = {}
        for path, m in meta.items():
            self.by_dotted.setdefault(str(m["dotted"]), path)
            for fname in m["functions"]:  # type: ignore[union-attr]
                self.name_index.setdefault(fname, []).append(path)

    def _module_by_dotted(self, dotted: str) -> Optional[str]:
        exact = self.by_dotted.get(dotted)
        if exact is not None:
            return exact
        # unambiguous suffix match: files linted outside their package
        # root (fixtures, ad-hoc paths) carry shorter dotted names than
        # the absolute names their imports use
        cands = [
            path for name, path in self.by_dotted.items()
            if dotted.endswith("." + name) or name.endswith("." + dotted)
        ]
        return cands[0] if len(cands) == 1 else None

    def _functions(self, path: str) -> Set[str]:
        return self.meta[path]["functions"]  # type: ignore[return-value]

    def resolve(self, module_path: str,
                name: str) -> Optional[Tuple[str, str]]:
        """Resolve a dotted call name seen in ``module_path`` to
        ``(target module path, function name)``, or None.  The local
        module's own flat table is consulted first so behavior degrades
        exactly to the PR-6 per-module pass when nothing cross-module
        matches."""
        m = self.meta.get(module_path)
        if m is None:
            return None
        functions: Set[str] = m["functions"]  # type: ignore[assignment]
        aliases: Dict[str, str] = m["aliases"]  # type: ignore[assignment]
        from_imports: Dict[str, Tuple[str, str]] = (
            m["from_imports"]  # type: ignore[assignment]
        )
        parts = name.split(".")
        if len(parts) == 1:
            if name in functions:
                return (module_path, name)
            imp = from_imports.get(name)
            if imp is not None:
                tgt = self._module_by_dotted(imp[0])
                if tgt is not None and imp[1] in self._functions(tgt):
                    return (tgt, imp[1])
            return None
        if parts[0] in ("self", "cls"):
            mm = parts[-1]
            if mm in functions:
                return (module_path, mm)
            return self._resolve_unique(mm)
        # module-alias prefixes, longest first: z.f / z.sub.f / x.y.f
        for i in range(len(parts) - 1, 0, -1):
            prefix = ".".join(parts[:i])
            dotted = aliases.get(prefix)
            if dotted is None and prefix in from_imports:
                base, orig = from_imports[prefix]
                joined = f"{base}.{orig}" if base else orig
                dotted = joined if self._module_by_dotted(joined) else None
            if dotted is None:
                continue
            sub = parts[i:-1]
            tgt = self._module_by_dotted(
                ".".join([dotted] + list(sub)) if sub else dotted
            )
            if tgt is not None and parts[-1] in self._functions(tgt):
                return (tgt, parts[-1])
            return None
        # arbitrary receiver: unique-name fallback
        return self._resolve_unique(parts[-1])

    def _resolve_unique(self, name: str) -> Optional[Tuple[str, str]]:
        if name in _GENERIC_NAMES or name.startswith("__"):
            return None
        cands = self.name_index.get(name, ())
        if len(cands) == 1:
            return (cands[0], name)
        return None


def module_meta(module: "LintModule",
                ix: Optional[ModuleIndex] = None) -> Dict[str, object]:
    """The picklable resolution metadata of one module — the ONE metadata
    shape both Program (in-process) and the --jobs workers feed to
    :class:`ResolverTables`, so the two paths can never diverge."""
    ix = ix if ix is not None else ModuleIndex(module)
    return {
        "dotted": ix.dotted,
        "functions": {fn.name for fn in module.functions()},
        "aliases": dict(ix.module_aliases),
        "from_imports": dict(ix.from_imports),
    }


class Program:
    """All linted modules plus the cross-module call resolver."""

    def __init__(self, modules: Iterable["LintModule"]):
        self.modules: List["LintModule"] = list(modules)
        self.index: Dict[str, ModuleIndex] = {
            m.path: ModuleIndex(m) for m in self.modules
        }
        self.tables = ResolverTables({
            path: module_meta(ix.module, ix)
            for path, ix in self.index.items()
        })
        #: scratch space rules use to stash interprocedural summaries
        self.cache: Dict[str, object] = {}

    def resolve_call(
        self, module_path: str, name: str
    ) -> Optional[Tuple[str, str]]:
        return self.tables.resolve(module_path, name)
