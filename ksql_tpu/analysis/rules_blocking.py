"""blocking-under-lock rule: slow/blocking work inside ``with <lock>:``.

The PR-8 race rule made "mutate shared state under a lock" the blessed
idiom — which quietly invites the opposite failure: the lock body grows a
jit compile, a device transfer, file IO, a ``sleep`` or a chaos
``fault_point``, and every OTHER thread contending on that lock (REST
handlers, push-session polls, the poll loop, heartbeat gossip) stalls
behind one slow holder.  A wedged XLA compile under the engine lock is
the poll-loop freeze PR 8's deadline supervision exists to contain — this
rule keeps new instances from shipping at all.

Mechanics (on the whole-program substrate):

1. **Direct markers** — calls that block or can block: ``*.sleep``,
   ``faults.fault_point``, file IO (``open``, ``os.replace/rename/...``,
   ``shutil.*``, ``pickle/json`` file dump/load, ``tempfile.*``), jit
   compile/abstract tracing (``jax.jit``, ``jax.eval_shape``), and
   device transfers (``jax.device_get`` / ``device_put`` /
   ``.block_until_ready``).
2. **Interprocedural summaries** — :meth:`prepare` summarizes every
   function's direct markers, then propagates them along the Program's
   resolved call edges for a bounded number of global passes (the
   donated-aliasing idiom), so ``with lock: self._flush()`` is flagged
   when ``_flush`` three hops down fsyncs a file — with the chain named.
3. **Lock bodies** — a ``with`` item whose context expression names the
   fence machinery (the race rule's ``*lock*``/``*fence*`` tokens, same
   :func:`~ksql_tpu.analysis.rules_race._is_fence_name` test that makes
   ``with self._lock:`` a valid race guard) is a lock body; every call
   inside it resolving to a blocking marker is a finding.
4. **Entrypoint gating** — the race rule's entrypoint map scopes the
   sweep: only modules with declared concurrency (``threading.Thread``
   spawns or ``# graftlint: entrypoint=`` marks) are checked — a lock in
   a single-threaded script has nobody to starve — and each finding
   names the concurrent entrypoints that reach the holding function.

Suppress a reviewed case with ``# graftlint: disable=blocking-under-lock``
plus a justification (e.g. the lock exists precisely to serialize that
IO and every contender tolerates the latency).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ksql_tpu.analysis.lint import (
    Finding,
    LintModule,
    Rule,
    call_name,
    dotted_name,
)
from ksql_tpu.analysis.rules_race import RaceAnalysis, _is_fence_name

#: bounded interprocedural propagation depth (the aliasing-rule idiom:
#: chains settle within a few global passes instead of a fixpoint)
MAX_PASSES = 3


def _own_nodes(fn: ast.FunctionDef):
    """Nodes executed when ``fn`` itself runs — nested def/lambda/class
    bodies excluded (they are their own summary units; a sleep inside a
    returned closure does not block the caller), matching the check
    phase's _body_calls discipline."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))

_OS_IO = {
    "replace", "rename", "renames", "remove", "unlink", "fsync",
    "makedirs", "mkdir", "rmdir", "truncate", "link", "symlink",
}
_FILE_FNS = {
    "pickle.dump", "pickle.load", "json.dump", "json.load",
    "tempfile.mkstemp", "tempfile.mkdtemp", "tempfile.NamedTemporaryFile",
}
_JIT_FNS = {"jax.jit", "jax.eval_shape", "jax.make_jaxpr"}
_TRANSFER_FNS = {"jax.device_get", "jax.device_put"}


def classify_blocking_call(name: Optional[str]) -> Optional[str]:
    """The blocking kind of a dotted call name, or None."""
    if not name:
        return None
    parts = name.split(".")
    last = parts[-1]
    if last == "sleep":
        return "sleep"
    if last == "fault_point":
        return "fault_point"
    if name == "open" or (len(parts) == 2 and parts[0] == "os"
                          and last in _OS_IO):
        return "file-io"
    if parts[0] == "shutil" or name in _FILE_FNS:
        return "file-io"
    if name in _JIT_FNS:
        return "jit-compile"
    if name in _TRANSFER_FNS or last == "block_until_ready":
        return "device-transfer"
    return None


class BlockingUnderLockRule(Rule):
    name = "blocking-under-lock"
    doc = ("jit compile/dispatch, device transfers, file IO, sleep and "
           "fault_point must not run while holding a lock — move them "
           "outside the lock body or record a reviewed justification")

    def __init__(self) -> None:
        #: (module path, function name) -> {(kind, detail chain)}
        self._summaries: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        self._program = None

    # ------------------------------------------------------ preparation
    def prepare(self, program) -> None:
        self._program = program
        self._summaries = {}
        # pass 0: direct markers per function
        for module in program.modules:
            for fn in module.functions():
                direct = {
                    (kind, "")
                    for kind in self._direct_kinds(module, fn)
                }
                if direct:
                    self._summaries[(module.path, fn.name)] = direct
        # passes 1..N: propagate along resolved call edges; a callee's
        # blocking kind surfaces on the caller with the chain recorded
        for _ in range(MAX_PASSES):
            changed = False
            for module in program.modules:
                for fn in module.functions():
                    for node in _own_nodes(fn):
                        if not isinstance(node, ast.Call):
                            continue
                        cname = call_name(node)
                        if cname is None:
                            continue
                        target = program.resolve_call(module.path, cname)
                        if target is None:
                            continue
                        callee = self._summaries.get(target)
                        if not callee:
                            continue
                        key = (module.path, fn.name)
                        mine = self._summaries.setdefault(key, set())
                        for kind, via in callee:
                            chain = target[1] + (
                                f" -> {via}" if via else ""
                            )
                            entry = (kind, chain)
                            # the chain label keeps only the FIRST hop
                            # per kind to bound summary growth
                            if not any(k == kind for k, _ in mine):
                                mine.add(entry)
                                changed = True
            if not changed:
                break

    @staticmethod
    def _direct_kinds(module: LintModule, fn: ast.FunctionDef) -> Set[str]:
        out: Set[str] = set()
        for node in _own_nodes(fn):
            if isinstance(node, ast.Call):
                kind = classify_blocking_call(call_name(node))
                if kind:
                    out.add(kind)
        return out

    # ------------------------------------------------------------ check
    def check(self, module: LintModule) -> Iterable[Finding]:
        if not any(
            isinstance(n, ast.Call)
            and call_name(n) in ("threading.Thread", "Thread")
            for n in ast.walk(module.tree)
        ) and not module.entrypoint_marks:
            return []  # single-threaded module: nobody to starve
        analysis = RaceAnalysis(module)
        out: List[Finding] = []
        seen: Set[Tuple[int, str]] = set()
        for w in ast.walk(module.tree):
            if not isinstance(w, ast.With):
                continue
            lock_name = self._lock_item(w)
            if lock_name is None:
                continue
            fn = self._enclosing_fn(module, w)
            eps = sorted(
                analysis.fn_entrypoints.get(id(fn), ())
            ) if fn is not None else []
            for node in self._body_calls(w):
                cname = call_name(node)
                hit = self._blocking_of(module.path, cname)
                if hit is None:
                    continue
                kind, chain = hit
                key = (node.lineno, f"{kind}:{cname}")
                if key in seen:
                    continue
                seen.add(key)
                via = f" (via {chain})" if chain else ""
                reach = (
                    f"; lock holder reachable from entrypoints [{', '.join(eps)}]"
                    if eps else ""
                )
                out.append(Finding(
                    rule=self.name,
                    path=module.path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"{kind} call '{cname}'{via} inside 'with "
                        f"{lock_name}:' — every thread contending on the "
                        f"lock stalls behind it{reach}; move it outside "
                        "the lock body or record a reviewed justification "
                        "with '# graftlint: disable=blocking-under-lock'"
                    ),
                ))
        return out

    def _blocking_of(
        self, path: str, cname: Optional[str]
    ) -> Optional[Tuple[str, str]]:
        direct = classify_blocking_call(cname)
        if direct is not None:
            return (direct, "")
        if cname is None or self._program is None:
            return None
        target = self._program.resolve_call(path, cname)
        if target is None:
            return None
        summ = self._summaries.get(target)
        if not summ:
            return None
        # one finding per call site: report the most actionable kind
        # (deterministic order keeps the sweep stable)
        kind, chain = sorted(summ)[0]
        return (kind, target[1] + (f" -> {chain}" if chain else ""))

    @staticmethod
    def _lock_item(w: ast.With) -> Optional[str]:
        for item in w.items:
            expr = item.context_expr
            name = dotted_name(expr)
            if name is None and isinstance(expr, ast.Call):
                name = call_name(expr)
            if name is not None and any(
                _is_fence_name(part) for part in name.split(".")
            ):
                return name
        return None

    @staticmethod
    def _enclosing_fn(module: LintModule,
                      node: ast.AST) -> Optional[ast.FunctionDef]:
        cur = module.parent(node)
        while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            cur = module.parent(cur)
        return cur

    @staticmethod
    def _body_calls(w: ast.With):
        """Call nodes executed inside the with body (nested def/class
        bodies excluded — they run when called, not while holding)."""
        stack: List[ast.AST] = list(w.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))
