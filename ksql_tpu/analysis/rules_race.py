"""Shared-state-race rule: unguarded mutation of two-entrypoint state.

PRs 5–7 piled concurrency machinery onto a codebase whose poll loop used
to be single-threaded: supervised tick workers, ``FamilyMemberExecutor``
delivery (member emissions fire during the PRIMARY's tick), push-session
heal loops (driven from HTTP handler threads), the gossip/heartbeat and
steady-state process loops, REST handlers.  Nothing checked any of it
statically; the PR-5/6 fence idiom (``alive()`` identity test, emit-fence
revocation, ``engine_lock``) is pure discipline.

This rule machine-checks the discipline, per module:

1. **Entrypoint discovery** — every ``threading.Thread(target=...)``
   call names an entrypoint, plus any ``def`` annotated ``# graftlint:
   entrypoint=<label>`` for callback-driven concurrency the syntax can't
   reveal (family delivery, push-session emit paths, HTTP handlers).
   A worker the spawner ``join``s is classified *joined*: it never runs
   concurrently with its spawner except in the deadline-abandonment
   window, whose contract is exactly what ``unfenced-handle-mutation``
   checks — so joined workers appear in the ``--threads`` map but do not
   create race pairs here.  Functions not reachable from any declared
   entrypoint form the implicit ``main`` entrypoint.
2. **Access classification** — per entrypoint, an intra-module call
   graph (bounded depth; ``self.m`` plus annotation-typed receivers:
   ``server: KsqlServer`` resolves ``server.m()`` and keys
   ``server.attr`` as ``KsqlServer.attr``) collects attribute reads and
   mutations.  ``__init__``/``__new__`` bodies are exempt — the object
   is not yet published to another thread.
3. **Race check** — a MUTATION of state reachable from two concurrent
   entrypoints is flagged unless guarded: a positive ``alive()``-test
   branch or dominating bail-out (the rules_fence semantics), an
   enclosing ``with <...lock...>:`` context, or a reviewed single-writer
   claim ``# graftlint: owner=<label>`` naming an entrypoint that can
   actually reach the mutation (a stale owner claim does not suppress).
   Attributes that ARE the synchronization primitive (``*fence*`` /
   ``*token*`` / ``*lock*`` names) are the guard mechanism, not racy
   state.

Scope note: the map is intra-module — an engine attribute mutated by a
REST thread shows up in rest.py's map (where the thread lives), not in
engine.py's.  ``scripts/lint.py --threads`` dumps the per-module maps so
reviewers see the concurrency surface at a glance.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ksql_tpu.analysis.lint import (
    Finding,
    LintModule,
    Rule,
    call_name,
    dotted_name,
)
from ksql_tpu.analysis.rules_fence import (
    _is_bailout,
    _mentions_with_polarity,
)

_MUTATORS = {
    "add", "append", "appendleft", "clear", "discard", "extend", "insert",
    "pop", "popitem", "remove", "setdefault", "update",
}
#: name TOKENS that mark the fence/synchronization machinery — matched
#: against underscore-split words, never raw substrings (`wall_clock` /
#: `blocked` must stay race-checked; `'lock' in 'clock'` would hide them)
_FENCE_ATTR_MARKERS = ("fence", "token", "lock", "locked")


def _is_fence_name(name: str) -> bool:
    return any(
        part in _FENCE_ATTR_MARKERS for part in name.lower().split("_")
    )
#: receiver-attribute names that are per-thread/local by construction
_LOCAL_ATTRS = {"daemon", "name"}
_EXEMPT_FNS = {"__init__", "__new__"}
_MAIN = "main"
_CALLGRAPH_DEPTH = 10


@dataclasses.dataclass
class _Access:
    key: str          # "Class.attr" or "<recv>.attr"
    node: ast.AST     # the access site (mutation: the statement/call)
    fn: ast.FunctionDef
    is_mutation: bool


@dataclasses.dataclass
class Entrypoint:
    label: str
    root: ast.FunctionDef
    line: int
    kind: str  # "thread" | "thread-joined" | "annotated" | "main"
    reachable: Set[int] = dataclasses.field(default_factory=set)


class RaceAnalysis:
    """Entrypoint map + shared-state classification for one module.

    Built once per module; the rule reads :meth:`findings`, the CLI
    ``--threads`` report reads :meth:`report`."""

    def __init__(self, module: LintModule):
        self.module = module
        self.fns: List[ast.FunctionDef] = module.functions()
        self._by_name: Dict[str, List[ast.FunctionDef]] = {}
        for fn in self.fns:
            self._by_name.setdefault(fn.name, []).append(fn)
        self._class_of: Dict[int, Optional[str]] = {
            id(fn): self._enclosing_class(fn) for fn in self.fns
        }
        self._types: Dict[int, Dict[str, str]] = {
            id(fn): self._typed_receivers(fn) for fn in self.fns
        }
        for fn in self.fns:  # refine: locals typed by callee -> returns
            self._infer_local_types(fn)
        self._edges: Dict[int, Set[int]] = {
            id(fn): self._callees(fn) for fn in self.fns
        }
        self.entrypoints: List[Entrypoint] = self._discover_entrypoints()
        for ep in self.entrypoints:
            ep.reachable = self._reach(ep.root)
        self._add_main()
        #: fn id -> labels of CONCURRENT entrypoints (+ main) executing it
        self.fn_entrypoints: Dict[int, Set[str]] = {}
        for ep in self.entrypoints:
            if ep.kind == "thread-joined":
                continue  # joined: serialized with its spawner
            for fid in ep.reachable:
                self.fn_entrypoints.setdefault(fid, set()).add(ep.label)
        self._accesses: List[_Access] = []
        for fn in self.fns:
            if fn.name in _EXEMPT_FNS:
                continue  # pre-publication: no other thread exists yet
            if id(fn) in self.fn_entrypoints:
                self._collect_accesses(fn)
        #: state key -> entrypoint labels touching it
        self.key_entrypoints: Dict[str, Set[str]] = {}
        for a in self._accesses:
            self.key_entrypoints.setdefault(a.key, set()).update(
                self.fn_entrypoints.get(id(a.fn), ())
            )
        self.shared: Dict[str, Set[str]] = {
            k: eps for k, eps in self.key_entrypoints.items()
            if len(eps) > 1
        }

    # ------------------------------------------------------------- graph
    def _enclosing_class(self, fn: ast.FunctionDef) -> Optional[str]:
        cur = self.module.parent(fn)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = self.module.parent(cur)
        return None

    def _typed_receivers(self, fn: ast.FunctionDef) -> Dict[str, str]:
        """Receiver name -> class name, from parameter annotations of this
        function and every enclosing one (closures: the REST handler's
        ``server: KsqlServer``), plus ``self`` -> the enclosing class."""
        out: Dict[str, str] = {}
        cls = self._class_of[id(fn)]
        if cls is not None:
            out["self"] = cls
        cur: Optional[ast.AST] = fn
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for arg in cur.args.args + cur.args.kwonlyargs:
                    ann = arg.annotation
                    name = None
                    if isinstance(ann, ast.Name):
                        name = ann.id
                    elif isinstance(ann, ast.Constant) \
                            and isinstance(ann.value, str):
                        name = ann.value.split(".")[-1]
                    elif isinstance(ann, ast.Attribute):
                        name = ann.attr
                    if name is not None:
                        out.setdefault(arg.arg, name)
            cur = self.module.parent(cur)
        return out

    @staticmethod
    def _ann_name(ann: Optional[ast.AST]) -> Optional[str]:
        if isinstance(ann, ast.Name):
            return ann.id
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            return ann.value.split(".")[-1]
        if isinstance(ann, ast.Attribute):
            return ann.attr
        return None

    def _infer_local_types(self, fn: ast.FunctionDef) -> None:
        """``sess = server.open_push_query(...)`` types ``sess`` from the
        resolved callee's ``-> PushQuerySession`` return annotation, so
        the call graph follows handler locals into their classes."""
        types = self._types[id(fn)]
        for node in self._own_nodes(fn):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                continue
            name = call_name(node.value)
            if name is None:
                continue
            parts = name.split(".")
            callee = None
            if len(parts) == 1 and parts[0] in self._by_name:
                cands = self._by_name[parts[0]]
                callee = cands[0] if len(cands) == 1 else None
            elif len(parts) == 2:
                cls = (
                    self._class_of[id(fn)]
                    if parts[0] in ("self", "cls")
                    else types.get(parts[0])
                )
                if cls is not None:
                    callee = self._method_of(cls, parts[1])
            if callee is not None:
                ret = self._ann_name(callee.returns)
                if ret is not None:
                    types.setdefault(node.targets[0].id, ret)

    def _method_of(self, cls: str, name: str) -> Optional[ast.FunctionDef]:
        for cand in self._by_name.get(name, ()):
            if self._class_of[id(cand)] == cls:
                return cand
        return None

    def _callees(self, fn: ast.FunctionDef) -> Set[int]:
        out: Set[int] = set()
        types = self._types[id(fn)]
        for node in self._own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            parts = name.split(".")
            bare = parts[-1]
            if bare not in self._by_name or len(parts) > 2:
                continue
            if len(parts) == 2 and parts[0] not in ("self", "cls"):
                # annotation-typed receiver: server.run_query(...) with
                # server: KsqlServer resolves to the class's method
                cls = types.get(parts[0])
                target = (
                    self._method_of(cls, bare) if cls is not None else None
                )
                if target is not None:
                    out.add(id(target))
                continue
            cands = self._by_name[bare]
            best = None
            for cand in cands:
                if self._class_of[id(cand)] == self._class_of[id(fn)]:
                    best = cand
                    break
            for cand in cands:
                if self._nested_in(cand, fn):
                    best = cand  # a local def shadows same-named methods
                    break
            out.add(id(best if best is not None else cands[0]))
        return out

    def _nested_in(self, inner: ast.AST, outer: ast.AST) -> bool:
        cur = self.module.parent(inner)
        while cur is not None:
            if cur is outer:
                return True
            cur = self.module.parent(cur)
        return False

    def _own_nodes(self, fn: ast.FunctionDef):
        """Walk fn's body excluding nested function/class definitions —
        those are their own call-graph nodes."""
        stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # ------------------------------------------------------- entrypoints
    def _discover_entrypoints(self) -> List[Entrypoint]:
        eps: List[Entrypoint] = []
        roots: Set[int] = set()
        for node in ast.walk(self.module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name not in ("threading.Thread", "Thread"):
                continue
            target = next(
                (kw.value for kw in node.keywords if kw.arg == "target"),
                None,
            )
            if target is None:
                continue
            tname = dotted_name(target)
            if tname is None:
                continue
            fn = self._resolve_target(tname, node)
            if fn is None:
                continue  # external callable (serve_forever, ...)
            label = tname.split(".")[-1].lstrip("_")
            kind = "thread-joined" if self._is_joined(node) else "thread"
            eps.append(Entrypoint(label, fn, node.lineno, kind))
            roots.add(id(fn))
        for fn in self.fns:
            # the annotation may sit on/above the def line OR on/above a
            # decorator line — bind against the whole header span so a
            # decorated entrypoint is not silently dropped
            header_lines = {fn.lineno} | {
                d.lineno for d in fn.decorator_list
            }
            label = next(
                (self.module.entrypoint_marks[line]
                 for line in sorted(header_lines)
                 if line in self.module.entrypoint_marks),
                None,
            )
            if label is not None and id(fn) not in roots:
                eps.append(Entrypoint(label, fn, fn.lineno, "annotated"))
                roots.add(id(fn))
        return eps

    def dangling_entrypoint_marks(self) -> List[int]:
        """entrypoint= annotation lines that bound to NO function — a
        misplaced mark (decorated def handled, but e.g. a blank line
        between comment and def, or a mark on a plain statement) means
        the author believes concurrency checking exists that silently
        does not; the rule reports it loudly instead."""
        headers: Set[int] = set()
        for fn in self.fns:
            headers |= {fn.lineno} | {d.lineno for d in fn.decorator_list}
        marks = self.module.entrypoint_marks
        out = []
        for line in sorted(marks):
            # a standalone mark registers at the comment line AND the next
            # line: the mark is bound if either registration hit a header
            same = [o for o in (line - 1, line, line + 1)
                    if marks.get(o) == marks[line]]
            if any(o in headers for o in same):
                continue
            if line - 1 in same:
                continue  # second line of an already-reported mark
            out.append(line)
        return out

    def _is_joined(self, thread_call: ast.Call) -> bool:
        """True when the spawning function joins the worker it creates
        (``w = Thread(...)`` ... ``w.join(timeout)``): the spawner blocks,
        so worker and spawner are serialized — the deadline-abandonment
        window is the fence rule's jurisdiction, not a free-running
        race."""
        encl = self.module.parent(thread_call)
        while encl is not None and not isinstance(
            encl, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            encl = self.module.parent(encl)
        if encl is None:
            return False
        assigned: Optional[str] = None
        asg = self.module.parent(thread_call)
        if isinstance(asg, ast.Assign) and len(asg.targets) == 1 \
                and isinstance(asg.targets[0], ast.Name):
            assigned = asg.targets[0].id
        if assigned is None:
            return False
        for node in self._own_nodes(encl):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == assigned
            ):
                return True
        return False

    def _resolve_target(self, tname: str,
                        site: ast.Call) -> Optional[ast.FunctionDef]:
        parts = tname.split(".")
        if len(parts) > 2 or (len(parts) == 2
                              and parts[0] not in ("self", "cls")):
            return None
        cands = self._by_name.get(parts[-1], [])
        # prefer a def nested in the function containing the Thread call
        encl = self.module.parent(site)
        while encl is not None and not isinstance(
            encl, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            encl = self.module.parent(encl)
        for cand in cands:
            if encl is not None and self._nested_in(cand, encl):
                return cand
        return cands[0] if cands else None

    def _add_main(self) -> None:
        """The implicit main entrypoint: functions not reachable from any
        declared entrypoint (nested defs included — they are reached via
        enclosing callers when actually called)."""
        claimed: Set[int] = set()
        for ep in self.entrypoints:
            claimed |= {id(ep.root)}
            claimed |= ep.reachable
        main_reach: Set[int] = set()
        for fn in self.fns:
            if id(fn) in claimed:
                continue
            parent = self.module.parent(fn)
            while isinstance(parent, ast.ClassDef):
                parent = self.module.parent(parent)
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            main_reach |= self._reach(fn)
        if main_reach:
            # one synthetic entrypoint for the whole main surface
            root = next(fn for fn in self.fns if id(fn) in main_reach)
            ep = Entrypoint(_MAIN, root, root.lineno, "main")
            ep.reachable = main_reach
            self.entrypoints.append(ep)

    def _reach(self, root: ast.FunctionDef) -> Set[int]:
        seen = {id(root)}
        frontier = [id(root)]
        for _ in range(_CALLGRAPH_DEPTH):
            nxt = []
            for fid in frontier:
                for callee in self._edges.get(fid, ()):
                    if callee not in seen:
                        seen.add(callee)
                        nxt.append(callee)
            if not nxt:
                break
            frontier = nxt
        return seen

    # ---------------------------------------------------------- accesses
    def _local_names(self, fn: ast.FunctionDef) -> Set[str]:
        out: Set[str] = set()
        params = {
            x.arg for x in fn.args.args + fn.args.kwonlyargs
            + fn.args.posonlyargs
        }
        for node in self._own_nodes(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            out.add(n.id)
            elif isinstance(node, (ast.For,)):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
            elif isinstance(node, ast.comprehension):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
            elif isinstance(node, ast.withitem) \
                    and node.optional_vars is not None:
                for n in ast.walk(node.optional_vars):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
        return out - params

    def _key_of(self, recv: ast.AST, attr: str,
                fn: ast.FunctionDef, locals_: Set[str]) -> Optional[str]:
        if attr.startswith("__") or attr in _LOCAL_ATTRS:
            return None
        if _is_fence_name(attr):
            return None  # the synchronization primitive itself
        if not isinstance(recv, ast.Name):
            return None
        if recv.id in locals_:
            return None  # locally-bound alias: identity unknown
        cls = self._types[id(fn)].get(recv.id)
        if cls is not None:
            return f"{cls}.{attr}"
        # untyped parameter or closure variable: key by its (stable) name
        return f"{recv.id}.{attr}"

    def _collect_accesses(self, fn: ast.FunctionDef) -> None:
        locals_ = self._local_names(fn)
        for node in self._own_nodes(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    self._mutation_target(t, node, fn, locals_)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                self._mutation_target(node.target, node, fn, locals_)
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in _MUTATORS \
                        and isinstance(f.value, ast.Attribute):
                    key = self._key_of(f.value.value, f.value.attr, fn,
                                       locals_)
                    if key is not None:
                        self._accesses.append(_Access(key, node, fn, True))
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                # `self.m(...)` is a method dispatch, not a state read —
                # keeping it would list every called method as shared
                # state in the --threads map
                parent = self.module.parent(node)
                if isinstance(parent, ast.Call) and parent.func is node:
                    continue
                key = self._key_of(node.value, node.attr, fn, locals_)
                if key is not None:
                    self._accesses.append(_Access(key, node, fn, False))

    def _mutation_target(self, target: ast.AST, stmt: ast.stmt,
                         fn: ast.FunctionDef, locals_: Set[str]) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._mutation_target(e, stmt, fn, locals_)
            return
        if isinstance(target, ast.Attribute):
            key = self._key_of(target.value, target.attr, fn, locals_)
            if key is not None:
                self._accesses.append(_Access(key, stmt, fn, True))
        elif isinstance(target, ast.Subscript) \
                and isinstance(target.value, ast.Attribute):
            key = self._key_of(target.value.value, target.value.attr, fn,
                               locals_)
            if key is not None:
                self._accesses.append(_Access(key, stmt, fn, True))

    # ------------------------------------------------------------ guards
    def guard_of(self, access: _Access) -> Optional[str]:
        """The guard covering this mutation, or None: 'fence' (positive
        alive()-branch / dominating bail-out), 'lock' (enclosing with on
        a *lock* object), 'owner' (validated single-writer annotation)."""
        node, fn = access.node, access.fn
        label = self.module.owner_marks.get(node.lineno)
        if label is not None:
            reach = self.fn_entrypoints.get(id(fn), set())
            if label in reach:
                return "owner"
        cur: Optional[ast.AST] = node
        while cur is not None:
            parent = self.module.parent(cur)
            if isinstance(parent, ast.If):
                if cur in parent.body and _mentions_with_polarity(
                    parent.test, "alive", want_neg=False
                ):
                    return "fence"
                if cur in parent.orelse and _mentions_with_polarity(
                    parent.test, "alive", want_neg=True
                ):
                    return "fence"
            if isinstance(parent, ast.With):
                for item in parent.items:
                    expr = item.context_expr
                    name = dotted_name(expr)
                    if name is None and isinstance(expr, ast.Call):
                        name = call_name(expr)
                    if name is not None and any(
                        _is_fence_name(part) for part in name.split(".")
                    ):
                        return "lock"
            for field in ("body", "orelse", "finalbody"):
                block = getattr(parent, field, None)
                if isinstance(block, list) and cur in block:
                    idx = block.index(cur)
                    if any(_is_bailout(s, "alive") for s in block[:idx]):
                        return "fence"
            cur = parent
        return None

    # ---------------------------------------------------------- findings
    def findings(self) -> List[Finding]:
        out: List[Finding] = []
        seen: Set[Tuple[int, str]] = set()
        for a in self._accesses:
            if not a.is_mutation or a.key not in self.shared:
                continue
            if self.guard_of(a) is not None:
                continue
            k = (a.node.lineno, a.key)
            if k in seen:
                continue
            seen.add(k)
            eps = ", ".join(sorted(self.shared[a.key]))
            out.append(Finding(
                rule=SharedStateRaceRule.name,
                path=self.module.path,
                line=a.node.lineno,
                col=a.node.col_offset,
                message=(
                    f"unguarded mutation of '{a.key}', state reachable "
                    f"from entrypoints [{eps}] — guard with the fence "
                    "idiom (alive() test / lock context) or record a "
                    "reviewed single-writer claim with '# graftlint: "
                    "owner=<entrypoint>'"
                ),
            ))
        return out

    # ------------------------------------------------------------ report
    def report(self) -> Dict[str, object]:
        """The --threads entrypoint map: declared entrypoints, their
        reach, and the shared-state keys with per-mutation guard
        status."""
        by_id = {id(fn): fn for fn in self.fns}
        eps = []
        for ep in self.entrypoints:
            if ep.kind == "main":
                continue
            eps.append({
                "label": ep.label,
                "kind": ep.kind,
                "root": ep.root.name,
                "line": ep.line,
                "reaches": sorted({
                    by_id[fid].name for fid in ep.reachable if fid in by_id
                }),
            })
        shared = {}
        for key, labels in sorted(self.shared.items()):
            muts = [a for a in self._accesses
                    if a.key == key and a.is_mutation]
            shared[key] = {
                "entrypoints": sorted(labels),
                "mutations": [
                    {
                        "line": a.node.lineno,
                        "fn": a.fn.name,
                        "guard": self.guard_of(a) or "UNGUARDED",
                    }
                    for a in muts
                ],
            }
        return {"entrypoints": eps, "shared": shared}


class SharedStateRaceRule(Rule):
    name = "shared-state-race"
    doc = ("state reachable from two thread entrypoints may only be "
           "mutated under the fence idiom (alive() test / lock context / "
           "owner= annotation)")

    def check(self, module: LintModule) -> Iterable[Finding]:
        if not any(
            isinstance(n, ast.Call)
            and call_name(n) in ("threading.Thread", "Thread")
            for n in ast.walk(module.tree)
        ) and not module.entrypoint_marks:
            return []  # no concurrency machinery in this module
        analysis = RaceAnalysis(module)
        out = analysis.findings()
        for line in analysis.dangling_entrypoint_marks():
            # a mark that bound nothing fails LOUD: the author believes
            # this module's concurrency is being checked and it is not
            out.append(Finding(
                rule=self.name, path=module.path, line=line, col=0,
                message=(
                    "dangling '# graftlint: entrypoint=' annotation: it "
                    "is not attached to a def (put it on, or directly "
                    "above, the function's decorator/def line) — no "
                    "entrypoint was registered"
                ),
            ))
        return out
