"""ksql-tpu: a TPU-native streaming SQL framework.

A from-scratch re-design of the capabilities of ksqlDB (the reference at
/root/reference): streaming SQL over partitioned logs, persistent
materialized-view queries, pull/push queries — with the execution backend
built for TPU from day one:

* queries compile to XLA: columnar micro-batches, fused elementwise
  expression kernels, segment-reductions for aggregation;
* keyed window state lives in HBM (hash-slotted device arrays) instead of
  RocksDB;
* GROUP BY / PARTITION BY shuffles are ICI all-to-all collectives under
  ``shard_map`` over a device mesh instead of broker repartition topics;
* durability via changelog batches + device-state snapshots instead of
  Kafka transactions.

Layering mirrors the reference seam (serializable plan IR with a pluggable
backend — see SURVEY.md): common → serde → metastore → parser → execution
(plan IR) → runtime (XLA lowering) → engine → server → clients.
"""

__version__ = "0.1.0"
