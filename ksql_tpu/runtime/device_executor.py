"""DeviceExecutor — runs a persistent query on the XLA backend.

The engine-side adapter giving CompiledDeviceQuery (runtime/lowering.py) the
same record-at-a-time executor interface as OracleExecutor, so the engine's
poll loop can drive either backend through one seam — the analog of the
reference's ExecutionStep.build() double-dispatch into a runtime
(ksqldb-execution/.../plan/ExecutionStep.java:68 →
ksqldb-streams/.../KSPlanBuilder.java:62).

Records are deserialized with the shared source decoder, micro-batched up
to the configured batch size, stepped through the compiled device function,
and the resulting SinkEmits are written to the sink topic through the shared
SinkWriter — exactly the path oracle emissions take, so downstream queries,
pull-query materialization, and QTT observation are backend-agnostic.

Batching semantics: EMIT FINAL emission is watermark-driven inside the
device step and therefore batch-size invariant; EMIT CHANGES coalesces to
one change per key per batch, so when per-record changelog parity is
required (ksql.emit.per.record, the reference's cache-off behavior) the
executor runs with batch size 1.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Dict, List, Optional

from ksql_tpu.common import faults, tracing
from ksql_tpu.common.batch import HostBatch
from ksql_tpu.compiler.jax_expr import DeviceUnsupported
from ksql_tpu.execution import steps as st
from ksql_tpu.functions.registry import FunctionRegistry
from ksql_tpu.runtime.lowering import CompiledDeviceQuery
from ksql_tpu.runtime.oracle import (
    SinkEmit,
    SinkWriter,
    StreamRow,
    decode_source_record,
)
from ksql_tpu.runtime.topics import Broker, Record


class DeviceExecutor:
    """OracleExecutor-interface adapter over the XLA backend."""

    backend = "device"

    def __init__(
        self,
        plan: st.QueryPlan,
        broker: Broker,
        registry: FunctionRegistry,
        on_error: Optional[Callable[[str, Exception], None]] = None,
        emit_callback: Optional[Callable[[SinkEmit], None]] = None,
        batch_size: int = 4096,
        per_record: bool = True,
        store_capacity: int = 1 << 17,
        sliced: Optional[bool] = None,
        slice_ring_max: int = 512,
    ):
        self.plan = plan
        self.broker = broker
        self.on_error = on_error or (lambda expr, e: None)
        self.emit_callback = emit_callback
        # batch-granularity emit hook (fused tap residuals): called once
        # per decoded emission batch, before the per-emit callback fan-out
        # (the engine wires it to the handle's push_batch_listeners)
        self.batch_emit_callback = None
        # some plan shapes require per-record stepping regardless of the
        # engine's batched default: fk joins (a right change fans out
        # store-wide) and self-joins (record-interleaved sides)
        per_record = per_record or _needs_per_record(plan)
        self.device = CompiledDeviceQuery(
            plan,
            registry,
            capacity=1 if (per_record and not _is_suppress(plan)) else batch_size,
            store_capacity=store_capacity,
            sliced=sliced,
            slice_ring_max=slice_ring_max,
        )
        # batched mode double-buffers: emission decode lags one batch so
        # host ingest overlaps device compute (flushed every drain tick)
        self.device.pipeline = not per_record and not _is_suppress(plan)
        # HAVING over an EMIT CHANGES table emits retractions on device via
        # the per-slot hpass verdict column (lowering._emit_agg)
        self.source_step = self.device.source
        self.table_step = self.device.table_source  # join right side or None
        self.right_step = self.device.right_source  # ss-join right or None
        if (
            self.right_step is not None
            and self.right_step.topic == self.source_step.topic
            and self.device.capacity > 1
        ):
            # self-join parity needs record-interleaved left/right steps
            raise DeviceUnsupported("batched self-join on device")
        self.sink_writer = SinkWriter(self.device.sink, broker, self.on_error)
        self._native_fields = self._native_ingest_spec()
        # rows decoded by the C++ tier, keyed by source format label
        # (surfaced as ksql_native_ingest_rows_total{format})
        self.native_ingest_rows: Dict[str, int] = {}
        self._raw: List[Record] = []
        self._rows: List[dict] = []
        self._ts: List[int] = []
        self._parts: List[int] = []
        self._offsets: List[int] = []
        # per-probe table-side buffers + topic -> probe-index routing
        self._tbuf: List[dict] = [
            {"rows": [], "ts": [], "del": [], "parts": [], "offs": []}
            for _ in self.device.join_chain
        ]
        self._join_topics = {
            js.table_source.topic: i
            for i, js in enumerate(self.device.join_chain)
        }
        self._rrows: List[dict] = []
        self._rts: List[int] = []
        self._rparts: List[int] = []
        self._roffs: List[int] = []
        self._changes: List[tuple] = []  # table-mode (key, old, new, ts)
        # table-table join: change buffer + topic -> side routing
        self._tt_buf: List[tuple] = []
        self._tt_topics = {}
        if self.device.tt_join is not None:
            self._tt_topics = {
                self.device.tt_left_source.topic: "l",
                self.device.tt_right_source.topic: "r",
            }
        self._fk_topics = {}
        if self.device.fk_join is not None:
            if self.device.capacity > 1:
                # a right change fans out store-wide: per-record only
                raise DeviceUnsupported("batched fk join on device")
            self._fk_topics = {
                self.device.fk_left_source.topic: "l",
                self.device.fk_right_source.topic: "r",
            }
        self.stream_time = -(2 ** 63)
        # records whose device step ran but whose emissions are still held
        # by the pipeline double-buffer (decoded next batch / at drain) —
        # those records are NOT durable yet for commit-point purposes
        self._pipeline_pending = 0

    # -------------------------------------------------------- epoch layer
    def pending_records(self) -> int:
        """Records handed to process() whose effects are not yet durable:
        host-buffered micro-batch rows plus (in pipeline mode) the batch
        whose emissions the double-buffer still holds.  The engine's
        per-record commit points only advance past records NOT counted
        here, so a mid-batch crash replays exactly the non-durable tail."""
        n = (len(self._raw) + len(self._rows) + len(self._changes)
             + len(self._tt_buf) + len(self._rrows))
        n += sum(len(b["rows"]) for b in self._tbuf)
        return n + self._pipeline_pending

    def _pipelines_held(self) -> bool:
        """True when the device's double-buffer actually defers emission
        decode — process_arrays with pipeline on, minus the paths that
        return their own emits synchronously (suppress disables the flag;
        session and ss-join steps bypass the hold)."""
        d = self.device
        return bool(
            getattr(d, "pipeline", False)
            and not getattr(d, "session", False)
            and d.ss_join is None
        )

    @property
    def record_synchronous(self) -> bool:
        """True when every record is fully through the device (emissions
        produced) before its process() returns — per-record micro-batches
        without pipelining.  Commit points are then per record, and a
        poison record is attributable to the exact process() call."""
        return (
            self.device.capacity == 1
            and not getattr(self.device, "pipeline", False)
        )

    @property
    def stateful(self) -> bool:
        """True when device state could double-count on replay (the engine
        then refuses in-place poison skips: device stores cannot roll back
        one record, so the poison path is replay-without-record)."""
        d = self.device
        return bool(
            d.agg is not None or d.join is not None or d.ss_join is not None
            or d.tt_join is not None or d.fk_join is not None
            or d.join_chain or d.table_mode or d.table_agg
        )

    # ----------------------------------------------------------- tracing
    def _device_step(self, fn, *args, **kw):
        """Run one device-step entry under the flight recorder, splitting
        jit-compile ticks from cache-hit executes: if the device's jit
        cache grew during the call, the wall time was dominated by
        trace+compile (``device.compile``, jit_miss count); otherwise it
        was pure dispatch+execute (``device.execute``, jit_hit)."""
        tr = tracing.active()
        if tr is None:
            return fn(*args, **kw)
        entries = getattr(self.device, "jit_cache_entries", None)
        before = entries() if entries is not None else 0
        depth = tr._depth
        tr._depth += 1
        t0 = _time.perf_counter()
        try:
            return fn(*args, **kw)
        finally:
            tr._depth = depth
            dur = _time.perf_counter() - t0
            missed = (entries() if entries is not None else 0) - before
            if missed > 0:
                tr.add_span("device.compile", t0, dur, depth)
                tr.stage("device.compile", dur, jit_miss=missed)
            else:
                tr.add_span("device.execute", t0, dur, depth)
                tr.stage("device.execute", dur, jit_hit=1)

    # ------------------------------------------------------------- interface
    def process(self, topic: str, record: Record) -> List[SinkEmit]:
        """Buffer one record; runs the device step when the micro-batch is
        full.  The engine calls drain() at the end of each poll tick.

        With a join, stream and table records interleave: a topic switch
        flushes the other side's buffer first, so device steps observe the
        same record order the row oracle would."""
        if faults.armed():
            # device-dispatch seam: a raise here models an XLA dispatch /
            # transfer failure and exercises the engine's restart path
            faults.fault_point("device.dispatch", self.plan.query_id)
        if topic in self._join_topics:
            idx = self._join_topics[topic]
            step = self.device.join_chain[idx].table_source
            ev = decode_source_record(step, record, self.on_error)
            if ev is None:
                return []
            self.stream_time = max(self.stream_time, ev.ts)
            out = self._run_batch() if self._rows else []
            schema = step.schema
            if ev.new is not None:
                row = ev.new
            else:  # tombstone: key columns only
                row = {c.name: None for c in schema.columns()}
                for c, v in zip(schema.key_columns, ev.key):
                    row[c.name] = v
            buf = self._tbuf[idx]
            buf["rows"].append(row)
            buf["ts"].append(ev.ts)
            buf["del"].append(ev.new is None)
            buf["parts"].append(record.partition)
            buf["offs"].append(record.offset)
            if len(buf["rows"]) >= self.device.capacity:
                self._run_table_batch(idx)
            return out
        if self.device.fk_join is not None and topic in self._fk_topics:
            side = self._fk_topics[topic]
            ev = decode_source_record(
                self.device.fk_left_source if side == "l"
                else self.device.fk_right_source,
                record, self.on_error,
            )
            if ev is None:
                return []
            self.stream_time = max(self.stream_time, ev.ts)
            return self._run_fk_change(side, ev, record)
        if self.device.tt_join is not None and topic in self._tt_topics:
            side = self._tt_topics[topic]
            ev = decode_source_record(
                self.device.tt_left_source if side == "l"
                else self.device.tt_right_source,
                record, self.on_error,
            )
            if ev is None:
                return []
            self.stream_time = max(self.stream_time, ev.ts)
            out2: List[SinkEmit] = []
            if self._tt_buf and self._tt_buf[0][0] != side:
                out2.extend(self._run_tt_batch())  # keep cross-side order
            self._tt_buf.append(
                (side, ev.key, ev.old, ev.new, ev.ts,
                 record.partition, record.offset)
            )
            if len(self._tt_buf) >= self.device.capacity:
                out2.extend(self._run_tt_batch())
            return out2
        out: List[SinkEmit] = []
        if (
            (self.device.table_mode or self.device.table_agg)
            and topic == self.source_step.topic
        ):
            ev = decode_source_record(self.source_step, record, self.on_error)
            if ev is None:
                return []
            # event-time watermark advance for table-mode sources (the
            # stream-row paths below already do this at decode)
            self.stream_time = max(self.stream_time, ev.ts)
            self._changes.append(
                (ev.key, ev.old, ev.new, ev.ts, record.partition, record.offset)
            )
            if len(self._changes) >= self.device.capacity:
                return self._run_change_batch()
            return []
        if topic == self.source_step.topic:
            if (
                self._native_fields is not None
                and isinstance(record.value, (str, bytes))
            ):
                # native tier: defer decode, batch JSON -> arrays in C++
                # (stream time advances at parse, matching decode-time
                # advance on the per-record path)
                if self._rows:  # keep arrival order across decode tiers
                    out.extend(self._run_batch())
                self._raw.append(record)
                if len(self._raw) >= self.device.capacity:
                    out.extend(self._run_native_batch())
                return out
            if self._raw:
                # a non-JSON-payload record (tombstone, dict): keep order
                out.extend(self._run_native_batch())
            ev = decode_source_record(self.source_step, record, self.on_error)
            if (
                ev is not None
                and isinstance(ev, StreamRow)
                and ev.row is None
                and self.device.agg is None
                and self.device.join is None
                and self.device.ss_join is None
                and not any(
                    isinstance(op, st.StreamFilter) for op in self.device.pre_ops
                )
            ):
                # null-value stream records pass filter-less projections
                # through unchanged (oracle SelectNode); a repartition
                # recomputes the key from the key columns alone
                # (SelectKeyNode null-row semantics); filters drop them
                out.extend(self._run_batch() if self._rows else [])
                key = ev.key
                for op in self.device.pre_ops:
                    if isinstance(op, st.StreamSelectKey):
                        src = {
                            c.name: v
                            for c, v in zip(
                                op.source.schema.key_columns, key or ()
                            )
                        }
                        key = tuple(
                            f(src) for f in self._null_keyers(op)
                        )
                emit = SinkEmit(key, None, ev.ts, ev.window)
                self._dispatch([emit])
                out.append(emit)
                return out
            if ev is not None and isinstance(ev, StreamRow) and ev.row is not None:
                if any(b["rows"] for b in self._tbuf):
                    self._run_table_batch()
                if self._rrows:
                    out.extend(self._run_right_batch())
                self.stream_time = max(self.stream_time, ev.ts)
                if self.device.flatmap is not None:
                    # UDTF explode runs host-side per record; the device
                    # pipeline consumes the exploded rows
                    for row in self._explode(ev):
                        self._rows.append(row)
                        self._ts.append(ev.ts)
                        self._parts.append(record.partition)
                        self._offsets.append(record.offset)
                else:
                    row = ev.row
                    if self.device.windowed_source and ev.window is not None:
                        # windowed-topic re-import: the key's window rides
                        # the batch as WINDOWSTART/WINDOWEND value columns
                        row = dict(row)
                        row["WINDOWSTART"], row["WINDOWEND"] = ev.window
                    self._rows.append(row)
                    self._ts.append(ev.ts)
                    self._parts.append(record.partition)
                    self._offsets.append(record.offset)
                if len(self._rows) >= self.device.capacity:
                    out.extend(self._run_batch())
        if self.right_step is not None and topic == self.right_step.topic:
            ev = decode_source_record(self.right_step, record, self.on_error)
            if ev is not None and isinstance(ev, StreamRow) and ev.row is not None:
                if self._rows:
                    out.extend(self._run_batch())
                self.stream_time = max(self.stream_time, ev.ts)
                self._rrows.append(ev.row)
                self._rts.append(ev.ts)
                self._rparts.append(record.partition)
                self._roffs.append(record.offset)
                if len(self._rrows) >= self.device.capacity:
                    out.extend(self._run_right_batch())
        return out

    # --------------------------------------------------- native ingest tier
    def _native_ingest_spec(self):
        return native_ingest_fields(self.device)

    def _run_native_batch(self) -> List[SinkEmit]:
        """Batch decode in C++ straight into device arrays.  Rows the
        native parser can't take replay through the Python per-record
        decoder (identical error/null semantics); the surrounding GOOD
        rows keep their columnar arrays — the chunk is walked as
        contiguous good/bad segments in arrival order, so emission order
        matches the pure-Python path exactly."""
        import numpy as np

        from ksql_tpu import native

        records, self._raw = self._raw, []
        dev = self.device
        cap = dev.capacity
        out: List[SinkEmit] = []
        tr = tracing.active()
        for s in range(0, len(records), cap):
            chunk = records[s : s + cap]
            n = len(chunk)
            t0 = _time.perf_counter() if tr is not None else 0.0
            try:
                data, valid, row_ok, learned = native.parse_batch(
                    [r.value for r in chunk], self._native_fields
                )
            except Exception:  # noqa: BLE001 — e.g. invalid UTF-8 in a
                # learned string: replay the chunk through the per-record
                # decoder, which drops exactly the offending records
                data, valid, learned = {}, {}, []
                row_ok = np.zeros(n, bool)
            dev.dictionary.learn_pairs(learned)
            if tr is not None and row_ok.any():
                # the native tier IS the good rows' deserialize: batch
                # payloads -> columnar arrays in C++ (the per-record path
                # records the same stage inside decode_source_record)
                tr.stage(
                    "deserialize", _time.perf_counter() - t0,
                    n=int(row_ok.sum()),
                )
            i = 0
            while i < n:
                j = i + 1
                good = bool(row_ok[i])
                while j < n and bool(row_ok[j]) == good:
                    j += 1
                if good:
                    columns = {
                        name: (d[i:j], valid[name][i:j])
                        for name, d in data.items()
                    }
                    out.extend(self._native_segment(chunk[i:j], columns))
                else:
                    for r in chunk[i:j]:
                        ev = decode_source_record(
                            self.source_step, r, self.on_error
                        )
                        if (
                            ev is not None
                            and isinstance(ev, StreamRow)
                            and ev.row is not None
                        ):
                            self.stream_time = max(self.stream_time, ev.ts)
                            self._rows.append(ev.row)
                            self._ts.append(ev.ts)
                            self._parts.append(r.partition)
                            self._offsets.append(r.offset)
                    out.extend(self._run_batch() if self._rows else [])
                i = j
        return out

    def _native_segment(self, chunk, columns) -> List[SinkEmit]:
        """Device-step one contiguous run of natively decoded records.
        ``columns`` holds the segment's (data, valid) slices per parsed
        value field; key columns are decoded (vectorized when the key
        shape allows) and merged here."""
        from ksql_tpu.common.batch import encode_column

        dev = self.device
        n = len(chunk)
        key_cols = list(self.source_step.schema.key_columns)
        self.stream_time = max(
            self.stream_time, max(r.timestamp for r in chunk)
        )
        label = self._native_fields["format"]
        self.native_ingest_rows[label] = (
            self.native_ingest_rows.get(label, 0) + n
        )
        spec_names = {spec.name for spec in dev.layout.specs}
        columns = {
            name: cv for name, cv in columns.items() if name in spec_names
        }
        if key_cols:
            decoded = self._vectorized_keys(chunk, key_cols)
            if decoded is None:
                decoded = self._per_record_keys(chunk, key_cols)
            for c in key_cols:
                if c.name not in spec_names:
                    continue
                kvals, kok = decoded[c.name]
                enc = encode_column(kvals, kok, c.type)
                if enc.dictionary is not None:
                    dev.dictionary.learn(enc.hashes64, enc.dictionary)
                    kd = enc.hashes64[enc.data]
                else:
                    kd = enc.data
                columns[c.name] = (kd, kok)
        emits = self._native_process(
            n, columns,
            [r.timestamp for r in chunk],
            [r.offset for r in chunk],
            [r.partition for r in chunk],
        )
        if self._pipelines_held():
            # the double-buffer now holds THIS segment's emissions (the
            # returned emits belong to the previous batch)
            self._pipeline_pending = n
        self._dispatch(emits)
        return emits

    def _native_process(self, n, columns, timestamps, offsets, partitions):
        """Hand a natively decoded columnar segment to the device.
        ``assemble`` COPIES the slices into fresh padded buffers, so the
        decoder's output is never aliased into donated jit state.  The
        distributed executor overrides this with the mesh lane split."""
        arrays = self.device.layout.assemble(
            n, columns, timestamps, offsets=offsets, partitions=partitions
        )
        return self._device_step(self.device.process_arrays, arrays)

    def _vectorized_keys(self, chunk, key_cols):
        """Columnar key decode for the common shape — ONE scalar key
        column under a non-positional format, where deserialize_key
        reduces to _coerce(payload, type) per record.  When every key in
        the segment is already the column's host type (or None) the
        coercion is the identity and the whole loop collapses to an
        object-array build; anything else returns None and the caller
        runs the exact per-record path."""
        import numpy as np

        from ksql_tpu.common.types import SqlBaseType as B

        if len(key_cols) != 1:
            return None
        kf = str(self.source_step.formats.key_format or "").upper()
        if kf in ("DELIMITED", "PROTOBUF", "PROTOBUF_NOSR"):
            return None
        c = key_cols[0]
        keys = [r.key for r in chunk]
        kinds = set(map(type, keys))
        kinds.discard(type(None))
        base = c.type.base
        if base == B.STRING:
            identity = kinds <= {str}
        elif base in (B.BIGINT, B.INTEGER):
            # bool is a distinct type() from int, so boolean keys (which
            # _coerce rejects for int columns) never take the fast path
            identity = kinds <= {int}
        elif base == B.DOUBLE:
            identity = kinds <= {float}
        else:
            return None
        if not identity:
            return None
        karr = np.empty(len(keys), object)
        karr[:] = keys
        kok = np.array([k is not None for k in keys], bool)
        return {c.name: (karr, kok)}

    def _per_record_keys(self, chunk, key_cols):
        """Record-at-a-time key decode (multi-column, positional formats,
        cross-type coercions) — exact deserialize_key semantics."""
        import numpy as np

        from ksql_tpu.serde import formats as fmt

        n = len(chunk)
        kvals = {c.name: np.empty(n, object) for c in key_cols}
        kok = {c.name: np.zeros(n, bool) for c in key_cols}
        for i, r in enumerate(chunk):
            if r.key is None:
                continue
            row = fmt.deserialize_key(
                self.source_step.formats.key_format, r.key, key_cols,
                delimiter=getattr(
                    self.source_step.formats, "key_delimiter", None
                ),
            )
            for c in key_cols:
                v = row.get(c.name)
                kvals[c.name][i] = v
                kok[c.name][i] = v is not None
        return {c.name: (kvals[c.name], kok[c.name]) for c in key_cols}

    def _explode(self, ev: StreamRow) -> List[dict]:
        """Host flat-map: the ops below the StreamFlatMap plus the UDTF
        expansion itself, via the oracle's nodes (KudtfFlatMapper analog)."""
        chain = getattr(self, "_flatmap_chain", None)
        if chain is None:
            from ksql_tpu.runtime.oracle import (
                Compiler,
                FilterNode,
                FlatMapNode,
                SelectKeyNode,
                SelectNode,
            )

            compiler = Compiler(self.device.registry, self.on_error)

            def mk(op):
                if isinstance(op, st.StreamFilter):
                    return FilterNode(op, compiler, False)
                if isinstance(op, st.StreamSelect):
                    return SelectNode(op, compiler)
                if isinstance(op, st.StreamSelectKey):
                    return SelectKeyNode(op, compiler)
                return FlatMapNode(op, compiler)

            chain = [
                mk(op)
                for op in (*self.device.flatmap_pre_ops, self.device.flatmap)
            ]
            self._flatmap_chain = chain
        events = [ev]
        for node in chain:
            nxt = []
            for e in events:
                nxt.extend(node.receive(0, e))
            events = nxt
        return [e.row for e in events if e.row is not None]

    def _null_keyers(self, op):
        """Compiled key expressions for null-row repartition passthrough.
        Expressions touching value columns yield a null key component for
        null-value rows (oracle SelectKeyNode / PartitionByParamsFactory)."""
        cache = getattr(self, "_null_keyer_cache", None)
        if cache is None:
            cache = self._null_keyer_cache = {}
        fns = cache.get(id(op))
        if fns is None:
            from ksql_tpu.execution.expressions import referenced_columns
            from ksql_tpu.runtime.oracle import Compiler

            compiler = Compiler(self.device.registry, self.on_error)
            key_names = {c.name for c in op.source.schema.key_columns}
            fns = [
                (
                    compiler.expr(e, op.source.schema)
                    if all(n in key_names for n in referenced_columns(e))
                    else (lambda src: None)
                )
                for e in op.key_expressions
            ]
            cache[id(op)] = fns
        return fns

    def _run_change_batch(self) -> List[SinkEmit]:
        import numpy as np

        changes = self._changes
        self._changes = []
        schema = self.source_step.schema
        out: List[SinkEmit] = []
        cap = self.device.capacity
        for i in range(0, len(changes), cap):
            chunk = changes[i : i + cap]
            keys = [c[0] for c in chunk]
            ts = [c[3] for c in chunk]
            parts = [c[4] for c in chunk]
            offs = [c[5] for c in chunk]
            has_old = np.array([c[1] is not None for c in chunk], bool)
            has_new = np.array([c[2] is not None for c in chunk], bool)
            new_hb = HostBatch.from_rows(
                schema, [c[2] or {} for c in chunk], timestamps=ts,
                partitions=parts, offsets=offs,
            )
            old_hb = HostBatch.from_rows(
                schema, [c[1] or {} for c in chunk], timestamps=ts,
                partitions=parts, offsets=offs,
            )
            emits = self._device_step(
                self.device.process_table_changes,
                new_hb, old_hb, keys, has_new, has_old, ts,
            )
            self._dispatch(emits)
            out.extend(emits)
        return out

    @staticmethod
    def _change_batches(schema, changes):
        """(new_hb, old_hb, deletes, has_old) for table-change tuples of
        (key, old, new, ts, partition, offset); delete rows become
        key-only new rows so the change key always probes."""
        import numpy as np

        def as_row(key, row):
            if row is not None:
                return row
            r = {c.name: None for c in schema.columns()}
            for c, v in zip(schema.key_columns, key):
                r[c.name] = v
            return r

        ts = [c[3] for c in changes]
        parts = [c[4] for c in changes]
        offs = [c[5] for c in changes]
        new_hb = HostBatch.from_rows(
            schema, [as_row(c[0], c[2]) for c in changes], timestamps=ts,
            partitions=parts, offsets=offs,
        )
        old_hb = HostBatch.from_rows(
            schema, [c[1] or {} for c in changes], timestamps=ts,
            partitions=parts, offsets=offs,
        )
        deletes = np.array([c[2] is None for c in changes], np.int32)
        has_old = np.array([c[1] is not None for c in changes], bool)
        return new_hb, old_hb, deletes, has_old

    def _run_fk_change(self, side: str, ev, record: Record) -> List[SinkEmit]:
        """One fk-join table change through the device (per-record)."""
        src = (
            self.device.fk_left_source if side == "l"
            else self.device.fk_right_source
        )
        new_hb, old_hb, deletes, has_old = self._change_batches(
            src.schema,
            [(ev.key, ev.old, ev.new, ev.ts, record.partition, record.offset)],
        )
        emits = self._device_step(
            self.device.process_fk, side, new_hb, old_hb, deletes, has_old
        )
        self._dispatch(emits)
        return emits

    def _run_tt_batch(self) -> List[SinkEmit]:
        """One single-side batch of table-table-join changes through the
        device (rows carry their key columns; deletes are key-only)."""
        import numpy as np

        buf, self._tt_buf = self._tt_buf, []
        out: List[SinkEmit] = []
        cap = self.device.capacity
        for i in range(0, len(buf), cap):
            chunk = buf[i : i + cap]
            side = chunk[0][0]
            src = (
                self.device.tt_left_source if side == "l"
                else self.device.tt_right_source
            )
            new_hb, old_hb, deletes, has_old = self._change_batches(
                src.schema, [c[1:] for c in chunk]
            )
            emits = self._device_step(
                self.device.process_tt, side, new_hb, old_hb, deletes, has_old
            )
            self._dispatch(emits)
            out.extend(emits)
        return out

    def drain(self) -> List[SinkEmit]:
        """Flush the partial micro-batches (end of a poll tick)."""
        out: List[SinkEmit] = []
        if self._raw:
            out.extend(self._run_native_batch())
        if self._tt_buf:
            out.extend(self._run_tt_batch())
        if self._changes:
            out.extend(self._run_change_batch())
        if any(b["rows"] for b in self._tbuf):
            self._run_table_batch()
        if self._rrows:
            out.extend(self._run_right_batch())
        if self._rows:
            out.extend(self._run_batch())
        if self.device.pipeline:
            emits = self._device_step(self.device.flush_pipeline)
            self._pipeline_pending = 0
            self._dispatch(emits)
            out.extend(emits)
        if self.right_step is not None:
            # record-driven time advance: expire join buffers, emitting
            # deferred null-pads (oracle _advance_time after each record)
            emits = self._device_step(self.device.ss_expire_host)
            self._dispatch(emits)
            out.extend(emits)
        return out

    def flush_time(self, stream_time: int) -> List[SinkEmit]:
        """Advance event time explicitly (end-of-input flush for EMIT
        FINAL)."""
        out = self.drain()
        self.stream_time = max(self.stream_time, stream_time)
        emits = self._device_step(self.device.flush, self.stream_time)
        self._dispatch(emits)
        out.extend(emits)
        return out

    # -------------------------------------------------------------- internal
    def _run_table_batch(self, idx: int = None) -> None:
        import numpy as np

        indices = range(len(self._tbuf)) if idx is None else (idx,)
        cap = self.device.capacity
        for j in indices:
            buf = self._tbuf[j]
            if not buf["rows"]:
                continue
            schema = self.device.join_chain[j].table_source.schema
            rows, ts, dels = buf["rows"], buf["ts"], buf["del"]
            parts, offs = buf["parts"], buf["offs"]
            self._tbuf[j] = {
                "rows": [], "ts": [], "del": [], "parts": [], "offs": []
            }
            for i in range(0, len(rows), cap):
                hb = HostBatch.from_rows(
                    schema, rows[i : i + cap], timestamps=ts[i : i + cap],
                    partitions=parts[i : i + cap], offsets=offs[i : i + cap],
                )
                self._device_step(
                    self.device.process_table,
                    hb, np.asarray(dels[i : i + cap], bool), idx=j,
                )

    def _run_right_batch(self) -> List[SinkEmit]:
        schema = self.right_step.schema
        rows, ts = self._rrows, self._rts
        parts, offs = self._rparts, self._roffs
        self._rrows, self._rts = [], []
        self._rparts, self._roffs = [], []
        out: List[SinkEmit] = []
        cap = self.device.capacity
        for i in range(0, len(rows), cap):
            hb = HostBatch.from_rows(
                schema, rows[i : i + cap], timestamps=ts[i : i + cap],
                partitions=parts[i : i + cap], offsets=offs[i : i + cap],
            )
            emits = self._device_step(self.device.process_ss, hb, "r")
            self._dispatch(emits)
            out.extend(emits)
        return out

    def _run_batch(self) -> List[SinkEmit]:
        schema = self.device.device_source_schema()
        rows, ts = self._rows, self._ts
        parts, offs = self._parts, self._offsets
        self._rows, self._ts, self._parts, self._offsets = [], [], [], []
        out: List[SinkEmit] = []
        cap = self.device.capacity
        for i in range(0, len(rows), cap):
            hb = HostBatch.from_rows(
                schema,
                rows[i : i + cap],
                timestamps=ts[i : i + cap],
                partitions=parts[i : i + cap],
                offsets=offs[i : i + cap],
            )
            emits = self._device_step(self.device.process, hb)
            if self._pipelines_held():
                # pipelined: the returned emits are the PREVIOUS batch's;
                # this chunk's records stay non-durable until the next
                # process/flush decodes them
                self._pipeline_pending = len(rows[i : i + cap])
            self._dispatch(emits)
            out.extend(emits)
        return out

    def _dispatch(self, emits: List[SinkEmit]) -> None:
        if not emits:
            return
        if self.batch_emit_callback is not None:
            # batch boundary first: push pipelines stash the (possibly
            # device-resident) columnar block so their residual kernel can
            # evaluate it before the rows fan out one at a time below
            self.batch_emit_callback(emits)
        # block-batched sink encode: serialize the emission block's values
        # column-at-a-time up front; the per-emit loop below keeps its
        # exact interleaving (callbacks, emit_seq ordinals, fault context,
        # retries) and just skips the row serializer where precoded
        precoded = self.sink_writer.encode_batch(emits)
        if precoded is None:
            for e in emits:
                if self.emit_callback is not None:
                    self.emit_callback(e)
                self.sink_writer.produce(e)
        else:
            for e, v in zip(emits, precoded):
                if self.emit_callback is not None:
                    self.emit_callback(e)
                self.sink_writer.produce(e, precoded=v)


class DistributedDeviceExecutor(DeviceExecutor):
    """DeviceExecutor variant that drives a DistributedDeviceQuery over the
    device mesh — the engine-facing productization of parallel/distributed.

    The record-at-a-time executor interface is inherited unchanged; the
    micro-batch entry points route through the sharded runner, which splits
    each batch round-robin into per-shard lanes (data parallelism), crosses
    rows to their key-owner shard over one ICI all-to-all (the
    repartition-topic analog), and folds into device-sharded state.  Plans
    the distribution layer does not cover yet raise DeviceUnsupported at
    construction, and the engine's fallback ladder drops them to the
    single-device DeviceExecutor (NOT the oracle — see _build_executor)."""

    backend = "distributed"

    def __init__(
        self,
        plan: st.QueryPlan,
        broker: Broker,
        registry: FunctionRegistry,
        on_error: Optional[Callable[[str, Exception], None]] = None,
        emit_callback: Optional[Callable[[SinkEmit], None]] = None,
        batch_size: int = 4096,
        per_record: bool = False,
        store_capacity: int = 1 << 17,
        n_shards: Optional[int] = None,
        sliced: Optional[bool] = None,
        slice_ring_max: int = 512,
    ):
        from ksql_tpu.parallel.distributed import DistributedDeviceQuery
        from ksql_tpu.parallel.mesh import make_mesh

        if per_record:
            raise DeviceUnsupported(
                "per-record emission cadence is not distributed (micro-batch "
                "lanes are the unit of mesh parallelism); run single-device"
            )
        if _needs_per_record(plan):
            # fk joins / self-joins auto-select record-synchronous stepping
            # on the single-device executor; a round-robin lane split would
            # break their record-interleaved semantics
            raise DeviceUnsupported(
                "plan requires per-record stepping (fk join / self join); "
                "not distributed — run single-device"
            )
        # distribution gaps derivable from the plan alone are rejected
        # BEFORE the single-device lowering below — otherwise every such
        # statement pays the full CompiledDeviceQuery construction twice
        # (once thrown away here, once in the engine's fallback rung)
        _reject_undistributable_plan(plan)
        mesh = make_mesh(n_shards)
        nd = int(len(mesh.devices.reshape(-1)))
        # ksql.batch.capacity is the HOST micro-batch bound: the mesh splits
        # it into n_shards lanes, so the per-shard static shape shrinks
        per_shard = max(1, batch_size // nd)
        super().__init__(
            plan, broker, registry,
            on_error=on_error, emit_callback=emit_callback,
            batch_size=per_shard, per_record=False,
            store_capacity=store_capacity,
            sliced=sliced, slice_ring_max=slice_ring_max,
        )
        compiled = self.device
        compiled.pipeline = False  # the sharded runner decodes per step
        self.device = DistributedDeviceQuery(compiled, mesh)
        # the C++ ingest tier stays engaged on the mesh: _native_process
        # routes decoded columns through the sharded runner's own
        # round-robin lane split (process_columns), so the bypass the
        # engine counted through PR 16 no longer exists for eligible plans
        self.native_ingest_bypassed = False

    def _native_process(self, n, columns, timestamps, offsets, partitions):
        # mesh-aware ingest: hand the decoder's column slices to the
        # sharded runner, which splits them into per-shard lanes and
        # assembles each lane at the per-shard static shape (the
        # single-device whole-batch assemble would bake the wrong
        # capacity).  process_columns copies every slice into fresh lane
        # buffers, keeping decoder output out of donated jit state.
        return self._device_step(
            self.device.process_columns,
            n, columns, timestamps, offsets, partitions,
        )

    def suspect_shard(self) -> Optional[int]:
        """Shard lane whose host-side dispatch section is (still) in
        flight — the engine's mesh fault domain reads it when a tick blows
        its deadline: a hang wedged inside ``mesh.shard.dispatch`` leaves
        the marker on the wedged lane, making the deadline attributable to
        ONE shard instead of the whole query."""
        return self.device.current_shard

    def shard_metrics(self) -> dict:
        """Per-shard gauges for /metrics (rows in/out, exchange volume,
        store occupancy — the shard-store observability of the tentpole)."""
        d = self.device
        return {
            "shards": d.n_shards,
            "rows-in": d.shard_rows_in.tolist(),
            "rows-out": d.shard_rows_out.tolist(),
            "exchange-rows": d.shard_exchange_rows.tolist(),
            # exchanged volume at the mesh's estimated row width — the
            # telemetry timeline's per-shard bytes series and the
            # ksql_shard_exchange_bytes Prometheus gauge
            "exchange-bytes": [
                int(r * d._exch_row_bytes)
                for r in d.shard_exchange_rows.tolist()
            ],
            "store-occupancy": d.shard_store_occupancy.tolist(),
            "watermark-ms": d.shard_watermark_ms.tolist(),
        }


class FamilyMemberExecutor:
    """Executor stub for a query attached to a window-family primary.

    The member's records are consumed, deserialized, aggregated, and
    window-combined inside the PRIMARY query's shared sliced pipeline
    (CompiledDeviceQuery.attach_member); emissions arrive through the
    ``deliver`` callback the engine wired at attach time, produced to this
    member's own sink topic.  The member's own poll tick therefore only
    advances its consumer offsets — records are observed-and-dropped, since
    the primary already folded them (consuming them twice would
    double-count).

    On promotion (primary terminated), the engine rebuilds the member as a
    standalone executor: it resumes from its consumer position with FRESH
    window state — the PR-5 stateful-rebuild posture, with partially-filled
    windows re-derived from that offset forward."""

    backend = "device"
    device = None  # no compiled pipeline of its own
    stateful = False  # shared state lives (and checkpoints) on the primary
    pipeline = False

    def __init__(
        self,
        plan: st.QueryPlan,
        broker: Broker,
        primary_query_id: str,
        on_error: Optional[Callable[[str, Exception], None]] = None,
        emit_callback: Optional[Callable[[SinkEmit], None]] = None,
    ):
        self.plan = plan
        self.primary_query_id = primary_query_id
        self.on_error = on_error or (lambda expr, e: None)
        self.emit_callback = emit_callback
        sink = plan.physical_plan
        if not isinstance(sink, (st.StreamSink, st.TableSink)):
            raise DeviceUnsupported("family member plan without sink")
        self.sink_writer = SinkWriter(sink, broker, self.on_error)
        self.stream_time = -(2 ** 63)

    # thread entrypoint: called from the PRIMARY query's tick — under tick
    # supervision that is the primary's worker thread, not the thread
    # polling this member  # graftlint: entrypoint=family-delivery
    def deliver(self, emits: List[SinkEmit]) -> None:
        """Emission fan-out target the primary's device step calls with
        this member's decoded window combines (during the PRIMARY's tick)."""
        for e in emits:
            if self.emit_callback is not None:
                self.emit_callback(e)
            self.sink_writer.produce(e)

    # ---- engine poll-loop interface: observe offsets, process nothing
    def process(self, topic: str, record: Record) -> List[SinkEmit]:
        self.stream_time = max(self.stream_time, record.timestamp or 0)
        return []

    def drain(self) -> List[SinkEmit]:
        return []

    def flush_time(self, stream_time: int) -> List[SinkEmit]:
        self.stream_time = max(self.stream_time, stream_time)
        return []

    def pending_records(self) -> int:
        return 0


def native_ingest_fields(dev):
    """Decode spec for the C++ batch decoder over ``dev``
    (a CompiledDeviceQuery): a dict with ``mode`` (native.MODE_*),
    ``fields`` ((name, FT code) pairs), ``delimiter`` and a ``format``
    label for metrics — or None when the query's source needs the Python
    per-record path (unsupported format, timestamp/header extraction,
    nested/path/host-computed columns).  Module-level so the static
    backend classifier (analysis/plan_verifier) can report whether a
    distributed placement engages the native tier."""
    from ksql_tpu.common.types import SqlBaseType as B

    step = dev.source
    if (
        dev.table_mode or dev.table_agg or dev.ss_join is not None
        or dev.join is not None or dev.flatmap is not None
        or not isinstance(step, st.StreamSource)
    ):
        return None
    vf = str(step.formats.value_format).upper()
    if vf not in ("JSON", "DELIMITED"):
        return None
    if step.timestamp_column or getattr(step, "header_columns", ()):
        return None
    try:
        from ksql_tpu import native
    except Exception:  # noqa: BLE001
        return None
    if not native.available():
        return None
    code_of = {
        B.BIGINT: native.FT_BIGINT,
        B.INTEGER: native.FT_INT,
        B.DOUBLE: native.FT_DOUBLE,
        B.BOOLEAN: native.FT_BOOLEAN,
        B.STRING: native.FT_STRING,
    }
    value_cols = list(step.schema.value_columns)
    delimiter = ","
    if vf == "JSON":
        if (
            step.formats.wrap_single_values is False
            and len(value_cols) == 1
        ):
            # SerdeFeature UNWRAP_SINGLES: one bare JSON scalar per payload
            mode = native.MODE_JSON_SINGLE
        else:
            # multi-column schemas always wrap, regardless of the flag
            mode = native.MODE_JSON
    else:
        mode = native.MODE_DELIMITED
        raw = step.formats.value_delimiter
        if raw is not None:
            named = {"SPACE": " ", "TAB": "\t"}
            delimiter = named.get(str(raw).upper(), str(raw))
        if (
            len(delimiter) != 1 or not delimiter.isascii()
            or delimiter in ('"', "\n", "\r")
        ):
            return None
    key_names = {c.name for c in step.schema.key_columns}
    for spec in dev.layout.specs:
        if spec.name in key_names:
            continue
        if spec.path is not None or spec.host_fn is not None:
            return None
        if spec.sql_type.base not in code_of:
            return None
    # parse EVERY value column, not just the ones the query reads: the
    # Python decoder coerces the whole row, so a bad value in an unused
    # column must still drop the record (via the fallback replay)
    fields = []
    for c in value_cols:
        code = code_of.get(c.type.base)
        if code is None:
            return None
        if not c.name.isascii():
            # the native matcher folds case ASCII-only; a non-ASCII
            # field name needs Python's full-Unicode str.upper()
            return None
        fields.append((c.name, code))
    return {
        "mode": mode,
        "fields": fields,
        "delimiter": delimiter,
        "format": vf,
    }


def _reject_undistributable_plan(plan: st.QueryPlan) -> None:
    """Raise DeviceUnsupported for distribution gaps visible in the plan
    itself, before any lowering work is spent.  Gaps only the lowering
    analysis can see (EARLIEST/LATEST's arrival-sequence need) are still
    caught by DistributedDeviceQuery's constructor."""
    stj = 0
    for s in st.walk_steps(plan.physical_plan):
        if isinstance(s, (st.TableTableJoin, st.ForeignKeyTableTableJoin)):
            raise DeviceUnsupported(
                "distributed table-table/foreign-key joins pending; run "
                "them single-device"
            )
        if isinstance(s, st.TableSuppress):
            raise DeviceUnsupported(
                "EMIT FINAL is not yet distributed (per-shard flush "
                "pending); run it single-device or on the row oracle"
            )
        if isinstance(s, st.StreamTableJoin):
            stj += 1
    if stj > 1:
        raise DeviceUnsupported(
            "distributed n-way stream-table join chains pending; run "
            "them single-device"
        )
    # a CTAS over a table source (table transform / table aggregation)
    # steps through change batches, which have no lane decomposition yet
    src_types = [
        type(s) for s in st.walk_steps(plan.physical_plan)
        if isinstance(s, (st.TableSource, st.WindowedTableSource))
    ]
    if src_types and stj == 0:
        raise DeviceUnsupported(
            "distributed table-source transforms pending; run them "
            "single-device"
        )


def _is_suppress(plan: st.QueryPlan) -> bool:
    return any(
        isinstance(s, st.TableSuppress) for s in st.walk_steps(plan.physical_plan)
    )


def _needs_per_record(plan: st.QueryPlan) -> bool:
    """Plan shapes that auto-select per-record stepping under a batched
    engine default: fk joins and same-topic (self) joins."""
    topics = []
    for s in st.walk_steps(plan.physical_plan):
        if isinstance(s, st.ForeignKeyTableTableJoin):
            return True
        if isinstance(s, st.StreamSource):
            topics.append(s.topic)
    return len(topics) != len(set(topics))
