"""XlaPlanBuilder — lowers the ExecutionStep DAG to one jitted device step.

The backend seam analog: where the reference's KSPlanBuilder
(ksqldb-streams/.../KSPlanBuilder.java:62) visits each ExecutionStep and
emits Kafka Streams DSL nodes (one processor per step, record-at-a-time),
this builder fuses the *entire* supported pipeline —

    Source → Filter*/Select*/SelectKey* → GroupBy → [Windowed]Aggregate
           → TableSelect*/TableFilter(HAVING) → [Suppress] → Sink

— into a single ``step(state, batch) → (state, emits)`` function compiled
once by XLA (static shapes, donated state, no host round-trips).  Per-step
processors would defeat XLA fusion; the step DAG remains the serialization
and planning boundary, not the execution granularity.

Unsupported steps or expressions raise DeviceUnsupported and the engine
falls back to the row oracle (runtime/oracle.py) — same posture as the
reference's codegen→interpreter fallback.

Semantic deltas vs the record-at-a-time oracle (documented, by design):
* EMIT CHANGES coalesces to one change per key per micro-batch (equivalent
  to Kafka Streams with its record cache enabled — the production default);
* late-record grace is evaluated against the stream time at batch start.

HAVING pass→fail transitions emit tombstones via the per-slot ``hpass``
verdict column (the oracle's retraction semantics).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ksql_tpu.common import tracing
from ksql_tpu.common import types as T
from ksql_tpu.common.batch import HostBatch
from ksql_tpu.common.errors import QueryRuntimeException
from ksql_tpu.common.schema import LogicalSchema
from ksql_tpu.common.types import SqlBaseType, SqlType
from ksql_tpu.compiler.jax_expr import (
    DCol,
    DeviceUnsupported,
    JaxExprCompiler,
    _dtype_for as _dtype_of_probe,
)
from ksql_tpu.execution import expressions as ex
from ksql_tpu.execution import steps as st
from ksql_tpu.execution.interpreter import ExpressionCompiler, TypeResolver
from ksql_tpu.functions.registry import FunctionRegistry
from ksql_tpu.ops import window as W
from ksql_tpu.ops.device_aggs import DeviceAgg, compile_device_agg
from ksql_tpu.ops.hash_store import (
    AggComponent,
    StoreLayout,
    combine_hash,
    init_store,
    probe_find,
    probe_insert,
    scatter_combine,
    winners_per_slot,
)
from ksql_tpu.parser.ast_nodes import WindowType
from ksql_tpu.runtime.device import BatchLayout, DictionaryServer, decode_value
from ksql_tpu.runtime.oracle import DEFAULT_GRACE_MS, SinkEmit

# the device path is int64/float64 throughout (timestamps, hashes, BIGINT);
# enable x64 once at import — flipping the process-global flag per query
# construction would invalidate jit caches of concurrently-running queries
jax.config.update("jax_enable_x64", True)


def _note_transfer(key: str, arrays: Dict[str, Any]) -> None:
    """Account host<->device bytes on the flight recorder's
    ``device.transfer`` stage (``.nbytes`` is metadata — no device sync)."""
    tr = tracing.active()
    if tr is None:
        return
    tr.counter(
        "device.transfer",
        **{key: int(sum(getattr(v, "nbytes", 0) for v in arrays.values()))},
    )

_HASHED = (
    SqlBaseType.STRING, SqlBaseType.BYTES,
    # nested values are opaque dictionary codes on device (see device.py)
    SqlBaseType.ARRAY, SqlBaseType.MAP, SqlBaseType.STRUCT,
)

#: HBM budget for a store's aggregate state arrays; wide vector components
#: (collect caps up to 4096 elements/key) trade initial slot count for width
_VEC_STATE_BUDGET_BYTES = 256 << 20
_NESTED_BASES = (SqlBaseType.ARRAY, SqlBaseType.MAP, SqlBaseType.STRUCT)


def _collect_struct_paths(exprs, schema):
    """(struct_paths, flattened_roots) for struct columns dereferenced to
    scalar leaves: each path becomes a synthetic flat column ``ROOT->F.G``.
    A struct whose every use is a path drops from the layout; one also used
    whole keeps its (dictionary-coded) column next to the path columns."""
    paths: Dict[str, Tuple[str, Tuple[str, ...], SqlType]] = {}
    bare_structs: set = set()
    struct_cols = {
        c.name: c.type
        for c in schema.columns()
        if c.type.base == SqlBaseType.STRUCT
    }

    def leaf_type(root: str, fields: Tuple[str, ...]) -> Optional[SqlType]:
        t = struct_cols.get(root)
        for f in fields:
            if t is None or t.base != SqlBaseType.STRUCT:
                return None
            t = next(
                (ft for fn, ft in (t.fields or ()) if fn.upper() == f.upper()),
                None,
            )
        if t is None or t.base in _NESTED_BASES:
            return None
        return t

    def scan(node):
        if isinstance(node, ex.Dereference):
            from ksql_tpu.compiler.jax_expr import (
                deref_fields,
                deref_root,
                deref_synth_name,
            )

            cur = deref_root(node)
            if isinstance(cur, ex.ColumnRef) and cur.name in struct_cols:
                fields = deref_fields(node)
                lt = leaf_type(cur.name, fields)
                if lt is None:
                    bare_structs.add(cur.name)
                else:
                    paths[deref_synth_name(cur.name, fields)] = (
                        cur.name, fields, lt,
                    )
                return
            scan(cur)
            return
        if isinstance(node, ex.ColumnRef):
            if node.name in struct_cols:
                bare_structs.add(node.name)
            return
        if dataclasses.is_dataclass(node) and not isinstance(node, type):
            for f in dataclasses.fields(node):
                v = getattr(node, f.name)
                if isinstance(v, ex.Expression):
                    scan(v)
                elif isinstance(v, (list, tuple)):
                    for item in v:
                        if isinstance(item, ex.Expression):
                            scan(item)
                        elif (
                            isinstance(item, tuple)
                            and len(item) == 2
                            and isinstance(item[1], ex.Expression)
                        ):
                            scan(item[1])

    for e in exprs:
        scan(e)
    # paths extract even when the struct is ALSO used whole (the bare
    # column rides as a dictionary code next to its flat path columns);
    # only fully-flattened roots leave the layout
    out = [
        (synth, root, fields, lt)
        for synth, (root, fields, lt) in sorted(paths.items())
    ]
    roots = {root for _s, root, _f, _t in out} - bare_structs
    return out, roots


def _repr64(col: DCol) -> jnp.ndarray:
    """Raw 64-bit key repr of a column (hash for strings, bitcast for f64,
    widened int otherwise)."""
    b = col.sql_type.base
    if b in _HASHED:
        return col.data
    if b in (SqlBaseType.DOUBLE, SqlBaseType.DECIMAL):
        return jax.lax.bitcast_convert_type(col.data.astype(jnp.float64), jnp.int64)
    return col.data.astype(jnp.int64)


def _decode_repr(data: np.ndarray, sql_type: SqlType) -> np.ndarray:
    if sql_type.base in (SqlBaseType.DOUBLE, SqlBaseType.DECIMAL):
        return data.view(np.float64)
    return data


def _host_repr64(value, sql_type: SqlType) -> Optional[int]:
    """Host-side mirror of _repr64 for one literal key value (keyed pull
    lookups).  None = no stable repr (nested literals) — caller scans."""
    if value is None:
        return None
    b = sql_type.base
    if b in _HASHED:
        if isinstance(value, (str, bytes)):
            from ksql_tpu.common.batch import stable_hash64

            return int(stable_hash64(value))
        return None
    if b in (SqlBaseType.DOUBLE, SqlBaseType.DECIMAL):
        return int(np.float64(value).view(np.int64))
    if b == SqlBaseType.BOOLEAN:
        return int(bool(value))
    return int(value)


@dataclasses.dataclass
class _AggSpec:
    fname: str
    arg_exprs: Tuple[ex.Expression, ...]
    device: DeviceAgg
    out_name: str


#: slice-ring combines cover exactly the monoid component kinds
_DECOMPOSABLE = ("add", "min", "max")


class FamilyAttachRefused(DeviceUnsupported):
    """A shared-pipeline attach the runtime must refuse — classified and
    observable: ``reason_code`` is the stable label of
    ``ksql_query_family_attach_refused_total{reason}`` (shared with the
    cost model's reject codes, planner/mqo.py) and ``details`` feeds the
    ``family.reslice.refuse`` plog + /alerts evidence entry."""

    def __init__(self, reason_code: str, msg: str, **details):
        super().__init__(msg)
        self.reason_code = reason_code
        self.details = details


@dataclasses.dataclass
class _MemberSpec:
    """One query of a window family sharing a sliced device pipeline.

    The primary query is ``members[0]``; attached queries differ in
    (size, advance, grace, retention), their post-aggregation
    projection/sink schema, and — since the MQO generalization — their
    aggregate SET: ``agg_map`` maps each member-local aggregate to its
    index in the pipeline's shared (union) partial set, which is what
    lets one per-(key, slice) partial store serve every member's window
    combine.  ``agg_map=None`` means the full shared set in order (the
    pre-MQO exact-match family)."""

    query_id: Optional[str]
    size_ms: int
    advance_ms: int
    grace_ms: int
    retention_ms: int
    agg_schema: LogicalSchema  # aggregate output schema (key column names)
    post_ops: List["st.ExecutionStep"]
    sink_schema: LogicalSchema  # emitted row schema
    deliver: Optional[Callable[[List["SinkEmit"]], None]] = None
    agg_map: Optional[List[int]] = None


@dataclasses.dataclass
class _PrefixMemberSpec:
    """One stateless query riding a shared source-prefix pipeline: the
    member's full filter/project chain (source-side-first suffix past the
    shared prefix is its residual) plus its sink schema.  Evaluated as an
    extra branch of the primary's stateless device step — the push
    registry's tap seam lifted from identity pipelines to arbitrary
    shared prefixes."""

    query_id: str
    pre_ops: List["st.ExecutionStep"]
    sink_schema: LogicalSchema
    deliver: Optional[Callable[[List["SinkEmit"]], None]] = None


def _op_fingerprint(op) -> tuple:
    """Structural identity of one Filter/Select step — the unit of
    shared-prefix matching across member chains."""
    if isinstance(op, st.StreamFilter):
        return ("filter", repr(op.predicate))
    return (
        "select",
        tuple((n, repr(e)) for n, e in getattr(op, "selects", ())),
        # key renames change the step's output env: two Selects that
        # differ only here must not fingerprint as one shared step
        tuple(getattr(op, "key_names", ()) or ()),
    )


def _refs_of_ops(ops) -> set:
    """Source columns referenced anywhere in a step chain."""
    out: set = set()
    for s in ops:
        if hasattr(s, "predicate"):
            out.update(ex.referenced_columns(s.predicate))
        if hasattr(s, "selects"):
            for _, e in s.selects:
                out.update(ex.referenced_columns(e))
        if hasattr(s, "key_expressions"):
            for e in s.key_expressions:
                out.update(ex.referenced_columns(e))
    return out


@dataclasses.dataclass
class _JoinSpec:
    """One stream-table probe of an n-way join chain (deepest-first)."""

    step: "st.StreamTableJoin"
    table_source: "st.TableSource"
    table_pre_ops: List["st.ExecutionStep"]
    # stream-side ops between the PREVIOUS probe (or the source) and this one
    between_ops: List["st.ExecutionStep"]
    layout: Optional[BatchLayout] = None
    cols: List = dataclasses.field(default_factory=list)
    capacity: int = 0
    seen_overflow: int = 0


class CompiledDeviceQuery:
    """A query lowered to the XLA backend.

    Host API: ``process(HostBatch) -> List[SinkEmit]`` for the stream
    source; ``flush(stream_time)`` forces suppressed (EMIT FINAL) windows
    out; ``state`` is the device store pytree (checkpointable).
    """

    def __init__(
        self,
        plan: st.QueryPlan,
        registry: FunctionRegistry,
        capacity: int = 8192,
        store_capacity: int = 1 << 17,
        table_store_capacity: int = 1 << 16,
        ss_buffer_capacity: int = 2048,
        ss_out_capacity: Optional[int] = None,
        analyze_only: bool = False,
        sliced: Optional[bool] = None,
        slice_ring_max: int = 512,
    ):
        self.plan = plan
        self.registry = registry
        self.capacity = capacity
        self.store_capacity = store_capacity
        self.dictionary = DictionaryServer()

        # ---- structural analysis (reject anything not yet device-lowered)
        self.sink: Optional[st.ExecutionStep] = None
        self.suppress = False
        self.windowed_source = False  # WindowedStreamSource re-import
        self.post_ops: List[st.ExecutionStep] = []  # TableSelect/TableFilter
        self.agg: Optional[st.ExecutionStep] = None
        self.group: Optional[st.ExecutionStep] = None
        self.pre_ops: List[st.ExecutionStep] = []  # Filter/Select/SelectKey
        self.mid_ops: List[st.ExecutionStep] = []  # ops between join and agg/sink
        self.join: Optional[st.StreamTableJoin] = None
        self.join_chain: List[_JoinSpec] = []
        self.table_source: Optional[st.TableSource] = None
        self.table_pre_ops: List[st.ExecutionStep] = []
        self.ss_join: Optional[st.StreamStreamJoin] = None
        self.right_source: Optional[st.StreamSource] = None
        self.right_pre_ops: List[st.ExecutionStep] = []
        self.table_mode = False  # table-to-table transform (per-change)
        self.table_agg = False  # aggregation over a TABLE source (undo+apply)
        self.tt_join: Optional[st.TableTableJoin] = None
        self.tt_left_source: Optional[st.TableSource] = None
        self.tt_right_source: Optional[st.TableSource] = None
        self.tt_left_ops: List[st.ExecutionStep] = []
        self.tt_right_ops: List[st.ExecutionStep] = []
        self.flatmap: Optional[st.StreamFlatMap] = None
        self.flatmap_pre_ops: List[st.ExecutionStep] = []
        self.fk_join: Optional[st.ForeignKeyTableTableJoin] = None
        self.fk_left_source: Optional[st.TableSource] = None
        self.fk_right_source: Optional[st.TableSource] = None
        self.fk_left_ops: List[st.ExecutionStep] = []
        self.fk_right_ops: List[st.ExecutionStep] = []
        self.source: Optional[st.StreamSource] = None
        self._analyze(plan.physical_plan)

        self.window = getattr(self.agg, "window", None) if self.agg is not None else None
        self.session = (
            self.window is not None
            and self.window.window_type == WindowType.SESSION
        )
        if self.session and self.suppress:
            raise DeviceUnsupported("EMIT FINAL SESSION windows on device")
        if self.session and self.join is not None:
            raise DeviceUnsupported("SESSION windows over a join on device")
        self.session_slots = 4  # concurrent sessions tracked per key (grows)
        grace = getattr(self.window, "grace_ms", None) if self.window else None
        # EMIT FINAL defaults to zero grace (emit right at window end);
        # EMIT CHANGES keeps the legacy 24h default (oracle AggregateNode)
        self.grace_ms = grace if grace is not None else (
            0 if self.suppress else DEFAULT_GRACE_MS
        )
        # windowed-store retention (KS: max(explicit retention, size+grace))
        self.retention_ms: Optional[int] = None
        if self.window is not None and self.window.window_type != WindowType.SESSION:
            size = self.window.size_ms
            self.retention_ms = max(
                getattr(self.window, "retention_ms", None) or 0,
                size + self.grace_ms,
            )
        # hopping windows expand each batch k-fold before the shuffle
        self.expansion = 1
        if self.window is not None and self.window.window_type == WindowType.HOPPING:
            self.expansion = W.hopping_expansion(
                self.window.size_ms, self.window.advance_ms
            )

        # ---- host-computed expression columns: scalar expressions with no
        # device lowering (string ops, subscripts, struct/array construction,
        # lambdas) evaluate host-side at encode and ride in as columns
        self._host_exprs: List[Tuple[str, Any, SqlType, Tuple[str, ...]]] = []
        self._extract_host_exprs()

        # ---- aggregation specs
        self.agg_specs: List[_AggSpec] = []
        self.key_types: List[SqlType] = []
        if self.agg is not None:
            self._build_agg_specs()

        # ---- stream slicing (hopping windows): per-(key, slice) partials
        # replace the k-fold expansion when every aggregate decomposes
        self._setup_slicing(sliced, slice_ring_max)

        # ---- ingress layout: only the columns the pipeline reads.
        # Shared source-prefix members (attach_prefix_member) widen the
        # layout to the union of every member chain's reads — empty here.
        self.prefix_members: List[_PrefixMemberSpec] = []
        #: leading self.pre_ops steps every prefix member shares (applied
        #: once per batch; each member then runs only its residual suffix)
        self._prefix_shared_len = 0
        self._build_ingress_layout()

        # ---- table-side ingress + device table store (stream-table join)
        self.table_layout: Optional[BatchLayout] = None
        self.table_schema: Optional[LogicalSchema] = None
        self.table_cols: List = []
        self.table_store_capacity = 0
        if self.join is not None:
            # downstream reads: mid ops, later probes' keys/between ops,
            # post ops, grouping, agg args, sink — a probe's store holds
            # only right-side columns something above it actually reads
            down = _refs_of_ops(self.mid_ops) | _refs_of_ops(self.post_ops)
            if self.group is not None:
                for e in getattr(self.group, "group_by_expressions", ()):
                    down.update(ex.referenced_columns(e))
            for spec in self.agg_specs:
                for e in spec.arg_exprs:
                    down.update(ex.referenced_columns(e))
            down.update(c.name for c in self._emit_schema().columns())
            for jspec in self.join_chain:
                down.update(ex.referenced_columns(jspec.step.left_key))
                down.update(_refs_of_ops(jspec.between_ops))
                down.update(c.name for c in jspec.step.schema.key_columns)
            for jspec in self.join_chain:
                tsrc = jspec.table_source.schema
                tneeded = _refs_of_ops(jspec.table_pre_ops)
                tneeded.update(ex.referenced_columns(jspec.step.right_key))
                tneeded &= {c.name for c in tsrc.columns()}
                tneeded.update(c.name for c in tsrc.key_columns)
                jspec.layout = BatchLayout(
                    tsrc, sorted(tneeded), capacity, self.dictionary
                )
                jspec.cols = [
                    c for c in jspec.step.right.schema.value_columns
                    if c.name in down
                ]
                jspec.capacity = table_store_capacity
            last = self.join_chain[-1]
            self.table_layout = last.layout
            self.table_schema = last.step.right.schema
            self.table_cols = last.cols
            self.table_store_capacity = table_store_capacity

        # ---- stream-stream join: right ingress + device ring buffers
        self.right_layout: Optional[BatchLayout] = None
        self.ss_cols: Dict[str, List] = {}
        if self.ss_join is not None:
            from ksql_tpu.parser.ast_nodes import JoinType

            ss = self.ss_join
            rsrc = self.right_source.schema
            rneeded = _refs_of_ops(self.right_pre_ops)
            rneeded.update(ex.referenced_columns(ss.right_key))
            rneeded &= {c.name for c in rsrc.columns()}
            rneeded.update(c.name for c in rsrc.key_columns)
            self.right_layout = BatchLayout(
                rsrc, sorted(rneeded), capacity, self.dictionary
            )
            down = _refs_of_ops(self.mid_ops)
            down.update(c.name for c in self._emit_schema().columns())
            down.update(c.name for c in ss.schema.key_columns)
            for side, step in (("l", ss.left), ("r", ss.right)):
                # nested columns buffer as dictionary codes like strings
                self.ss_cols[side] = [
                    c for c in step.schema.columns() if c.name in down
                ]
            self.ss_before = ss.before_ms
            self.ss_after = ss.after_ms
            # klip-36: explicit GRACE selects deferred (emit-at-close)
            # left/outer semantics; without it, legacy eager null-padding
            self.ss_deferred = ss.grace_ms is not None
            self.ss_grace = (
                ss.grace_ms if ss.grace_ms is not None else DEFAULT_GRACE_MS
            )
            self.ss_pad_sides = set()
            if ss.join_type in (JoinType.LEFT, JoinType.OUTER):
                self.ss_pad_sides.add("l")
            if ss.join_type in (JoinType.RIGHT, JoinType.OUTER):
                self.ss_pad_sides.add("r")
            # window-store retention (admission horizon vs the OWN side's
            # stream time): size + grace, as the reference's join stores
            self.ss_retention = self.ss_before + self.ss_after + self.ss_grace
            self.ss_capacity = max(ss_buffer_capacity, capacity)
            self.ss_out_cap = ss_out_capacity or max(64, 2 * capacity)

        # ---- table-table join: per-side ingress + two-sided device store
        self.tt_layouts: Dict[str, BatchLayout] = {}
        self.tt_cols: Dict[str, List] = {}
        self.tt_store_capacity = 0
        if self.tt_join is not None:
            down = _refs_of_ops(self.pre_ops)
            down.update(c.name for c in self._emit_schema().columns())
            down.update(c.name for c in self.tt_join.schema.key_columns)
            for side, src, ops, key_expr in (
                ("l", self.tt_left_source, self.tt_left_ops, self.tt_join.left_key),
                ("r", self.tt_right_source, self.tt_right_ops, self.tt_join.right_key),
            ):
                sschema = src.schema
                needed2 = _refs_of_ops(ops)
                needed2.update(ex.referenced_columns(key_expr))
                if not ops:
                    needed2.update(down)
                needed2 &= {c.name for c in sschema.columns()}
                needed2.update(c.name for c in sschema.key_columns)
                self.tt_layouts[side] = BatchLayout(
                    sschema, sorted(needed2), capacity, self.dictionary
                )
                post = ops[-1].schema if ops else sschema
                self.tt_cols[side] = [
                    c for c in post.columns() if c.name in down
                ]
            self.tt_store_capacity = table_store_capacity

        # ---- fk join: per-side ingress + left(pk,fk)/right(pk) stores
        self.fk_layouts: Dict[str, BatchLayout] = {}
        self.fk_cols: Dict[str, List] = {}
        self.fk_store_capacity = 0
        if self.fk_join is not None:
            down = _refs_of_ops(self.pre_ops)
            down.update(c.name for c in self._emit_schema().columns())
            down.update(c.name for c in self.fk_join.schema.key_columns)
            for side, src, ops in (
                ("l", self.fk_left_source, self.fk_left_ops),
                ("r", self.fk_right_source, self.fk_right_ops),
            ):
                sschema = src.schema
                needed2 = _refs_of_ops(ops)
                if side == "l":
                    needed2.update(
                        ex.referenced_columns(
                            self.fk_join.foreign_key_expression
                        )
                    )
                if not ops:
                    needed2.update(down)
                needed2 &= {c.name for c in sschema.columns()}
                needed2.update(c.name for c in sschema.key_columns)
                self.fk_layouts[side] = BatchLayout(
                    sschema, sorted(needed2), capacity, self.dictionary
                )
                post = ops[-1].schema if ops else sschema
                self.fk_cols[side] = [
                    c for c in post.columns() if c.name in down
                ]
            self.fk_store_capacity = table_store_capacity

        self.store_layout: Optional[StoreLayout] = None
        self._needs_seq = False
        if self.agg is not None:
            comps = self._agg_components()
            # wide vector state (collect caps / slice rings) shrinks the
            # initial slot count to a bounded HBM budget; the store still
            # grows on demand
            row_bytes = sum(
                np.dtype(c.dtype).itemsize * c.width for c in comps
            )
            budget_slots = max(1024, _VEC_STATE_BUDGET_BYTES // max(row_bytes, 1))
            while store_capacity > 1024 and store_capacity > budget_slots:
                store_capacity //= 2
            self.store_capacity = store_capacity
            self.store_layout = StoreLayout(
                capacity=store_capacity,
                num_keys=len(self.key_types),
                components=tuple(comps),
                windowed=self.window is not None,
            )
            # EARLIEST/LATEST aggs order by a global arrival sequence
            self._needs_seq = any(c.combine == "argset" for c in comps)

        self._state: Optional[Dict[str, jnp.ndarray]] = None  # lazy
        if analyze_only:
            # the static classifier's probe (analysis/plan_verifier): every
            # plan-derivable DeviceUnsupported above has had its chance to
            # raise — stop before jit wrapping and the abstract traces, so
            # classification costs plan analysis only
            return
        self._compile_steps()

        # abstract trace now: any DeviceUnsupported (expression/function not
        # lowered) must surface at construction so the engine can fall back
        # to the oracle BEFORE the query starts (no XLA compile, no alloc)
        state_shapes = jax.eval_shape(self.init_state)
        if self.ss_join is not None:
            jax.eval_shape(
                self._trace_ss_l, state_shapes, self.layout.array_structs()
            )
            jax.eval_shape(
                self._trace_ss_r, state_shapes, self.right_layout.array_structs()
            )
            jax.eval_shape(self._trace_ss_expire, state_shapes)
        elif self.table_agg:
            jax.eval_shape(
                self._trace_table_agg_step, state_shapes,
                self.layout.array_structs(), self.layout.array_structs(),
            )
        elif self.tt_join is not None:
            for side in ("l", "r"):
                structs = self.tt_layouts[side].array_structs()
                structs_new = dict(structs)
                structs_new["delete"] = jax.ShapeDtypeStruct(
                    (self.capacity,), np.int32
                )
                jax.eval_shape(
                    lambda st_, an, ao, s=side: self._trace_tt_step(
                        st_, an, ao, s
                    ),
                    state_shapes, structs_new, structs,
                )
        elif self.fk_join is not None:
            for side, trace in (
                ("l", self._trace_fk_left), ("r", self._trace_fk_right)
            ):
                structs = self.fk_layouts[side].array_structs()
                sn = dict(structs)
                sn["delete"] = jax.ShapeDtypeStruct(
                    (self.capacity,), np.int32
                )
                jax.eval_shape(trace, state_shapes, sn, structs)
        else:
            jax.eval_shape(
                self._trace_step, state_shapes, self.layout.array_structs()
            )
        for i in range(len(self.join_chain)):
            jax.eval_shape(
                lambda st_, ar, i=i: self._trace_table_step(st_, ar, i),
                state_shapes,
                self._table_array_structs(i),
            )

    def _trace_ss_l(self, state, arrays):
        return self._trace_ss_step("l", state, arrays)

    def _trace_ss_r(self, state, arrays):
        return self._trace_ss_step("r", state, arrays)

    def _compile_steps(self) -> None:
        if self.ss_join is not None:
            # no donation: a match-overflow / buffer-overwrite batch is
            # re-run on the pre-step state after growth
            self._ss_l = jax.jit(self._trace_ss_l)
            self._ss_r = jax.jit(self._trace_ss_r)
            self._ss_expire = jax.jit(self._trace_ss_expire)
            return
        # session steps run undonated: a sessions-per-key overflow grows
        # the slot count and re-runs the batch on the pre-step state
        donate = () if self.session else (0,)
        self._step = jax.jit(self._trace_step, donate_argnums=donate)
        self._evict = jax.jit(self._trace_evict, donate_argnums=0)
        if self.join is not None:
            self._table_steps = {
                i: jax.jit(
                    lambda st_, ar, i=i: self._trace_table_step(st_, ar, i),
                    donate_argnums=0,
                )
                for i in range(len(self.join_chain))
            }
            self._table_step = self._table_steps[len(self.join_chain) - 1]
        if self.table_agg:
            self._ta_step = jax.jit(
                self._trace_table_agg_step, donate_argnums=0
            )

    @property
    def state(self) -> Dict[str, jnp.ndarray]:
        if self._state is None:
            self._state = self.init_state()
        return self._state

    @state.setter
    def state(self, value: Dict[str, jnp.ndarray]) -> None:
        self._state = value

    def device_state_bytes(self) -> Dict[str, int]:
        """Live device-state bytes per memory-model component — the
        introspection seam the static footprint model
        (analysis/mem_model.py) is pinned against: sums each state
        array's ``nbytes`` (metadata only, no device sync) grouped by
        the model's one key->component classification."""
        from ksql_tpu.analysis.mem_model import measure_state_bytes

        return measure_state_bytes(self.state, sliced=self.sliced)

    # ------------------------------------------------------------ analysis
    def _analyze(self, step: st.ExecutionStep) -> None:
        cur = step
        if isinstance(cur, (st.StreamSink, st.TableSink)):
            self.sink = cur
            cur = cur.source
        else:
            raise DeviceUnsupported("plan without sink")
        if isinstance(cur, st.TableSuppress):
            self.suppress = True
            cur = cur.source
        while isinstance(cur, (st.TableSelect, st.TableFilter)):
            self.post_ops.append(cur)
            cur = cur.source
        self.post_ops.reverse()
        if isinstance(cur, (st.StreamAggregate, st.StreamWindowedAggregate)):
            self.agg = cur
            cur = cur.source
            if not isinstance(cur, (st.StreamGroupBy, st.StreamGroupByKey)):
                raise DeviceUnsupported(f"aggregate over {type(cur).__name__}")
            self.group = cur
            cur = cur.source
        elif isinstance(cur, st.TableAggregate):
            # table aggregation: every source change undoes the old row's
            # contributions at its old group key and applies the new row's
            # at its new key (KudafUndoAggregator + KudafAggregator)
            if self.suppress:
                raise DeviceUnsupported("suppress over a table aggregation")
            self.agg = cur
            self.table_agg = True
            cur = cur.source
            if not isinstance(cur, st.TableGroupBy):
                raise DeviceUnsupported(
                    f"table aggregate over {type(cur).__name__}"
                )
            self.group = cur
            cur = cur.source
            ops: List[st.ExecutionStep] = []
            while isinstance(cur, (st.TableFilter, st.TableSelect)):
                ops.append(cur)
                cur = cur.source
            ops.reverse()
            self.pre_ops = ops
            if not isinstance(cur, st.TableSource):
                raise DeviceUnsupported(
                    f"table aggregate source {type(cur).__name__} on device"
                )
            self.source = cur
            return
        elif self.post_ops or self.suppress or isinstance(cur, st.TableTableJoin):
            # table-to-table transform (CTAS without aggregation): lower the
            # TableFilter/TableSelect chain as a stateless per-change
            # pipeline; old/new verdicts drive tombstones host-side
            # (TableFilterBuilder/TableSelectBuilder analog)
            if self.suppress:
                raise DeviceUnsupported("suppress without aggregation")
            # post_ops was collected sink-downwards then reversed; its first
            # element's source chain must end at a TableSource (or a
            # pk-equi TableTableJoin of two TableSources)
            chain = list(self.post_ops)
            base = chain[0].source if chain else cur
            if isinstance(base, st.TableTableJoin):
                self._analyze_tt_join(base, chain)
                return
            if isinstance(base, st.ForeignKeyTableTableJoin):
                self._analyze_fk_join(base, chain)
                return
            if not isinstance(base, st.TableSource):
                raise DeviceUnsupported(
                    "table transforms without aggregation over "
                    f"{type(base).__name__ if base is not None else 'nothing'}"
                )
            self.table_mode = True
            self.pre_ops = chain
            self.post_ops = []
            self.source = base
            return
        while isinstance(cur, (st.StreamFilter, st.StreamSelect, st.StreamSelectKey)):
            self.pre_ops.append(cur)
            cur = cur.source
        self.pre_ops.reverse()
        if isinstance(cur, st.StreamFlatMap):
            # UDTF explode: variable fan-out is XLA-hostile, so the flat-map
            # (and anything below it) runs host-side per record and the
            # device pipeline starts at the exploded schema
            self.flatmap = cur
            cur = cur.source
            ops2: List[st.ExecutionStep] = []
            while isinstance(
                cur, (st.StreamFilter, st.StreamSelect, st.StreamSelectKey)
            ):
                ops2.append(cur)
                cur = cur.source
            ops2.reverse()
            self.flatmap_pre_ops = ops2
            if not isinstance(cur, st.StreamSource):
                raise DeviceUnsupported(
                    f"flat-map source {type(cur).__name__} on device"
                )
            self.source = cur
            return
        if isinstance(cur, st.StreamTableJoin):
            # stream-table join (possibly an n-way chain A⋈B⋈C): the stream
            # side keeps flowing through the row pipeline; each table side
            # materializes into its own keyed device store, probed in chain
            # order (StreamTableJoinBuilder analog,
            # ksqldb-streams/.../StreamTableJoinBuilder.java:43)
            from ksql_tpu.parser.ast_nodes import JoinType

            self.mid_ops = self.pre_ops
            chain_rev: List[Tuple] = []  # outermost-first while walking down
            while isinstance(cur, st.StreamTableJoin):
                if cur.join_type not in (JoinType.INNER, JoinType.LEFT):
                    raise DeviceUnsupported(
                        f"{cur.join_type} stream-table join on device"
                    )
                tops: List[st.ExecutionStep] = []
                rcur = cur.right
                while isinstance(
                    rcur, (st.TableSelect, st.TableFilter, st.TableSelectKey)
                ):
                    tops.append(rcur)
                    rcur = rcur.source
                tops.reverse()
                if not isinstance(rcur, st.TableSource):
                    raise DeviceUnsupported(
                        f"join right source {type(rcur).__name__} on device"
                    )
                ops: List[st.ExecutionStep] = []
                lcur = cur.left
                while isinstance(
                    lcur, (st.StreamFilter, st.StreamSelect, st.StreamSelectKey)
                ):
                    ops.append(lcur)
                    lcur = lcur.source
                ops.reverse()
                # `ops` sit between this join and whatever feeds its left
                chain_rev.append((cur, rcur, tops, ops))
                cur = lcur
            if not isinstance(cur, st.StreamSource):
                raise DeviceUnsupported(
                    f"join left source {type(cur).__name__} on device"
                )
            self.source = cur
            # deepest-first probe order; each spec's between_ops run BEFORE
            # its probe (they transform that join's left input)
            for join_step, tsrc, tops, between in reversed(chain_rev):
                self.join_chain.append(
                    _JoinSpec(join_step, tsrc, tops, between)
                )
            topics = [j.table_source.topic for j in self.join_chain]
            if len(set(topics)) != len(topics):
                # two probes of one changelog topic (self-join via aliases)
                # can't be routed topic->probe; the oracle handles it
                raise DeviceUnsupported(
                    "same-topic stream-table join chain on device"
                )
            deepest = self.join_chain[0]
            self.pre_ops = list(deepest.between_ops)
            deepest.between_ops = []
            self.join = self.join_chain[-1].step
            self.table_source = self.join_chain[-1].table_source
            self.table_pre_ops = self.join_chain[-1].table_pre_ops
            return
        if isinstance(cur, st.StreamStreamJoin):
            # stream-stream windowed join: both sides buffer in device ring
            # stores; each incoming batch matches the opposite buffer over
            # the WITHIN window, with klip-36 eager/deferred null-padding
            # (StreamStreamJoinBuilder.java:33,114 analog)
            if self.agg is not None or self.post_ops or self.suppress:
                raise DeviceUnsupported(
                    "aggregation over a stream-stream join on device"
                )
            self.ss_join = cur
            self.mid_ops = self.pre_ops
            for attr, src_attr, ops_attr in (
                ("source", "left", "pre_ops"),
                ("right_source", "right", "right_pre_ops"),
            ):
                c2 = getattr(cur, src_attr)
                ops: List[st.ExecutionStep] = []
                while isinstance(
                    c2, (st.StreamFilter, st.StreamSelect, st.StreamSelectKey)
                ):
                    ops.append(c2)
                    c2 = c2.source
                ops.reverse()
                setattr(self, ops_attr, ops)
                if not isinstance(c2, st.StreamSource):
                    raise DeviceUnsupported(
                        f"join {src_attr} source {type(c2).__name__} on device"
                    )
                setattr(self, attr, c2)
            return
        if isinstance(cur, st.WindowedStreamSource):
            # windowed-topic re-import: rows carry (key, windowStart, end)
            # keys; WINDOWSTART/WINDOWEND ride the batch as value columns
            # and re-attach to emitted rows.  Stateless pipelines only —
            # re-aggregating a windowed stream stays on the oracle.
            if self.agg is not None or self.post_ops or self.suppress:
                raise DeviceUnsupported(
                    "aggregation over a windowed source on device"
                )
            self.windowed_source = True
            self.source = cur
            return
        if not isinstance(cur, st.StreamSource):
            raise DeviceUnsupported(f"device source {type(cur).__name__}")
        self.source = cur

    def _analyze_fk_join(
        self, join: "st.ForeignKeyTableTableJoin", chain
    ) -> None:
        """Foreign-key table-table join: left keyed by its own pk, joined
        on fk(left) = pk(right).  A right change fans out to every left
        row with that fk — a vectorized full scan of the left store's fk
        column (the device reading of the reference's subscription/response
        topology, ForeignKeyTableTableJoinBuilder)."""
        from ksql_tpu.parser.ast_nodes import JoinType

        if join.join_type not in (JoinType.INNER, JoinType.LEFT):
            raise DeviceUnsupported(
                f"{join.join_type} foreign-key join on device"
            )
        self.fk_join = join
        self.pre_ops = chain
        self.post_ops = []
        for side, attr_src, attr_ops in (
            ("left", "fk_left_source", "fk_left_ops"),
            ("right", "fk_right_source", "fk_right_ops"),
        ):
            cur2 = getattr(join, side)
            ops: List[st.ExecutionStep] = []
            while isinstance(cur2, (st.TableSelect, st.TableFilter)):
                ops.append(cur2)
                cur2 = cur2.source
            ops.reverse()
            setattr(self, attr_ops, ops)
            if not isinstance(cur2, st.TableSource):
                raise DeviceUnsupported(
                    f"fk join {side} source {type(cur2).__name__} on device"
                )
            setattr(self, attr_src, cur2)
        if self.fk_left_source.topic == self.fk_right_source.topic:
            raise DeviceUnsupported("same-topic fk join on device")
        if len(join.left.schema.key_columns) != 1:
            raise DeviceUnsupported("multi-column fk-join left key on device")
        self.source = self.fk_left_source

    def _analyze_tt_join(self, join: "st.TableTableJoin", chain) -> None:
        """Primary-key table-table join: both tables materialize into ONE
        two-sided device store keyed by the pk; each change joins against
        the resident other side and flows through the post-join transform
        chain (TableTableJoinBuilder analog)."""
        from ksql_tpu.parser.ast_nodes import JoinType

        if join.join_type not in (JoinType.INNER, JoinType.LEFT,
                                  JoinType.RIGHT, JoinType.OUTER):
            raise DeviceUnsupported(
                f"{join.join_type} table-table join on device"
            )
        self.table_mode = True
        self.tt_join = join
        self.pre_ops = chain  # post-join transforms (per-change pipeline)
        self.post_ops = []
        for side, attr_src, attr_ops in (
            ("left", "tt_left_source", "tt_left_ops"),
            ("right", "tt_right_source", "tt_right_ops"),
        ):
            cur2 = getattr(join, side)
            ops: List[st.ExecutionStep] = []
            while isinstance(cur2, (st.TableSelect, st.TableFilter)):
                ops.append(cur2)
                cur2 = cur2.source
            ops.reverse()
            setattr(self, attr_ops, ops)
            if not isinstance(cur2, st.TableSource):
                raise DeviceUnsupported(
                    f"table-table join {side} source "
                    f"{type(cur2).__name__} on device"
                )
            setattr(self, attr_src, cur2)
        if self.tt_left_source.topic == self.tt_right_source.topic:
            # per-record left/right interleaving of a self-join needs the
            # oracle's port routing; topic->side routing can't express it
            raise DeviceUnsupported("same-topic table-table join on device")
        self.source = self.tt_left_source

    def device_source_schema(self) -> LogicalSchema:
        """Schema of the rows entering the device pipeline: the flat-map's
        exploded schema when one runs host-side, else the source's.
        Windowed sources append WINDOWSTART/WINDOWEND as value columns —
        the executor injects them from each record's windowed key."""
        if self.flatmap is not None:
            return self.flatmap.schema
        if self.windowed_source:
            cached = self.__dict__.get("_windowed_src_schema")
            if cached is None:
                b = LogicalSchema.builder()
                for c in self.source.schema.key_columns:
                    b.key_column(c.name, c.type)
                for c in self.source.schema.value_columns:
                    b.value_column(c.name, c.type)
                b.value_column("WINDOWSTART", T.BIGINT)
                b.value_column("WINDOWEND", T.BIGINT)
                cached = self.__dict__["_windowed_src_schema"] = b.build()
            return cached
        return self.source.schema

    def _pre_agg_schema(self) -> LogicalSchema:
        if self.mid_ops:
            return self.mid_ops[-1].schema
        if self.join is not None:
            return self.join.schema
        return (
            self.pre_ops[-1].schema
            if self.pre_ops
            else self.device_source_schema()
        )

    def _emit_schema(self) -> LogicalSchema:
        """Schema of rows leaving the device (sink schema)."""
        return self.sink.schema

    def _build_ingress_layout(self) -> None:
        """(Re)derive the ingress BatchLayout: only the columns the
        pipeline reads — the primary's own chain, grouping and aggregate
        arguments, plus (shared-prefix pipelines) the union of every
        attached member chain's reads and sink columns.  Re-run on
        prefix-member attach/detach and on shared-partial-set extension;
        the executor reads ``self.layout`` per batch, so a rebuild takes
        effect at the next encode."""
        needed = _refs_of_ops(self.pre_ops) | _refs_of_ops(self.mid_ops)
        scope_exprs: List[ex.Expression] = []
        for s_ in [*self.pre_ops, *self.mid_ops]:
            if hasattr(s_, "predicate"):
                scope_exprs.append(s_.predicate)
            for _n, e_ in getattr(s_, "selects", ()):
                scope_exprs.append(e_)
            for e_ in getattr(s_, "key_expressions", ()):
                scope_exprs.append(e_)
        if self.group is not None:
            for e in getattr(self.group, "group_by_expressions", ()):
                needed.update(ex.referenced_columns(e))
                scope_exprs.append(e)
        for spec in self.agg_specs:
            for e in spec.arg_exprs:
                needed.update(ex.referenced_columns(e))
                scope_exprs.append(e)
        src_schema = self.device_source_schema()
        src_cols = {c.name for c in src_schema.columns()}
        # stateless pipelines need every sink column that maps to a source col
        if self.agg is None:
            needed.update(c.name for c in self._emit_schema().columns())
        for m in self.prefix_members:
            needed |= _refs_of_ops(m.pre_ops)
            for s_ in m.pre_ops:
                if hasattr(s_, "predicate"):
                    scope_exprs.append(s_.predicate)
                for _n, e_ in getattr(s_, "selects", ()):
                    scope_exprs.append(e_)
            needed.update(c.name for c in m.sink_schema.columns())
        needed &= src_cols
        # key columns always ride along (key passthrough in Select)
        needed.update(c.name for c in src_schema.key_columns)
        if self.windowed_source:
            # emitted rows must re-attach the source window
            needed.update(("WINDOWSTART", "WINDOWEND"))
        # struct columns touched ONLY through scalar field paths flatten to
        # synthetic path columns extracted at encode (the struct itself
        # never reaches HBM)
        struct_paths, flattened_roots = _collect_struct_paths(
            scope_exprs, src_schema
        )
        needed -= flattened_roots
        self.layout = BatchLayout(
            src_schema, sorted(needed), self.capacity, self.dictionary,
            struct_paths=struct_paths,
            host_exprs=self._host_exprs,
        )

    # ------------------------------------- host-computed expression columns
    def _having_retract(self) -> bool:
        """Whether this query tracks per-slot HAVING verdicts for
        retraction emission (EMIT CHANGES aggregation with a HAVING
        filter; EMIT FINAL and sessions filter at emission instead)."""
        return (
            not self.suppress
            and not self.session
            and any(isinstance(op, st.TableFilter) for op in self.post_ops)
        )

    def _probe_compilable(self, e, types: Dict[str, SqlType]) -> bool:
        """Can the device expression compiler lower ``e`` over these column
        types?  Probed eagerly on 1-row arrays (construction-time only)."""
        env = {
            name: DCol(
                jnp.zeros((1,), _dtype_of_probe(t)), jnp.zeros((1,), bool), t
            )
            for name, t in types.items()
        }
        try:
            JaxExprCompiler(env, 1, DictionaryServer()).compile(e)
            return True
        except Exception:  # noqa: BLE001 — anything untraceable stays host
            return False

    def _extract_host_exprs(self) -> None:
        """Rewrite source-scope expressions the device cannot lower into
        references to host-computed encode columns.

        The reference evaluates every expression on CPU anyway (Janino
        codegen); here only the expressions XLA cannot express stay on the
        host — the rest of the query remains device-resident.  An
        expression qualifies when every column it references traces back
        unchanged to the physical source row (so encode can evaluate it)."""
        if self.source is None or self.ss_join is not None:
            return

        # DECIMAL note: extraction and decimals compose safely — an
        # extracted expression runs on the host with exact decimal
        # arithmetic, while decimal expressions the device CAN lower keep
        # their existing f64 semantics (documented deviation, ≤15-digit
        # columns only; wider columns still reject at layout build).
        from ksql_tpu.common.schema import PSEUDOCOLUMNS
        from ksql_tpu.runtime.oracle import Compiler as _OracleCompiler

        src_schema = self.device_source_schema()
        src_names = {c.name for c in src_schema.columns()}
        # probe-env types: source columns + pseudocolumns + struct-path
        # synthetic leaves (collected over the original expressions)
        types: Dict[str, SqlType] = {
            c.name: c.type for c in src_schema.columns()
        }
        for n_, t_ in PSEUDOCOLUMNS.items():
            types.setdefault(n_, t_)
        scope: List[ex.Expression] = []
        for op in self.pre_ops:
            scope.append(getattr(op, "predicate", None))
            scope.extend(e2 for _n2, e2 in getattr(op, "selects", ()))
            scope.extend(getattr(op, "key_expressions", ()))
        if self.group is not None:
            scope.extend(getattr(self.group, "group_by_expressions", ()))
        if self.agg is not None:
            for call in self.agg.aggregations:
                scope.extend(call.args)
        for synth, _root, _fields, lt in _collect_struct_paths(
            [e2 for e2 in scope if e2 is not None], src_schema
        )[0]:
            types[synth] = lt
        # name -> source column it still transparently aliases (None = opaque)
        mapping: Dict[str, Optional[str]] = {n2: n2 for n2 in src_names}
        for n2 in PSEUDOCOLUMNS:
            mapping.setdefault(n2, n2)
        oracle_c = _OracleCompiler(self.registry, lambda w, err: None)

        def free_refs(node, scope=frozenset()):
            """Column refs free in ``node`` (lambda params are bound within
            their body only — a same-named OUTER ref stays free)."""
            if isinstance(node, ex.LambdaExpression):
                yield from free_refs(node.body, scope | set(node.params))
                return
            if isinstance(node, ex.ColumnRef):
                if node.name not in scope:
                    yield node
                return
            if isinstance(node, ex.Expression):
                for f in dataclasses.fields(node):
                    yield from free_refs(getattr(node, f.name), scope)
            elif isinstance(node, (list, tuple)):
                for item in node:
                    yield from free_refs(item, scope)

        def try_extract(e):
            """Return a replacement expression, or None to keep ``e``."""
            if e is None or self._probe_compilable(e, types):
                return None
            bound = {
                p
                for node in ex.walk(e)
                if isinstance(node, ex.LambdaExpression)
                for p in node.params
            }
            refs = list(free_refs(e))
            if not refs:
                return None
            if bound & {r.name for r in refs}:
                # a lambda param shadows a FREE outer column of the same
                # name: the name-based rewrite below cannot distinguish
                # them, so this expression stays unextracted
                return None
            sub = {}
            for r in refs:
                if r.source or mapping.get(r.name) is None:
                    return None  # opaque/qualified input: stays unsupported
                sub[r.name] = mapping[r.name]
            rewritten = ex.rewrite(
                e,
                lambda nd: (
                    ex.ColumnRef(name=sub[nd.name], source=None)
                    if isinstance(nd, ex.ColumnRef) and nd.name in sub
                    else nd
                ),
            )
            try:
                compiled = oracle_c.expr(rewritten, src_schema)
            except Exception:  # noqa: BLE001 — let the normal path fail
                return None
            synth = f"__HX{len(self._host_exprs)}"
            self._host_exprs.append((
                synth, compiled, compiled.sql_type or T.STRING,
                tuple(dict.fromkeys(
                    r2.name for r2 in free_refs(rewritten)
                )),
            ))
            types[synth] = compiled.sql_type or T.STRING
            mapping[synth] = None
            return ex.ColumnRef(name=synth, source=None)

        new_pre: List[st.ExecutionStep] = []
        for op in self.pre_ops:
            changed = {}
            if getattr(op, "predicate", None) is not None:
                r = try_extract(op.predicate)
                if r is not None:
                    changed["predicate"] = r
            if getattr(op, "selects", ()):
                new_sel = []
                sel_changed = False
                for alias, e2 in op.selects:
                    r = try_extract(e2)
                    new_sel.append((alias, r if r is not None else e2))
                    sel_changed = sel_changed or r is not None
                if sel_changed:
                    changed["selects"] = tuple(new_sel)
            if getattr(op, "key_expressions", ()):
                new_keys = []
                k_changed = False
                for e2 in op.key_expressions:
                    r = try_extract(e2)
                    new_keys.append(r if r is not None else e2)
                    k_changed = k_changed or r is not None
                if k_changed:
                    changed["key_expressions"] = tuple(new_keys)
            new_op = dataclasses.replace(op, **changed) if changed else op
            new_pre.append(new_op)
            if getattr(op, "selects", ()):
                # projection: downstream names remap through this op
                out_map: Dict[str, Optional[str]] = {}
                out_types: Dict[str, SqlType] = {}
                for c2 in op.schema.key_columns:
                    out_map[c2.name] = mapping.get(c2.name)
                    out_types[c2.name] = c2.type
                for alias, e2 in op.selects:
                    if isinstance(e2, ex.ColumnRef) and not e2.source:
                        out_map[alias] = mapping.get(e2.name)
                    else:
                        out_map[alias] = None
                for c2 in op.schema.columns():
                    out_types[c2.name] = c2.type
                for n2, t2 in PSEUDOCOLUMNS.items():
                    out_map.setdefault(n2, n2)
                    out_types.setdefault(n2, t2)
                # synthetic columns stay visible below the projection
                for s2, _f2, t2, _r2 in self._host_exprs:
                    out_types[s2] = t2
                    out_map.setdefault(s2, None)
                mapping.clear()
                mapping.update(out_map)
                types.clear()
                types.update(out_types)
        self.pre_ops = new_pre
        if self.group is not None:
            exprs = tuple(getattr(self.group, "group_by_expressions", ()))
            if exprs:
                new_g = tuple(
                    (try_extract(e2) or e2) for e2 in exprs
                )
                if new_g != exprs:
                    self.group = dataclasses.replace(
                        self.group, group_by_expressions=new_g
                    )
        if self.agg is not None:
            new_calls = []
            a_changed = False
            for call in self.agg.aggregations:
                new_args = tuple((try_extract(a2) or a2) for a2 in call.args)
                if new_args != call.args:
                    call = dataclasses.replace(call, args=new_args)
                    a_changed = True
                new_calls.append(call)
            if a_changed:
                self.agg = dataclasses.replace(
                    self.agg, aggregations=tuple(new_calls)
                )

    def _build_agg_specs(self) -> None:
        src_schema = self._pre_agg_schema()
        types = {c.name: c.type for c in src_schema.columns()}
        from ksql_tpu.common.schema import PSEUDOCOLUMNS, WINDOW_BOUNDS

        for n, t in {**PSEUDOCOLUMNS, **WINDOW_BOUNDS}.items():
            types.setdefault(n, t)
        for synth, _fn, t, _refs in self._host_exprs:
            types[synth] = t
        resolver = ExpressionCompiler(
            TypeResolver(types), self.registry, lambda w, e: None
        )
        for i, call in enumerate(self.agg.aggregations):
            arg_types = [resolver.compile(a).sql_type for a in call.args]
            udaf = self.registry.udaf(call.function, arg_types)
            if udaf.device_kind is None:
                raise DeviceUnsupported(f"UDAF {call.function} on device")
            if call.distinct:
                raise DeviceUnsupported("DISTINCT aggregation on device")
            rt = udaf.returns
            result_type = rt(arg_types) if callable(rt) else rt
            for t in [*arg_types, result_type]:
                if t.base == SqlBaseType.DECIMAL and (t.precision or 0) > 15:
                    # f64 carries <=15 significant digits exactly; wider
                    # decimal aggregation keeps the (exact) oracle
                    raise DeviceUnsupported("DECIMAL aggregation on device")
            lits: List[object] = []
            if udaf.literal_params:
                from ksql_tpu.execution import expressions as ex2

                for a in call.args[len(call.args) - udaf.literal_params:]:
                    if isinstance(a, (ex2.IntegerLiteral, ex2.LongLiteral)):
                        lits.append(int(a.value))
                    elif isinstance(a, ex2.BooleanLiteral):
                        lits.append(bool(a.value))
                    else:
                        lits.append(None)
            device = compile_device_agg(
                udaf.device_kind, arg_types, result_type, fname=call.function,
                literals=lits,
            )
            if self.session and any(
                c.width > 1 for c in device.components
            ):
                # session segment-merge folds components pairwise; vector
                # state (collect/topk) has no pairwise combine formulation
                raise DeviceUnsupported(
                    f"{call.function} over SESSION windows on device"
                )
            if self.table_agg and device.undo_contribs is None and any(
                c.combine != "add" for c in device.components
            ):
                # table retractions need sign-invertible state: pure 'add'
                # decompositions (count/sum/avg/stddev/correlation) undo by
                # negation, histogram by signed decrement (undo_contribs);
                # min/max/collect/topk keep the oracle
                raise DeviceUnsupported(
                    f"{call.function} over a table aggregation on device"
                )
            self.agg_specs.append(
                _AggSpec(call.function, call.args, device, f"KSQL_AGG_VARIABLE_{i}")
            )
        self.key_types = [c.type for c in self.agg.schema.key_columns]

    # ------------------------------------------------------- stream slicing
    def _agg_components(self) -> List[AggComponent]:
        """Store component list for the aggregate state arrays.  Sliced
        stores widen every (scalar, monoid) component to a per-key ring of
        ``slice_ring`` slice partials; the expansion path keeps the
        per-(key, window) scalar layout."""
        comps: List[AggComponent] = [
            AggComponent("max", "int64", np.iinfo(np.int64).min)
        ]
        for spec in self.agg_specs:
            comps.extend(spec.device.components)
        if self.sliced:
            comps = [
                dataclasses.replace(c, width=self.slice_ring) for c in comps
            ]
        return comps

    def _slice_ineligibility(self, ring_max: int) -> Optional[str]:
        """Why this hopping aggregation must keep the k-fold expansion path
        (None = sliced-eligible).  Every string here is a windowing-shape
        fallback reason the engine counts in ``fallback_reasons``."""
        w = self.window
        if self.suppress:
            return (
                "EMIT FINAL hopping windows keep the expansion path "
                "(per-window close tracking on slices pending)"
            )
        if self._having_retract():
            return (
                "HAVING retraction over hopping windows keeps the "
                "expansion path (per-window verdict state)"
            )
        for spec in self.agg_specs:
            if any(
                c.combine not in _DECOMPOSABLE for c in spec.device.components
            ):
                return (
                    f"non-decomposable aggregate {spec.fname} keeps the "
                    "expansion path (no monoid merge for its device state)"
                )
        if W.hopping_expansion(w.size_ms, w.advance_ms) < 2:
            return (
                "hopping ADVANCE equals SIZE (k=1): the expansion path is "
                "already slice-optimal"
            )
        sw = W.slice_width(w.size_ms, w.advance_ms)
        ring = self.retention_ms // sw + 2
        if ring > ring_max:
            return (
                f"hopping slice ring of {ring} slices exceeds "
                f"ksql.slicing.max.ring={ring_max} (slice width {sw}ms, "
                f"retention {self.retention_ms}ms) — set an explicit GRACE "
                "PERIOD or raise the cap; keeping the expansion path"
            )
        return None

    def _setup_slicing(self, sliced_opt: Optional[bool], ring_max: int) -> None:
        self.sliced = False
        self.slice_width = 0
        self.slice_ring = 0
        self.slice_ring_max = ring_max
        #: widest member retention — drives sliced eviction and admission
        self.family_retention_ms = self.retention_ms or 0
        #: hopping fan-out of the PRIMARY window (EXPLAIN surfaces it even
        #: on the sliced path, where the batch itself no longer expands)
        self.hop_k = self.expansion
        #: why a hopping query stayed on the expansion path (None when
        #: sliced, or not a hopping aggregation at all)
        self.windowing_fallback: Optional[str] = None
        #: fused-tap-residual handoff (ISSUE 12): when armed, _decode_emits
        #: keeps each batch's columnar emit arrays (device-resident, scalar
        #: columns) in last_raw_block for the push registry's batch
        #: listeners — the tap kernel evaluates over them directly instead
        #: of re-encoding the fanned-out host rows
        self.collect_raw_emits = False
        self.last_raw_block: Optional[Dict[str, Any]] = None
        self.members: List[_MemberSpec] = []
        hopping = (
            self.window is not None
            and self.window.window_type == WindowType.HOPPING
        )
        if not hopping:
            if sliced_opt is True:
                raise DeviceUnsupported(
                    "sliced aggregation requires a HOPPING windowed "
                    "aggregation"
                )
            return
        reason = self._slice_ineligibility(ring_max)
        if reason is None and sliced_opt is False:
            reason = (
                "hopping runs the expansion path (slicing disabled for "
                "this executor)"
            )
        if reason is not None:
            if sliced_opt is True:
                raise DeviceUnsupported(reason)
            self.windowing_fallback = reason
            return
        self.sliced = True
        self.expansion = 1  # no k-fold batch blow-up before the shuffle
        w = self.window
        self.slice_width = W.slice_width(w.size_ms, w.advance_ms)
        self.slice_ring = self.retention_ms // self.slice_width + 2
        self.family_retention_ms = self.retention_ms
        self.members = [
            _MemberSpec(
                query_id=None,
                size_ms=w.size_ms,
                advance_ms=w.advance_ms,
                grace_ms=self.grace_ms,
                retention_ms=self.retention_ms,
                agg_schema=self.agg.schema,
                post_ops=list(self.post_ops),
                sink_schema=self._emit_schema(),
                # the primary's own aggregates are the head of the shared
                # (union) partial set; extensions only ever append
                agg_map=list(range(len(self.agg_specs))),
            )
        ]

    # ------------------------------------------------ window-family sharing
    def family_signature(self) -> Optional[tuple]:
        """Hashable identity of this query's window family, or None when
        the shape cannot share a sliced pipeline.  Two queries with equal
        signatures differ only in window (size, advance, grace, retention)
        and post-aggregation projection — they can share one per-(key,
        slice) partial store with per-query combine fan-out."""
        if not self.sliced or self.source is None:
            return None
        if self.join is not None or self.join_chain or self.flatmap is not None:
            return None  # join/table state is per-pipeline; don't share it
        if any(isinstance(op, st.TableFilter) for op in self.post_ops):
            return None  # HAVING members would need per-member verdicts
        pre = tuple(
            (
                type(op).__name__,
                repr(getattr(op, "predicate", None)),
                repr(tuple(getattr(op, "selects", ()))),
                repr(tuple(getattr(op, "key_expressions", ()))),
            )
            for op in self.pre_ops
        )
        group = tuple(
            repr(e)
            for e in getattr(self.group, "group_by_expressions", ())
        )
        aggs = tuple(
            (spec.fname, repr(spec.arg_exprs)) for spec in self.agg_specs
        )
        fmts = getattr(self.source, "formats", None)
        return (
            self.source.topic,
            str(getattr(fmts, "value_format", "")),
            str(getattr(fmts, "key_format", "")),
            pre,
            group,
            aggs,
            tuple(c.type.base for c in self.agg.schema.key_columns),
        )

    def correlated_signature(self) -> Optional[tuple]:
        """The MQO's *correlated-window* grouping key (Factor Windows):
        :meth:`family_signature` minus the aggregate set — same source /
        formats / pre-ops / GROUP BY / key types, ANY sizes, advances and
        aggregates.  Members grouped by this signature share one slice
        ring through the shared (union) partial set."""
        sig = self.family_signature()
        if sig is None:
            return None
        return sig[:5] + sig[6:]  # drop the aggs element

    def agg_signature_keys(self) -> List[tuple]:
        """Identity of each shared aggregate partial — (function, args);
        the unit the shared-partial merge dedupes on."""
        return [(s.fname, repr(s.arg_exprs)) for s in self.agg_specs]

    def plan_family_merge(self, probe: "CompiledDeviceQuery") -> Dict[str, Any]:
        """What attaching ``probe`` would do to this shared pipeline:
        post-gcd slice width, re-priced ring span, the member's agg_map
        into the shared partial set, the genuinely NEW partials, and the
        live store size.  Pure planning — no mutation; shared by
        :meth:`attach_member` and the cost model (planner/mqo.py) so the
        two can never disagree."""
        import math as _math

        w = probe.window
        new_sw = _math.gcd(
            self.slice_width, W.slice_width(w.size_ms, w.advance_ms)
        )
        shared = {k: i for i, k in enumerate(self.agg_signature_keys())}
        agg_map: List[int] = []
        new_specs: List[_AggSpec] = []
        for spec in probe.agg_specs:
            k = (spec.fname, repr(spec.arg_exprs))
            j = shared.get(k)
            if j is None:
                j = len(self.agg_specs) + len(new_specs)
                shared[k] = j
                new_specs.append(spec)
            agg_map.append(j)
        new_ring = (
            max(
                self.retention_ms,
                probe.retention_ms,
                *[m.retention_ms for m in self.members],
            )
            // new_sw
            + 2
        )
        return {
            "width_ms": new_sw,
            "width_changed": new_sw != self.slice_width,
            "ring": new_ring,
            "agg_map": agg_map,
            "new_specs": new_specs,
            "store_rows": self._store_rows(),
        }

    def attach_member(
        self,
        plan: "st.QueryPlan",
        query_id: str,
        deliver: Callable[[List["SinkEmit"]], None],
        probe: Optional["CompiledDeviceQuery"] = None,
    ) -> None:
        """Join ``plan`` (correlated window: same source/pre-ops/GROUP BY,
        any size/advance/aggregate set) onto this sliced pipeline: one
        consumer, one device dispatch per tick, shared (union) partials,
        per-member window combine at emission.  Raises DeviceUnsupported
        when the plan is not family-compatible (the caller then builds it
        a standalone executor) and FamilyAttachRefused for the classified
        runtime refusals (re-gcd or new partials over a non-empty store,
        ring cap).  ``probe`` reuses a caller's analyze-only lowering of
        the same plan instead of re-analyzing."""
        if not self.sliced:
            raise DeviceUnsupported(
                "window-family sharing requires a sliced primary pipeline"
            )
        if probe is None:
            probe = CompiledDeviceQuery(
                plan, self.registry, capacity=1, analyze_only=True,
                slice_ring_max=self.slice_ring_max,
            )
        if not probe.sliced:
            raise DeviceUnsupported(
                probe.windowing_fallback
                or "family member is not sliced-eligible"
            )
        if probe.correlated_signature() != self.correlated_signature():
            raise DeviceUnsupported(
                "window family signature mismatch (source / pre-ops / "
                "GROUP BY / key types must be identical to share a "
                "sliced pipeline)"
            )
        merge = self.plan_family_merge(probe)
        new_sw, new_ring = merge["width_ms"], merge["ring"]
        store_rows = merge["store_rows"]
        if merge["width_changed"] and store_rows:
            raise FamilyAttachRefused(
                "reslice",
                f"window family slice-width change ({self.slice_width}ms "
                f"-> {new_sw}ms) requires an empty slice store "
                f"({store_rows} key slots live) — attach family members "
                "before data flows (or terminate and restart the family)",
                oldWidthMs=self.slice_width, newWidthMs=new_sw,
                storeRows=store_rows,
            )
        if merge["new_specs"] and store_rows:
            raise FamilyAttachRefused(
                "new-partials",
                f"{len(merge['new_specs'])} aggregate partial(s) new to "
                "the shared set require an empty slice store "
                f"({store_rows} key slots live) — already-folded slices "
                "hold no contributions for them",
                newPartials=len(merge["new_specs"]),
                storeRows=store_rows,
            )
        if new_ring > self.slice_ring_max:
            raise FamilyAttachRefused(
                "ring-cap",
                f"window family slice ring of {new_ring} slices exceeds "
                f"ksql.slicing.max.ring={self.slice_ring_max}",
                ring=new_ring, ringMax=self.slice_ring_max,
            )
        spec = _MemberSpec(
            query_id=query_id,
            size_ms=probe.window.size_ms,
            advance_ms=probe.window.advance_ms,
            grace_ms=probe.grace_ms,
            retention_ms=probe.retention_ms,
            agg_schema=probe.agg.schema,
            post_ops=list(probe.post_ops),
            sink_schema=probe._emit_schema(),
            deliver=deliver,
            agg_map=merge["agg_map"],
        )
        # atomic attach: every validation above has passed — mutate, and
        # roll everything back if the re-layout/recompile still raises, so
        # a failed attach can never leave a half-attached member spec
        # producing to the member's sink (nor a torn shared layout)
        snap = (
            list(self.members), self.family_retention_ms,
            list(self.agg_specs), self.layout, self.store_layout,
            self.slice_width, self.slice_ring, self._state,
        )
        # idempotent per query id: a member restart re-attaches in place
        self.members = [m for m in self.members if m.query_id != query_id]
        self.members.append(spec)
        self.family_retention_ms = max(m.retention_ms for m in self.members)
        try:
            if merge["new_specs"]:
                self._extend_shared_specs(merge["new_specs"])
            self._resize_ring(new_sw, max(new_ring, self.slice_ring))
            # eager shape check (the __init__ contract): any aggregate or
            # post-op expression the device cannot lower must surface NOW
            # — at the member's attach — not crash the primary's next tick
            jax.eval_shape(
                self._trace_step, jax.eval_shape(self.init_state),
                self.layout.array_structs(),
            )
        except Exception:
            (self.members, self.family_retention_ms, self.agg_specs,
             self.layout, self.store_layout, self.slice_width,
             self.slice_ring, self._state) = snap
            self._compile_steps()
            raise

    def detach_member(self, query_id: str) -> None:
        """Remove a terminated member; the ring keeps its width (slices
        already folded at the family slice width stay combinable)."""
        before = len(self.members)
        self.members = [m for m in self.members if m.query_id != query_id]
        if len(self.members) != before:
            self.family_retention_ms = max(
                m.retention_ms for m in self.members
            )
            self._compile_steps()

    def shared_member_ids(self) -> List[str]:
        return [m.query_id for m in self.members if m.query_id is not None]

    def _store_empty(self) -> bool:
        return self._store_rows() == 0

    def _store_rows(self) -> int:
        """Live key slots in the slice store (0 = empty; the precondition
        for width changes and shared-partial-set extensions)."""
        if self._state is None:
            return 0
        return int(jnp.sum(self._state["occ"][:-1]))

    def _extend_shared_specs(self, new_specs: List[_AggSpec]) -> None:
        """Grow the shared (union) partial set — empty store only, the
        caller has verified: append the new aggregates' components to the
        store layout, widen the ingress layout to cover their argument
        columns, and drop the (empty) state for lazy re-init at the new
        shapes.  Existing members' agg_maps stay valid: extension only
        ever appends."""
        base = len(self.agg_specs)
        self.agg_specs = list(self.agg_specs) + [
            dataclasses.replace(s, out_name=f"KSQL_AGG_VARIABLE_{base + i}")
            for i, s in enumerate(new_specs)
        ]
        comps = self._agg_components()
        self.store_layout = dataclasses.replace(
            self.store_layout, components=tuple(comps)
        )
        self._build_ingress_layout()
        self._state = None
        # mutate-then-recompile contract (graftlint jit-retrace): the
        # traced steps close over agg_specs/store_layout — re-jit here
        # (idempotent: the attach's _resize_ring recompiles again)
        self._compile_steps()

    def _spec_comp_starts(self) -> List[int]:
        """Starting store-component index of each shared aggregate spec
        (component 0 is the per-slot ts watermark)."""
        starts: List[int] = []
        idx = 1
        for spec in self.agg_specs:
            starts.append(idx)
            idx += len(spec.device.components)
        return starts

    # ------------------------------------------- shared source prefixes
    def prefix_signature(self) -> Optional[tuple]:
        """Hashable identity of this pipeline's shareable source prefix,
        or None when the shape cannot share a source scan: stateless
        Filter/Select chains over a plain StreamSource with a stream
        sink.  Members grouped by this signature run as residual branches
        of ONE shared device step (planner/mqo.py decides whether they
        should)."""
        if (
            self.agg is not None or self.join is not None or self.join_chain
            or self.ss_join is not None or self.tt_join is not None
            or self.fk_join is not None or self.flatmap is not None
            or self.table_mode or self.windowed_source or self.suppress
            or self.source is None or not isinstance(self.sink, st.StreamSink)
        ):
            return None
        if self._host_exprs:
            # host-computed encode columns are per-pipeline; a shared
            # layout cannot carry every member's host closures
            return None
        if any(
            not isinstance(op, (st.StreamFilter, st.StreamSelect))
            for op in self.pre_ops
        ):
            return None  # SelectKey repartitions don't share a scan
        fmts = getattr(self.source, "formats", None)
        src_schema = getattr(self.source, "schema", None)
        return (
            "prefix",
            self.source.topic,
            str(getattr(fmts, "value_format", "")),
            str(getattr(fmts, "key_format", "")),
            # the full declared source schema: two streams over ONE topic
            # with same-named differently-typed columns (a legitimate
            # multi-stream-per-topic pattern) must never share a scan —
            # the shared ingress layout encodes per the primary's types
            # and the member would decode garbage
            tuple(
                (c.name, repr(c.type))
                for c in (src_schema.columns() if src_schema else ())
            ),
            str(getattr(self.source, "timestamp_column", None)),
            str(getattr(self.source, "timestamp_format", None)),
        )

    def attach_prefix_member(
        self,
        plan: "st.QueryPlan",
        query_id: str,
        deliver: Callable[[List["SinkEmit"]], None],
        probe: Optional["CompiledDeviceQuery"] = None,
    ) -> None:
        """Join a compatible stateless query onto this pipeline's shared
        source prefix: the member's filter/project chain becomes a
        residual branch of the shared device step (its suffix past the
        common prefix), its rows delivered through ``deliver`` to its own
        sink.  Stateless — re-layout + recompile are always safe."""
        if probe is None:
            probe = CompiledDeviceQuery(
                plan, self.registry, capacity=1, analyze_only=True,
            )
        sig = probe.prefix_signature()
        if sig is None or sig != self.prefix_signature():
            raise DeviceUnsupported(
                "source-prefix signature mismatch (stateless "
                "filter/project chain over the same source topic and "
                "formats required to share a scan)"
            )
        spec = _PrefixMemberSpec(
            query_id=query_id,
            pre_ops=list(probe.pre_ops),
            sink_schema=probe._emit_schema(),
            deliver=deliver,
        )
        old = list(self.prefix_members)
        # idempotent per query id: a member restart re-attaches in place
        self.prefix_members = [
            m for m in self.prefix_members if m.query_id != query_id
        ]
        self.prefix_members.append(spec)
        try:
            self._rebuild_prefix_plumbing()
        except Exception:
            self.prefix_members = old
            self._rebuild_prefix_plumbing()
            raise

    def detach_prefix_member(self, query_id: str) -> None:
        """Remove a terminated prefix member and shrink the shared layout
        back to the surviving chains."""
        before = len(self.prefix_members)
        self.prefix_members = [
            m for m in self.prefix_members if m.query_id != query_id
        ]
        if len(self.prefix_members) != before:
            self._rebuild_prefix_plumbing()

    def shared_prefix_member_ids(self) -> List[str]:
        return [m.query_id for m in self.prefix_members]

    def _rebuild_prefix_plumbing(self) -> None:
        """Recompute the shared prefix (longest structurally-common run of
        leading steps across the primary's and every member's chain),
        widen the ingress layout to the union of reads, recompile, and
        eagerly shape-check so an unlowerable member residual surfaces at
        attach, not on the primary's next tick."""
        chains = [self.pre_ops] + [m.pre_ops for m in self.prefix_members]
        shared = 0
        if self.prefix_members:
            limit = min(len(c) for c in chains)
            while shared < limit:
                fps = {_op_fingerprint(c[shared]) for c in chains}
                if len(fps) != 1:
                    break
                shared += 1
        self._prefix_shared_len = shared
        self._build_ingress_layout()
        self._compile_steps()
        jax.eval_shape(
            self._trace_step, jax.eval_shape(self.init_state),
            self.layout.array_structs(),
        )

    #: host mirrors driving pre-dispatch ring sizing: a LOWER bound on the
    #: device stream clock (read back with the per-batch load counters) and
    #: the oldest slice index any batch could have written
    _mirror_max_ts: int = -(2 ** 62)
    _host_min_slice: int = 2 ** 62

    def ensure_ring_for(self, ts: np.ndarray, valid: np.ndarray) -> None:
        """Pre-dispatch ring sizing: the ring must span every slice that is
        simultaneously LIVE this batch — from the admission floor (the
        oldest slice a still-open window can cover: stream time − family
        retention) up to the batch's newest slice — or two live slices
        would fold into one ring cell.  Timestamps are host-visible before
        dispatch, and the floor is conservatively bounded by host mirrors
        (a lagging lower bound on the device stream clock, and the oldest
        slice ever sent), so growth here is exact-or-conservative and the
        in-trace horizon cut only ever fires past the hard
        ksql.slicing.max.ring cap."""
        if not self.sliced or ts.size == 0:
            return
        v = np.asarray(valid, bool)
        if not v.any():
            return
        tt = np.asarray(ts)[v]
        width = self.slice_width
        smin = int(tt.min()) // width
        smax = int(tt.max()) // width
        self._host_min_slice = min(self._host_min_slice, smin)
        floor = self._host_min_slice
        if self._mirror_max_ts > -(2 ** 61):
            # the admission cut in-trace uses the batch-START stream clock:
            # anything below clock − retention never reaches a ring cell,
            # so the ring need not span it (an ancient replayed record in
            # an old batch must not keep the sizing pinned wide forever)
            floor = max(
                floor,
                (self._mirror_max_ts - self.family_retention_ms) // width,
            )
        needed = smax - min(floor, smax) + 2
        target = min(needed, self.slice_ring_max)
        if needed > self.slice_ring and target != self.slice_ring:
            # skip the no-op resize once pinned at the cap: _resize_ring
            # recompiles unconditionally (load-bearing for attach/detach),
            # and a per-batch retrace would collapse throughput
            self._resize_ring(self.slice_width, target)
        # after THIS batch folds, the device clock is ≥ the batch max —
        # advance the mirror host-side so the next batch's floor is tight
        # even before (or without) a device readback
        self._mirror_max_ts = max(self._mirror_max_ts, int(tt.max()))

    def _resize_ring(self, new_sw: int, new_ring: int) -> None:
        """Re-shape the slice ring for a changed family (slice width and/or
        ring span).  Live partials are remapped host-side by their absolute
        slice index; a width change only happens on an empty store (checked
        by the caller), so no partial ever needs splitting."""
        width_changed = new_sw != self.slice_width
        ring_changed = new_ring != self.slice_ring
        self.slice_width = new_sw
        self.slice_ring = new_ring
        if ring_changed or width_changed:
            self.store_layout = dataclasses.replace(
                self.store_layout,
                components=tuple(
                    dataclasses.replace(c, width=new_ring)
                    for c in self.store_layout.components
                ),
            )
            if self._state is not None and not self._store_empty():
                self._regrow_ring(new_ring)
            else:
                self._state = None  # lazy re-init at the new shapes
        self._compile_steps()

    def _regrow_ring(self, new_ring: int) -> None:
        """Host-side ring regrow: every live (slot, slice) partial moves to
        ``slice_id % new_ring`` in the widened arrays (new_ring >= the live
        span, so no two live slices of one key collide)."""
        old = {
            k: np.asarray(v) for k, v in jax.device_get(dict(self.state)).items()
        }
        new = dict(old)
        ids = old["slice_id"]
        live = ids >= 0
        rix, cix = np.nonzero(live)
        npos = (ids[rix, cix] % new_ring).astype(np.int64)
        c1 = ids.shape[0]
        nid = np.full((c1, new_ring), -1, np.int64)
        nid[rix, npos] = ids[rix, cix]
        new["slice_id"] = nid
        for j, comp in enumerate(self.store_layout.components):
            col = old[f"a{j}"]
            ncol = np.full(
                (c1, new_ring), comp.init, dtype=np.dtype(comp.dtype)
            )
            ncol[rix, npos] = col[rix, cix]
            new[f"a{j}"] = ncol
        # jnp.array (copy), not asarray: rebuilt host buffers must never be
        # zero-copy aliased into donated jit state
        self.state = {k: jnp.array(v) for k, v in new.items()}

    # ----------------------------------------------- sliced fold + combine
    def _sliced_scatter(
        self,
        store: Dict[str, jnp.ndarray],
        slots: jnp.ndarray,
        payload: Dict[str, jnp.ndarray],
        contribs: Sequence[jnp.ndarray],
    ) -> Dict[str, jnp.ndarray]:
        """Fold per-row contributions into each key slot's slice ring at
        ``ring_pos = (slice_index % slice_ring)``.  A targeted ring cell
        whose stored slice_id differs is a recycled cell from an earlier
        ring wrap: it resets to the component inits first (idempotent —
        every batch row targeting one cell carries the SAME slice index,
        guaranteed by the pre_exchange ring-wrap horizon cut)."""
        store = dict(store)
        active = payload["active"]
        dump = jnp.int32(self.store_capacity)
        ring = self.slice_ring
        sidx = payload["wstart"] // self.slice_width  # absolute slice index
        pos = jnp.remainder(sidx, ring).astype(jnp.int32)
        eff = jnp.where(active, slots, dump)
        live = active & (slots != dump)
        cur = store["slice_id"][eff, pos]
        stale = live & (cur != sidx)
        tgt_stale = jnp.where(stale, eff, dump)
        for j, comp in enumerate(self.store_layout.components):
            col = store[f"a{j}"]
            init = jnp.asarray(comp.init, col.dtype)
            # duplicate (slot, pos) writers all write the same init value,
            # so the unordered scatter-set stays deterministic
            col = col.at[tgt_stale, pos].set(init)
            ref = col.at[eff, pos]
            contrib = contribs[j]
            if comp.combine == "add":
                col = ref.add(contrib.astype(col.dtype))
            elif comp.combine == "min":
                col = ref.min(contrib.astype(col.dtype))
            else:  # 'max' — _slice_ineligibility admits only the monoids
                col = ref.max(contrib.astype(col.dtype))
            store[f"a{j}"] = col
        tgt_live = jnp.where(live, eff, dump)
        store["slice_id"] = store["slice_id"].at[tgt_live, pos].set(sidx)
        store["slast"] = store["slast"].at[eff].max(
            jnp.where(live, payload["wstart"], -(2 ** 62))
        )
        store["dirty"] = store["dirty"].at[eff].set(True)
        store["dirty"] = store["dirty"].at[self.store_capacity].set(False)
        return store

    def _combine_windows(
        self,
        store: Dict[str, jnp.ndarray],
        slot_lane: jnp.ndarray,
        w_lane: jnp.ndarray,
        member: _MemberSpec,
    ) -> Tuple[Dict[str, DCol], jnp.ndarray, jnp.ndarray]:
        """Monoid-merge the covering slices of each (slot, window) lane and
        finalize into an expression env over the aggregate schema.

        ``w_lane`` is the window start in SLICE units; the window covers
        slices ``w .. w + spw - 1``.  A ring cell whose slice_id mismatches
        the expected absolute index reads as the component init (identity),
        which is how empty and recycled cells drop out of the merge."""
        nn = int(slot_lane.shape[0])
        S = W.slices_per_window(member.size_ms, self.slice_width)
        t = jnp.arange(S, dtype=jnp.int64)
        slice_ids = w_lane[:, None] + t[None, :]  # (nn, S)
        pos = jnp.remainder(slice_ids, self.slice_ring).astype(jnp.int32)
        slot2 = slot_lane[:, None]
        idok = store["slice_id"][slot2, pos] == slice_ids
        view: Dict[str, jnp.ndarray] = {}
        for j, comp in enumerate(self.store_layout.components):
            col = store[f"a{j}"][slot2, pos]  # (nn, S)
            init = jnp.asarray(comp.init, col.dtype)
            colm = jnp.where(idok, col, init)
            if comp.combine == "add":
                view[f"a{j}"] = jnp.sum(colm, axis=1)
            elif comp.combine == "min":
                view[f"a{j}"] = jnp.min(colm, axis=1)
            else:  # 'max'
                view[f"a{j}"] = jnp.max(colm, axis=1)
        view["knull"] = store["knull"][slot_lane]
        view["wstart"] = w_lane * self.slice_width
        for i in range(len(self.key_types)):
            view[f"key{i}"] = store[f"key{i}"][slot_lane]
        ident = jnp.arange(nn, dtype=jnp.int32)
        return self._finalized_env(
            view, ident, nn, wsize_ms=member.size_ms,
            agg_schema=member.agg_schema, agg_map=member.agg_map,
        )

    def _member_emit(
        self,
        env: Dict[str, DCol],
        row_ts: jnp.ndarray,
        dec_exceeded: jnp.ndarray,
        mask: jnp.ndarray,
        member: _MemberSpec,
        nn: int,
    ) -> Dict[str, jnp.ndarray]:
        """Post-aggregation ops + emission packing for one family member.
        Sliced pipelines never carry HAVING-retraction state (ineligible),
        so TableFilter here only narrows the mask."""
        for op in member.post_ops:
            c = JaxExprCompiler(env, nn, self.dictionary)
            if isinstance(op, st.TableFilter):
                pred = c.compile(op.predicate)
                mask = mask & pred.valid & pred.data.astype(bool)
            else:  # TableSelect
                new_env: Dict[str, DCol] = {}
                src_keys = [k.name for k in op.source.schema.key_columns]
                out_keys = [k.name for k in op.schema.key_columns]
                for new_name, old_name in zip(out_keys, src_keys):
                    if old_name in env:
                        new_env[new_name] = env[old_name]
                for name, e in op.selects:
                    new_env[name] = c.compile(e)
                for p in ("ROWTIME", "WINDOWSTART", "WINDOWEND"):
                    if p in env:
                        new_env[p] = env[p]
                env = new_env
        emits = self._pack_emits(
            env, mask, row_ts, schema=member.sink_schema
        )
        emits["dec_envelope"] = jnp.sum(
            (dec_exceeded & mask).astype(jnp.int64)
        ).reshape(1)
        return emits

    def _sliced_member_emits(
        self,
        store: Dict[str, jnp.ndarray],
        slots: jnp.ndarray,
        payload: Dict[str, jnp.ndarray],
        member: _MemberSpec,
        max_ts_pre: jnp.ndarray,
    ) -> Dict[str, jnp.ndarray]:
        """One member's per-batch emission: every still-open window of this
        member covering a touched slice emits one coalesced change (the
        expansion path's one-change-per-(key, window)-per-batch cadence,
        at O(touched · k) combine lanes instead of O(rows · k) state
        lanes)."""
        active = payload["active"] & (slots != jnp.int32(self.store_capacity))
        n = int(active.shape[0])
        width = self.slice_width
        S = W.slices_per_window(member.size_ms, width)
        A = member.advance_ms // width
        k = W.hopping_expansion(member.size_ms, member.advance_ms)
        nn = n * k
        dump = jnp.int32(self.store_capacity)
        sidx = payload["wstart"] // width
        newest = sidx - jnp.remainder(sidx, A)  # newest covering window
        hops = jnp.repeat(jnp.arange(k, dtype=jnp.int64), n)
        w_lane = jnp.tile(newest, k) - hops * A  # window start, slice units
        s_lane = jnp.tile(sidx, k)
        slot_lane = jnp.tile(slots, k)
        act_lane = jnp.tile(active, k)
        covers = (w_lane + S > s_lane) & (w_lane >= 0)
        open_w = (
            w_lane * width + member.size_ms + member.grace_ms > max_ts_pre
        )
        mask = act_lane & covers & open_w
        # one lane per distinct (slot, window): sort-based first-occurrence
        # (two touched slices of one key can cover the same window)
        eff_slot = jnp.where(mask, slot_lane, dump)
        eff_w = jnp.where(mask, w_lane, jnp.int64(np.iinfo(np.int64).max))
        lane_idx = jnp.arange(nn)
        order = jnp.lexsort((lane_idx, eff_w, eff_slot))
        so_s, so_w = eff_slot[order], eff_w[order]
        first = (
            (so_s != jnp.concatenate([jnp.full((1,), -1, so_s.dtype), so_s[:-1]]))
            | (so_w != jnp.concatenate([so_w[:1] + 1, so_w[:-1]]))
        ).at[0].set(True)
        winner = jnp.zeros(nn, bool).at[order].set(first & (so_s != dump))
        winner = winner & mask
        env, row_ts, dec_exceeded = self._combine_windows(
            store, slot_lane, w_lane, member
        )
        return self._member_emit(
            env, row_ts, dec_exceeded, winner, member, nn
        )

    # ----------------------------------------------------------- state mgmt
    def changelog_dirty_state(self) -> Dict[str, Any]:
        """Dirty-set seam for the incremental changelog journal
        (runtime/changelog.py): one commit-point host capture in
        checkpoint-serde shape.  The journal diffs consecutive captures,
        so only the ring/agg/join cells a tick actually touched reach
        the frame."""
        from ksql_tpu.runtime.checkpoint import _snapshot_device

        return _snapshot_device(self)

    def changelog_apply_state(self, data: Dict[str, Any]) -> None:
        """Inverse of changelog_dirty_state — restore a (possibly
        journal-patched) capture.  Host arrays are copied on the way in
        (_unflatten_state uses jnp.array) so journal-decoded buffers
        never alias donated jit state."""
        from ksql_tpu.runtime.checkpoint import _restore_device

        _restore_device(self, data)

    def init_state(self) -> Dict[str, jnp.ndarray]:
        if self.store_layout is None:
            state = {"max_ts": jnp.array(np.iinfo(np.int64).min, jnp.int64)}
            if self.tt_join is not None:
                state["ttab"] = self._init_tt_store()
            if self.fk_join is not None:
                state["fkl"] = self._init_fk_store("l")
                state["fkr"] = self._init_fk_store("r")
            for i in range(len(self.join_chain)):
                state[self._jtab_key(i)] = self._init_table_store(i)
            if self.ss_join is not None:
                b1 = self.ss_capacity + 1
                for s in ("l", "r"):
                    state[f"ss{s}_ts"] = jnp.zeros(b1, jnp.int64)
                    state[f"ss{s}_krepr"] = jnp.zeros(b1, jnp.int64)
                    state[f"ss{s}_kval"] = jnp.zeros(b1, bool)
                    state[f"ss{s}_live"] = jnp.zeros(b1, bool)
                    state[f"ss{s}_matched"] = jnp.zeros(b1, bool)
                    state[f"ss{s}_seq"] = jnp.zeros(b1, jnp.int64)
                    for col in self.ss_cols[s]:
                        state[f"ss{s}_v_{col.name}"] = jnp.zeros(
                            b1, self._table_col_dtype(col)
                        )
                        state[f"ss{s}_m_{col.name}"] = jnp.zeros(b1, bool)
                    state[f"ss{s}_cursor"] = jnp.zeros((), jnp.int64)
                    state[f"ss{s}_smax"] = jnp.array(
                        np.iinfo(np.int64).min, jnp.int64
                    )
            return state
        state = init_store(self.store_layout)
        if self.sliced:
            c1 = self.store_capacity + 1
            # absolute slice index stored per ring cell (-1 = empty); a
            # gather whose expected index mismatches reads as identity —
            # that is how stale cells from a previous ring wrap die
            state["slice_id"] = jnp.full(
                (c1, self.slice_ring), -1, jnp.int64
            )
            # newest slice start folded per key slot (drives eviction)
            state["slast"] = jnp.full(c1, -(2 ** 62), jnp.int64)
        if self._needs_seq:
            state["agg_seq"] = jnp.zeros((), jnp.int64)
        if self._having_retract():
            # per-slot "previously passed HAVING": a pass->fail transition
            # on an EMIT CHANGES table emits a tombstone (the oracle's
            # HAVING retraction semantics, TableFilterBuilder)
            state["hpass"] = jnp.zeros(self.store_capacity + 1, bool)
        if self.session:
            c1 = self.store_capacity + 1
            state["sess_start"] = jnp.zeros(c1, jnp.int64)
            state["sess_end"] = jnp.zeros(c1, jnp.int64)
        for i in range(len(self.join_chain)):
            state[self._jtab_key(i)] = self._init_table_store(i)
        if self.suppress:
            # EMIT FINAL emission clock: stream time over ALL source records
            # (even rows later dropped by filters / null group keys), matching
            # the oracle executor's stream_time; `max_ts` (the aggregate's
            # clock, post-filter rows only) keeps driving late-record drops
            state["emit_clock"] = jnp.array(np.iinfo(np.int64).min, jnp.int64)
            # first-touch order per slot: ties in final-emission order (same
            # window end) break by window creation order, as the oracle's
            # insertion-ordered buffer does
            state["born"] = jnp.full(
                self.store_capacity + 1, np.iinfo(np.int64).max, jnp.int64
            )
            state["row_clock"] = jnp.zeros((), jnp.int64)
            # a window emits its final result exactly once: late-but-in-grace
            # records may re-dirty an emitted slot (the oracle accepts them
            # into state but its `emitted` set blocks re-emission)
            state["emitted"] = jnp.zeros(self.store_capacity + 1, bool)
        return state

    # --------------------------------------------- join table store (device)
    def _table_col_dtype(self, col) -> Any:
        return np.int64 if col.type.base in _HASHED else col.type.device_dtype()

    def _init_table_store(self, idx: int = -1) -> Dict[str, jnp.ndarray]:
        """Device table store for one join probe's right side: a keyed hash
        store (pk repr in key0) whose per-column value arrays are
        overwritten last-write-wins — the RocksDB-materialized KTable analog
        (SourceBuilderBase forced materialization)."""
        jspec = self.join_chain[idx]
        lay = StoreLayout(capacity=jspec.capacity, num_keys=1, components=())
        s = init_store(lay)
        c1 = jspec.capacity + 1
        for col in jspec.cols:
            s[f"v_{col.name}"] = jnp.zeros(c1, self._table_col_dtype(col))
            s[f"m_{col.name}"] = jnp.zeros(c1, bool)
        return s

    def _table_array_structs(self, idx: int = -1) -> Dict[str, Any]:
        out = self.join_chain[idx].layout.array_structs()
        out["delete"] = jax.ShapeDtypeStruct((self.capacity,), np.bool_)
        return out

    def _trace_table_step(
        self, state: Dict[str, jnp.ndarray], arrays: Dict[str, jnp.ndarray],
        idx: int = -1,
    ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        """Fold one batch of table-changelog records into one join probe's
        device table store.  Upserts overwrite last-write-wins (one winner
        per slot per batch); tombstones free the slot (grave — probe chains
        stay intact until the host rebuild compacts)."""
        jspec = self.join_chain[idx]
        key = self._jtab_key(idx)
        n = self.capacity
        env = self._source_env(arrays, jspec.layout)
        active = arrays["row_valid"]
        env, active = self._apply_ops(jspec.table_pre_ops, env, active, n)
        c = JaxExprCompiler(env, n, self.dictionary)
        kcol = c.compile(jspec.step.right_key)
        krepr = _repr64(kcol)
        khash = combine_hash([krepr])
        act = active & kcol.valid
        cap_t = jspec.capacity
        dump = jnp.int32(cap_t)
        zeros64 = jnp.zeros(n, jnp.int64)
        jt, slots = probe_insert(
            dict(state[key]), cap_t, khash, zeros64, [krepr],
            jnp.zeros(n, jnp.int32), act,
        )
        rowidx = jnp.arange(n, dtype=jnp.int32)
        last = jnp.full(cap_t + 1, -1, jnp.int32).at[
            jnp.where(act, slots, dump)
        ].max(rowidx)
        winner = act & (slots != dump) & (last[slots] == rowidx)
        delete = arrays["delete"]
        up = winner & ~delete
        tgt = jnp.where(up, slots, dump)
        for col in jspec.cols:
            d = env[col.name]
            dt = self._table_col_dtype(col)
            jt[f"v_{col.name}"] = jt[f"v_{col.name}"].at[tgt].set(
                d.data.astype(dt)
            )
            jt[f"m_{col.name}"] = jt[f"m_{col.name}"].at[tgt].set(d.valid)
        dl = winner & delete
        tgtd = jnp.where(dl, slots, dump)
        occ = jt["occ"].at[tgtd].set(False).at[cap_t].set(False)
        grave = jt["grave"].at[tgtd].set(True).at[cap_t].set(False)
        # deleted-then-reinserted within a batch resolved by the winner; a
        # delete winner leaves a grave, a later batch's insert reclaims it
        jt["occ"], jt["grave"] = occ, grave
        state = dict(state)
        state[key] = jt
        metrics = {
            "occupancy": jnp.sum(occ | grave),
            "overflow": jt["overflow"],
        }
        return state, metrics

    def _jtabs_of(self, state) -> Dict[str, Dict[str, jnp.ndarray]]:
        """The chain's join stores keyed by their state names."""
        return {
            self._jtab_key(i): state[self._jtab_key(i)]
            for i in range(len(self.join_chain))
        }

    def _jtab_key(self, idx: int) -> str:
        """State key for probe ``idx``: the outermost store keeps the legacy
        name 'jtab' (distributed replication + checkpoints address it);
        inner probes of an n-way chain get 'jtab<i>'."""
        if idx < 0:
            idx += len(self.join_chain)
        return "jtab" if idx == len(self.join_chain) - 1 else f"jtab{idx}"

    # ------------------------------------------------- table aggregation
    def _ta_side(
        self, store: Dict[str, jnp.ndarray], arrays: Dict[str, jnp.ndarray],
        undo: bool,
    ):
        """One side of a table-aggregation step: pre-ops + group keys +
        (sign-adjusted) contributions folded into the store.  Undo probes
        find-only (a missing group means the old row never aggregated)."""
        n = self.capacity
        cap = self.store_capacity
        dump = jnp.int32(cap)
        env = self._source_env(arrays)
        active = arrays["row_valid"]
        env, active = self._apply_ops(self.pre_ops, env, active, n)
        ts = arrays["ts"]
        c = JaxExprCompiler(env, n, self.dictionary)
        group_exprs = tuple(getattr(self.group, "group_by_expressions", ()))
        if group_exprs:
            key_cols = [c.compile(e) for e in group_exprs]
        else:
            key_cols = [env[col.name] for col in self.group.schema.key_columns]
        reprs = [_repr64(kc) for kc in key_cols]
        knull = jnp.zeros(n, jnp.int32)
        for i, kc in enumerate(key_cols):
            knull = knull | (~kc.valid).astype(jnp.int32) << i
        active = active & (knull == 0)
        khash = combine_hash(reprs + [knull.astype(jnp.int64)])
        contribs: List[jnp.ndarray] = [
            jnp.where(active, ts, np.iinfo(np.int64).min)
        ]
        for spec in self.agg_specs:
            args = [c.compile(e) for e in spec.arg_exprs]
            if undo and spec.device.undo_contribs is not None:
                cs = spec.device.undo_contribs(args, active)
            else:
                cs = spec.device.contribs(args, active, None)
                if undo:
                    cs = [-x for x in cs]  # all-'add': undo = negate
            contribs.extend(cs)
        zeros64 = jnp.zeros(n, jnp.int64)
        if undo:
            slots = probe_find(store, cap, khash, zeros64, active)
            active = active & (slots != dump)
        else:
            store, slots = probe_insert(
                store, cap, khash, zeros64, reprs, knull, active
            )
        slot_or_dump = jnp.where(active, slots, dump)
        store = scatter_combine(
            store, self.store_layout, slot_or_dump, contribs,
            # removal (negative vec heads, collect_list undo) traces only
            # into the undo side — the apply side never carries them
            vec_undo=undo,
        )
        return store, slot_or_dump, active, ts

    def _trace_table_agg_step(
        self,
        state: Dict[str, jnp.ndarray],
        a_new: Dict[str, jnp.ndarray],
        a_old: Dict[str, jnp.ndarray],
    ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        """Aggregate one batch of table changes: undo old rows, apply new
        rows, emit one change per touched group per side — the batched
        KGroupedTable subtractor/adder (KudafUndoAggregator analog)."""
        store = dict(state)
        n = self.capacity
        store, slots_old, act_old, ts_old = self._ta_side(store, a_old, True)
        e_old = self._emit_agg(
            store, slots_old,
            winners_per_slot(slots_old, act_old, self.store_capacity),
            n, ts_override=ts_old,
        )
        store, slots_new, act_new, ts_new = self._ta_side(store, a_new, False)
        e_new = self._emit_agg(
            store, slots_new,
            winners_per_slot(slots_new, act_new, self.store_capacity),
            n, ts_override=ts_new,
        )
        emits = {
            k: jnp.concatenate([e_old[k], e_new[k]]) for k in e_old
        }
        neg = np.iinfo(np.int64).min
        batch_max = jnp.maximum(
            jnp.max(jnp.where(act_old, ts_old, neg)),
            jnp.max(jnp.where(act_new, ts_new, neg)),
        )
        store["max_ts"] = jnp.maximum(store["max_ts"], batch_max)
        emits["occupancy"] = jnp.sum(store["occ"] | store["grave"])
        emits["graves"] = jnp.sum(store["grave"])
        emits["overflow"] = store["overflow"]
        return store, emits

    def _upsert_side(
        self, store, cols, env, touched, slots, has_new, act_valid, cap,
        prefix: str = "", live_key: str = "live",
    ):
        """Last-writer-wins upsert of one side's columns + liveness (shared
        by the table-table and fk join store updates); returns the write
        targets so callers can add side-specific columns (e.g. fk reprs)."""
        dump = jnp.int32(cap)
        n = touched.shape[0]
        rowidx = jnp.arange(n, dtype=jnp.int32)
        found = slots != dump
        last = jnp.full(cap + 1, -1, jnp.int32).at[
            jnp.where(touched, slots, dump)
        ].max(rowidx)
        winner = touched & found & (last[slots] == rowidx)
        up = winner & has_new
        tgt = jnp.where(up, slots, dump)
        for col in cols:
            d = env[col.name]
            dt = self._table_col_dtype(col)
            store[f"{prefix}v_{col.name}"] = store[
                f"{prefix}v_{col.name}"
            ].at[tgt].set(d.data.astype(dt))
            store[f"{prefix}m_{col.name}"] = store[
                f"{prefix}m_{col.name}"
            ].at[tgt].set(d.valid & act_valid)
        live = store[live_key].at[tgt].set(True)
        tgtd = jnp.where(winner & ~has_new, slots, dump)
        live = live.at[tgtd].set(False).at[cap].set(False)
        store[live_key] = live
        return tgt

    # ------------------------------------------------- foreign-key join
    def _init_fk_store(self, side: str) -> Dict[str, jnp.ndarray]:
        """Keyed store for one fk-join side; the left side also carries its
        fk repr (scanned on right changes) and both carry liveness."""
        lay = StoreLayout(
            capacity=self.fk_store_capacity, num_keys=1, components=()
        )
        s = init_store(lay)
        c1 = self.fk_store_capacity + 1
        s["live"] = jnp.zeros(c1, bool)
        if side == "l":
            s["fkrepr"] = jnp.zeros(c1, jnp.int64)
            s["fkvalid"] = jnp.zeros(c1, bool)
        for col in self.fk_cols[side]:
            s[f"v_{col.name}"] = jnp.zeros(c1, self._table_col_dtype(col))
            s[f"m_{col.name}"] = jnp.zeros(c1, bool)
        return s

    def _fk_env(
        self, side: str, arrays: Dict[str, jnp.ndarray]
    ) -> Tuple[Dict[str, DCol], jnp.ndarray]:
        ops = self.fk_left_ops if side == "l" else self.fk_right_ops
        env = self._source_env(arrays, self.fk_layouts[side])
        active = arrays["row_valid"]
        return self._apply_ops(ops, env, active, self.capacity)

    def _fk_joined(
        self, lenv: Dict[str, DCol], l_present: jnp.ndarray,
        renv: Dict[str, DCol], r_present: jnp.ndarray, n: int,
    ) -> Tuple[Dict[str, DCol], jnp.ndarray]:
        """Joined env + validity: INNER needs both sides, LEFT pads right."""
        from ksql_tpu.parser.ast_nodes import JoinType

        env: Dict[str, DCol] = {}
        for col in self.fk_cols["l"]:
            d = lenv[col.name]
            env[col.name] = DCol(d.data, d.valid & l_present, col.type)
        for col in self.fk_cols["r"]:
            d = renv[col.name]
            env[col.name] = DCol(d.data, d.valid & r_present, col.type)
        if self.fk_join.join_type == JoinType.INNER:
            jok = l_present & r_present
        else:  # LEFT
            jok = l_present
        return env, jok

    def _trace_fk_left(self, state, a_new, a_old):
        """One batch of LEFT-table changes: update the left store (pk, fk,
        columns), join old/new rows against the resident right row for
        their fk, run the transform chain, emit rows/tombstones."""
        n = self.capacity
        cap = self.fk_store_capacity
        dump = jnp.int32(cap)
        fkl = dict(state["fkl"])
        fkr = state["fkr"]
        env_new, act_new = self._fk_env("l", a_new)
        env_old, act_old = self._fk_env("l", a_old)
        has_new = a_new["delete"] == 0
        has_old = a_old["row_valid"]
        key_col = self.fk_join.left.schema.key_columns[0]
        kcol = env_new[key_col.name]
        krepr = _repr64(kcol)
        khash = combine_hash([krepr])
        touched = a_new["row_valid"] & kcol.valid
        zeros64 = jnp.zeros(n, jnp.int64)
        fkl, slots = probe_insert(
            fkl, cap, khash, zeros64, [krepr], jnp.zeros(n, jnp.int32),
            touched,
        )
        cfk = JaxExprCompiler(env_new, n, self.dictionary)
        fk_new = cfk.compile(self.fk_join.foreign_key_expression)
        cfo = JaxExprCompiler(env_old, n, self.dictionary)
        fk_old = cfo.compile(self.fk_join.foreign_key_expression)

        def right_of(fk):
            rh = combine_hash([_repr64(fk)])
            rslots = probe_find(
                fkr, cap, rh, jnp.zeros(n, jnp.int64), fk.valid
            )
            rfound = fk.valid & (rslots != dump) & fkr["live"][rslots]
            renv = {
                col.name: DCol(
                    fkr[f"v_{col.name}"][rslots],
                    fkr[f"m_{col.name}"][rslots] & rfound,
                    col.type,
                )
                for col in self.fk_cols["r"]
            }
            return renv, rfound

        renv_old, rok_old = right_of(fk_old)
        renv_new, rok_new = right_of(fk_new)
        l_old = act_old & has_old
        l_new = act_new & a_new["row_valid"] & has_new
        jenv_old, jok_old = self._fk_joined(env_old, l_old, renv_old, rok_old, n)
        jenv_new, jok_new = self._fk_joined(env_new, l_new, renv_new, rok_new, n)
        for out_key in self.fk_join.schema.key_columns:
            # the result key is the left pk: valid even for delete rows
            jenv_old[out_key.name] = kcol
            jenv_new[out_key.name] = kcol
        fenv_new, fok_new = self._apply_ops(self.pre_ops, jenv_new, jok_new, n)
        _, fok_old = self._apply_ops(self.pre_ops, jenv_old, jok_old, n)
        # a left-row delete forwards a (null, null) change; it survives to
        # the sink as a tombstone only through a filter-free chain (the
        # oracle's FilterNode drops a change neither side of which passes)
        if any(isinstance(op, st.TableFilter) for op in self.pre_ops):
            left_delete = jnp.zeros(n, bool)
        else:
            left_delete = a_new["row_valid"] & ~has_new & has_old
        tgt = self._upsert_side(
            fkl, self.fk_cols["l"], env_new, touched, slots, has_new,
            act_new, cap,
        )
        fkl["fkrepr"] = fkl["fkrepr"].at[tgt].set(_repr64(fk_new))
        fkl["fkvalid"] = fkl["fkvalid"].at[tgt].set(fk_new.valid)
        state = dict(state)
        state["fkl"] = fkl
        emits = self._pack_emits(
            fenv_new, fok_new | fok_old | left_delete, a_new["ts"]
        )
        emits["tombstone"] = ~fok_new
        emits["occupancy"] = jnp.sum(fkl["occ"] | fkl["grave"])
        emits["overflow"] = fkl["overflow"] + fkr["overflow"]
        return state, emits

    def _trace_fk_right(self, state, a_new, a_old):
        """One RIGHT-table change (per-record): update the right store,
        then fan out over every resident left row whose fk matches —
        a vectorized scan of the left store's fk column."""
        n = self.capacity
        cap = self.fk_store_capacity
        dump = jnp.int32(cap)
        fkr = dict(state["fkr"])
        fkl = state["fkl"]
        env_new, act_new = self._fk_env("r", a_new)
        env_old, act_old = self._fk_env("r", a_old)
        has_new = a_new["delete"] == 0
        has_old = a_old["row_valid"]
        key_col = self.fk_join.right.schema.key_columns[0]
        kcol = env_new[key_col.name]
        krepr = _repr64(kcol)
        khash = combine_hash([krepr])
        touched = a_new["row_valid"] & kcol.valid
        zeros64 = jnp.zeros(n, jnp.int64)
        fkr, slots = probe_insert(
            fkr, cap, khash, zeros64, [krepr], jnp.zeros(n, jnp.int32),
            touched,
        )
        # store update first: the fan-out reads left rows, not the right
        # store (old/new right values come from this change)
        self._upsert_side(
            fkr, self.fk_cols["r"], env_new, touched, slots, has_new,
            act_new, cap,
        )
        state = dict(state)
        state["fkr"] = fkr
        # ---- fan-out over the left store (per-record: row 0 is the change)
        m = cap + 1
        match = (
            fkl["live"]
            & fkl["fkvalid"]
            & (fkl["fkrepr"] == krepr[0])
            & touched[0]
        )
        lenv = {
            col.name: DCol(
                fkl[f"v_{col.name}"], fkl[f"m_{col.name}"] & match, col.type
            )
            for col in self.fk_cols["l"]
        }

        def bcast(env_side, present_row):
            return (
                {
                    col.name: DCol(
                        jnp.broadcast_to(d.data[:1], (m,) + d.data.shape[1:]),
                        jnp.broadcast_to(d.valid[:1], (m,)) & present_row,
                        col.type,
                    )
                    for col in self.fk_cols["r"]
                    for d in (env_side[col.name],)
                },
                jnp.broadcast_to(present_row, (m,)),
            )

        renv_old, r_old_p = bcast(env_old, (act_old & has_old)[:1])
        renv_new, r_new_p = bcast(
            env_new, (act_new & a_new["row_valid"] & has_new)[:1]
        )
        jenv_old, jok_old = self._fk_joined(lenv, match, renv_old, r_old_p, m)
        jenv_new, jok_new = self._fk_joined(lenv, match, renv_new, r_new_p, m)
        lkey_t = self.fk_join.left.schema.key_columns[0].type
        lkey = DCol(self._decode_key64(fkl["key0"], lkey_t), match, lkey_t)
        for out_key in self.fk_join.schema.key_columns:
            jenv_old[out_key.name] = lkey
            jenv_new[out_key.name] = lkey
        fenv_new, fok_new = self._apply_ops(self.pre_ops, jenv_new, jok_new, m)
        _, fok_old = self._apply_ops(self.pre_ops, jenv_old, jok_old, m)
        ts = jnp.broadcast_to(a_new["ts"][:1], (m,))
        emits = self._pack_emits(fenv_new, fok_new | fok_old, ts)
        emits["tombstone"] = ~fok_new
        emits["occupancy"] = jnp.sum(fkr["occ"] | fkr["grave"])
        emits["overflow"] = fkl["overflow"] + fkr["overflow"]
        return state, emits

    def process_fk(
        self, side: str, new_batch: HostBatch, old_batch: HostBatch,
        deletes: np.ndarray, has_old: np.ndarray,
    ) -> List[SinkEmit]:
        """Host entry for one single-side batch of fk-join changes (right
        changes run one record per step: the fan-out is store-wide)."""
        if not hasattr(self, "_fk_steps"):
            self._fk_steps = {
                "l": jax.jit(self._trace_fk_left, donate_argnums=0),
                "r": jax.jit(self._trace_fk_right, donate_argnums=0),
            }
        layout = self.fk_layouts[side]
        a_new = layout.encode(new_batch)
        a_old = layout.encode(old_batch)
        pad = np.zeros(self.capacity, np.int32)
        pad[: len(deletes)] = deletes
        a_new["delete"] = pad
        ho = np.zeros(self.capacity, bool)
        ho[: len(has_old)] = has_old
        a_old["row_valid"] = ho
        ov_before = int(self.state["fkl"]["overflow"]) + int(
            self.state["fkr"]["overflow"]
        )
        self.state, emits = self._fk_steps[side](self.state, a_new, a_old)
        if int(emits["overflow"]) > ov_before:
            raise QueryRuntimeException(
                "device fk-join store overflowed; "
                f"capacity={self.fk_store_capacity}"
            )
        if (
            int(emits["occupancy"]) + self.capacity
            > 0.75 * self.fk_store_capacity
        ):
            self._grow_fk()
        out = self._decode_emits(emits, sort=False)
        if side == "r":
            # the oracle fans out in repr-sorted left-key order
            from ksql_tpu.functions.udafs import _hashable

            out.sort(key=lambda e2: repr((_hashable(
                e2.key[0] if len(e2.key) == 1 else e2.key
            ), e2.key)))
        return out

    def _grow_fk(self, factor: int = 2) -> None:
        self.fk_store_capacity *= factor
        self._rebuild_keyed_store(
            "fkl", self.fk_store_capacity, lambda: self._init_fk_store("l")
        )
        self._rebuild_keyed_store(
            "fkr", self.fk_store_capacity, lambda: self._init_fk_store("r")
        )
        if hasattr(self, "_fk_steps"):
            del self._fk_steps

    # ------------------------------------------------- table-table join
    def _init_tt_store(self) -> Dict[str, jnp.ndarray]:
        """Two-sided keyed store for a pk table-table join: one slot per
        pk holds BOTH tables' resident rows + per-side liveness — the
        device analog of the two materialized KTables the reference joins
        (TableTableJoinBuilder)."""
        lay = StoreLayout(
            capacity=self.tt_store_capacity, num_keys=1, components=()
        )
        s = init_store(lay)
        c1 = self.tt_store_capacity + 1
        for side in ("l", "r"):
            s[f"{side}_live"] = jnp.zeros(c1, bool)
            for col in self.tt_cols[side]:
                s[f"{side}_v_{col.name}"] = jnp.zeros(
                    c1, self._table_col_dtype(col)
                )
                s[f"{side}_m_{col.name}"] = jnp.zeros(c1, bool)
        return s

    def _tt_joined_env(
        self, side: str, env_s: Dict[str, DCol], present_s: jnp.ndarray,
        tt: Dict[str, jnp.ndarray], slots: jnp.ndarray, found: jnp.ndarray,
    ) -> Tuple[Dict[str, DCol], jnp.ndarray]:
        """(joined env, join-valid mask) for one side's change rows against
        the resident other side."""
        from ksql_tpu.parser.ast_nodes import JoinType

        other = "r" if side == "l" else "l"
        o_live = tt[f"{other}_live"][slots] & found
        env: Dict[str, DCol] = {}
        for col in self.tt_cols[side]:
            d = env_s.get(col.name)
            if d is None:
                raise DeviceUnsupported(
                    f"join column {col.name} not on device"
                )
            env[col.name] = DCol(d.data, d.valid & present_s, col.type)
        for col in self.tt_cols[other]:
            env[col.name] = DCol(
                tt[f"{other}_v_{col.name}"][slots],
                tt[f"{other}_m_{col.name}"][slots] & o_live,
                col.type,
            )
        jt = self.tt_join.join_type
        l_p = present_s if side == "l" else o_live
        r_p = present_s if side == "r" else o_live
        if jt == JoinType.INNER:
            jok = l_p & r_p
        elif jt == JoinType.LEFT:
            jok = l_p
        elif jt == JoinType.RIGHT:
            jok = r_p
        else:  # OUTER
            jok = l_p | r_p
        # the join result's key column carries the pk (valid even when the
        # present side is the other one — the change key is always known)
        key_expr = (
            self.tt_join.left_key if side == "l" else self.tt_join.right_key
        )
        kcol = JaxExprCompiler(env_s, self.capacity, self.dictionary).compile(
            key_expr
        )
        for out_key in self.tt_join.schema.key_columns:
            env[out_key.name] = kcol
        return env, jok

    def _trace_tt_step(
        self, state, a_new, a_old, side: str,
    ):
        """One batch of side ``side`` table changes: update the side's
        resident columns, join old/new rows against the other side, run the
        post-join transform chain on both, and emit rows / tombstones with
        the oracle's TableChange semantics."""
        n = self.capacity
        cap = self.tt_store_capacity
        dump = jnp.int32(cap)
        layout = self.tt_layouts[side]
        ops = self.tt_left_ops if side == "l" else self.tt_right_ops
        key_expr = (
            self.tt_join.left_key if side == "l" else self.tt_join.right_key
        )
        tt = dict(state["ttab"])

        def side_env(arrays):
            env = self._source_env(arrays, layout)
            active = arrays["row_valid"]
            return self._apply_ops(ops, env, active, n)

        env_new, act_new = side_env(a_new)
        env_old, act_old = side_env(a_old)
        has_new = a_new["delete"] == 0
        # the change key comes from the NEW batch's key columns (key-only
        # rows for deletes), so every change row can probe
        c = JaxExprCompiler(env_new, n, self.dictionary)
        kcol = c.compile(key_expr)
        krepr = _repr64(kcol)
        khash = combine_hash([krepr])
        touched = a_new["row_valid"] & kcol.valid
        zeros64 = jnp.zeros(n, jnp.int64)
        tt, slots = probe_insert(
            tt, cap, khash, zeros64, [krepr], jnp.zeros(n, jnp.int32), touched
        )
        found = slots != dump
        # joined envs BEFORE the side update (the other side is untouched
        # by this single-side batch; the s side reads its own change rows)
        jenv_old, jok_old = self._tt_joined_env(
            side, env_old, act_old & a_old["row_valid"], tt, slots, found
        )
        jenv_new, jok_new = self._tt_joined_env(
            side, env_new, act_new & a_new["row_valid"] & has_new,
            tt, slots, found,
        )
        # post-join transform chain: full pipeline on new, verdict on old
        fenv_new, fok_new = self._apply_ops(self.pre_ops, jenv_new, jok_new, n)
        _, fok_old = self._apply_ops(self.pre_ops, jenv_old, jok_old, n)
        # side update: last writer per slot wins; a delete clears liveness
        self._upsert_side(
            tt, self.tt_cols[side], env_new, touched, slots, has_new,
            act_new, cap, prefix=f"{side}_", live_key=f"{side}_live",
        )
        state = dict(state)
        state["ttab"] = tt
        ts = a_new["ts"]
        emits = self._pack_emits(fenv_new, fok_new | fok_old, ts)
        emits["tombstone"] = ~fok_new
        emits["occupancy"] = jnp.sum(tt["occ"] | tt["grave"])
        emits["overflow"] = tt["overflow"]
        return state, emits

    def process_tt(
        self, side: str, new_batch: HostBatch, old_batch: HostBatch,
        deletes: np.ndarray, has_old: np.ndarray,
    ) -> List[SinkEmit]:
        """Host entry for one single-side batch of table-table-join
        changes."""
        if not hasattr(self, "_tt_steps"):
            self._tt_steps = {
                s: jax.jit(
                    lambda st_, an, ao, s=s: self._trace_tt_step(st_, an, ao, s),
                    donate_argnums=0,
                )
                for s in ("l", "r")
            }
        layout = self.tt_layouts[side]
        a_new = layout.encode(new_batch)
        a_old = layout.encode(old_batch)
        pad = np.zeros(self.capacity, np.int32)
        pad[: len(deletes)] = deletes
        a_new["delete"] = pad
        ho = np.zeros(self.capacity, bool)
        ho[: len(has_old)] = has_old
        a_old["row_valid"] = ho
        ov_before = int(self.state["ttab"]["overflow"])
        self.state, emits = self._tt_steps[side](self.state, a_new, a_old)
        if int(emits["overflow"]) > ov_before:
            raise QueryRuntimeException(
                "device table-table join store overflowed; "
                f"capacity={self.tt_store_capacity}"
            )
        if int(emits["occupancy"]) + self.capacity > 0.75 * self.tt_store_capacity:
            self._grow_tt()
        return self._decode_emits(emits, sort=False)

    def _grow_tt(self, factor: int = 2) -> None:
        """Double the two-sided join store (host rebuild + recompile)."""
        self.tt_store_capacity *= factor
        self._rebuild_keyed_store(
            "ttab", self.tt_store_capacity, self._init_tt_store
        )
        if hasattr(self, "_tt_steps"):
            del self._tt_steps  # shapes changed: recompile on next batch

    def process_table(
        self, batch: HostBatch, deletes: np.ndarray, idx: int = -1
    ) -> None:
        """Host entry for one table-side micro-batch (rows + tombstone
        mask) of join probe ``idx``."""
        if idx < 0:
            idx += len(self.join_chain)
        jspec = self.join_chain[idx]
        arrays = jspec.layout.encode(batch)
        pad = np.zeros(self.capacity, bool)
        pad[: len(deletes)] = deletes
        arrays["delete"] = pad
        _note_transfer("h2d_bytes", arrays)
        self.state, metrics = self._table_steps[idx](self.state, arrays)
        overflow = int(metrics["overflow"])
        if overflow > jspec.seen_overflow:
            jspec.seen_overflow = overflow
            raise QueryRuntimeException(
                f"device join-table store overflowed ({overflow} rows); "
                "growth failed to keep pace with key cardinality"
            )
        if int(metrics["occupancy"]) + self.capacity > 0.75 * jspec.capacity:
            self._grow_table(idx=idx)

    _table_seen_overflow = 0

    def _rebuild_keyed_store(self, state_key: str, capacity: int, init_fn) -> None:
        """Host-side rebuild of a keyed sub-store into fresh arrays of
        ``capacity``: live slots re-insert (numpy probe), per-slot columns
        follow, scalars (overflow counters) carry over.  Shared by the
        join-table and table-table-join growth paths."""
        state = dict(self.state)
        old = {
            k: np.asarray(v)
            for k, v in jax.device_get(state.pop(state_key)).items()
        }
        new = {k: np.array(v) for k, v in jax.device_get(init_fn()).items()}
        live = np.nonzero(old["occ"][:-1])[0]
        if live.size:
            from ksql_tpu.ops.hash_store import host_insert

            slots = host_insert(
                new["occ"], new["khash"], new["wstart"], capacity,
                old["khash"][live], old["wstart"][live],
            )
            for name in old:
                if name in ("occ", "khash", "wstart") or old[name].ndim == 0:
                    continue
                new[name][slots] = old[name][live]
        for name in old:
            if old[name].ndim == 0:  # overflow, max_ts
                new[name] = old[name]
        # jnp.array (copy) — a zero-copy view over the host rebuild buffer
        # would alias memory the next (donating) step hands to XLA to
        # recycle while numpy still owns it: intermittent heap corruption
        state[state_key] = {k: jnp.array(v) for k, v in new.items()}
        self.state = state

    def _grow_table(self, factor: int = 2, idx: int = -1) -> None:
        """Double one join-table store: host-side rebuild, then recompile
        (the step functions capture the capacity as a static)."""
        if idx < 0:
            idx += len(self.join_chain)
        jspec = self.join_chain[idx]
        jspec.capacity *= factor
        if idx == len(self.join_chain) - 1:
            self.table_store_capacity = jspec.capacity
        self._rebuild_keyed_store(
            self._jtab_key(idx), jspec.capacity,
            lambda: self._init_table_store(idx),
        )
        self._compile_steps()

    def _apply_join(
        self, env: Dict[str, DCol], active: jnp.ndarray, n: int,
        jtabs: Dict[str, Dict[str, jnp.ndarray]],
    ) -> Tuple[Dict[str, DCol], jnp.ndarray]:
        """Per-row probe of each join store in chain order (an n-way join is
        a sequence of probes with its between-ops applied before each):
        gather right-side columns for matches; INNER drops non-matches,
        LEFT null-pads (StreamTableJoinNode semantics, oracle.py)."""
        from ksql_tpu.parser.ast_nodes import JoinType

        for idx, jspec in enumerate(self.join_chain):
            env, active = self._apply_ops(jspec.between_ops, env, active, n)
            jtab = jtabs[self._jtab_key(idx)]
            c = JaxExprCompiler(env, n, self.dictionary)
            kcol = c.compile(jspec.step.left_key)
            krepr = _repr64(kcol)
            khash = combine_hash([krepr])
            look = active & kcol.valid
            cap_t = jspec.capacity
            slots = probe_find(
                jtab, cap_t, khash, jnp.zeros(n, jnp.int64), look
            )
            found = look & (slots != cap_t)
            if jspec.step.join_type == JoinType.INNER:
                active = found
            for col in jspec.cols:
                data = jtab[f"v_{col.name}"][slots]
                valid = jtab[f"m_{col.name}"][slots] & found
                env[col.name] = DCol(data, valid, col.type)
            # the right side's pk column (stored as the probe key repr)
            for kc in jspec.step.right.schema.key_columns:
                kdata = jtab["key0"][slots]
                if kc.type.base in (SqlBaseType.DOUBLE, SqlBaseType.DECIMAL):
                    kdata = jax.lax.bitcast_convert_type(kdata, jnp.float64)
                elif kc.type.base not in _HASHED:
                    kdata = kdata.astype(kc.type.device_dtype())
                env[kc.name] = DCol(kdata, found, kc.type)
            # the join result's key column carries the join key value
            for out_key in jspec.step.schema.key_columns:
                env[out_key.name] = kcol
        return env, active

    # ----------------------------------------- stream-stream join (device)
    def _decode_key64(self, data: jnp.ndarray, sql_type: SqlType) -> jnp.ndarray:
        if sql_type.base in (SqlBaseType.DOUBLE, SqlBaseType.DECIMAL):
            return jax.lax.bitcast_convert_type(data, jnp.float64)
        if sql_type.base not in _HASHED:
            return data.astype(sql_type.device_dtype())
        return data

    def ss_routing_hash(
        self, side: str, arrays: Dict[str, jnp.ndarray]
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(join-key group hash, post-filter active) per row of one ss-join
        side — the shard router for the distributed path (both sides of a
        key must land on the ring buffers of one shard; rows this side's
        pre-op filters drop must not burn exchange bucket slots)."""
        n = arrays["row_valid"].shape[0]
        layout = self.layout if side == "l" else self.right_layout
        pre = self.pre_ops if side == "l" else self.right_pre_ops
        env = self._source_env(arrays, layout)
        env, active = self._apply_ops(pre, env, arrays["row_valid"], n)
        key_expr = self.ss_join.left_key if side == "l" else self.ss_join.right_key
        kcol = JaxExprCompiler(env, n, self.dictionary).compile(key_expr)
        return combine_hash([_repr64(kcol)]), active

    def _trace_ss_step(
        self, side: str, state: Dict[str, jnp.ndarray],
        arrays: Dict[str, jnp.ndarray],
    ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        """One batch of side ``side`` against the opposite ring buffer.

        Vectorized WITHIN-window equi-match (n×B mask → static-size nonzero
        compaction), eager null-padding for legacy LEFT/OUTER, buffer
        insertion with overwrite-loss accounting.  Oracle parity: matching
        sees the buffer *before* this batch's expiry (the executor runs the
        expire kernel after, as OracleExecutor._advance_time does)."""
        ss = self.ss_join
        n = arrays["row_valid"].shape[0]  # >= capacity post-exchange
        layout = self.layout if side == "l" else self.right_layout
        pre = self.pre_ops if side == "l" else self.right_pre_ops
        env = self._source_env(arrays, layout)
        active = arrays["row_valid"]
        env, active = self._apply_ops(pre, env, active, n)
        key_expr = ss.left_key if side == "l" else ss.right_key
        c = JaxExprCompiler(env, n, self.dictionary)
        kcol = c.compile(key_expr)
        krepr = _repr64(kcol)
        ts = arrays["ts"]
        o = "r" if side == "l" else "l"
        B = self.ss_capacity
        b1 = B + 1
        ots = state[f"ss{o}_ts"]
        key_eq = (
            (krepr[:, None] == state[f"ss{o}_krepr"][None, :])
            & kcol.valid[:, None]
            & state[f"ss{o}_kval"][None, :]
        )
        if side == "l":
            tw = (ts[:, None] - self.ss_before <= ots[None, :]) & (
                ots[None, :] <= ts[:, None] + self.ss_after
            )
        else:
            tw = (ots[None, :] - self.ss_before <= ts[:, None]) & (
                ts[:, None] <= ots[None, :] + self.ss_after
            )
        m = active[:, None] & state[f"ss{o}_live"][None, :] & key_eq & tw
        total = jnp.sum(m)
        oc = self.ss_out_cap
        (flat,) = jnp.nonzero(m.reshape(-1), size=oc, fill_value=0)
        mvalid = jnp.arange(oc) < total
        mi = (flat // b1).astype(jnp.int32)
        mj = (flat % b1).astype(jnp.int32)
        row_matched = jnp.any(m, axis=1)
        # running stream times (per row, record order): global for pad
        # timing, per-side for store admission — the oracle's
        # stream_time/side_max split
        neg64 = np.iinfo(np.int64).min
        cm_global = jnp.maximum(
            jax.lax.cummax(jnp.where(arrays["row_valid"], ts, neg64)),
            state["max_ts"],
        )
        cm_side = jnp.maximum(
            jax.lax.cummax(jnp.where(arrays["row_valid"], ts, neg64)),
            state[f"ss{side}_smax"],
        )
        swin = self.ss_after if side == "l" else self.ss_before
        pad = jnp.zeros(n, bool)
        if side in self.ss_pad_sides:
            if self.ss_deferred:
                # window already closed on arrival: pad now (klip-36)
                pad = active & ~row_matched & (
                    ts + swin + self.ss_grace < cm_global
                )
            else:
                pad = active & ~row_matched
        admitted = active & (
            ts >= cm_side - self.ss_retention if self.ss_deferred
            else jnp.ones(n, bool)
        )

        # ---------------- emission env: oc match rows + n pad rows
        nn = oc + n
        out_env: Dict[str, DCol] = {}
        for s2 in ("l", "r"):
            for col in self.ss_cols[s2]:
                if s2 == side:
                    d = env[col.name]
                    mdata = d.data[mi]
                    mval = d.valid[mi] & mvalid
                    pdata, pval = d.data, d.valid & pad
                else:
                    mdata = state[f"ss{s2}_v_{col.name}"][mj]
                    mval = state[f"ss{s2}_m_{col.name}"][mj] & mvalid
                    pdata = jnp.zeros(n, mdata.dtype)
                    pval = jnp.zeros(n, bool)
                out_env[col.name] = DCol(
                    jnp.concatenate([mdata, pdata]),
                    jnp.concatenate([mval, pval]),
                    col.type,
                )
        for out_key in ss.schema.key_columns:
            out_env[out_key.name] = DCol(
                jnp.concatenate([kcol.data[mi], kcol.data]),
                jnp.concatenate([kcol.valid[mi] & mvalid, kcol.valid & pad]),
                out_key.type,
            )
        out_ts = jnp.concatenate([jnp.maximum(ts[mi], ots[mj]), ts])
        out_env["ROWTIME"] = DCol(out_ts, jnp.ones(nn, bool), T.BIGINT)
        mask = jnp.concatenate([mvalid, pad])
        out_env, mask = self._apply_ops(self.mid_ops, out_env, mask, nn)
        emits = self._pack_emits(out_env, mask, out_ts)
        # oracle emission order: per incoming row, matches in buffer
        # insertion (seq) order, then the row's own eager null-pad
        emits["ord_a"] = jnp.concatenate(
            [mi.astype(jnp.int64), jnp.arange(n, dtype=jnp.int64)]
        )
        emits["ord_b"] = jnp.concatenate(
            [state[f"ss{o}_seq"][mj],
             jnp.full(n, np.iinfo(np.int64).max, jnp.int64)]
        )
        emits["ss_matchovf"] = jnp.maximum(total - oc, 0)

        # ------- insert the batch's ADMITTED rows into its own ring buffer
        state = dict(state)
        cnt = jnp.cumsum(admitted.astype(jnp.int64))
        seq0 = state[f"ss{side}_cursor"]
        seqs = seq0 + cnt - 1
        tgt = jnp.where(admitted, (seqs % B).astype(jnp.int32), jnp.int32(B))
        batch_max = jnp.max(
            jnp.where(arrays["row_valid"], arrays["ts"], np.iinfo(np.int64).min)
        )
        new_max = jnp.maximum(state["max_ts"], batch_max)
        new_smax = jnp.maximum(state[f"ss{side}_smax"], batch_max)
        unexpired = (
            state[f"ss{side}_ts"] + self.ss_retention >= new_smax
            if self.ss_deferred
            else state[f"ss{side}_ts"] + swin + self.ss_grace >= new_max
        )
        emits["ss_lost"] = jnp.sum(
            admitted & state[f"ss{side}_live"][tgt] & unexpired[tgt]
        )
        state[f"ss{side}_ts"] = state[f"ss{side}_ts"].at[tgt].set(ts)
        state[f"ss{side}_krepr"] = state[f"ss{side}_krepr"].at[tgt].set(krepr)
        state[f"ss{side}_kval"] = state[f"ss{side}_kval"].at[tgt].set(kcol.valid)
        state[f"ss{side}_seq"] = state[f"ss{side}_seq"].at[tgt].set(seqs)
        state[f"ss{side}_matched"] = (
            state[f"ss{side}_matched"].at[tgt].set(row_matched | pad)
        )
        state[f"ss{side}_live"] = (
            state[f"ss{side}_live"].at[tgt].set(True).at[B].set(False)
        )
        for col in self.ss_cols[side]:
            d = env[col.name]
            dt = self._table_col_dtype(col)
            state[f"ss{side}_v_{col.name}"] = (
                state[f"ss{side}_v_{col.name}"].at[tgt].set(d.data.astype(dt))
            )
            state[f"ss{side}_m_{col.name}"] = (
                state[f"ss{side}_m_{col.name}"].at[tgt].set(d.valid)
            )
        state[f"ss{side}_cursor"] = seq0 + jnp.sum(admitted)
        state[f"ss{o}_matched"] = state[f"ss{o}_matched"] | jnp.any(m, axis=0)
        state["max_ts"] = new_max
        state[f"ss{side}_smax"] = new_smax
        return state, emits

    def _trace_ss_expire(
        self, state: Dict[str, jnp.ndarray]
    ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        """Expire buffered entries past window+grace; klip-36 deferred mode
        emits null-padded LEFT/OUTER/RIGHT rows at close (the oracle's
        StreamStreamJoinNode.on_time)."""
        ss = self.ss_join
        t = state["max_ts"]
        b1 = self.ss_capacity + 1
        state = dict(state)
        nn = 2 * b1
        out_env: Dict[str, DCol] = {}
        emit_masks: Dict[str, jnp.ndarray] = {}
        for side in ("l", "r"):
            win = self.ss_after if side == "l" else self.ss_before
            live = state[f"ss{side}_live"]
            closed = live & (
                state[f"ss{side}_ts"] + win + self.ss_grace < t
            )
            if self.ss_deferred and side in self.ss_pad_sides:
                emit_masks[side] = closed & ~state[f"ss{side}_matched"]
            else:
                emit_masks[side] = jnp.zeros(b1, bool)
            if self.ss_deferred:
                # a padded entry stays resident (late matches may still
                # arrive); eviction follows the own store's retention
                state[f"ss{side}_matched"] = (
                    state[f"ss{side}_matched"] | emit_masks[side]
                )
                state[f"ss{side}_live"] = live & (
                    state[f"ss{side}_ts"] + self.ss_retention
                    >= state[f"ss{side}_smax"]
                )
            else:
                state[f"ss{side}_live"] = live & ~closed
        # env: [left-part rows (b1) | right-part rows (b1)]
        for s2 in ("l", "r"):
            for col in self.ss_cols[s2]:
                own_d = state[f"ss{s2}_v_{col.name}"]
                own_m = state[f"ss{s2}_m_{col.name}"]
                zero_d = jnp.zeros(b1, own_d.dtype)
                zero_m = jnp.zeros(b1, bool)
                if s2 == "l":
                    data = jnp.concatenate([own_d, zero_d])
                    valid = jnp.concatenate([own_m & emit_masks["l"], zero_m])
                else:
                    data = jnp.concatenate([zero_d, own_d])
                    valid = jnp.concatenate([zero_m, own_m & emit_masks["r"]])
                out_env[col.name] = DCol(data, valid, col.type)
        for out_key in ss.schema.key_columns:
            parts_d, parts_v = [], []
            for s2 in ("l", "r"):
                parts_d.append(
                    self._decode_key64(state[f"ss{s2}_krepr"], out_key.type)
                )
                parts_v.append(state[f"ss{s2}_kval"] & emit_masks[s2])
            out_env[out_key.name] = DCol(
                jnp.concatenate(parts_d), jnp.concatenate(parts_v),
                out_key.type,
            )
        out_ts = jnp.concatenate([state["ssl_ts"], state["ssr_ts"]])
        out_env["ROWTIME"] = DCol(out_ts, jnp.ones(nn, bool), T.BIGINT)
        mask = jnp.concatenate([emit_masks["l"], emit_masks["r"]])
        out_env, mask = self._apply_ops(self.mid_ops, out_env, mask, nn)
        emits = self._pack_emits(out_env, mask, out_ts)
        # oracle on_time sorts by ts (stable over left-then-right iteration)
        emits["ord_a"] = out_ts
        side_rank = jnp.concatenate(
            [jnp.zeros(b1, jnp.int64), jnp.full(b1, 1 << 40, jnp.int64)]
        )
        emits["ord_b"] = side_rank + jnp.concatenate(
            [state["ssl_seq"], state["ssr_seq"]]
        )
        return state, emits

    # ------------------------------------------------------ ss join host API
    def process_ss(self, batch: HostBatch, side: str) -> List[SinkEmit]:
        layout = self.layout if side == "l" else self.right_layout
        arrays = layout.encode(batch)
        _note_transfer("h2d_bytes", arrays)
        while True:
            step = self._ss_l if side == "l" else self._ss_r
            new_state, emits = step(self.state, arrays)
            if int(emits["ss_matchovf"]) > 0:
                self._grow_ss(out=True)  # re-run this batch, larger match cap
                continue
            if int(emits["ss_lost"]) > 0:
                self._grow_ss(buf=True)  # re-run, larger ring buffers
                continue
            break
        self.state = new_state
        return self._decode_emits(emits)

    def ss_expire_host(self) -> List[SinkEmit]:
        self.state, emits = self._ss_expire(self.state)
        return self._decode_emits(emits)

    def ss_flush(self, stream_time: int) -> List[SinkEmit]:
        state = dict(self.state)
        state["max_ts"] = jnp.maximum(
            state["max_ts"], jnp.asarray(stream_time, jnp.int64)
        )
        self.state = state
        return self.ss_expire_host()

    def _grow_ss(self, buf: bool = False, out: bool = False) -> None:
        if out:
            self.ss_out_cap *= 2
        if buf:
            old_cap = self.ss_capacity
            self.ss_capacity = old_cap * 2
            b1 = self.ss_capacity + 1
            old = {
                k: np.asarray(v)
                for k, v in jax.device_get(self.state).items()
            }
            new = dict(self.state)
            for s in ("l", "r"):
                live = np.nonzero(old[f"ss{s}_live"][:-1])[0]
                # compact by seq: relative order (and thus ord_b ordering)
                # is preserved under reassignment
                live = live[np.argsort(old[f"ss{s}_seq"][live])]
                k = live.size
                for key in list(old):
                    if not key.startswith(f"ss{s}_"):
                        continue
                    v = old[key]
                    if v.ndim == 0:
                        continue
                    grown = np.zeros(b1, v.dtype)
                    grown[:k] = v[live]
                    # jnp.array (copy), not asarray: the ss steps run
                    # undonated today, but a rebuild buffer zero-copy-aliased
                    # into state is one donate_argnums change away from the
                    # PR-2 heap corruption — the aliasing lint keeps every
                    # grow path copying
                    new[key] = jnp.array(grown)
                newseq = np.zeros(b1, np.int64)
                newseq[:k] = np.arange(k)
                new[f"ss{s}_seq"] = jnp.array(newseq)
                newlive = np.zeros(b1, bool)
                newlive[:k] = True
                new[f"ss{s}_live"] = jnp.array(newlive)
                new[f"ss{s}_cursor"] = jnp.asarray(k, jnp.int64)
            self.state = new
        self._compile_steps()

    # ------------------------------------------------------------- tracing
    def _source_env(
        self, arrays: Dict[str, jnp.ndarray], layout: Optional[BatchLayout] = None
    ) -> Dict[str, DCol]:
        env: Dict[str, DCol] = {}
        for spec in (layout or self.layout).specs:
            env[spec.name] = DCol(
                arrays[f"v_{spec.name}"], arrays[f"m_{spec.name}"], spec.sql_type
            )
        # shape-derived, not self.capacity: the distributed ss-join path
        # feeds post-exchange arrays wider than the ingest capacity
        ones = jnp.ones(arrays["ts"].shape[0], bool)
        env["ROWTIME"] = DCol(arrays["ts"], ones, T.BIGINT)
        env["ROWOFFSET"] = DCol(arrays["offset"], ones, T.BIGINT)
        env["ROWPARTITION"] = DCol(arrays["partition"], ones, T.INTEGER)
        return env

    def _apply_ops(
        self, ops: Sequence[st.ExecutionStep], env: Dict[str, DCol],
        active: jnp.ndarray, n: int,
    ) -> Tuple[Dict[str, DCol], jnp.ndarray]:
        for op in ops:
            c = JaxExprCompiler(env, n, self.dictionary)
            if isinstance(op, (st.StreamFilter, st.TableFilter)):
                pred = c.compile(op.predicate)
                active = active & pred.valid & pred.data.astype(bool)
            elif isinstance(op, (st.StreamSelect, st.TableSelect)):
                new_env: Dict[str, DCol] = {}
                src_keys = [k.name for k in op.source.schema.key_columns]
                out_keys = [k.name for k in op.schema.key_columns]
                for new_name, old_name in zip(out_keys, src_keys):
                    if old_name in env:
                        new_env[new_name] = env[old_name]
                for name, e in op.selects:
                    new_env[name] = c.compile(e)
                for p in ("ROWTIME", "ROWOFFSET", "ROWPARTITION",
                          "WINDOWSTART", "WINDOWEND"):
                    if p in env:
                        new_env[p] = env[p]
                env = new_env
            elif isinstance(op, (st.StreamSelectKey, st.TableSelectKey)):
                for col, e in zip(op.schema.key_columns, op.key_expressions):
                    env[col.name] = c.compile(e)
            else:  # pragma: no cover
                raise DeviceUnsupported(type(op).__name__)
        return env, active

    def _apply_pre_ops(
        self, env: Dict[str, DCol], active: jnp.ndarray, n: int
    ) -> Tuple[Dict[str, DCol], jnp.ndarray]:
        return self._apply_ops(self.pre_ops, env, active, n)

    def _trace_step(
        self, state: Dict[str, jnp.ndarray], arrays: Dict[str, jnp.ndarray]
    ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        if self.agg is None:
            n = self.capacity
            env = self._source_env(arrays)
            active = arrays["row_valid"]
            # shared source prefix: the structurally-common leading steps
            # run ONCE; the primary and every prefix member branch off the
            # post-prefix env with only their residual suffixes (with no
            # members the prefix is empty and this is the plain chain)
            shared_n = self._prefix_shared_len if self.prefix_members else 0
            env, active = self._apply_ops(
                self.pre_ops[:shared_n], env, active, n
            )
            penv, pactive = env, active
            env, active = self._apply_ops(
                self.pre_ops[shared_n:], env, active, n
            )
            if self.join is not None:
                env, active = self._apply_join(
                    env, active, n, self._jtabs_of(state)
                )
                env, active = self._apply_ops(self.mid_ops, env, active, n)
            ts = arrays["ts"]
            batch_max_ts = jnp.max(jnp.where(active, ts, np.iinfo(np.int64).min))
            emits = self._emit_stateless(env, active, ts)
            for m in self.prefix_members:
                menv, mact = self._apply_ops(
                    m.pre_ops[shared_n:], penv, pactive, n
                )
                sub = self._pack_emits(menv, mact, ts, schema=m.sink_schema)
                # query-id-keyed lanes (see the fam: lanes above): decode
                # routes by identity, never by list position
                for k2, v2 in sub.items():
                    emits[f"pfx:{m.query_id}:{k2}"] = v2
            state = dict(state)
            state["max_ts"] = jnp.maximum(state["max_ts"], batch_max_ts)
            return state, emits
        if self.session:
            return self._trace_session_step(state, arrays)
        payload = self.pre_exchange(
            state["max_ts"], arrays, state.get("emit_clock"),
            jtabs=self._jtabs_of(state), seq_base=state.get("agg_seq"),
        )
        store, emits = self.post_exchange(state, payload)
        if self._needs_seq:
            store["agg_seq"] = state["agg_seq"] + self.capacity
        return store, emits

    # --------------------------------------------------- SESSION aggregation
    def _trace_session_step(
        self, state: Dict[str, jnp.ndarray], arrays: Dict[str, jnp.ndarray]
    ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        """SESSION windows as a sort + segmented interval-merge.

        The reference merges sessions record-at-a-time inside the session
        store (StreamAggregateBuilder.java:142-352, SessionWindows).  The
        columnar formulation: batch rows become singleton sessions, the
        (≤ session_slots) stored sessions of every key present in the batch
        are gathered, everything is sorted by (key, start), and one
        segmented cummax scan merges intervals whose gap is within the
        inactivity gap.  Merged segments are scattered back as the key's new
        session set; every touched stored session emits a tombstone and
        every row-containing segment emits its merged aggregate — exactly
        the oracle's remove-then-put emission (_receive_session)."""
        payload = self.pre_session_exchange(
            state["max_ts"], arrays, seq_base=state.get("agg_seq")
        )
        return self.post_session_exchange(state, payload)

    def pre_session_exchange(
        self,
        max_ts: jnp.ndarray,
        arrays: Dict[str, jnp.ndarray],
        seq_base: Optional[jnp.ndarray] = None,
    ) -> Dict[str, jnp.ndarray]:
        """Per-row phase of the SESSION step before the shuffle boundary:
        transforms, group-key hashing, late-record drop, aggregate
        contributions.  The flat payload crosses the ICI all-to-all in the
        multi-chip path, exactly like pre_exchange for fixed windows."""
        n = self.capacity
        env = self._source_env(arrays)
        active = arrays["row_valid"]
        env, active = self._apply_pre_ops(env, active, n)
        ts = arrays["ts"]
        c = JaxExprCompiler(env, n, self.dictionary)
        group_exprs = tuple(getattr(self.group, "group_by_expressions", ()))
        if group_exprs:
            key_cols = [c.compile(e) for e in group_exprs]
        else:
            key_cols = [env[col.name] for col in self.group.schema.key_columns]
        reprs = [_repr64(kc) for kc in key_cols]
        knull_ok = jnp.ones(n, bool)
        for kc in key_cols:
            knull_ok = knull_ok & kc.valid
        active = active & knull_ok
        khash = combine_hash(reprs + [jnp.zeros(n, jnp.int64)])
        # late-record drop past session grace (running per-record stream
        # time in ARRIVAL order — computed before any exchange, matching
        # the oracle's max_ts-at-receive semantics)
        cm = jnp.maximum(
            jax.lax.cummax(
                jnp.where(arrays["row_valid"], ts, np.iinfo(np.int64).min)
            ),
            max_ts,
        )
        active = active & (ts + self.grace_ms + self.window.gap_ms >= cm)
        # row aggregate contributions (component 0 = ts watermark)
        contribs: List[jnp.ndarray] = [jnp.where(active, ts, np.iinfo(np.int64).min)]
        rseq = None
        if self._needs_seq:
            rseq = seq_base + jnp.arange(n, dtype=jnp.int64)
        for spec in self.agg_specs:
            args = [c.compile(e) for e in spec.arg_exprs]
            contribs.extend(spec.device.contribs(args, active, rseq))
        payload: Dict[str, jnp.ndarray] = {
            "khash": khash, "ts": ts, "active": active, "cm": cm,
        }
        for k, r in enumerate(reprs):
            payload[f"repr{k}"] = r
        for j, arr in enumerate(contribs):
            payload[f"c{j}"] = arr
        return payload

    def post_session_exchange(
        self, state: Dict[str, jnp.ndarray], payload: Dict[str, jnp.ndarray]
    ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        """State-owning phase of the SESSION step after the shuffle: gather
        the key's stored sessions, segmented interval-merge, rewrite the
        store, emit tombstones + merged aggregates."""
        ncomp = len(self.store_layout.components)
        nkeys = len(self.key_types)
        n = payload["ts"].shape[0]
        khash, ts = payload["khash"], payload["ts"]
        active, cm = payload["active"], payload["cm"]
        reprs = [payload[f"repr{k}"] for k in range(nkeys)]
        contribs = [payload[f"c{j}"] for j in range(ncomp)]
        cap = self.store_capacity
        gap = self.window.gap_ms
        S = self.session_slots
        m = n * (S + 1)
        neg = np.iinfo(np.int64).min

        # ---- first active occurrence of each key in the batch
        order0 = jnp.lexsort((jnp.arange(n), jnp.where(active, khash, 0)))
        khs = jnp.where(active, khash, 0)[order0]
        acts = active[order0]
        firsts = jnp.concatenate(
            [jnp.ones(1, bool), khs[1:] != khs[:-1]]
        ) & acts
        # first active row per key: among actives sorted by (khash, idx)
        first_occ = jnp.zeros(n, bool).at[order0].set(firsts) & active

        # ---- item arrays: [rows | store session i=0..S-1 per first-occ row]
        it_kh = [jnp.where(active, khash, 0)]
        it_start = [ts]
        it_end = [ts]
        it_alive = [active]
        it_isrow = [active]
        it_slot = [jnp.full(n, cap, jnp.int32)]
        it_rowidx = [jnp.arange(n, dtype=jnp.int64)]
        it_reprs = [[r for r in reprs]]
        it_comps = [contribs]
        # jnp.max(cm) == the arrival-order cummax's last element on a
        # single device, and stays correct when exchange scrambles rows
        batch_stream_time = jnp.maximum(state["max_ts"], jnp.max(cm))
        for i in range(S):
            slots_i = probe_find(
                state, cap, khash, jnp.full(n, i, jnp.int64), first_occ
            )
            found = first_occ & (slots_i != cap)
            # store retention: expired sessions (end + gap + grace behind
            # stream time) still DELETE from the store but no longer merge
            unexpired = (
                state["sess_end"][slots_i] + self.window.gap_ms + self.grace_ms
                >= batch_stream_time
            )
            it_kh.append(jnp.where(found & unexpired, khash, 0))
            it_start.append(state["sess_start"][slots_i])
            it_end.append(state["sess_end"][slots_i])
            it_alive.append(found & unexpired)
            it_isrow.append(jnp.zeros(n, bool))
            it_slot.append(slots_i)
            it_rowidx.append(jnp.arange(n, dtype=jnp.int64))
            it_reprs.append([state[f"key{k}"][slots_i] for k in range(nkeys)])
            it_comps.append([state[f"a{j}"][slots_i] for j in range(ncomp)])
        kh = jnp.concatenate(it_kh)
        start = jnp.concatenate(it_start)
        end = jnp.concatenate(it_end)
        alive = jnp.concatenate(it_alive)
        isrow = jnp.concatenate(it_isrow)
        slot = jnp.concatenate(it_slot)
        rowidx = jnp.concatenate(it_rowidx)
        reprs_m = [
            jnp.concatenate([p[k] for p in it_reprs]) for k in range(nkeys)
        ]
        comps_m = [
            jnp.concatenate([p[j] for p in it_comps]) for j in range(ncomp)
        ]
        # dead items take a unique sentinel key so they never merge
        kh = jnp.where(alive, kh, jnp.arange(m, dtype=jnp.int64) + (1 << 62))
        start = jnp.where(alive, start, 0)
        end = jnp.where(alive, end, 0)

        # ---- sort by (key, start) and segmented interval-merge
        orderm = jnp.lexsort((start, kh))
        kh, start, end = kh[orderm], start[orderm], end[orderm]
        alive, isrow, slot = alive[orderm], isrow[orderm], slot[orderm]
        rowidx = rowidx[orderm]
        reprs_m = [r[orderm] for r in reprs_m]
        comps_m = [cm[orderm] for cm in comps_m]

        def seg_combine(a, b):
            ka, ea = a
            kb, eb = b
            return kb, jnp.where(ka == kb, jnp.maximum(ea, eb), eb)

        _, segend = jax.lax.associative_scan(seg_combine, (kh, end))
        prev_kh = jnp.concatenate([jnp.full(1, -1, jnp.int64), kh[:-1]])
        prev_segend = jnp.concatenate([jnp.full(1, neg, jnp.int64), segend[:-1]])
        boundary = (kh != prev_kh) | (start > prev_segend + gap)
        seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1

        seg_start = jax.ops.segment_min(start, seg, num_segments=m)
        seg_end = jax.ops.segment_max(end, seg, num_segments=m)
        seg_alive = (
            jax.ops.segment_max(alive.astype(jnp.int32), seg, num_segments=m) > 0
        )
        seg_has_row = (
            jax.ops.segment_max(
                (isrow & alive).astype(jnp.int32), seg, num_segments=m
            ) > 0
        )
        seg_kh = jax.ops.segment_max(jnp.where(alive, kh, neg), seg, num_segments=m)
        big = np.iinfo(np.int64).max
        seg_minrow = jax.ops.segment_min(
            jnp.where(isrow & alive, rowidx, big), seg, num_segments=m
        )
        seg_reprs = [
            jax.ops.segment_max(jnp.where(alive, r, neg), seg, num_segments=m)
            for r in reprs_m
        ]
        seg_comps = []
        comp_list = list(self.store_layout.components)
        last_order_j = 0
        for j, comp in enumerate(comp_list):
            v = comps_m[j]
            fill = jnp.asarray(comp.init, v.dtype)
            v = jnp.where(alive, v, fill)
            if comp.combine == "add":
                seg_comps.append(jax.ops.segment_sum(v, seg, num_segments=m))
                last_order_j = j
            elif comp.combine == "min":
                seg_comps.append(jax.ops.segment_min(v, seg, num_segments=m))
                last_order_j = j
            elif comp.combine == "max":
                seg_comps.append(jax.ops.segment_max(v, seg, num_segments=m))
                last_order_j = j
            else:  # argset: payload of the preceding order component's winner
                order_vals = jnp.where(
                    alive,
                    comps_m[last_order_j],
                    jnp.asarray(
                        comp_list[last_order_j].init,
                        comps_m[last_order_j].dtype,
                    ),
                )
                winner = alive & (
                    order_vals == seg_comps[last_order_j][seg]
                ) & (
                    order_vals
                    != jnp.asarray(
                        comp_list[last_order_j].init, order_vals.dtype
                    )
                )
                seg_comps.append(
                    jax.ops.segment_sum(
                        jnp.where(winner, v, jnp.zeros_like(v)),
                        seg,
                        num_segments=m,
                    )
                )

        # ---- rewrite the store: drop every gathered session, re-insert the
        # merged session set (fresh slot indices 0..count-1 per key)
        state = dict(state)
        del_mask = ~isrow & alive
        tgt_del = jnp.where(del_mask, slot, jnp.int32(cap))
        occ = state["occ"].at[tgt_del].set(False).at[cap].set(False)
        grave = state["grave"].at[tgt_del].set(True).at[cap].set(False)
        state["occ"], state["grave"] = occ, grave
        # rank of each segment within its key (new slot index)
        key_boundary = kh != prev_kh
        key_id = jnp.cumsum(key_boundary.astype(jnp.int32)) - 1
        key_first_seg = jax.ops.segment_min(seg, key_id, num_segments=m)
        rank = seg - key_first_seg[key_id]  # per item; valid at boundaries
        winner = boundary & seg_alive[seg]
        sess_ovf = jnp.sum(winner & (rank >= S))
        ins_act = winner & (rank < S)
        state, ins_slots = probe_insert(
            state, cap, kh, rank.astype(jnp.int64),
            [r[seg] for r in seg_reprs],
            jnp.zeros(m, jnp.int32), ins_act,
        )
        tgt_ins = jnp.where(ins_act, ins_slots, jnp.int32(cap))
        state["sess_start"] = state["sess_start"].at[tgt_ins].set(seg_start[seg])
        state["sess_end"] = state["sess_end"].at[tgt_ins].set(seg_end[seg])
        for j in range(ncomp):
            col = state[f"a{j}"]
            state[f"a{j}"] = col.at[tgt_ins].set(seg_comps[j][seg].astype(col.dtype))
        state["dirty"] = state["dirty"].at[tgt_ins].set(True)
        state["dirty"] = state["dirty"].at[cap].set(False)
        batch_max = jnp.max(jnp.where(active, ts, neg))
        state["max_ts"] = jnp.maximum(state["max_ts"], batch_max)
        if self._needs_seq:
            state["agg_seq"] = state["agg_seq"] + n

        # ---- emissions: tombstones for touched stored sessions (part A,
        # per item), merged aggregates per row-containing segment (part B,
        # at boundary items)
        tomb = del_mask & seg_has_row[seg]
        emit_seg = winner & seg_has_row[seg]
        nn = 2 * m
        out_env: Dict[str, DCol] = {}
        for k, colk in enumerate(self.agg.schema.key_columns):
            data_a = self._decode_key64(reprs_m[k], colk.type)
            data_b = self._decode_key64(seg_reprs[k][seg], colk.type)
            out_env[colk.name] = DCol(
                jnp.concatenate([data_a, data_b]),
                jnp.concatenate([tomb, emit_seg]),
                colk.type,
            )
        comp_idx = 1
        row_ts_a = comps_m[0]
        row_ts_b = seg_comps[0][seg]
        for spec in self.agg_specs:
            nc = len(spec.device.components)
            ca = [comps_m[comp_idx + j] for j in range(nc)]
            cb = [seg_comps[comp_idx + j][seg] for j in range(nc)]
            da, va = spec.device.finalize(ca)
            db, vb = spec.device.finalize(cb)
            out_env[spec.out_name] = DCol(
                jnp.concatenate([da, db]),
                jnp.concatenate([va & tomb, vb & emit_seg]),
                spec.device.result_type,
            )
            comp_idx += nc
        out_ts = jnp.concatenate([row_ts_a, row_ts_b])
        ones = jnp.ones(nn, bool)
        out_env["ROWTIME"] = DCol(out_ts, ones, T.BIGINT)
        out_env["WINDOWSTART"] = DCol(
            jnp.concatenate([start, seg_start[seg]]), ones, T.BIGINT
        )
        out_env["WINDOWEND"] = DCol(
            jnp.concatenate([end, seg_end[seg]]), ones, T.BIGINT
        )
        mask = jnp.concatenate([tomb, emit_seg])
        # post-agg projections (HAVING rejected upstream for sessions)
        for op in self.post_ops:
            c2 = JaxExprCompiler(out_env, nn, self.dictionary)
            if isinstance(op, st.TableSelect):
                new_env: Dict[str, DCol] = {}
                src_keys = [k2.name for k2 in op.source.schema.key_columns]
                out_keys = [k2.name for k2 in op.schema.key_columns]
                for nname, oname in zip(out_keys, src_keys):
                    if oname in out_env:
                        new_env[nname] = out_env[oname]
                for name, e in op.selects:
                    new_env[name] = c2.compile(e)
                for p in ("ROWTIME", "WINDOWSTART", "WINDOWEND"):
                    new_env[p] = out_env[p]
                out_env = new_env
            else:
                raise DeviceUnsupported(f"{type(op).__name__} over SESSION")
        emits = self._pack_emits(out_env, mask, out_ts)
        emits["tombstone"] = jnp.concatenate(
            [jnp.ones(m, bool), jnp.zeros(m, bool)]
        )
        # per-record oracle order: a record's tombstones (by session start),
        # then its merged session
        ord_row = jnp.where(seg_minrow[seg] == big, 0, seg_minrow[seg])
        emits["ord_a"] = jnp.concatenate([ord_row, ord_row])
        emits["ord_b"] = jnp.concatenate([start, jnp.full(m, big, jnp.int64)])
        emits["sess_ovf"] = sess_ovf
        emits["occupancy"] = jnp.sum(state["occ"] | state["grave"])
        emits["graves"] = jnp.sum(state["grave"])
        emits["overflow"] = state["overflow"]
        return state, emits

    def pre_exchange(
        self,
        max_ts: jnp.ndarray,
        arrays: Dict[str, jnp.ndarray],
        emit_clock: Optional[jnp.ndarray] = None,
        jtabs: Optional[Dict[str, Dict[str, jnp.ndarray]]] = None,
        seq_base: Optional[jnp.ndarray] = None,
    ) -> Dict[str, jnp.ndarray]:
        """Per-row phase before the shuffle boundary: transforms, window
        assignment, group-key hashing, aggregate contributions.  The returned
        flat payload is exactly what crosses the ICI all-to-all in the
        multi-chip path (the repartition-topic analog, SURVEY §2.3)."""
        n = self.capacity
        env = self._source_env(arrays)
        active = arrays["row_valid"]
        env, active = self._apply_pre_ops(env, active, n)
        if self.join is not None:
            env, active = self._apply_join(env, active, n, jtabs)
            env, active = self._apply_ops(self.mid_ops, env, active, n)
        ts = arrays["ts"]

        # ---------------- window assignment (expand for hopping)
        w = self.window
        if w is None:
            wstart = jnp.zeros(n, jnp.int64)
            wsize = 0
            k = 1
        elif w.window_type == WindowType.TUMBLING:
            wstart = W.tumbling_starts(ts, w.size_ms)
            wsize = w.size_ms
            k = 1
        elif w.window_type == WindowType.HOPPING and self.sliced:
            # stream slicing: each row lands in exactly ONE slice; the
            # per-window combine happens at emission (post_exchange), so
            # nothing expands before the shuffle
            wstart = W.slice_starts(ts, self.slice_width)
            wsize = w.size_ms
            k = 1
            # admission = the expansion path's any-window-open rule, per
            # family member: the NEWEST window covering the record's slice
            # ends at advance-aligned(ts) + size, and a record whose every
            # covering window is closed (end + grace <= stream time at
            # batch start) never reaches state on either path
            open_any = jnp.zeros(n, bool)
            for m in self.members:
                newest = ts - jnp.remainder(ts, m.advance_ms)
                open_any = open_any | (
                    newest + m.size_ms + m.grace_ms > max_ts
                )
            # ring-wrap safety cut: live slices must span < slice_ring
            # slices, or two batch rows could fold different slices into
            # one ring cell.  The cut sits at the family retention horizon
            # (ring = retention/width + 2), so it only drops records the
            # retention pass would evict this batch anyway — evaluated
            # against the IN-BATCH max ts, the one place the sliced path
            # is stricter than the expansion path's batch-start clock.
            batch_max = jnp.maximum(
                max_ts,
                jnp.max(jnp.where(active, ts, np.iinfo(np.int64).min)),
            )
            horizon_ok = (
                wstart + (self.slice_ring - 1) * self.slice_width > batch_max
            )
            active = active & open_any & horizon_ok
        elif w.window_type == WindowType.HOPPING:
            wstart, in_win = W.hopping_starts(ts, w.size_ms, w.advance_ms)
            wsize = w.size_ms
            k = W.hopping_expansion(w.size_ms, w.advance_ms)
            env = {
                name: DCol(W.expand(c.data, k), W.expand(c.valid, k), c.sql_type)
                for name, c in env.items()
            }
            active = W.expand(active, k) & in_win
            ts = W.expand(ts, k)
        else:  # pragma: no cover
            raise DeviceUnsupported(f"window {w.window_type}")
        nn = n * k

        # ---------------- group key
        group_exprs = tuple(getattr(self.group, "group_by_expressions", ()))
        c = JaxExprCompiler(env, nn, self.dictionary)
        if group_exprs:
            key_cols = [c.compile(e) for e in group_exprs]
        else:  # GROUP BY KEY (GroupByKey): existing key columns
            key_cols = [env[col.name] for col in self.group.schema.key_columns]
        reprs = [_repr64(kc) for kc in key_cols]
        knull = jnp.zeros(nn, jnp.int32)
        for i, kc in enumerate(key_cols):
            knull = knull | (~kc.valid).astype(jnp.int32) << i
        # rows with a null grouping expression are excluded (KS GroupBy);
        # note: the store's knull column is therefore always 0 today — kept
        # in the layout for formats that may re-admit null keys
        active = active & (knull == 0)
        khash = combine_hash(reprs + [knull.astype(jnp.int64)])

        # Late-record handling: a window is closed once stream time reaches
        # end + grace (inclusive).  EMIT FINAL uses the per-record stream
        # time (running max over rows reaching the aggregation, seeded with
        # the pre-batch stream time — the batched equivalent of the oracle's
        # `max_ts` advance; tiled hopping copies repeat each record's ts,
        # which leaves the running max's value set unchanged) because its
        # emission depends on the exact watermark sequence.  EMIT CHANGES
        # evaluates grace against the batch-start stream time (documented
        # delta: keeps the cummax scan off the hot path).
        if self.suppress:
            cm = jnp.maximum(
                jax.lax.cummax(jnp.where(active, ts, np.iinfo(np.int64).min)),
                max_ts,
            )
            active = active & (wstart + wsize + self.grace_ms > cm)
            # emission clock: per-record stream time over ALL raw source
            # rows (pre-filter, pre-expansion; length n not nn — the
            # emission test only needs the sorted watermark value set)
            cm_emit = jax.lax.cummax(
                jnp.where(arrays["row_valid"], arrays["ts"], np.iinfo(np.int64).min)
            )
            if emit_clock is not None:
                cm_emit = jnp.maximum(cm_emit, emit_clock)
        elif w is not None and not self.sliced:
            active = active & (wstart + wsize + self.grace_ms > max_ts)

        payload: Dict[str, jnp.ndarray] = {
            "khash": khash,
            "wstart": wstart,
            "knull": knull,
            "ts": ts,
            "active": active,
        }
        if self.suppress:
            payload["cm"] = cm_emit
        for i, r in enumerate(reprs):
            payload[f"repr{i}"] = r
        # contributions (component 0 is the per-slot ts watermark)
        contribs: List[jnp.ndarray] = [
            jnp.where(active, ts, np.iinfo(np.int64).min)
        ]
        seq = None
        if self._needs_seq:
            # arrival sequence: identical across a row's hopping copies so
            # per-(key,window) ordering follows arrival, not tiling
            base = seq_base if seq_base is not None else jnp.int64(0)
            seq = base + jnp.arange(n, dtype=jnp.int64)
            if k > 1:
                seq = W.expand(seq, k)
        for spec in self.agg_specs:
            args = [c.compile(e) for e in spec.arg_exprs]
            contribs.extend(spec.device.contribs(args, active, seq))
        for j, contrib in enumerate(contribs):
            payload[f"c{j}"] = contrib
        return payload

    def post_exchange(
        self, state: Dict[str, jnp.ndarray], payload: Dict[str, jnp.ndarray]
    ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        """State-owning phase after the shuffle boundary: probe/insert the
        keyed store, fold contributions, emit coalesced changes."""
        active = payload["active"]
        nn = active.shape[0]
        reprs = [payload[f"repr{i}"] for i in range(len(self.key_types))]
        # sliced stores key per GROUP KEY only (the slice ring hangs off the
        # key slot); expansion keys per (group key, window start)
        probe_w = (
            jnp.zeros_like(payload["wstart"])
            if self.sliced
            else payload["wstart"]
        )
        store, slots = probe_insert(
            state,
            self.store_capacity,
            payload["khash"],
            probe_w,
            reprs,
            payload["knull"],
            active,
        )
        ncomp = len(self.store_layout.components)
        contribs = [payload[f"c{j}"] for j in range(ncomp)]
        dump = jnp.int32(self.store_capacity)
        slot_or_dump = jnp.where(active, slots, dump)
        if self.sliced:
            # the per-batch emission mask must see the stream time AT BATCH
            # START (the expansion path's documented EMIT CHANGES clock) —
            # capture it before the fold advances max_ts
            max_ts_pre = state["max_ts"]
            store = self._sliced_scatter(store, slot_or_dump, payload, contribs)
        else:
            store = scatter_combine(
                store, self.store_layout, slot_or_dump, contribs
            )
        batch_max_ts = jnp.max(
            jnp.where(active, payload["ts"], np.iinfo(np.int64).min)
        )
        store["max_ts"] = jnp.maximum(store["max_ts"], batch_max_ts)

        # ---------------- emission (one change per touched key per batch)
        if self.suppress:
            # EMIT FINAL: a window emits iff some observed stream time T
            # lands in [close, start + retention] (close = end + grace) —
            # past the horizon the store segment is evicted unemitted, the
            # reference's windowed-store retention behavior (see
            # oracle.SuppressNode).  The per-record stream-time sequence is
            # non-decreasing, so searchsorted finds the first T >= close.
            size = self.window.size_ms
            cm = jnp.sort(payload["cm"])  # non-decreasing; sort guards the
            # post-shuffle case where rows arrive key-partitioned
            m = cm.shape[0]
            ws = store["wstart"]
            close = ws + size + self.grace_ms
            horizon = ws + self.retention_ms
            pos = jnp.searchsorted(cm, close)
            t_first = cm[jnp.minimum(pos, m - 1)]
            reachable = (pos < m) & (t_first <= horizon)
            final_t = cm[m - 1]
            store["emit_clock"] = jnp.maximum(store["emit_clock"], final_t)
            # record first-touch order for this batch's rows
            order = store["row_clock"] + jnp.arange(nn, dtype=jnp.int64)
            store["born"] = store["born"].at[slot_or_dump].min(
                jnp.where(active, order, np.iinfo(np.int64).max)
            )
            store["row_clock"] = store["row_clock"] + nn
            cand = store["occ"] & store["dirty"] & ~store["emitted"]
            emit_now = cand & reachable
            evict_now = cand & (close <= final_t) & ~reachable
            store["dirty"] = store["dirty"] & ~(emit_now | evict_now)
            store["emitted"] = store["emitted"] | emit_now
            store["occ"] = store["occ"] & ~evict_now
            store["grave"] = store["grave"] | evict_now
            store["born"] = jnp.where(
                evict_now, np.iinfo(np.int64).max, store["born"]
            )
            for j, comp in enumerate(self.store_layout.components):
                col = store[f"a{j}"]
                mask2 = evict_now[:, None] if col.ndim == 2 else evict_now
                store[f"a{j}"] = jnp.where(
                    mask2, jnp.asarray(comp.init, col.dtype), col
                )
            emits: Dict[str, jnp.ndarray] = {
                "emit_mask": jnp.zeros(nn, bool),
                "suppress_emit": emit_now,
            }
        elif self.sliced:
            # per-member window combine + emission: members[0] is this
            # query's own window; attached family members ride prefixed
            emits = self._sliced_member_emits(
                store, slots, payload, self.members[0], max_ts_pre
            )
            for member in self.members[1:]:
                sub = self._sliced_member_emits(
                    store, slots, payload, member, max_ts_pre
                )
                # lanes key by QUERY ID, not position: a pipelined batch's
                # emits outlive the member list that traced them — a
                # detach/re-attach between trace and decode must never
                # shift one member's rows onto another's sink
                for k2, v2 in sub.items():
                    emits[f"fam:{member.query_id}:{k2}"] = v2
        else:
            winners = winners_per_slot(slots, active, self.store_capacity)
            emits = self._emit_agg(store, slots, winners, nn)
        # load metrics, read host-side by process() to trigger growth
        # (graves hold probe-chain slots until compaction, so they count)
        emits["occupancy"] = jnp.sum(store["occ"] | store["grave"])
        emits["graves"] = jnp.sum(store["grave"])
        emits["overflow"] = store["overflow"]
        if self.sliced:
            # host mirror of the stream clock (rides the existing per-batch
            # load readback): lower-bounds the admission floor ensure_ring_for
            # sizes the ring against
            emits["smax_ts"] = store["max_ts"]
        return store, emits

    def _finalized_env(
        self,
        store: Dict[str, jnp.ndarray],
        slots: jnp.ndarray,
        nn: int,
        wsize_ms: Optional[int] = None,
        agg_schema: Optional[LogicalSchema] = None,
        agg_map: Optional[List[int]] = None,
    ) -> Tuple[Dict[str, DCol], jnp.ndarray]:
        """Gather + finalize store state at ``slots`` into an expression env
        over the aggregate's output schema.  Also returns the per-lane
        exactness-envelope verdict (True = this lane's accumulator passed
        its exact_abs_bound and the finalized value may have drifted);
        callers mask out dump-slot lanes before acting on it.  ``wsize_ms``
        overrides the window size for WINDOWEND (family members share one
        slice store but emit their own window bounds).  ``agg_map``
        restricts finalization to a member's own subset of the shared
        (union) partial set, re-bound to the member-local
        KSQL_AGG_VARIABLE_<i> names its post-ops and sink reference."""
        exceeded = jnp.zeros(nn, bool)
        env: Dict[str, DCol] = {}
        key_cols = (agg_schema or self.agg.schema).key_columns
        knull = store["knull"][slots]
        for i, col in enumerate(key_cols):
            data = store[f"key{i}"][slots]
            valid = (knull >> i & 1) == 0
            if col.type.base in (SqlBaseType.DOUBLE, SqlBaseType.DECIMAL):
                data = jax.lax.bitcast_convert_type(data, jnp.float64)
            elif col.type.base not in _HASHED:
                data = data.astype(col.type.device_dtype())
            env[col.name] = DCol(data, valid, col.type)
        row_ts = store["a0"][slots]
        starts = self._spec_comp_starts()
        indices = agg_map if agg_map is not None else range(len(self.agg_specs))
        for i, j in enumerate(indices):
            spec = self.agg_specs[j]
            ncomp = len(spec.device.components)
            base = starts[j]
            comps = [store[f"a{base + t}"][slots] for t in range(ncomp)]
            if spec.device.exact_abs_bound is not None:
                exceeded = exceeded | (
                    jnp.abs(comps[0]) > spec.device.exact_abs_bound
                )
            out_name = (
                spec.out_name if agg_map is None
                else f"KSQL_AGG_VARIABLE_{i}"
            )
            fin = spec.device.finalize(comps)
            if len(fin) == 4:  # map result: (keys2d, row_valid, present2d, counts2d)
                data, valid, present, counts = fin
                env[out_name] = DCol(
                    data, present, spec.device.result_type,
                    elem_valid=present, aux=counts,
                )
            elif len(fin) == 3:  # vector result: (data2d, present2d, elem_valid2d)
                data, valid, ev = fin
                env[out_name] = DCol(
                    data, valid, spec.device.result_type, elem_valid=ev
                )
            else:
                data, valid = fin
                env[out_name] = DCol(data, valid, spec.device.result_type)
        ones = jnp.ones(nn, bool)
        env["ROWTIME"] = DCol(row_ts, ones, T.BIGINT)
        if self.session:
            env["WINDOWSTART"] = DCol(store["sess_start"][slots], ones, T.BIGINT)
            env["WINDOWEND"] = DCol(store["sess_end"][slots], ones, T.BIGINT)
        elif self.window is not None:
            ws = store["wstart"][slots]
            size = wsize_ms if wsize_ms is not None else self.window.size_ms
            env["WINDOWSTART"] = DCol(ws, ones, T.BIGINT)
            env["WINDOWEND"] = DCol(ws + size, ones, T.BIGINT)
        return env, row_ts, exceeded

    def _emit_agg(
        self,
        store: Dict[str, jnp.ndarray],
        slots: jnp.ndarray,
        mask: jnp.ndarray,
        nn: int,
        ts_override: Optional[jnp.ndarray] = None,
    ) -> Dict[str, jnp.ndarray]:
        env, row_ts, dec_exceeded = self._finalized_env(store, slots, nn)
        if ts_override is not None:
            # table-change emissions carry the triggering record's timestamp
            # (oracle _receive_table_change), not the slot watermark
            row_ts = ts_override
            env["ROWTIME"] = DCol(
                ts_override, jnp.ones(nn, bool), T.BIGINT
            )
        # post-agg projection / HAVING
        tomb_h = None
        for op in self.post_ops:
            c = JaxExprCompiler(env, nn, self.dictionary)
            if isinstance(op, st.TableFilter):
                pred = c.compile(op.predicate)
                pass_now = pred.valid & pred.data.astype(bool)
                if "hpass" in store:
                    # HAVING retraction: a slot that previously emitted a
                    # passing row and now fails emits a tombstone.  hpass
                    # updates IN PLACE in the caller's store dict (both
                    # callers pass a fresh dict they keep using).
                    dump = jnp.int32(self.store_capacity)
                    prev = store["hpass"][slots]
                    t = mask & prev & ~pass_now
                    tomb_h = t if tomb_h is None else (tomb_h | t)
                    touched = jnp.where(mask, slots, dump)
                    store["hpass"] = store["hpass"].at[touched].set(pass_now)
                    mask = mask & (pass_now | t)
                else:
                    mask = mask & pass_now
            else:  # TableSelect
                new_env: Dict[str, DCol] = {}
                src_keys = [k.name for k in op.source.schema.key_columns]
                out_keys = [k.name for k in op.schema.key_columns]
                for new_name, old_name in zip(out_keys, src_keys):
                    if old_name in env:
                        new_env[new_name] = env[old_name]
                for name, e in op.selects:
                    new_env[name] = c.compile(e)
                for p in ("ROWTIME", "WINDOWSTART", "WINDOWEND"):
                    if p in env:
                        new_env[p] = env[p]
                env = new_env
        emits = self._pack_emits(env, mask, row_ts)
        if tomb_h is not None:
            emits["tombstone"] = tomb_h
        # exactness-envelope verdict for the EMITTED lanes only (dump-slot
        # gathers hold accumulated garbage and must not trip it); rank-1 so
        # the table-agg old/new emit concatenation composes
        emits["dec_envelope"] = jnp.sum(
            (dec_exceeded & mask).astype(jnp.int64)
        ).reshape(1)
        return emits

    def _emit_stateless(
        self, env: Dict[str, DCol], active: jnp.ndarray, ts: jnp.ndarray
    ) -> Dict[str, jnp.ndarray]:
        return self._pack_emits(env, active, ts)

    def _pack_emits(
        self,
        env: Dict[str, DCol],
        mask: jnp.ndarray,
        ts: jnp.ndarray,
        schema: Optional[LogicalSchema] = None,
    ) -> Dict[str, jnp.ndarray]:
        out: Dict[str, jnp.ndarray] = {"emit_mask": mask, "emit_ts": ts}
        schema = schema if schema is not None else self._emit_schema()
        for col in schema.columns():
            d = env.get(col.name)
            if d is None:
                raise DeviceUnsupported(f"sink column {col.name} not computed on device")
            out[f"v_{col.name}"] = d.data
            out[f"m_{col.name}"] = d.valid
            if d.data.ndim == 2:  # vector column: per-element null bits
                out[f"e_{col.name}"] = (
                    d.elem_valid if d.elem_valid is not None else d.valid
                )
                if d.aux is not None:  # map column: per-element counts
                    out[f"c_{col.name}"] = d.aux
        if (self.window is not None or self.windowed_source) and "WINDOWSTART" in env:
            out["ws"] = env["WINDOWSTART"].data
            out["we"] = env["WINDOWEND"].data
        return out

    def _trace_evict(
        self, store: Dict[str, jnp.ndarray]
    ) -> Dict[str, jnp.ndarray]:
        """Retention pass: free slots whose window left retention, resetting
        components so reclaimed slots start clean.  Run periodically from
        the host (amortized — the RocksDB-compaction analog), not per step.
        Suppressed-but-unflushed windows are kept until flush()."""
        store = dict(store)
        if self.sliced:
            # sliced slots are per KEY: a slot expires only once its NEWEST
            # slice left the family retention window (individual stale ring
            # cells recycle in place at the next wrap)
            expired = store["occ"] & (
                store["slast"] + self.family_retention_ms < store["max_ts"]
            )
            store["slast"] = jnp.where(expired, -(2 ** 62), store["slast"])
            store["slice_id"] = jnp.where(
                expired[:, None], jnp.int64(-1), store["slice_id"]
            )
        else:
            expired = store["occ"] & (
                store["wstart"] + self.retention_ms < store["max_ts"]
            )
        if self.suppress:
            expired = expired & ~store["dirty"]
        store["occ"] = store["occ"] & ~expired
        store["grave"] = store["grave"] | expired
        store["dirty"] = store["dirty"] & ~expired
        if "hpass" in store:
            store["hpass"] = store["hpass"] & ~expired
        if "born" in store:
            store["born"] = jnp.where(
                expired, np.iinfo(np.int64).max, store["born"]
            )
            store["emitted"] = store["emitted"] & ~expired
        for j, comp in enumerate(self.store_layout.components):
            col = store[f"a{j}"]
            mask2 = expired[:, None] if col.ndim == 2 else expired
            store[f"a{j}"] = jnp.where(
                mask2, jnp.asarray(comp.init, col.dtype), col
            )
        return store

    # ------------------------------------------------------------ host API
    EVICT_INTERVAL = 64  # batches between retention passes

    #: jitted step attributes (dict-valued entries hold per-side/per-probe
    #: jits) — enumerated for the flight recorder's jit-cache accounting
    _JIT_ATTRS = (
        "_step", "_evict", "_ss_l", "_ss_r", "_ss_expire", "_ta_step",
        "_verdict", "_table_steps", "_fk_steps", "_tt_steps",
    )

    def jit_cache_entries(self) -> int:
        """Total in-memory jit cache entries across this query's compiled
        steps.  The executor samples it around each device call: a growing
        cache means that call paid a trace+compile (flight-recorder
        ``device.compile`` / jit_miss), a flat one was a cache hit."""
        fns = []
        for name in self._JIT_ATTRS:
            f = getattr(self, name, None)
            fns.extend(f.values() if isinstance(f, dict) else (f,))
        return tracing.jit_cache_size(fns)

    #: when True (batched engine mode), emission decode lags one batch so
    #: host encode of batch i+1 overlaps device compute of batch i — the
    #: double-buffered DMA row of SURVEY §2.3.  Per-record parity mode
    #: keeps it off (emissions must surface with their record).
    pipeline = False
    _pending_emits: Optional[Dict[str, jnp.ndarray]] = None

    def process(self, batch: HostBatch) -> List[SinkEmit]:
        if self.ss_join is not None:
            return self.process_ss(batch, "l")
        return self.process_arrays(self.layout.encode(batch))

    def process_arrays(self, arrays: Dict[str, np.ndarray]) -> List[SinkEmit]:
        """One encoded micro-batch through the device step (the entry the
        native ingest tier feeds directly, bypassing HostBatch)."""
        _note_transfer("h2d_bytes", arrays)
        if self.sliced:
            self.ensure_ring_for(arrays["ts"], arrays["row_valid"])
        if self.session:
            while True:
                new_state, emits = self._step(self.state, arrays)
                if int(emits["sess_ovf"]) > 0:
                    # more concurrent sessions per key than tracked slots:
                    # grow and re-run the batch (steps are undonated)
                    self._grow_sessions()
                    continue
                break
            self.state = new_state
        else:
            self.state, emits = self._step(self.state, arrays)
        result: Optional[List[SinkEmit]] = None
        if self.suppress:
            # windows the step closed this batch — emitted BEFORE the
            # retention pass / store growth below, which remap or reset
            # slots (dirty already cleared in-trace; values stay resident)
            idx = np.nonzero(np.asarray(emits["suppress_emit"]))[0]
            result = self._emit_slots(idx)
        if self.agg is not None:
            self._batches += 1
            if (
                self.retention_ms is not None
                and self._batches % self.EVICT_INTERVAL == 0
            ):
                self.state = self._evict(self.state)
        if result is not None:
            self._react_to_load(emits)
            return result
        if self.pipeline and not self.suppress and not self.session:
            emits, self._pending_emits = self._pending_emits, emits
            if emits is None:
                return []
            # sample the load check: int() forces a device sync, and in
            # pipelined mode the 0.75-occupancy growth threshold leaves
            # several batches of headroom
            if self.agg is not None and self._batches % 4 == 0:
                self._react_to_load(emits)
        elif self.agg is not None:
            self._react_to_load(emits)
        self._deliver_members(emits)
        return self._decode_emits(emits)

    def _deliver_members(self, emits: Dict[str, jnp.ndarray]) -> None:
        """Decode + deliver the attached members' emission blocks
        (``fam:<qid>:`` window-family lanes and ``pfx:<qid>:`` shared
        source-prefix lanes of the shared device step).  Delivered lanes
        are REMOVED from ``emits`` so the primary's own decode (and its
        d2h transfer accounting) never sees them twice.  Lanes route by
        QUERY ID: a pipelined batch decoded after a detach/re-attach must
        never shift one member's rows onto another's sink."""
        lanes = [
            (f"fam:{m.query_id}:", m) for m in self.members[1:]
        ] + [
            (f"pfx:{m.query_id}:", m) for m in self.prefix_members
        ]
        for prefix, member in lanes:
            sub = {
                key[len(prefix):]: emits.pop(key)
                for key in list(emits)
                if key.startswith(prefix)
            }
            if not sub or member.deliver is None:
                continue
            rows = self._decode_emits(sub, schema=member.sink_schema)
            if rows:
                member.deliver(rows)
        # lanes of members detached between the batch's trace and this
        # (pipelined) decode: DROP them — the member is gone or mid-
        # rebuild, and its parked rows must not reach any other sink
        for key in list(emits):
            if key.startswith("fam:") or key.startswith("pfx:"):
                emits.pop(key)

    def _trace_verdict(self, arrays: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        """Filter verdict only (no emission) — evaluates the table pipeline
        over a batch of OLD rows to decide tombstones."""
        n = self.capacity
        env = self._source_env(arrays)
        active = arrays["row_valid"]
        _env, active = self._apply_pre_ops(env, active, n)
        return active

    def process_table_changes(
        self, new_batch: HostBatch, old_batch: HostBatch,
        keys: List[tuple], has_new: np.ndarray, has_old: np.ndarray,
        ts: List[int],
    ) -> List[SinkEmit]:
        """Table-to-table transform step: one device pass over the NEW rows
        (projection + filter) and one verdict pass over the OLD rows; a
        change whose new row fails (or is a delete) while its old row passed
        emits a tombstone (reference TableFilter forwarding semantics)."""
        if self.table_agg:
            a_new = self.layout.encode(new_batch)
            a_old = self.layout.encode(old_batch)
            pad_old = np.zeros(self.capacity, bool)
            pad_old[: len(keys)] = has_old
            a_old["row_valid"] = pad_old
            pad_new = np.zeros(self.capacity, bool)
            pad_new[: len(keys)] = has_new
            a_new["row_valid"] = pad_new
            self.state, emits = self._ta_step(self.state, a_new, a_old)
            self._react_to_load(emits)
            return self._decode_emits(emits, sort=False)
        if not hasattr(self, "_verdict"):
            self._verdict = jax.jit(self._trace_verdict)
        arrays_new = self.layout.encode(new_batch)
        self.state, emits = self._step(self.state, arrays_new)
        old_ok = np.zeros(len(keys), bool)
        if has_old.any():
            old_ok_dev = np.asarray(self._verdict(self.layout.encode(old_batch)))
            old_ok = old_ok_dev[: len(keys)] & has_old
        new_mask = np.asarray(emits["emit_mask"])[: len(keys)] & has_new
        rows = self._decode_emits(emits, sort=False)
        by_index: Dict[int, SinkEmit] = {}
        order = np.nonzero(np.asarray(emits["emit_mask"]))[0]
        for pos, e in zip(order, rows):
            if pos < len(keys):
                by_index[int(pos)] = e
        out: List[SinkEmit] = []
        for i, key in enumerate(keys):
            if new_mask[i]:
                e = by_index.get(i)
                if e is not None:
                    out.append(SinkEmit(key, e.row, ts[i], e.window))
            elif old_ok[i]:
                out.append(SinkEmit(key, None, ts[i], None))
        return out

    def flush_pipeline(self) -> List[SinkEmit]:
        """Decode the deferred batch (poll-tick boundary)."""
        emits, self._pending_emits = self._pending_emits, None
        if emits is None:
            return []
        if self.agg is not None:
            self._react_to_load(emits)
        self._deliver_members(emits)
        return self._decode_emits(emits)

    _seen_overflow = 0
    _batches = 0

    def _react_to_load(self, emits: Dict[str, jnp.ndarray]) -> None:
        """Grow the store before it can overflow (and surface data loss
        loudly if it somehow did — slot exhaustion drops aggregates)."""
        if "smax_ts" in emits:
            self._mirror_max_ts = max(
                self._mirror_max_ts, int(emits["smax_ts"])
            )
        overflow = int(emits["overflow"])
        if overflow > self._seen_overflow:
            self._seen_overflow = overflow
            raise QueryRuntimeException(
                f"device state store overflowed ({overflow} rows lost); "
                f"store_capacity={self.store_capacity} is undersized for the "
                "key×window cardinality — restart the query from its "
                "changelog with a larger store"
            )
        occupancy = int(emits["occupancy"])
        headroom = self.capacity * self.expansion
        if self.pipeline:
            headroom *= 4  # load checks are sampled every 4th batch
        if occupancy + headroom > 0.75 * self.store_capacity:
            if self.retention_ms is not None:
                # evict expired windows now (off-cadence), then compact the
                # tombstones away in place — the RocksDB compaction analog;
                # grow only if the table is still dense with LIVE entries
                self.state = self._evict(self.state)
                live = self._grow(factor=1)
                if (
                    live + headroom > 0.5 * self.store_capacity
                    and self._grow_allowed()
                ):
                    self._grow()
            elif self._grow_allowed():
                self._grow()

    def _grow_sessions(self, factor: int = 2) -> None:
        """More concurrent sessions per key: probe identities (khash, slot)
        stay valid, only the gather loop bound changes — recompile."""
        self.session_slots *= factor
        self._step = jax.jit(self._trace_step)

    #: HBM admission budget enforced at store-growth time (bytes; 0 = no
    #: gate).  Wired by the engine from ksql.analysis.memory.budget.bytes,
    #: with ``on_grow_refuse`` carrying the refusal into the processing
    #: log + /alerts evidence.  ``_grow_refused_at`` memoizes one refusal
    #: per capacity so a saturated store logs once, not once per batch.
    memory_budget_bytes = 0
    on_grow_refuse = None
    _grow_refused_at = -1

    def _grow_allowed(self, factor: int = 2) -> bool:
        """Gate a store doubling against the HBM budget: project the
        post-grow footprint from the LIVE per-component measurement
        (store-capacity-scaled components double; separately-sized
        join-table / ss-buffer stores do not) and refuse the grow when it
        would overflow ``ksql.analysis.memory.budget.bytes`` — the query
        keeps serving at its current capacity, with the store overflow
        counters making saturation visible (and the eventual overflow
        loud).

        Deliberately NOT gated: ``_grow_sessions`` — the sess_ovf retry
        loop cannot complete the in-flight batch without more session
        slots, so refusing there would spin forever or fail the query
        outright; the admission-time at-growth-cap price remains the
        sizing control for session state (documented in README)."""
        budget = int(self.memory_budget_bytes or 0)
        if not budget or factor <= 1:
            return True
        if self._grow_refused_at == self.store_capacity:
            return False  # already refused (and logged) at this capacity
        from ksql_tpu.analysis.mem_model import measure_state_bytes

        comps = measure_state_bytes(self.state, sliced=self.sliced)
        fixed = ("join.table", "ss.buffer", "tt.store", "fk.store")
        proj = sum(
            b if c.startswith(fixed) else b * factor
            for c, b in comps.items()
        )
        if proj <= budget:
            return True
        self._grow_refused_at = self.store_capacity
        scaled = {c: b for c, b in comps.items() if not c.startswith(fixed)}
        dom = max(scaled, key=scaled.get) if scaled else "store"
        msg = (
            f"store growth {self.store_capacity}->"
            f"{self.store_capacity * factor} slots refused: projected "
            f"footprint {proj} bytes > ksql.analysis.memory.budget.bytes="
            f"{budget} (dominant component {dom}="
            f"{scaled.get(dom, 0)}B live); serving continues at current "
            "capacity — watch the store overflow counter"
        )
        cb = self.on_grow_refuse
        if cb is not None:
            try:
                cb(msg, dom, int(proj), budget)
            except Exception:  # noqa: BLE001 — a logging failure must not
                pass  # turn a refusal into a query crash
        return False

    def _grow(self, factor: int = 2) -> int:
        """Rebuild the store host-side (numpy reinsert of live slots),
        dropping tombstones; factor=1 compacts in place, factor>1 also
        doubles capacity and recompiles for the new shapes.  Returns the
        number of live slots."""
        cur = dict(self.state)
        jtab = cur.pop("jtab", None)  # join-table store is sized separately
        old = {k: np.asarray(v) for k, v in jax.device_get(cur).items()}
        self.store_capacity *= factor
        self.store_layout = dataclasses.replace(
            self.store_layout, capacity=self.store_capacity
        )
        init = dict(self.init_state())
        init.pop("jtab", None)
        new = {
            k: np.array(v)  # writable copies: device_get arrays are read-only
            for k, v in jax.device_get(init).items()
        }
        scalars = {n for n, v in old.items() if v.ndim == 0}
        live = np.nonzero(old["occ"][:-1])[0]
        if live.size:
            from ksql_tpu.ops.hash_store import host_insert

            slots = host_insert(
                new["occ"],
                new["khash"],
                new["wstart"],
                self.store_capacity,
                old["khash"][live],
                old["wstart"][live],
            )
            for name in old:
                if name in scalars or name in ("occ", "khash", "wstart"):
                    continue
                new[name][slots] = old[name][live]
        for name in scalars:  # max_ts, overflow, emit_clock
            new[name] = old[name]
        # jnp.array (copy), not asarray: the rebuilt host arrays must not be
        # zero-copy aliased into state the donating step later recycles
        grown = {k: jnp.array(v) for k, v in new.items()}
        if jtab is not None:
            grown["jtab"] = jtab
        self.state = grown
        if factor != 1:  # shapes changed: recompile every store-shaped step
            self._compile_steps()
        return int(live.size)

    def _decode_emits(
        self,
        emits: Dict[str, jnp.ndarray],
        sort: bool = True,
        schema: Optional[LogicalSchema] = None,
    ) -> List[SinkEmit]:
        _note_transfer("d2h_bytes", emits)
        # a stale raw block must never outlive its batch: misalignment
        # with the fanned-out emits would hand the tap kernel the wrong
        # rows (the dispatcher validates n, so clearing is the guarantee)
        self.last_raw_block = None
        if "dec_envelope" in emits:
            n_drift = int(np.asarray(emits["dec_envelope"]).sum())
            if n_drift:
                # never emit a silently drifted decimal sum: the accumulated
                # value passed the float64-exact envelope the static gate
                # certified headroom for (device_aggs.exact_abs_bound)
                raise QueryRuntimeException(
                    f"DECIMAL SUM exceeded the 2^53-exact envelope on "
                    f"{n_drift} emitted aggregate(s); rerun this query on "
                    "the oracle backend (ksql.runtime.backend=oracle) for "
                    "unbounded decimal arithmetic"
                )
        mask = np.asarray(emits["emit_mask"])
        idx = np.nonzero(mask)[0]
        if idx.size == 0:
            return []
        if "ord_a" in emits:
            # explicit emission order (join match/expiry sequencing)
            oa = np.asarray(emits["ord_a"])[idx]
            ob = np.asarray(emits["ord_b"])[idx]
            idx = idx[np.lexsort((ob, oa))]
            sort = False
        schema = schema if schema is not None else self._emit_schema()
        cols: Dict[str, List[Any]] = {}
        for col in schema.columns():
            data = np.asarray(emits[f"v_{col.name}"])[idx]
            valid = np.asarray(emits[f"m_{col.name}"])[idx]
            if data.ndim == 2 and f"c_{col.name}" in emits:
                # map column (histogram): present elements decode as keys,
                # the count companion as values, regrouped per row
                nums = np.asarray(emits[f"c_{col.name}"])[idx]
                flat_present = valid.reshape(-1)
                keys = decode_value(
                    data.reshape(-1)[flat_present],
                    np.ones(int(flat_present.sum()), bool),
                    col.type.key or col.type.element, self.dictionary,
                )
                vals = nums.reshape(-1)[flat_present]
                counts = valid.sum(axis=1)
                bounds = np.cumsum(counts)[:-1]
                cols[col.name] = [
                    dict(zip(kp, (int(x) for x in vp)))
                    for kp, vp in zip(
                        np.split(np.asarray(keys, object), bounds),
                        np.split(vals, bounds),
                    )
                ]
                continue
            if data.ndim == 2:
                # vector column (collect/topk): decode only the present
                # elements, regroup into per-row lists by row counts
                ev = np.asarray(emits[f"e_{col.name}"])[idx]
                flat_present = valid.reshape(-1)
                elems = decode_value(
                    data.reshape(-1)[flat_present],
                    ev.reshape(-1)[flat_present],
                    col.type.element, self.dictionary,
                )
                counts = valid.sum(axis=1)
                bounds = np.cumsum(counts)[:-1]
                # element-wise object array: np.asarray would promote
                # equal-length list elements (nested ARRAY values) to 2-D
                flat = np.empty(len(elems), object)
                for i2, v2 in enumerate(elems):
                    flat[i2] = v2
                cols[col.name] = [
                    list(part) for part in np.split(flat, bounds)
                ]
                continue
            cols[col.name] = decode_value(data, valid, col.type, self.dictionary)
        ts = np.asarray(emits["emit_ts"])[idx]
        ws = np.asarray(emits["ws"])[idx] if "ws" in emits else None
        we = np.asarray(emits["we"])[idx] if "we" in emits else None
        tomb = (
            np.asarray(emits["tombstone"])[idx] if "tombstone" in emits else None
        )
        out: List[SinkEmit] = []
        key_names = [c.name for c in schema.key_columns]
        val_names = [c.name for c in schema.value_columns]
        collapse_null_keys = (
            self.agg is None
            and self.join is None
            and self.ss_join is None
            and not any(
                isinstance(op, (st.StreamSelectKey, st.TableSelectKey))
                for op in self.pre_ops
            )
        )
        for j in range(idx.size):
            key = tuple(cols[kn][j] for kn in key_names)
            if collapse_null_keys and key and all(k is None for k in key):
                # key passthrough of a null-key record: the oracle carries
                # an empty key tuple, which the sink writes as a null key
                key = ()
            if tomb is not None and tomb[j]:
                row = None
            else:
                row = {kn: cols[kn][j] for kn in key_names}
                row.update({vn: cols[vn][j] for vn in val_names})
            window = (int(ws[j]), int(we[j])) if ws is not None else None
            out.append(SinkEmit(key, row, int(ts[j]), window))
        if sort:
            # ts-major, window-start-minor: matches the oracle's per-record
            # ascending-window emission order for hopping expansions
            if self.collect_raw_emits:
                # keep the emit-order permutation so the raw block below
                # stays row-aligned with the fanned-out emits
                order = sorted(
                    range(len(out)),
                    key=lambda j: (out[j].ts, out[j].window or (0, 0)),
                )
                out = [out[j] for j in order]
                idx = idx[np.asarray(order, np.intp)]
            else:
                out.sort(key=lambda e: (e.ts, e.window or (0, 0)))
        if self.collect_raw_emits and out:
            # fused-residual handoff: the emission batch's scalar columns,
            # gathered on device in final emit order.  Vector/map columns
            # are skipped (the tap kernel host-paths spans that need them)
            raw_cols: Dict[str, Tuple[jnp.ndarray, jnp.ndarray]] = {}
            for col in schema.columns():
                data = emits.get(f"v_{col.name}")
                if data is None or data.ndim != 1:
                    continue
                raw_cols[col.name] = (
                    data[idx], emits[f"m_{col.name}"][idx]
                )
            self.last_raw_block = {
                "cols": raw_cols,
                "ts": emits["emit_ts"][idx],
                "row_none": np.fromiter(
                    (e.row is None for e in out), bool, count=len(out)
                ),
                "n": len(out),
                # identity of the emit list this block is aligned with —
                # the dispatcher checks it, so a member-lane decode can
                # never hand its block to the primary's fan-out
                "emits_id": id(out),
            }
        return out

    # --------------------------------------------- suppress (EMIT FINAL)
    def flush(self, stream_time: Optional[int] = None) -> List[SinkEmit]:
        """Emit & evict closed windows (EMIT FINAL path; host-side scan —
        off the hot loop, the TableSuppressBuilder analog)."""
        if self.ss_join is not None:
            if stream_time is None:
                return self.ss_expire_host()
            return self.ss_flush(stream_time)
        if not self.suppress or self.store_layout is None:
            return []
        state = jax.device_get(self.state)
        if stream_time is None:
            stream_time = int(state["max_ts"])
        occ = state["occ"]
        ws = state["wstart"]
        size = self.window.size_ms
        closed = (
            occ
            & state["dirty"]
            & ~state["emitted"]
            & (ws + size + self.grace_ms <= stream_time)
        )
        self.state = dict(self.state)
        # the flush watermark advances the emission clock even when nothing
        # closes (oracle flush_time semantics)
        self.state["emit_clock"] = jnp.maximum(
            self.state["emit_clock"], jnp.int64(stream_time)
        )
        idx = np.nonzero(closed)[0]
        if idx.size == 0:
            return []
        result = self._emit_slots(idx)
        # mark flushed windows clean (suppressed windows emit exactly once)
        slots = jnp.asarray(idx.astype(np.int32))
        self.state["dirty"] = self.state["dirty"].at[slots].set(False)
        self.state["emitted"] = self.state["emitted"].at[slots].set(True)
        return result

    def scan_store(self) -> List[SinkEmit]:
        """Materialized-state scan: every live slot of the HBM store,
        finalized + post-op'd + decoded.  Serves pull queries straight from
        device state (KsMaterializedTableIQv2 analog) instead of a host-side
        shadow dict.  EMIT FINAL tables expose only already-emitted windows
        (matching what downstream consumers have observed)."""
        if self.store_layout is None:
            return []
        occ = np.asarray(jax.device_get(self.state["occ"]))[:-1]
        if self.suppress:
            occ = occ & np.asarray(jax.device_get(self.state["emitted"]))[:-1]
        return self._emit_slots(np.nonzero(occ)[0])

    def lookup_store(self, key_tuples) -> Optional[List[SinkEmit]]:
        """Keyed pull fast path (KeyedTableLookupOperator vs
        TableScanOperator — PullPhysicalPlanBuilder.java:247-256): match the
        store's key-repr columns against the WHERE clause's exact keys on
        device, transfer and decode ONLY the matching slots.  Windowed
        stores return every window of the key.  Returns None when this
        store can't serve keyed lookups (no layout, or a key value with no
        64-bit repr) — the caller falls back to scan_store()."""
        if self.store_layout is None:
            return None
        reprs_per_tuple: List[List[int]] = []
        for kt in key_tuples:
            reprs = []
            for v, t in zip(kt, self.key_types):
                r = _host_repr64(v, t)
                if r is None:
                    return None
                reprs.append(r)
            reprs_per_tuple.append(reprs)
        occ = self.state["occ"][:-1]
        if self.suppress:
            occ = occ & self.state["emitted"][:-1]
        nonnull = self.state["knull"][:-1] == 0
        m_any = jnp.zeros_like(occ)
        for reprs in reprs_per_tuple:
            m = occ & nonnull
            for i, r in enumerate(reprs):
                m = m & (self.state[f"key{i}"][:-1] == jnp.int64(r))
            m_any = m_any | m
        idx = np.nonzero(np.asarray(jax.device_get(m_any)))[0]
        return self._emit_slots(idx)

    #: slots decoded by the most recent scan_store/lookup_store call — the
    #: store metric proving keyed pulls touch O(matches) slots, not
    #: O(live-slots) like a scan
    last_pull_slots_decoded: int = 0

    def _emit_slots_sliced(self, idx: np.ndarray) -> List[SinkEmit]:
        """Materialized-state decode for a SLICED store: expand each key
        slot's live slices into the (slot, window) pairs of the PRIMARY
        member still inside retention, monoid-merge the covering slices per
        window, and decode — the pull-query view of a sliced hopping
        aggregation.  Off the hot loop (host lane construction + eager
        device combine).

        Parity note: a late-but-in-grace record lands in its slice once,
        so a window that was already closed at its arrival still absorbs
        it HERE (the expansion store would not) — sliced pull results over
        closed-but-retained windows may include late records the
        per-window grace check dropped from emission on both paths."""
        self.last_pull_slots_decoded = int(idx.size)
        if idx.size == 0:
            return []
        member = self.members[0]
        sid = np.asarray(jax.device_get(self.state["slice_id"]))[idx]
        max_ts = int(jax.device_get(self.state["max_ts"]))
        width = self.slice_width
        S = W.slices_per_window(member.size_ms, width)
        A = member.advance_ms // width
        k = W.hopping_expansion(member.size_ms, member.advance_ms)
        pairs = set()
        rows, cols = np.nonzero(sid >= 0)
        for r, c in zip(rows, cols):
            s = int(sid[r, c])
            g = s - s % A
            for j in range(k):
                w = g - j * A
                if w < 0 or w + S <= s:
                    continue
                # mirror the expansion store's retention pass: windows past
                # wstart + retention are evicted, not scanned
                if w * width + member.retention_ms < max_ts:
                    continue
                pairs.add((int(idx[r]), w))
        if not pairs:
            return []
        # window-start-major, slot-minor: the windowed-scan order of the
        # expansion store's _emit_slots (ws then creation)
        lanes = sorted(pairs, key=lambda p: (p[1], p[0]))
        slot_lane = jnp.asarray(
            np.asarray([p[0] for p in lanes], np.int32)
        )
        w_lane = jnp.asarray(np.asarray([p[1] for p in lanes], np.int64))
        env, row_ts, dec_exceeded = self._combine_windows(
            self.state, slot_lane, w_lane, member
        )
        mask = jnp.ones(len(lanes), bool)
        emits = self._member_emit(
            env, row_ts, dec_exceeded, mask, member, len(lanes)
        )
        self.last_pull_slots_decoded = len(lanes)
        return self._decode_emits(emits, sort=False)

    def _emit_slots(self, idx: np.ndarray) -> List[SinkEmit]:
        """Finalize + post-op + decode the given store slots (EMIT FINAL
        emission path, shared by the per-batch close and end-of-stream
        flush), ordered by window start."""
        if self.sliced:
            return self._emit_slots_sliced(idx)
        self.last_pull_slots_decoded = int(idx.size)
        if idx.size == 0:
            return []
        ws_host = np.asarray(self.state["wstart"])[idx]
        born = (
            np.asarray(self.state["born"])[idx]
            if "born" in self.state
            else np.zeros(idx.size, np.int64)
        )
        # window-end-major (ws + fixed size), creation-order-minor — the
        # oracle SuppressNode's emission order
        idx = idx[np.lexsort((born, ws_host))]
        slots = jnp.asarray(idx.astype(np.int32))
        env, row_ts, dec_exceeded = self._finalized_env(
            self.state, slots, idx.size
        )
        mask = jnp.ones(idx.size, bool)
        # post-agg ops on the emitted rows
        for op in self.post_ops:
            c = JaxExprCompiler(env, idx.size, self.dictionary)
            if isinstance(op, st.TableFilter):
                pred = c.compile(op.predicate)
                mask = mask & pred.valid & pred.data.astype(bool)
            else:
                new_env = {}
                src_keys = [k.name for k in op.source.schema.key_columns]
                out_keys = [k.name for k in op.schema.key_columns]
                for nname, oname in zip(out_keys, src_keys):
                    if oname in env:
                        new_env[nname] = env[oname]
                for name, e in op.selects:
                    new_env[name] = c.compile(e)
                for p in ("ROWTIME", "WINDOWSTART", "WINDOWEND"):
                    if p in env:
                        new_env[p] = env[p]
                env = new_env
        emits = self._pack_emits(env, mask, row_ts)
        emits["dec_envelope"] = jnp.sum(
            (dec_exceeded & mask).astype(jnp.int64)
        ).reshape(1)
        # idx is already in emission order (window end, then creation) —
        # keep it; ts-sorting would break the oracle's suppress ordering
        return self._decode_emits(emits, sort=False)
