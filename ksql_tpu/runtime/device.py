"""Host↔device columnar staging: encode HostBatch → fixed-shape arrays.

The ingress analog of the reference's per-record deserialization
(GenericRowSerDe): rows are staged host-side into a :class:`HostBatch`, then
encoded to a dict of fixed-capacity numpy arrays (one compile per capacity
under jit):

* numeric/temporal columns → their device dtype, nulls masked;
* STRING/BYTES columns → the stable 64-bit hash of each value (device sees
  only hashes — variable-length data never reaches HBM).  The
  :class:`DictionaryServer` keeps the hash→value mapping host-side so sink
  emission can restore the original values (the egress analog of reading the
  key back out of RocksDB).

Array naming convention (the flat dict becomes a jit argument pytree):
``v_<COL>`` data, ``m_<COL>`` validity, plus ``ts`` (event-time ms),
``row_valid`` (fill mask), ``offset`` (per-row offset pseudocolumn) and
``partition``.
"""

from __future__ import annotations

import dataclasses
import decimal as _decimal
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ksql_tpu.common.batch import HostBatch, encode_column, stable_hash64
from ksql_tpu.common.schema import LogicalSchema
from ksql_tpu.common.types import SqlBaseType, SqlType

_HASHED = (SqlBaseType.STRING, SqlBaseType.BYTES)
_NESTED = (SqlBaseType.ARRAY, SqlBaseType.MAP, SqlBaseType.STRUCT)
#: types the device carries as int64 dictionary codes: strings/bytes plus
#: nested values used opaquely (passthrough, equality, grouping)
DICT_ENCODED = _HASHED + _NESTED


class DictionaryServer:
    """Accumulates hash64 → original value for hash-encoded columns.

    State-store keys on device are hashes; this is the host-side reverse map
    used when decoding emitted batches.  Bounded only by distinct-key
    cardinality (same asymptotics as the reference's RocksDB key set, but
    host-RAM resident; spill-to-disk is a future tier)."""

    def __init__(self) -> None:
        self._map: Dict[int, Any] = {}

    def learn(self, hashes: np.ndarray, values: np.ndarray) -> None:
        m = self._map
        for h, v in zip(hashes.tolist(), values.tolist()):
            if h not in m:
                m[h] = v

    def learn_value(self, value: Any) -> int:
        h = stable_hash64(value)
        self._map.setdefault(h, value)
        return h

    def learn_pairs(self, pairs) -> None:
        """Pre-hashed (hash, value) pairs (the native ingest tier)."""
        m = self._map
        for h, v in pairs:
            if h not in m:
                m[h] = v

    def lookup(self, h: int) -> Any:
        return self._map.get(h)

    def __len__(self) -> int:
        return len(self._map)


@dataclasses.dataclass(frozen=True)
class ColumnSpec:
    name: str
    sql_type: SqlType
    # struct-path column: (root column, field path) extracted at encode —
    # lets queries that only touch scalar leaves of a STRUCT column lower
    # without the struct itself ever reaching the device
    path: Optional[Tuple[str, Tuple[str, ...]]] = None
    # host-computed column: a compiled row fn evaluated at encode over the
    # named source columns — expressions with no device lowering (string
    # ops, subscripts, struct/array construction, lambdas) ride in as
    # result columns instead of forcing the whole query onto the oracle
    host_fn: Optional[Callable[[dict], Any]] = None
    host_refs: Tuple[str, ...] = ()

    @property
    def hashed(self) -> bool:
        return self.sql_type.base in DICT_ENCODED


class BatchLayout:
    """Fixed encoding layout for the columns a compiled query actually
    reads (unused columns — including nested types — are never encoded)."""

    def __init__(
        self,
        schema: LogicalSchema,
        columns: Sequence[str],
        capacity: int,
        dictionary: Optional[DictionaryServer] = None,
        struct_paths: Sequence[Tuple[str, str, Tuple[str, ...], SqlType]] = (),
        host_exprs: Sequence[
            Tuple[str, Callable[[dict], Any], SqlType, Tuple[str, ...]]
        ] = (),
    ):
        self.schema = schema
        self.capacity = capacity
        self.dictionary = dictionary if dictionary is not None else DictionaryServer()
        self.specs: List[ColumnSpec] = []
        for name in columns:
            col = schema.find_column(name)
            if col is None:
                raise KeyError(f"column {name} not in schema")
            if (
                col.type.base == SqlBaseType.DECIMAL
                and (col.type.precision or 0) > 15
            ):
                from ksql_tpu.compiler.jax_expr import DeviceUnsupported

                # f64 carries <= 15 significant digits exactly; wider
                # decimals keep the query on the (exact) oracle
                raise DeviceUnsupported(
                    f"DECIMAL({col.type.precision}) column {name} on device"
                )
            self.specs.append(ColumnSpec(col.name, col.type))
        for synth, root, path, leaf_t in struct_paths:
            self.specs.append(ColumnSpec(synth, leaf_t, path=(root, tuple(path))))
        for synth, fn, t, refs in host_exprs:
            self.specs.append(
                ColumnSpec(synth, t, host_fn=fn, host_refs=tuple(refs))
            )

    def array_structs(self) -> Dict[str, Any]:
        """ShapeDtypeStructs mirroring encode()'s output — lets callers
        abstractly trace the step (jax.eval_shape) without a real batch, so
        unsupported expressions surface at construction time."""
        import jax

        cap = self.capacity
        out: Dict[str, Any] = {}
        for spec in self.specs:
            dt = np.int64 if spec.hashed else spec.sql_type.device_dtype()
            out[f"v_{spec.name}"] = jax.ShapeDtypeStruct((cap,), dt)
            out[f"m_{spec.name}"] = jax.ShapeDtypeStruct((cap,), np.bool_)
        out["ts"] = jax.ShapeDtypeStruct((cap,), np.int64)
        out["row_valid"] = jax.ShapeDtypeStruct((cap,), np.bool_)
        out["offset"] = jax.ShapeDtypeStruct((cap,), np.int64)
        out["partition"] = jax.ShapeDtypeStruct((cap,), np.int32)
        return out

    # ---------------------------------------------------------------- encode
    def encode(self, batch: HostBatch) -> Dict[str, np.ndarray]:
        n, cap = batch.num_rows, self.capacity
        if n > cap:
            raise ValueError(f"batch of {n} rows exceeds capacity {cap}")
        out: Dict[str, np.ndarray] = {}
        for spec in self.specs:
            if spec.path is not None:
                root, fields = spec.path
                base_vals, base_valid = batch.column_or_pseudo(root)
                values = np.empty(n, object)
                valid = np.zeros(n, bool)
                fus = [f.upper() for f in fields]
                for i in range(n):
                    cur = base_vals[i] if base_valid[i] else None
                    for f, fu in zip(fields, fus):
                        if not isinstance(cur, dict):
                            cur = None
                            break
                        # struct field names match case-insensitively;
                        # exact hit first (the common case: schema-cased keys)
                        cur = cur.get(f) if f in cur else next(
                            (v for k, v in cur.items() if k.upper() == fu),
                            None,
                        )
                    values[i] = cur
                    valid[i] = cur is not None
            elif spec.host_fn is not None:
                cols = {}
                for ref in spec.host_refs:
                    cols[ref] = batch.column_or_pseudo(ref)
                tss = batch.timestamps
                values = np.empty(n, object)
                valid = np.zeros(n, bool)
                for i in range(n):
                    src = {
                        ref: (vals[i] if oks[i] else None)
                        for ref, (vals, oks) in cols.items()
                    }
                    src["ROWTIME"] = int(tss[i])
                    try:
                        v = spec.host_fn(src)
                    except Exception:  # noqa: BLE001 — per-row expression
                        v = None  # errors null out (processing-log semantics)
                    values[i] = v
                    valid[i] = v is not None
            else:
                values, valid = batch.column_or_pseudo(spec.name)
            if spec.hashed:
                enc = encode_column(values, valid, spec.sql_type)
                self.dictionary.learn(enc.hashes64, enc.dictionary)
                data = enc.hashes64[enc.data]
            else:
                enc = encode_column(values, valid, spec.sql_type)
                data = enc.data
            out[spec.name] = (data, np.asarray(valid, bool))
        return self.assemble(
            n, out, batch.timestamps,
            offsets=batch.offsets, partitions=batch.partitions,
        )

    def assemble(
        self,
        n: int,
        columns: Dict[str, Tuple[np.ndarray, np.ndarray]],
        timestamps,
        offsets=None,
        partitions=None,
    ) -> Dict[str, np.ndarray]:
        """Pad per-spec (data, valid) columns into the jit-ready array dict
        with the dtypes the traced layout declares (shared by encode() and
        the native ingest tier)."""
        cap = self.capacity
        out: Dict[str, np.ndarray] = {}
        for spec in self.specs:
            data, valid = columns[spec.name]
            dt = np.int64 if spec.hashed else spec.sql_type.device_dtype()
            dv = np.zeros(cap, dt)
            dv[:n] = data
            mv = np.zeros(cap, bool)
            mv[:n] = valid
            out[f"v_{spec.name}"] = dv
            out[f"m_{spec.name}"] = mv
        ts = np.zeros(cap, np.int64)
        ts[:n] = timestamps
        rv = np.zeros(cap, bool)
        rv[:n] = True
        off = np.zeros(cap, np.int64)
        if offsets is not None:
            off[:n] = offsets
        part = np.zeros(cap, np.int32)
        if partitions is not None:
            part[:n] = partitions
        out["ts"] = ts
        out["row_valid"] = rv
        out["offset"] = off
        out["partition"] = part
        return out

    # --------------------------------------------------------------- example
    def example(self) -> Dict[str, np.ndarray]:
        """An empty batch of the right shapes (for jit warm-up / dryrun)."""
        empty = HostBatch.from_rows(self.schema, [])
        return self.encode(empty)


def decode_value(
    data: np.ndarray,
    valid: np.ndarray,
    sql_type: SqlType,
    dictionary: DictionaryServer,
) -> List[Any]:
    """Decode one emitted device column back to Python values."""
    base = sql_type.base
    dec_quantum = None  # loop-invariant quantize target (decimal columns)
    out: List[Any] = []
    for x, ok in zip(data.tolist(), valid.tolist()):
        if not ok:
            out.append(None)
        elif base in DICT_ENCODED:
            out.append(dictionary.lookup(int(x)))
        elif base == SqlBaseType.BOOLEAN:
            out.append(bool(x))
        elif base == SqlBaseType.DECIMAL:
            # f64 carries <=15 significant digits exactly (layout gate);
            # quantizing the shortest-repr float recovers the exact decimal
            if dec_quantum is None:
                dec_quantum = _decimal.Decimal(1).scaleb(-(sql_type.scale or 0))
            out.append(
                _decimal.Decimal(repr(float(x))).quantize(
                    dec_quantum, rounding=_decimal.ROUND_HALF_UP
                )
            )
        elif base == SqlBaseType.DOUBLE:
            out.append(float(x))
        else:
            out.append(int(x))
    return out
