"""Row-mode plan executor — full streaming semantics.

One of the two backends over the ExecutionStep IR (the other is the columnar
XLA path in runtime/lowering.py), playing the role of the reference's
interpreter path (InterpretedExpressionFactory) generalized to whole
topologies.  It implements the complete Kafka-Streams-equivalent semantics
the reference gets from its runtime (KSPlanBuilder + Kafka Streams):

* per-record changelog emission (cache-off), table changes as
  (old, new) pairs with tombstones;
* event-time windows: tumbling, hopping, session (with merge + retraction),
  grace periods (default 24h, reference windows' legacy default), EMIT FINAL
  suppression on window close;
* stream-stream windowed joins with WITHIN (before, after) + GRACE —
  left/outer null-padding emitted only at window close (klip-36 semantics);
* stream-table, table-table, and foreign-key table-table joins with full
  retraction propagation;
* aggregate undo for table-source aggregations (KudafUndoAggregator).

This backend is the parity oracle for golden-file tests and the correctness
reference the device path is validated against.
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ksql_tpu.common import faults, tracing
from ksql_tpu.common.errors import QueryRuntimeException
from ksql_tpu.common.schema import LogicalSchema
from ksql_tpu.execution import expressions as ex
from ksql_tpu.execution import steps as st
from ksql_tpu.execution.interpreter import ExpressionCompiler, TypeResolver
from ksql_tpu.functions.registry import FunctionRegistry
from ksql_tpu.parser.ast_nodes import JoinType, WindowType
from ksql_tpu.runtime.topics import Broker, Record
from ksql_tpu.serde import formats as fmt
from ksql_tpu.functions.udafs import _hashable

DEFAULT_GRACE_MS = 24 * 3600 * 1000  # reference legacy default grace


# ------------------------------------------------------------------ events


@dataclasses.dataclass
class StreamRow:
    key: Tuple[Any, ...]
    row: Dict[str, Any]
    ts: int
    window: Optional[Tuple[int, int]] = None
    part: Optional[int] = None  # source record partition (ROWPARTITION)
    offset: Optional[int] = None  # source record offset (ROWOFFSET)


@dataclasses.dataclass
class TableChange:
    key: Tuple[Any, ...]
    old: Optional[Dict[str, Any]]
    new: Optional[Dict[str, Any]]
    ts: int
    window: Optional[Tuple[int, int]] = None
    part: Optional[int] = None
    offset: Optional[int] = None


Event = Any  # StreamRow | TableChange


# ------------------------------------------------------------------- nodes


class Node:
    """A processor node.  ``receive(port, event)`` returns emitted events;
    ``on_time(stream_time)`` fires window-close actions."""

    def __init__(self, step: st.ExecutionStep):
        self.step = step
        self.schema: LogicalSchema = step.schema

    def receive(self, port: int, event: Event) -> List[Event]:
        raise NotImplementedError

    def on_time(self, stream_time: int) -> List[Event]:
        return []

    def on_flush(self, stream_time: int) -> List[Event]:
        """Explicit flush (end-of-stream / checkpoint): defaults to the
        record-driven time advance."""
        return self.on_time(stream_time)


def _key_of(row: Dict[str, Any], schema: LogicalSchema) -> Tuple[Any, ...]:
    return tuple(row.get(c.name) for c in schema.key_columns)


def _with_pseudo(
    row: Dict[str, Any],
    ts: int,
    window: Optional[Tuple[int, int]],
    event: Any = None,
) -> Dict[str, Any]:
    out = dict(row)
    out["ROWTIME"] = ts
    if event is not None:
        out["ROWPARTITION"] = getattr(event, "part", None)
        out["ROWOFFSET"] = getattr(event, "offset", None)
    if window is not None:
        out["WINDOWSTART"], out["WINDOWEND"] = window
    return out


class Compiler:
    """Compiles a step DAG into a Node pipeline."""

    def __init__(self, registry: FunctionRegistry, on_error: Callable[[str, Exception], None]):
        self.registry = registry
        self.on_error = on_error

    def expr(self, e: ex.Expression, schema: LogicalSchema, extra: Optional[Dict] = None):
        return self._compiler_for(schema, extra).compile(e)

    def expr_raw(self, e: ex.Expression, schema: LogicalSchema, extra: Optional[Dict] = None):
        """Unguarded compile: errors propagate (UDTF parameter contract)."""
        return self._compiler_for(schema, extra).compile_raw(e)

    def _compiler_for(self, schema: LogicalSchema, extra: Optional[Dict] = None):
        types = {c.name: c.type for c in schema.columns()}
        from ksql_tpu.common.schema import PSEUDOCOLUMNS, WINDOW_BOUNDS

        for n, t in {**PSEUDOCOLUMNS, **WINDOW_BOUNDS, **(extra or {})}.items():
            types.setdefault(n, t)
        return ExpressionCompiler(TypeResolver(types), self.registry, self.on_error)


# --------------------------------------------------------------- transforms


class FilterNode(Node):
    def __init__(self, step, compiler: Compiler, is_table: bool):
        super().__init__(step)
        self.pred = compiler.expr(step.predicate, step.source.schema)
        self.is_table = is_table

    def receive(self, port, event):
        if isinstance(event, StreamRow):
            if event.row is None:
                return []
            row = _with_pseudo(event.row, event.ts, event.window, event)
            if self.pred(row) is True:
                return [event]
            return []
        old_ok = (
            event.old is not None
            and self.pred(_with_pseudo(event.old, event.ts, event.window, event)) is True
        )
        new_ok = (
            event.new is not None
            and self.pred(_with_pseudo(event.new, event.ts, event.window, event)) is True
        )
        old = event.old if old_ok else None
        new = event.new if new_ok else None
        if old is None and new is None:
            return []
        return [TableChange(event.key, old, new, event.ts, event.window)]


class SelectNode(Node):
    def __init__(self, step, compiler: Compiler):
        super().__init__(step)
        src_schema = step.source.schema
        self.selects = [(name, compiler.expr(e, src_schema)) for name, e in step.selects]
        self.key_names = [c.name for c in step.schema.key_columns]
        self.src_key_names = [c.name for c in src_schema.key_columns]

    def _project(self, row, ts, window, event=None):
        src = _with_pseudo(row, ts, window, event)
        out = {}
        # carry (possibly renamed) key columns through
        for new_name, old_name in zip(self.key_names, self.src_key_names):
            out[new_name] = row.get(old_name)
        for name, f in self.selects:
            out[name] = f(src)
        return out

    def receive(self, port, event):
        if isinstance(event, StreamRow):
            if event.row is None:
                return [event]  # stream null-value records pass through
            return [StreamRow(event.key,
                              self._project(event.row, event.ts, event.window, event),
                              event.ts, event.window, event.part, event.offset)]
        old = (self._project(event.old, event.ts, event.window, event)
               if event.old is not None else None)
        new = (self._project(event.new, event.ts, event.window, event)
               if event.new is not None else None)
        return [TableChange(event.key, old, new, event.ts, event.window,
                            event.part, event.offset)]


class SelectKeyNode(Node):
    def __init__(self, step, compiler: Compiler):
        super().__init__(step)
        src_schema = step.source.schema
        self.src_key_columns = list(src_schema.key_columns)
        self.key_fns = [compiler.expr(e, src_schema) for e in step.key_expressions]
        # PartitionByParamsFactory evaluates an expression over key columns
        # only when every column it references is a key column; for null-value
        # rows any value-dependent expression yields a null key component.
        from ksql_tpu.execution.expressions import referenced_columns

        key_names = {c.name for c in self.src_key_columns}
        self.key_only = [
            all(n in key_names for n in referenced_columns(e))
            for e in step.key_expressions
        ]
        self.out_schema = step.schema

    def receive(self, port, event):
        assert isinstance(event, StreamRow)
        if event.row is None:
            # null-value records pass through a repartition: expressions over
            # key columns alone still evaluate; anything touching the (null)
            # value row becomes a null key component
            src = {
                c.name: v for c, v in zip(self.src_key_columns, event.key or ())
            }
            key_vals = tuple(
                f(src) if ko else None
                for f, ko in zip(self.key_fns, self.key_only)
            )
            return [StreamRow(key_vals, None, event.ts, event.window,
                              event.part, event.offset)]
        src = _with_pseudo(event.row, event.ts, event.window, event)
        key_vals = tuple(f(src) for f in self.key_fns)
        row = dict(event.row)
        for c, v in zip(self.out_schema.key_columns, key_vals):
            row[c.name] = v
        return [StreamRow(key_vals, row, event.ts, event.window,
                          event.part, event.offset)]


class FlatMapNode(Node):
    def __init__(self, step, compiler: Compiler):
        super().__init__(step)
        src_schema = step.source.schema
        self.on_error = compiler.on_error
        self.fns = []
        for name, call in step.table_functions:
            # unguarded arg evaluators: an error in UDTF parameter
            # evaluation (or in the UDTF itself) skips the WHOLE row via
            # the processing log — KudtfFlatMapper's try/catch contract —
            # rather than becoming a NULL parameter
            arg_fns = [compiler.expr_raw(a, src_schema) for a in call.args]
            arg_types = [f.sql_type for f in arg_fns]
            udtf = compiler.registry.udtf(call.name, arg_types)
            self.fns.append((name, arg_fns, udtf))

    def receive(self, port, event):
        assert isinstance(event, StreamRow)
        if event.row is None:
            return []
        src = _with_pseudo(event.row, event.ts, event.window, event)
        columns = []
        try:
            for name, arg_fns, udtf in self.fns:
                args = [f(src) for f in arg_fns]
                columns.append((name, udtf.fn(*args)))
        except Exception as e:  # noqa: BLE001 — per-row processing error
            self.on_error("flat-map", e)
            return []
        n = max((len(v) for _, v in columns), default=0)
        out = []
        for i in range(n):
            row = dict(event.row)
            for name, vals in columns:
                row[name] = vals[i] if i < len(vals) else None
            out.append(StreamRow(event.key, row, event.ts, event.window,
                                 event.part, event.offset))
        return out


# -------------------------------------------------------------- aggregation


class AggregateNode(Node):
    """GroupBy + Aggregate (+ windows).  port 0 receives StreamRow from the
    grouped stream, or TableChange for table aggregation."""

    def __init__(self, step, compiler: Compiler, window=None, from_table=False,
                 emit_final=False):
        super().__init__(step)
        self.emit_final = emit_final
        group_step = step.source
        src_schema = group_step.source.schema
        self.group_fns = [compiler.expr(g, src_schema) for g in
                          getattr(group_step, "group_by_expressions", ())]
        self.key_names = [c.name for c in step.schema.key_columns]
        self.window = window
        self.from_table = from_table
        self.aggs = []
        for i, call in enumerate(step.aggregations):
            arg_fns = [compiler.expr(a, src_schema) for a in call.args]
            arg_types = [f.sql_type or __import__("ksql_tpu.common.types", fromlist=["STRING"]).STRING
                         for f in arg_fns]
            udaf = compiler.registry.udaf(call.function, arg_types)
            self.aggs.append((f"KSQL_AGG_VARIABLE_{i}", arg_fns, udaf))
        # state: key -> [agg_state...]; windowed: (key, win_start) -> ...
        self.state: Dict[Any, List[Any]] = {}
        self.session_windows: Dict[Tuple, List[Tuple[int, int, List[Any]]]] = {}
        grace = getattr(window, "grace_ms", None) if window else None
        # EMIT FINAL defaults to zero grace (emit right at window end);
        # EMIT CHANGES keeps the legacy 24h default for late-record drops
        self.grace_ms = grace if grace is not None else (
            0 if emit_final else DEFAULT_GRACE_MS
        )

    # ------------------------------------------------------------ helpers
    def _group_key(self, row, ts, window, event=None) -> Tuple[Any, ...]:
        src = _with_pseudo(row, ts, window, event)
        return tuple(f(src) for f in self.group_fns)

    def _args(self, row, ts, window, arg_fns, event=None):
        src = _with_pseudo(row, ts, window, event)
        return [f(src) for f in arg_fns]

    def _init_states(self):
        return [udaf.init() for _, _, udaf in self.aggs]

    def _result_row(self, key, states, window) -> Dict[str, Any]:
        out = {}
        for name, k in zip(self.key_names, key):
            out[name] = k
        for (name, _, udaf), s in zip(self.aggs, states):
            out[name] = udaf.result(s)
        return out

    def _accumulate(self, states, row, ts, window):
        new_states = []
        for (name, arg_fns, udaf), s in zip(self.aggs, states):
            args = self._args(row, ts, window, arg_fns)
            new_states.append(udaf.accumulate(s, *args))
        return new_states

    def _undo(self, states, row, ts, window):
        new_states = []
        for (name, arg_fns, udaf), s in zip(self.aggs, states):
            if udaf.undo is None:
                raise QueryRuntimeException(
                    f"aggregate {udaf.name} does not support table retraction"
                )
            args = self._args(row, ts, window, arg_fns)
            new_states.append(udaf.undo(s, *args))
        return new_states

    # ------------------------------------------------------------ windows
    def _windows_for(self, ts: int) -> List[Tuple[int, int]]:
        w = self.window
        if w is None:
            return [None]
        if w.window_type == WindowType.TUMBLING:
            start = ts - ts % w.size_ms
            return [(start, start + w.size_ms)]
        if w.window_type == WindowType.HOPPING:
            out = []
            start = ts - ts % w.advance_ms
            while start + w.size_ms > ts and start >= 0:
                out.append((start, start + w.size_ms))
                start -= w.advance_ms
            return out[::-1]
        raise QueryRuntimeException(f"unsupported window type {w.window_type}")

    # ------------------------------------------------------------ receive
    def receive(self, port, event):
        if isinstance(event, TableChange):
            return self._receive_table_change(event)
        if event.row is None:
            return []
        row, ts = event.row, event.ts
        key = self._group_key(row, ts, event.window, event)
        if any(k is None for k in key):
            return []  # rows with a null grouping expression are excluded
        w = self.window
        if w is not None and w.window_type == WindowType.SESSION:
            return self._receive_session(key, row, ts)
        self.max_ts = max(getattr(self, "max_ts", -(2**63)), ts)
        out = []
        hkey = _hashable(key)
        for win in self._windows_for(ts):
            if win is not None:
                # late-record drop: a window is closed once stream time
                # reaches end + grace (inclusive, KIP-825 and pre-825 alike:
                # tumbling-windows.json 'out of order - explicit grace
                # period' drops a record arriving exactly at the close)
                if win[1] + self.grace_ms <= self.max_ts:
                    continue
            state_key = (hkey, win[0]) if win else hkey
            entry = self.state.get(state_key)
            old_row = None
            if entry is None:
                states, wmax = self._init_states(), ts
            else:
                states, wmax = entry
                old_row = self._result_row(key, states, win)
                wmax = max(wmax, ts)
            states = self._accumulate(states, row, ts, win)
            self.state[state_key] = (states, wmax)
            new_row = self._result_row(key, states, win)
            # windowed aggregate rows carry the max record ts in the window
            out.append(TableChange(key, old_row, new_row, wmax if win else ts, win))
        return out

    def _receive_table_change(self, event: TableChange):
        out = []
        old_key = (
            self._group_key(event.old, event.ts, None)
            if event.old is not None
            else None
        )
        if old_key is not None and not any(k is None for k in old_key):
            # null-group rows were never aggregated: nothing to undo
            key = old_key
            hkey = _hashable(key)
            entry = self.state.get(hkey)
            if entry is not None:
                states, wmax = entry
                old_row = self._result_row(key, states, None)
                states = self._undo(states, event.old, event.ts, None)
                self.state[hkey] = (states, wmax)
                out.append(TableChange(key, old_row, self._result_row(key, states, None), event.ts))
        if event.new is not None:
            key = self._group_key(event.new, event.ts, None)
            if any(k is None for k in key):
                return out  # null grouping expression: row excluded
            hkey = _hashable(key)
            entry = self.state.get(hkey)
            old_row = self._result_row(key, entry[0], None) if entry is not None else None
            states = self._accumulate(entry[0] if entry is not None else self._init_states(),
                                      event.new, event.ts, None)
            self.state[hkey] = (states, event.ts)
            out.append(TableChange(key, old_row, self._result_row(key, states, None), event.ts))
        return out

    def _receive_session(self, key, row, ts):
        self.max_ts = max(getattr(self, "max_ts", -(2**63)), ts)
        if ts + self.grace_ms + self.window.gap_ms < self.max_ts:
            # late record past gap+grace: its session window could no longer
            # merge with anything live (session-windows.json 'out of order -
            # explicit grace period': close = ts + gap + grace)
            return []
        gap = self.window.gap_ms
        hkey = _hashable(key)
        # session entries: (start, end, states, last_update_ts)
        sessions = self.session_windows.setdefault(hkey, [])
        # store retention: a session whose close (end + gap + grace) is
        # behind stream time is gone from the store — a new record in its
        # range starts a fresh session instead of merging
        sessions[:] = [
            s for s in sessions
            if s[1] + gap + self.grace_ms >= self.max_ts
        ]
        merged_start = merged_end = ts
        emit_ts = ts
        merged_states = self._init_states()
        removed, keep = [], []
        for entry in sessions:
            s, e, states, last_ts = entry
            if s - gap <= ts <= e + gap:
                merged_start = min(merged_start, s)
                merged_end = max(merged_end, e)
                emit_ts = max(emit_ts, last_ts)
                merged_states = [
                    udaf.merge(a, b)
                    for (nm, fns, udaf), a, b in zip(self.aggs, merged_states, states)
                ]
                removed.append(entry)
            else:
                keep.append(entry)
        merged_states = self._accumulate(merged_states, row, ts, (merged_start, merged_end))
        keep.append((merged_start, merged_end, merged_states, emit_ts))
        keep.sort(key=lambda t: t[0])
        self.session_windows[hkey] = keep
        out = []
        for (s, e, states, last_ts) in removed:
            # retract merged-away sessions; each tombstone keeps its own
            # session's record timestamp (KS SessionWindow merge semantics)
            out.append(
                TableChange(key, self._result_row(key, states, (s, e)), None, last_ts, (s, e))
            )
        win = (merged_start, merged_end)
        out.append(
            TableChange(key, None, self._result_row(key, merged_states, win), emit_ts, win)
        )
        return out


class SuppressNode(Node):
    """EMIT FINAL (KIP-825 EmitStrategy.onWindowClose semantics, matching
    KStreamWindowAggregate.maybeForwardFinalResult):

    * time windows emit once their close (end + grace) is at or before the
      observed stream time, but ONLY while still inside the store's
      retention horizon (start >= stream_time - retention, retention =
      max(RETENTION clause, size + grace)) — mirroring the reference's
      windowed-store eviction: a stream-time jump past close + size drops
      the final result exactly as the evicted RocksDB segment would
      (suppress.json "final results for tumbling/hopping windows");
    * session windows emit on the watermark alone: close <= stream_time;
    * a tombstone (session merged away) un-buffers the pending window;
    * each (key, window) emits at most once, with the aggregate's timestamp
      (max record ts in the window)."""

    def __init__(self, step, window, grace_ms: int):
        super().__init__(step)
        self.buffer: Dict[Tuple, TableChange] = {}
        self.session = bool(window) and window.window_type == WindowType.SESSION
        self.grace_ms = grace_ms
        size = getattr(window, "size_ms", None) or 0
        self.retention_ms = max(getattr(window, "retention_ms", None) or 0,
                                size + grace_ms)
        self.emitted: set = set()
        self.prev_time = -(2**63)

    def receive(self, port, event):
        assert isinstance(event, TableChange)
        if event.window is None:
            return [event]
        k = (event.key, event.window)
        if k in self.emitted:
            return []
        if event.new is None:
            self.buffer.pop(k, None)
            return []
        self.buffer[k] = event
        return []

    def on_time(self, stream_time):
        if stream_time == self.prev_time:
            return []
        self.prev_time = stream_time
        out = []
        for k in sorted(self.buffer, key=lambda kk: kk[1][1]):
            ev = self.buffer[k]
            closed = ev.window[1] + self.grace_ms <= stream_time
            if not closed:
                continue
            evicted = (not self.session
                       and ev.window[0] < stream_time - self.retention_ms)
            if evicted:
                del self.buffer[k]  # the store segment is gone; never emits
                continue
            out.append(TableChange(ev.key, None, ev.new, ev.ts, ev.window))
            self.emitted.add(k)
            del self.buffer[k]
        return out

    def on_flush(self, stream_time):
        """Force-close every window past its close time (watermark), e.g. at
        end-of-stream — unlike record-driven advancement (on_time), this
        skips the retention-horizon eviction, so windows the store would
        already have dropped still emit their final result."""
        out = []
        for k in sorted(self.buffer, key=lambda kk: kk[1][1]):
            ev = self.buffer[k]
            if ev.window[1] + self.grace_ms <= stream_time:
                out.append(TableChange(ev.key, None, ev.new, ev.ts, ev.window))
                self.emitted.add(k)
                del self.buffer[k]
        return out


# ------------------------------------------------------------------- joins


def _join_rows(left_row, right_row, left_schema, right_schema, out_schema, key, ts):
    row = {}
    for c in out_schema.key_columns:
        pass
    if left_row:
        row.update(left_row)
    if right_row:
        row.update(right_row)
    out = {}
    for c in out_schema.columns():
        out[c.name] = row.get(c.name)
    # the join key value fills the key column (it may only exist on one side)
    for c, v in zip(out_schema.key_columns, key):
        out[c.name] = v
    return out


class StreamStreamJoinNode(Node):
    def __init__(self, step: st.StreamStreamJoin, compiler: Compiler):
        super().__init__(step)
        self.left_schema = step.left.schema
        self.right_schema = step.right.schema
        self.left_key_fn = compiler.expr(step.left_key, self.left_schema)
        self.right_key_fn = compiler.expr(step.right_key, self.right_schema)
        self.before = step.before_ms
        self.after = step.after_ms
        # klip-36: an explicit GRACE PERIOD selects the fixed (deferred)
        # left/outer join semantics; without it, legacy eager null-padding
        self.deferred = step.grace_ms is not None
        self.grace = step.grace_ms if step.grace_ms is not None else DEFAULT_GRACE_MS
        # per-side window-store stream time: admission is gated by the OWN
        # store's observed max ts (segment expiry), not the task stream time
        self.side_max = [-(2 ** 63), -(2 ** 63)]
        self.retention = self.before + self.after + self.grace
        self.join_type = step.join_type
        # windowed-key sources join on (key, window): start for time windows
        # (reference TimeWindowedSerde serializes only the start), exact
        # (start, end) for sessions — verified against joins.json
        self.window_kind = self._window_kind(step)
        self.left_buf: Dict[Any, List[list]] = {}
        self.right_buf: Dict[Any, List[list]] = {}

    @staticmethod
    def _window_kind(step) -> Optional[str]:
        for s in st.walk_steps(step.left):
            if isinstance(s, (st.WindowedStreamSource, st.WindowedTableSource)):
                return "SESSION" if s.window_type == "SESSION" else "TIME"
        return None

    def _win_match(self, w1, w2) -> bool:
        if self.window_kind is None:
            return True
        if w1 is None or w2 is None:
            return w1 == w2
        if self.window_kind == "SESSION":
            return w1 == w2
        return w1[0] == w2[0]

    def receive(self, port, event):
        assert isinstance(event, StreamRow)
        if event.row is None:
            return []  # null-value stream records don't join (KS drops them)
        row, ts = event.row, event.ts
        src = _with_pseudo(row, ts, event.window)
        out = []
        self.stream_time = max(
            getattr(self, "stream_time", -(2 ** 63)), ts
        )
        self.side_max[port] = max(self.side_max[port], ts)
        # admission: the record enters its own window store only while its
        # segment is live (per-store stream time, retention = size + grace);
        # a late record still PROBES the other store regardless
        admitted = (
            not self.deferred
            or ts >= self.side_max[port] - self.retention
        )
        if port == 0:
            k = self.left_key_fn(src)
            entry = [ts, row, [False], k, event.window]
            if admitted:
                self.left_buf.setdefault(_hashable(k), []).append(entry)
            if k is not None:
                for rentry in self.right_buf.get(_hashable(k), ()):
                    rts, rrow, rmatched, _rk, rwin = rentry
                    if ts - self.before <= rts <= ts + self.after and self._win_match(
                        event.window, rwin
                    ):
                        entry[2][0] = True
                        rmatched[0] = True
                        out.append(self._emit(k, row, rrow, max(ts, rts), event.window))
            if not entry[2][0] and self.join_type in (JoinType.LEFT, JoinType.OUTER):
                if not self.deferred:
                    out.append(self._emit(k, row, None, ts, event.window))
                elif ts + self.after + self.grace < self.stream_time:
                    # window already closed on arrival: pad now (klip-36) —
                    # even for records too late to enter their own store
                    entry[2][0] = True
                    out.append(self._emit(k, row, None, ts, event.window))
        else:
            k = self.right_key_fn(src)
            entry = [ts, row, [False], k, event.window]
            if admitted:
                self.right_buf.setdefault(_hashable(k), []).append(entry)
            if k is not None:
                for lentry in self.left_buf.get(_hashable(k), ()):
                    lts, lrow, lmatched, _lk, lwin = lentry
                    if lts - self.before <= ts <= lts + self.after and self._win_match(
                        lwin, event.window
                    ):
                        entry[2][0] = True
                        lmatched[0] = True
                        out.append(self._emit(k, lrow, row, max(ts, lts), lwin))
            if not entry[2][0] and self.join_type in (JoinType.OUTER, JoinType.RIGHT):
                if not self.deferred:
                    out.append(self._emit(k, None, row, ts, event.window))
                elif ts + self.before + self.grace < self.stream_time:
                    entry[2][0] = True
                    out.append(self._emit(k, None, row, ts, event.window))
        return out

    def _emit(self, k, lrow, rrow, ts, window=None):
        row = _join_rows(lrow, rrow, self.left_schema, self.right_schema, self.schema, (k,), ts)
        return StreamRow((k,), row, ts, window if self.window_kind else None)

    def on_time(self, stream_time):
        """Emit deferred null-pads at window close (klip-36) and expire
        buffer entries by their own store's retention horizon — a padded
        entry stays resident and can still join a late arrival, matching
        the reference's window-store/outer-join-store split."""
        out = []
        for port, buf in ((0, self.left_buf), (1, self.right_buf)):
            window = self.after if port == 0 else self.before
            for hk in list(buf):
                keep = []
                for entry in buf[hk]:
                    ts, row, matched, k, win = entry
                    if self.deferred:
                        if not matched[0] and ts + window + self.grace < stream_time:
                            if port == 0 and self.join_type in (JoinType.LEFT, JoinType.OUTER):
                                out.append(self._emit(k, row, None, ts, win))
                            elif port == 1 and self.join_type in (JoinType.OUTER, JoinType.RIGHT):
                                out.append(self._emit(k, None, row, ts, win))
                            matched[0] = True
                        if ts >= self.side_max[port] - self.retention:
                            keep.append(entry)
                    elif ts + window + self.grace >= stream_time:
                        keep.append(entry)
                if keep:
                    buf[hk] = keep
                else:
                    del buf[hk]
        out.sort(key=lambda e: e.ts)
        return out


class StreamTableJoinNode(Node):
    def __init__(self, step: st.StreamTableJoin, compiler: Compiler):
        super().__init__(step)
        self.left_schema = step.left.schema
        self.right_schema = step.right.schema
        self.left_key_fn = compiler.expr(step.left_key, self.left_schema)
        self.join_type = step.join_type
        self.table: Dict[Any, dict] = {}

    def receive(self, port, event):
        if port == 1:
            assert isinstance(event, TableChange)
            k = event.key[0] if len(event.key) == 1 else event.key
            if event.new is None:
                self.table.pop(_hashable(k), None)
            else:
                self.table[_hashable(k)] = event.new
            return []
        assert isinstance(event, StreamRow)
        if event.row is None:
            return []
        src = _with_pseudo(event.row, event.ts, event.window)
        k = self.left_key_fn(src)
        rrow = self.table.get(_hashable(k)) if k is not None else None
        if rrow is None and self.join_type != JoinType.LEFT:
            return []
        row = _join_rows(event.row, rrow, self.left_schema, self.right_schema,
                         self.schema, (k,), event.ts)
        return [StreamRow((k,), row, event.ts)]


class TableTableJoinNode(Node):
    def __init__(self, step: st.TableTableJoin, compiler: Compiler):
        super().__init__(step)
        self.left_schema = step.left.schema
        self.right_schema = step.right.schema
        self.join_type = step.join_type
        self.left: Dict[Any, dict] = {}
        self.right: Dict[Any, dict] = {}

    def _join(self, k, lrow, rrow, ts):
        jt = self.join_type
        if lrow is None and rrow is None:
            return None
        if jt == JoinType.INNER and (lrow is None or rrow is None):
            return None
        if jt == JoinType.LEFT and lrow is None:
            return None
        if jt == JoinType.RIGHT and rrow is None:
            return None
        return _join_rows(lrow, rrow, self.left_schema, self.right_schema,
                          self.schema, (k,), ts)

    def receive(self, port, event):
        assert isinstance(event, TableChange)
        k = event.key[0] if len(event.key) == 1 else event.key
        hk = _hashable(k)
        if port == 0:
            old_l = self.left.get(hk)
            new_l = event.new
            if new_l is None:
                self.left.pop(hk, None)
            else:
                self.left[hk] = new_l
            r = self.right.get(hk)
            old_j = self._join(k, old_l, r, event.ts)
            new_j = self._join(k, new_l, r, event.ts)
        else:
            old_r = self.right.get(hk)
            new_r = event.new
            if new_r is None:
                self.right.pop(hk, None)
            else:
                self.right[hk] = new_r
            l = self.left.get(hk)
            old_j = self._join(k, l, old_r, event.ts)
            new_j = self._join(k, l, new_r, event.ts)
        if old_j is None and new_j is None:
            return []
        return [TableChange((k,), old_j, new_j, event.ts)]


class FkJoinNode(Node):
    """Foreign-key table-table join: left keyed by its own pk, joined on
    fk(left) = pk(right) (ForeignKeyTableTableJoinBuilder analog)."""

    def __init__(self, step: st.ForeignKeyTableTableJoin, compiler: Compiler):
        super().__init__(step)
        self.left_schema = step.left.schema
        self.right_schema = step.right.schema
        self.fk_fn = compiler.expr(step.foreign_key_expression, self.left_schema)
        self.join_type = step.join_type
        self.left: Dict[Any, dict] = {}
        self.right: Dict[Any, dict] = {}
        self.fk_index: Dict[Any, set] = {}

    def _join(self, lk, lrow, rrow, ts):
        if lrow is None:
            return None
        if rrow is None and self.join_type != JoinType.LEFT:
            return None
        return _join_rows(lrow, rrow, self.left_schema, self.right_schema,
                          self.schema, lk if isinstance(lk, tuple) else (lk,), ts)

    def _fk_of(self, row, ts):
        return self.fk_fn(_with_pseudo(row, ts, None)) if row is not None else None

    def receive(self, port, event):
        assert isinstance(event, TableChange)
        out = []
        if port == 0:
            lk = event.key
            hlk = _hashable(lk)
            old = self.left.get(hlk)
            old_fk = self._fk_of(old, event.ts)
            new_fk = self._fk_of(event.new, event.ts)
            if event.new is None:
                self.left.pop(hlk, None)
            else:
                self.left[hlk] = event.new
            if old_fk is not None and old_fk != new_fk:
                self.fk_index.get(_hashable(old_fk), set()).discard((hlk, lk))
            if new_fk is not None:
                self.fk_index.setdefault(_hashable(new_fk), set()).add((hlk, lk))
            old_j = self._join(lk, old, self.right.get(_hashable(old_fk)), event.ts)
            new_j = self._join(lk, event.new, self.right.get(_hashable(new_fk)), event.ts)
            # a left-row delete always tombstones the result, even when the
            # join value was already null (KS FK-join forwarding)
            left_delete = event.new is None and old is not None
            if old_j is not None or new_j is not None or left_delete:
                out.append(TableChange(lk, old_j, new_j, event.ts))
        else:
            rk = event.key[0] if len(event.key) == 1 else event.key
            hrk = _hashable(rk)
            old_r = self.right.get(hrk)
            if event.new is None:
                self.right.pop(hrk, None)
            else:
                self.right[hrk] = event.new
            for hlk, lk in sorted(self.fk_index.get(hrk, ()), key=repr):
                lrow = self.left.get(hlk)
                old_j = self._join(lk, lrow, old_r, event.ts)
                new_j = self._join(lk, lrow, event.new, event.ts)
                if old_j is not None or new_j is not None:
                    out.append(TableChange(lk, old_j, new_j, event.ts))
        return out


# ------------------------------------------------------------------ executor


@dataclasses.dataclass
class SinkEmit:
    """One sink emission, shared by every executor backend.

    ``ts`` is the emission's event time: the triggering record's (possibly
    TIMESTAMP-column-extracted) timestamp on row paths, the aggregate's
    event time on stateful paths.  The health subsystem measures e2e
    latency as ``produce wall-time − ts`` off this field, so backends must
    stamp real event time here — micro-batched device paths may
    batch-approximate (their coalesced emission carries the batch's decoded
    per-row timestamps), which biases e2e conservatively, never optimistically."""

    key: Tuple[Any, ...]
    row: Optional[Dict[str, Any]]  # None = tombstone
    ts: int
    window: Optional[Tuple[int, int]] = None


def decode_source_record(
    source_step, record: Record, on_error: Callable[[str, Exception], None]
) -> Optional[Event]:
    """Deserialize one source-topic record into a StreamRow/TableChange
    (serde + headers + timestamp extraction + table-changelog old/new
    tracking).  Shared by every executor backend — which makes it the one
    choke point for the flight recorder's ``deserialize`` stage."""
    tr = tracing.active()
    if tr is None:
        return _decode_source_record(source_step, record, on_error)
    t0 = _time.perf_counter()
    try:
        return _decode_source_record(source_step, record, on_error)
    finally:
        tr.stage("deserialize", _time.perf_counter() - t0)


def _decode_source_record(
    source_step, record: Record, on_error: Callable[[str, Exception], None]
) -> Optional[Event]:
    schema = source_step.schema
    # serde construction + column pruning are per-step constants: cache on
    # the step (this is the per-record hot path of every executor)
    cached = source_step.__dict__.get("_decode_cache")
    if cached is None:
        value_serde = fmt.of(
            source_step.formats.value_format,
            properties={
                "VALUE_DELIMITER": source_step.formats.value_delimiter,
                "PROTO_NULLABLE_ALL": source_step.__dict__.get(
                    "_proto_nullable_all", False
                ),
                "PROTO_FLOAT32": source_step.__dict__.get("_proto_float32", ()),
            },
            wrap_single_values=source_step.formats.wrap_single_values,
        )
        header_cols = dict(getattr(source_step, "header_columns", ()) or ())
        value_columns = [
            c for c in schema.value_columns if c.name not in header_cols
        ]
        cached = (value_serde, header_cols, value_columns)
        source_step.__dict__["_decode_cache"] = cached
    value_serde, header_cols, value_columns = cached
    try:
        value_row = value_serde.deserialize(record.value, value_columns) \
            if record.value is not None else None
        key_row = {}
        if record.key is not None and schema.key_columns:
            key_row = fmt.deserialize_key(
                source_step.formats.key_format, record.key, schema.key_columns,
                delimiter=getattr(source_step.formats, "key_delimiter", None),
            )
    except Exception as e:
        on_error(f"deserialize:{source_step.topic}", e)
        return None
    if header_cols and value_row is not None:
        headers = list(record.headers or ())
        for col, hkey in header_cols.items():
            if hkey is None:
                value_row[col] = [
                    {"KEY": k, "VALUE": v} for k, v in headers
                ]
            else:
                value_row[col] = next(
                    (v for k, v in reversed(headers) if k == hkey), None
                )
    ts = record.timestamp
    if source_step.timestamp_column and value_row is not None:
        tv = value_row.get(source_step.timestamp_column)
        if tv is None and source_step.timestamp_column in key_row:
            tv = key_row[source_step.timestamp_column]
        if tv is not None:
            if isinstance(tv, str) and source_step.timestamp_format:
                from ksql_tpu.functions.udfs import _string_to_ts

                try:
                    tv = _string_to_ts(tv, source_step.timestamp_format)
                except Exception as e:
                    on_error("timestamp-extract", e)
                    return None
            try:
                ts = int(tv)
            except (TypeError, ValueError) as e:
                on_error("timestamp-extract", e)
                return None
            if ts < 0:
                # negative extracted timestamps drop the record
                # (reference MetadataTimestampExtractor semantics)
                return None
    is_table = isinstance(source_step, (st.TableSource, st.WindowedTableSource))
    if record.key is None and schema.key_columns:
        if is_table:
            return None  # table upsert with null key: skipped (KTable source)
        key: tuple = ()  # null key payload: stays a null key on passthrough
    else:
        key = tuple(key_row.get(c.name) for c in schema.key_columns)
        if is_table and key and all(k is None for k in key):
            return None
    if value_row is None:
        row = None
    else:
        row = dict(key_row)
        row.update(value_row)
    if is_table:
        if not hasattr(source_step, "_table_state"):
            source_step.__dict__["_table_state"] = {}
        state = source_step.__dict__["_table_state"]
        hkey = _hashable(key)
        old = state.get(hkey)
        if row is None:
            if hkey in state:
                del state[hkey]
        else:
            state[hkey] = row
        if old is None and row is None:
            return None
        return TableChange(key, old, row, ts, record.window,
                           record.partition, record.offset)
    return StreamRow(key, row, ts, record.window,
                     record.partition, record.offset)



def _apply_path_default(row, path, default):
    """Substitute ``default`` at a nested struct ``path`` whose value is
    null (SR-schema-id sinks; copy-on-write so shared rows stay intact)."""

    def rec(obj, i):
        if not isinstance(obj, dict):
            return obj
        k = path[i]
        key = k if k in obj else next(
            (kk for kk in obj if kk.upper() == k.upper()), k
        )
        v = obj.get(key)
        if i == len(path) - 1:
            if v is None:
                obj = dict(obj)
                obj[key] = default
            return obj
        nv = rec(v, i + 1)
        if nv is not v:
            obj = dict(obj)
            obj[key] = nv
        return obj

    return rec(row, 0)


#: sentinel for SinkWriter.produce's ``precoded`` parameter — None is a
#: meaningful precoded value (a tombstone's payload), so absence needs
#: its own marker
_UNSET = object()


def _json_scalar_frag(v):
    """``json.dumps(_jsonable(v))`` for scalar runtime types — the
    per-column fragment of JsonFormat.serialize's envelope, byte-exact
    (separators only affect containers, which raise here and fall back
    to the per-emit serializer)."""
    import json as _json

    if v is None:
        return "null"
    t = type(v)
    if t is bool:
        return "true" if v else "false"
    if t is int or t is float:
        if t is float:
            # Jackson renders non-finite doubles as strings (see _jsonable)
            if v != v:
                return '"NaN"'
            if v == float("inf"):
                return '"Infinity"'
            if v == float("-inf"):
                return '"-Infinity"'
        return repr(v)  # json.dumps delegates to int/float __repr__
    if t is str:
        return _json.dumps(v)  # ensure_ascii escapes, exactly
    raise TypeError(f"non-scalar sink value {t.__name__}")


def _delim_field_encoder(serde, first_field: bool):
    """One column's DelimitedFormat.serialize mirror (bool/bytes/float/str
    rendering + commons-csv minimal quoting).  The DECIMAL special case is
    unreachable: batch encode is gated to scalar non-DECIMAL columns."""
    import base64 as _b64

    quote = serde._quote

    def enc(v):
        if v is None:
            return ""
        if isinstance(v, bool):
            return quote("true" if v else "false", first_field)
        if isinstance(v, bytes):
            return quote(_b64.b64encode(v).decode("ascii"), first_field)
        if isinstance(v, float):
            from ksql_tpu.execution.interpreter import java_double_str

            return quote(java_double_str(v), first_field)
        return quote(str(v), first_field)

    return enc


class SinkWriter:
    """Serializes SinkEmits and produces them to the sink topic (the
    SinkBuilder.java:43/89 analog: value/key serde + sink timestamp column).
    Shared by every executor backend.

    ``enabled=False`` puts the query in STANDBY: it keeps consuming and
    materializing state (replica for pulls + warm failover) but publishes
    nothing — the num.standby.replicas analog for a shared data plane."""

    enabled = True
    #: bounded per-emit produce retries before the failure escalates to a
    #: tick replay (the engine arms this on micro-batched backends, where
    #: replaying the whole batch over one transient produce fault is the
    #: expensive alternative); retries are safe because a failed produce
    #: raises before the record enters the log
    produce_retries = 0
    #: effectively-once fence (runtime/changelog.py): emissions whose
    #: ordinal is at-or-below this durable high-water were already
    #: journaled + re-appended by recovery, so a post-restart replay
    #: suppresses them instead of duplicating (dupes across a process
    #: death stay bounded by the single in-flight tick)
    fence_seq = 0
    #: emissions the fence suppressed (metrics / test observability)
    fenced_out = 0
    #: when armed (a list), each successful produce appends
    #: ``(topic, key, value, ts, window)`` here; the engine drains it
    #: into the tick's changelog frame at the commit point
    journal_buf = None

    def __init__(self, sink_step, broker: Broker,
                 on_error: Callable[[str, Exception], None]):
        self.sink_step = sink_step
        self.broker = broker
        self.on_error = on_error
        #: 1-based logical emit ordinal — the sink.produce fault context
        #: (``<topic>#<n>#``) and the per-emit commit-point unit
        self.emit_seq = 0
        #: produce attempts that failed and were retried (metrics)
        self.retries_used = 0
        #: rows serialized by the batched column-at-a-time encoder
        #: (ksql_sink_batch_encoded_rows_total)
        self.batch_encoded_rows = 0
        #: precoded-value hand-off from produce() to _produce(); an instance
        #: stash keeps _produce a wrappable one-arg seam
        self._precoded = _UNSET
        broker.create_topic(sink_step.topic)
        self.value_serde = fmt.of(
            sink_step.formats.value_format,
            properties={
                "VALUE_DELIMITER": sink_step.formats.value_delimiter,
                "PROTO_NULLABLE_ALL": sink_step.__dict__.get(
                    "_proto_nullable_all", False
                ),
                "PROTO_FLOAT32": sink_step.__dict__.get("_proto_float32", ()),
            },
            wrap_single_values=sink_step.formats.wrap_single_values,
        )

    def encode_batch(self, emits: List[SinkEmit]) -> Optional[list]:
        """Array-at-a-time value encode for an emission block — the
        device-block handoff lifted to sinks.  Per-column encoders walk
        the block column-wise; the fragments join per row byte-identical
        to ``value_serde.serialize``.  Returns one precoded value per
        emit for ``produce(e, precoded=...)`` (``_UNSET`` where that row
        must serialize per-emit, e.g. an unexpected runtime type), or
        None when the whole block is ineligible: non-JSON/DELIMITED
        serde, armed fault proxy (serde fault points must fire per
        emit), DECIMAL or nested columns, path-shaped value_defaults.
        Per-emit semantics — emit_seq ordinals, the sink.produce fault
        context, retries, standby muting, timestamp extraction — all
        stay in produce()."""
        from ksql_tpu.common.types import SqlBaseType as B

        if not self.enabled or not emits:
            return None
        serde = self.value_serde
        cols = list(self.sink_step.schema.value_columns)
        if not cols:
            return None
        defaults = getattr(self.sink_step, "value_defaults", ()) or ()
        if any(not isinstance(n, str) for n, _ in defaults):
            return None  # nested-path defaults: per-emit serialize
        scalar = (B.BIGINT, B.INTEGER, B.DOUBLE, B.BOOLEAN, B.STRING)
        if any(c.type.base not in scalar for c in cols):
            return None
        if type(serde) is fmt.JsonFormat:
            delimited = False
        elif type(serde) is fmt.DelimitedFormat:
            delimited = True
        else:
            return None  # _FaultingFormat proxy, Avro envelope, protobuf...
        tr = tracing.active()
        t0 = _time.perf_counter() if tr is not None else 0.0
        flat = dict(defaults)
        rows = []
        for e in emits:
            row = e.row
            if row is not None and flat:
                row = {**flat, **row}
            rows.append(row)
        n = len(rows)
        columns: List[list] = []
        if delimited:
            encoders = [
                _delim_field_encoder(serde, i == 0) for i in range(len(cols))
            ]
        else:
            encoders = [_json_scalar_frag] * len(cols)
        for c, enc in zip(cols, encoders):
            name = c.name
            col = []
            for row in rows:
                if row is None:
                    col.append(None)
                else:
                    try:
                        col.append(enc(row.get(name)))
                    except Exception:  # noqa: BLE001 — per-emit fallback
                        col.append(_UNSET)
            columns.append(col)
        out: list = []
        encoded = 0
        if delimited:
            join = serde.delimiter.join
        else:
            import json as _json

            prefixes = [_json.dumps(c.name) + ":" for c in cols]
            unwrapped = not serde.wrap and len(cols) == 1
        for i in range(n):
            if rows[i] is None:
                out.append(None)  # tombstone: serialize returns None
                continue
            frags = [col[i] for col in columns]
            if any(f is _UNSET for f in frags):
                out.append(_UNSET)
                continue
            if delimited:
                out.append(join(frags))
            elif unwrapped:
                out.append(frags[0])
            else:
                out.append(
                    "{"
                    + ",".join(p + f for p, f in zip(prefixes, frags))
                    + "}"
                )
            encoded += 1
        self.batch_encoded_rows += encoded
        if tr is not None:
            # the block encode IS these emits' serialize time; produce()
            # still records its (now serialization-free) per-emit stage
            tr.stage("sink.produce", _time.perf_counter() - t0, n=encoded)
        return out

    def produce(self, e: SinkEmit, precoded=_UNSET) -> None:
        if not self.enabled:
            return  # standby: materialize-only, nothing published
        # _produce stays a one-arg seam (tests and operators wrap it with
        # single-argument shims); a precoded value from the block-batched
        # encoder is handed over via an instance stash cleared on exit
        self._precoded = precoded
        tr = tracing.active()
        try:
            if tr is None:
                return self._produce(e)
            t0 = _time.perf_counter()
            try:
                return self._produce(e)
            finally:
                tr.stage("sink.produce", _time.perf_counter() - t0)
        finally:
            self._precoded = _UNSET

    def _produce(self, e: SinkEmit) -> None:
        precoded = self._precoded
        self.emit_seq += 1
        if faults.armed():
            # per-emit chaos seam: the ordinal context lets a rule like
            # sink.produce@#5# kill exactly the 5th emit (replay-window
            # tests); fired once per LOGICAL emit, outside the retry loop,
            # so an injected kill always escalates deterministically
            faults.fault_point(
                "sink.produce", f"{self.sink_step.topic}#{self.emit_seq}#"
            )
        if self.emit_seq <= self.fence_seq:
            # effectively-once: this ordinal's record was durable in the
            # changelog journal and already re-appended by recovery — the
            # replayed derivation is suppressed, not re-published
            self.fenced_out += 1
            return
        schema = self.sink_step.schema
        if precoded is not _UNSET:
            # batched column-at-a-time encode already produced the exact
            # bytes (value_defaults applied there); skip the row serializer
            value = precoded
        else:
            row = e.row
            defaults = getattr(self.sink_step, "value_defaults", ()) or ()
            if row is not None and defaults:
                flat = {n: d for n, d in defaults if isinstance(n, str)}
                if flat:
                    row = {**flat, **row}
                for n, d in defaults:
                    if isinstance(n, (tuple, list)):
                        row = _apply_path_default(row, tuple(n), d)
            value = (
                self.value_serde.serialize(row, list(schema.value_columns))
                if row is not None
                else None
            )
        key = fmt.serialize_key(
            self.sink_step.formats.key_format, e.key, schema.key_columns,
            wrapped=getattr(self.sink_step.formats, "key_wrapped", False),
            delimiter=getattr(self.sink_step.formats, "key_delimiter", None),
        )
        ts = e.ts
        if self.sink_step.timestamp_column and e.row is not None:
            tv = e.row.get(self.sink_step.timestamp_column)
            if tv is not None:
                if isinstance(tv, str):
                    from ksql_tpu.functions.udfs import _string_to_ts

                    try:
                        tv = _string_to_ts(
                            tv,
                            getattr(self.sink_step, "timestamp_format", None)
                            or "yyyy-MM-dd'T'HH:mm:ssX",
                        )
                    except Exception as ex_:
                        self.on_error("timestamp-sink", ex_)
                        return
                ts = int(tv)
                if ts < 0:
                    return  # negative timestamps drop the record
        topic = self.broker.topic(self.sink_step.topic)
        record = Record(key=key, value=value, timestamp=ts, partition=-1,
                        window=e.window)
        attempts = int(self.produce_retries) + 1
        for i in range(attempts):
            try:
                topic.produce(record)
                if self.journal_buf is not None:
                    # durable-emission capture for the changelog frame;
                    # only records that actually entered the log count
                    self.journal_buf.append(
                        (self.sink_step.topic, key, value, ts, e.window)
                    )
                return
            except Exception as exc:  # noqa: BLE001 — transient produce
                # faults retry per emit; exhausting the budget escalates to
                # the engine's tick-replay path
                if i + 1 >= attempts:
                    raise
                self.retries_used += 1
                self.on_error(f"sink-produce-retry:{self.sink_step.topic}", exc)


class OracleExecutor:
    """Executes one QueryPlan over in-process topics, row at a time."""

    def __init__(
        self,
        plan: st.QueryPlan,
        broker: Broker,
        registry: FunctionRegistry,
        on_error: Optional[Callable[[str, Exception], None]] = None,
        emit_callback: Optional[Callable[[SinkEmit], None]] = None,
    ):
        self.plan = plan
        self.broker = broker
        self.registry = registry
        self.on_error = on_error or (lambda expr, e: None)
        self.emit_callback = emit_callback
        self.compiler = Compiler(registry, self.on_error)
        self.stream_time = -(2**63)
        # topic -> list of (source_step, path) ; path = [(node, port), ...]
        self.source_routes: Dict[str, List[Tuple[st.ExecutionStep, List[Tuple[Node, int]]]]] = {}
        self.nodes: List[Node] = []
        self.sink_step: Optional[st.ExecutionStep] = None
        self.sink_serde = None
        self._build(plan.physical_plan, [])
        self._window_grace = self._find_grace(plan.physical_plan)

    # ------------------------------------------------------------- building
    def _find_grace(self, step) -> int:
        for s in st.walk_steps(step):
            w = getattr(s, "window", None)
            if w is not None and getattr(w, "grace_ms", None) is not None:
                return w.grace_ms
        return DEFAULT_GRACE_MS

    def _find_window(self, step):
        for s in st.walk_steps(step):
            w = getattr(s, "window", None)
            if w is not None:
                return w
        return None

    def _build(self, step: st.ExecutionStep, path_above: List[Tuple[Node, int]]):
        """Recursively build nodes; ``path_above`` is the node chain from this
        step's parent up to the root (with input port numbers)."""
        t = type(step)
        if t in (st.StreamSource, st.WindowedStreamSource, st.TableSource, st.WindowedTableSource):
            self.source_routes.setdefault(step.topic, []).append((step, list(path_above)))
            return
        if t in (st.StreamFilter, st.TableFilter):
            node = FilterNode(step, self.compiler, t is st.TableFilter)
        elif t in (st.StreamSelect, st.TableSelect):
            node = SelectNode(step, self.compiler)
        elif t in (st.StreamSelectKey, st.TableSelectKey):
            node = SelectKeyNode(step, self.compiler)
        elif t is st.StreamFlatMap:
            node = FlatMapNode(step, self.compiler)
        elif t in (st.StreamAggregate, st.TableAggregate):
            node = AggregateNode(step, self.compiler, window=None,
                                 from_table=t is st.TableAggregate)
        elif t is st.StreamWindowedAggregate:
            node = AggregateNode(
                step, self.compiler, window=step.window,
                emit_final=any(isinstance(n, SuppressNode) for n, _ in path_above),
            )
        elif t is st.StreamStreamJoin:
            node = StreamStreamJoinNode(step, self.compiler)
        elif t is st.StreamTableJoin:
            node = StreamTableJoinNode(step, self.compiler)
        elif t is st.TableTableJoin:
            node = TableTableJoinNode(step, self.compiler)
        elif t is st.ForeignKeyTableTableJoin:
            node = FkJoinNode(step, self.compiler)
        elif t is st.TableSuppress:
            w = self._find_window(step)
            g = getattr(w, "grace_ms", None) if w is not None else None
            node = SuppressNode(step, w, g if g is not None else 0)
        elif t in (st.StreamSink, st.TableSink):
            self.sink_step = step
            self.sink_writer = SinkWriter(step, self.broker, self.on_error)
            self._build(step.source, path_above)
            return
        elif t in (st.StreamGroupBy, st.StreamGroupByKey, st.TableGroupBy):
            # folded into the aggregate node above it
            self._build(step.source, path_above)
            return
        else:
            raise QueryRuntimeException(f"oracle cannot execute step {t.__name__}")

        self.nodes.append(node)
        children = step.sources()
        if t in (st.StreamAggregate, st.StreamWindowedAggregate, st.TableAggregate):
            # skip the group-by marker step
            group = step.source
            children = group.sources()
        for port, child in enumerate(children):
            self._build(child, [(node, port)] + path_above)

    # ------------------------------------------------------------- running
    def process(self, topic: str, record: Record) -> List[SinkEmit]:
        """Push one record through the topology; returns sink emissions."""
        routes = self.source_routes.get(topic)
        if not routes:
            return []
        out: List[SinkEmit] = []
        for source_step, path in routes:
            ev = decode_source_record(source_step, record, self.on_error)
            if ev is None:
                continue
            self.stream_time = max(self.stream_time, ev.ts)
            out.extend(self._push(ev, path))
        # time-driven flushes (window close, suppression, join expiry)
        out.extend(self._advance_time())
        return out

    def flush_time(self, stream_time: int) -> List[SinkEmit]:
        """Advance stream time explicitly (end-of-input flush for EMIT FINAL
        and left-join close in tests)."""
        self.stream_time = max(self.stream_time, stream_time)
        return self._advance_time(force=True)

    # ------------------------------------------------------- state epochs
    #: every record is fully processed (and its emits produced) before
    #: process() returns — the engine's per-record commit points and
    #: in-place poison rollback rely on this
    record_synchronous = True

    @property
    def stateful(self) -> bool:
        """True when the topology holds state a replay could double-count
        (aggregates, joins, suppression buffers, table-source changelogs)."""
        cached = self.__dict__.get("_stateful")
        if cached is None:
            from ksql_tpu.runtime.checkpoint import _ORACLE_STATE_ATTRS

            cached = any(
                type(n).__name__ in _ORACLE_STATE_ATTRS for n in self.nodes
            ) or any(
                isinstance(s, (st.TableSource, st.WindowedTableSource))
                for s in st.walk_steps(self.plan.physical_plan)
            )
            self.__dict__["_stateful"] = cached
        return cached

    def state_epoch(self) -> Dict[str, Any]:
        """Deep snapshot of every stateful node's state plus the
        table-source decode changelogs — the per-record commit-point epoch
        the engine rolls back to (atomic poison skip) or restores into a
        rebuilt executor on a self-healing restart."""
        import copy

        from ksql_tpu.runtime.checkpoint import _ORACLE_STATE_ATTRS

        nodes = []
        for node in self.nodes:
            attrs = _ORACLE_STATE_ATTRS.get(type(node).__name__, ())
            nodes.append({
                a: copy.deepcopy(getattr(node, a))
                for a in attrs if hasattr(node, a)
            })
        tables = {}
        for i, step in enumerate(st.walk_steps(self.plan.physical_plan)):
            ts_ = step.__dict__.get("_table_state")
            if ts_ is not None:
                tables[i] = copy.deepcopy(ts_)
        return {"nodes": nodes, "tables": tables,
                "stream_time": self.stream_time}

    def restore_state_epoch(self, epoch: Dict[str, Any]) -> None:
        """Install an epoch taken by :meth:`state_epoch` (same plan, nodes
        rebuilt in the same deterministic order).  The stored epoch is
        deep-copied on the way in so it survives being restored more than
        once (rollback now, restart later)."""
        import copy

        epoch = copy.deepcopy(epoch)
        for node, nd in zip(self.nodes, epoch["nodes"]):
            for a, v in nd.items():
                setattr(node, a, v)
        for i, step in enumerate(st.walk_steps(self.plan.physical_plan)):
            if i in epoch["tables"]:
                step.__dict__["_table_state"] = epoch["tables"][i]
            else:
                # decode state accumulated after the epoch must not leak
                # into the replay's old/new tracking
                step.__dict__.pop("_table_state", None)
        if epoch.get("stream_time") is not None:
            self.stream_time = epoch["stream_time"]

    def changelog_dirty_state(self) -> Dict[str, Any]:
        """Dirty-set seam for the incremental changelog journal
        (runtime/changelog.py): one commit-point capture in
        checkpoint-serde shape.  _snapshot_oracle returns LIVE node
        references; the journal host-copies the capture before diffing,
        so this stays as cheap as the checkpoint path."""
        from ksql_tpu.runtime.checkpoint import _snapshot_oracle

        return _snapshot_oracle(self)

    def changelog_apply_state(self, data: Dict[str, Any]) -> None:
        """Restore a (possibly journal-patched) capture."""
        from ksql_tpu.runtime.checkpoint import _restore_oracle

        _restore_oracle(self, data)

    def _advance_time(self, force: bool = False) -> List[SinkEmit]:
        out = []
        for i, node in enumerate(self.nodes):
            evs = node.on_flush(self.stream_time) if force else node.on_time(self.stream_time)
            if not evs:
                continue
            # events continue from above this node
            path = self._path_above(node)
            for ev in evs:
                out.extend(self._push_from(ev, path))
        return out

    def _path_above(self, node: Node) -> List[Tuple[Node, int]]:
        # nodes were appended root-first during build; path above node =
        # reversed prefix of nodes list... simpler: recompute via search
        for topic_routes in self.source_routes.values():
            for _, path in topic_routes:
                for i, (n, port) in enumerate(path):
                    if n is node:
                        return path[i + 1 :]
        return []

    def _push(self, ev: Event, path: List[Tuple[Node, int]]) -> List[SinkEmit]:
        return self._push_from(ev, path)

    def _push_from(self, ev: Event, path: List[Tuple[Node, int]]) -> List[SinkEmit]:
        chaos = faults.armed()
        tr = tracing.active()
        if tr is None:
            events = [ev]
            for node, port in path:
                if chaos:
                    # per-stage chaos seam: a hang-mode rule here blocks the
                    # tick body mid-pipeline (the tick-deadline test seam)
                    faults.fault_point(
                        "stage.process",
                        f"{self.plan.query_id}:{node.step.ctx}",
                    )
                next_events = []
                for e in events:
                    next_events.extend(node.receive(port, e))
                events = next_events
                if not events:
                    return []
            return [emit for e in events for emit in self._emit(e)]
        # traced variant: per-ExecutionStep stage accumulation (the oracle's
        # node-at-a-time analog of the device backend's fused step timing)
        events = [ev]
        for node, port in path:
            if chaos:
                faults.fault_point(
                    "stage.process", f"{self.plan.query_id}:{node.step.ctx}"
                )
            t0 = _time.perf_counter()
            next_events = []
            for e in events:
                next_events.extend(node.receive(port, e))
            events = next_events
            tr.stage(f"stage:{node.step.ctx}", _time.perf_counter() - t0)
            if not events:
                return []
        return [emit for e in events for emit in self._emit(e)]

    # ------------------------------------------------------------ decoding
    # ------------------------------------------------------------ emitting
    def _emit(self, event: Event) -> List[SinkEmit]:
        if isinstance(event, StreamRow):
            emits = [SinkEmit(event.key, event.row, event.ts, event.window)]
        else:
            emits = [SinkEmit(event.key, event.new, event.ts, event.window)]
        out = []
        for e in emits:
            if self.emit_callback is not None:
                self.emit_callback(e)
            if self.sink_step is not None:
                self._produce(e)
            out.append(e)
        return out

    def _produce(self, e: SinkEmit):
        self.sink_writer.produce(e)