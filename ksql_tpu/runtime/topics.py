"""In-process partitioned log — the Kafka stand-in.

The reference's storage/transport layer is external Kafka (SURVEY §1 layer 0).
This framework's ingress/egress abstraction is a partitioned, offset-addressed
record log with the same semantics (keyed partitioning, per-partition
ordering, offsets, timestamps, tombstones).  The broker here is in-process;
a networked implementation can replace it behind the same interface.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ksql_tpu.common import faults
from ksql_tpu.common.batch import stable_hash64
from ksql_tpu.common.errors import KsqlException


@dataclasses.dataclass
class Record:
    key: Any  # python value (tuple for multi-col keys) or None
    value: Any  # serialized payload (bytes/str) or None = tombstone
    timestamp: int
    partition: int = 0
    offset: int = -1
    # topic-global produce sequence — preserves total produce order across
    # partitions (the reference's TopologyTestDriver observes outputs in
    # produce order regardless of partition count)
    seq: int = -1
    headers: Tuple[Tuple[str, bytes], ...] = ()
    # windowed keys carry (window_start, window_end) alongside the key
    window: Optional[Tuple[int, int]] = None


class Topic:
    def __init__(self, name: str, partitions: int = 1):
        self.name = name
        self.num_partitions = partitions
        self.partitions: List[List[Record]] = [[] for _ in range(partitions)]
        self._seq = 0
        self._lock = threading.RLock()

    def partition_for(self, key: Any) -> int:
        if key is None:
            # round-robin-ish: stable on current size
            with self._lock:
                return sum(len(p) for p in self.partitions) % self.num_partitions
        return stable_hash64(key) % self.num_partitions

    def produce(self, record: Record) -> Record:
        if faults.armed():
            value = faults.fault_point("topic.produce", self.name, record.value)
            if value is not record.value:
                record = dataclasses.replace(record, value=value)
        with self._lock:
            p = record.partition if record.partition >= 0 else 0
            if record.partition < 0 or record.partition >= self.num_partitions:
                p = self.partition_for(record.key)
            part = self.partitions[p]
            # hot path: direct construction (dataclasses.replace dominates
            # the produce profile at high event rates)
            record = Record(
                record.key, record.value, record.timestamp, p, len(part),
                self._seq, record.headers, record.window,
            )
            self._seq += 1
            part.append(record)
            return record

    def read(self, partition: int, offset: int, max_records: int = 1024) -> List[Record]:
        with self._lock:
            out = self.partitions[partition][offset : offset + max_records]
        if faults.armed() and out:
            # one fault opportunity per record handed out, so a rule with
            # `after=` can deterministically tear the middle of a batch;
            # corruption replaces the handed-out copy, never the log
            faulted = []
            for r in out:
                value = faults.fault_point("topic.read", self.name, r.value)
                faulted.append(
                    r if value is r.value else dataclasses.replace(r, value=value)
                )
            return faulted
        return out

    def end_offsets(self) -> List[int]:
        with self._lock:
            return [len(p) for p in self.partitions]

    def all_records(self) -> List[Record]:
        """All records in global produce order (for tests/PRINT)."""
        with self._lock:
            out = [r for p in self.partitions for r in p]
        return sorted(out, key=lambda r: r.seq)


class Broker:
    """Topic registry (KafkaTopicClient analog)."""

    def __init__(self) -> None:
        self._topics: Dict[str, Topic] = {}
        self._lock = threading.RLock()

    def create_topic(self, name: str, partitions: int = 1, if_not_exists: bool = True) -> Topic:
        with self._lock:
            t = self._topics.get(name)
            if t is not None:
                if not if_not_exists:
                    raise KsqlException(f"Topic {name} already exists")
                return t
            t = Topic(name, partitions)
            self._topics[name] = t
            return t

    def topic(self, name: str) -> Topic:
        with self._lock:
            t = self._topics.get(name)
        if t is None:
            raise KsqlException(f"Topic {name} does not exist")
        return t

    def has_topic(self, name: str) -> bool:
        with self._lock:
            return name in self._topics

    def delete_topic(self, name: str) -> None:
        with self._lock:
            self._topics.pop(name, None)

    def list_topics(self) -> List[str]:
        with self._lock:
            return sorted(self._topics)


class Consumer:
    """Per-query consumer over a set of topics with committed offsets."""

    def __init__(self, broker: Broker, topics: List[str], from_beginning: bool = True):
        self.broker = broker
        self.topic_names = list(topics)
        self.positions: Dict[Tuple[str, int], int] = {}
        for tn in self.topic_names:
            t = broker.topic(tn)
            for p in range(t.num_partitions):
                self.positions[(tn, p)] = 0 if from_beginning else t.end_offsets()[p]

    def poll(self, max_records: int = 4096) -> List[Tuple[str, Record]]:
        """Merge-read across subscribed topic-partitions in global produce
        (seq) order per topic, so multi-partition intermediate topics are
        consumed in the order upstream emitted them (per-partition order is
        a fortiori preserved).

        Heap-merge over per-partition cursors (each partition is already
        seq-ordered): O(taken · log P), instead of speculatively reading the
        full budget from every partition and discarding the overflow."""
        import heapq

        out: List[Tuple[str, Record]] = []
        budget = max_records
        for tn in self.topic_names:
            if budget <= 0:
                break
            t = self.broker.topic(tn)

            def part_iter(p: int, start: int):
                offset = start
                while True:
                    chunk = t.read(p, offset, 256)
                    if not chunk:
                        return
                    for r in chunk:
                        yield r.seq, p, r
                    offset += len(chunk)

            merged = heapq.merge(
                *(part_iter(p, self.positions[(tn, p)]) for p in range(t.num_partitions))
            )
            taken = 0
            for _seq, p, r in merged:
                if taken >= budget:
                    break
                self.positions[(tn, p)] += 1
                out.append((tn, r))
                taken += 1
            budget -= taken
        return out

    def fork(self, positions: Optional[Dict[Tuple[str, int], int]] = None
             ) -> "Consumer":
        """A new consumer over the same topics at ``positions`` (default:
        a copy of the current positions).  The tick-deadline watchdog uses
        this to fence an abandoned tick worker: the zombie keeps mutating
        the orphaned consumer while the query resumes on the fork."""
        c = Consumer.__new__(Consumer)
        c.broker = self.broker
        c.topic_names = list(self.topic_names)
        c.positions = dict(self.positions if positions is None else positions)
        return c

    def at_end(self) -> bool:
        for tn in self.topic_names:
            t = self.broker.topic(tn)
            ends = t.end_offsets()
            for p in range(t.num_partitions):
                if self.positions[(tn, p)] < ends[p]:
                    return False
        return True
