"""State checkpoint / restore — the changelog-restore analog.

The reference makes state durable two ways: the command topic WAL rebuilds
*metadata* (CommandRunner.java:260), and every store restores its *state*
from a compacted changelog topic on restart (Kafka Streams
StoreChangelogReader; SURVEY §5 checkpoint row).  Here the WAL already
exists (server/command_log.py); this module snapshots state:

* broker topic logs (the in-process Kafka stand-in owns the data tier, so
  durability of records lives here too);
* per-query executor state — the device store pytree (HBM hash stores,
  join table store, ring buffers, session stores) or the oracle's node
  dicts — plus consumer offsets, stream time, and the host-side
  materialization shadow.

Restore runs after WAL replay has re-created the queries: topics are
reloaded first, then each query's state and offsets, so processing resumes
exactly where the snapshot was taken (no reprocessing, no loss — the test
contract: kill + restore produces byte-identical sink output).

Snapshots are a single atomic pickle (tmp file + rename).  Pickle is
acceptable here for the same reason RocksDB SSTs are in the reference: the
checkpoint dir is node-local trusted state, not an interchange format.

Durability (ISSUE 16): each save wraps the pickle blob in a sha256
envelope and rotates the prior file to ``ckpt.prev`` before the rename,
keeping a two-generation chain.  Both restore paths verify the checksum
and fall back to the previous generation on a truncated / bit-flipped /
bad-checksum file — loudly (``checkpoint.corrupt`` plog + per-query
/alerts evidence), never by raising out of the rebuild path.  A version
mismatch still raises: an old-format snapshot is an operator decision,
not fallback material.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
import time
from typing import Any, Dict, Optional

import numpy as np

from ksql_tpu.common import faults, tracing

CHECKPOINT_FILE = "checkpoint.pkl"
#: the rotated previous generation — the fallback the verified-restore
#: chain reads when the current file fails its integrity check
CHECKPOINT_PREV_FILE = "ckpt.prev"
#: v2: stable_hash64 canonicalizes dict ordering by key hash (mixed-type /
#: null map keys) — hashes differ from v1 snapshots, which must not be
#: restored into post-change stores
#: v3: handle.materialized values grew an emit-timestamp element (standby
#: promotion replays original ROWTIMEs) — v2 3-tuples won't unpack
#: v4: per-query sink ``emit_seq`` high-water + a random ``ckpt_id`` that
#: chains incremental changelog frames (runtime/changelog.py) to their
#: generation; v3 snapshots predate the journal and must not silently
#: restore under one
CHECKPOINT_VERSION = 4


# ------------------------------------------------------------------ broker


def _snapshot_broker(broker) -> Dict[str, Any]:
    import dataclasses

    out = {}
    for name in broker.list_topics():
        t = broker.topic(name)
        with t._lock:
            out[name] = {
                "partitions": t.num_partitions,
                "seq": t._seq,
                "records": [
                    [dataclasses.astuple(r) for r in part] for part in t.partitions
                ],
            }
    return out


def _restore_broker(broker, data: Dict[str, Any]) -> None:
    from ksql_tpu.runtime.topics import Record, Topic

    for name, td in data.items():
        t = Topic(name, td["partitions"])
        t._seq = td["seq"]
        t.partitions = [
            [Record(*fields) for fields in part] for part in td["records"]
        ]
        # tail-preserving merge: WAL replay runs BEFORE restore and may
        # have re-created records newer than the snapshot (INSERT VALUES
        # issued after the last checkpoint are WAL-durable).  Replacing
        # the topic wholesale would clobber exactly the rows a crash is
        # supposed not to lose — keep every live record beyond the
        # snapshot's per-partition prefix.
        with broker._lock:
            live = broker._topics.get(name)
        if live is not None and live.num_partitions == t.num_partitions:
            with live._lock:
                for p in range(t.num_partitions):
                    t.partitions[p].extend(
                        live.partitions[p][len(t.partitions[p]):]
                    )
                t._seq = max(t._seq, live._seq)
        with broker._lock:
            broker._topics[name] = t


# ----------------------------------------------------------------- queries


def _flatten_state(state) -> Dict[str, np.ndarray]:
    import jax

    flat: Dict[str, np.ndarray] = {}
    for k, v in jax.device_get(state).items():
        if isinstance(v, dict):  # nested join-table store
            for k2, v2 in v.items():
                flat[f"{k}/{k2}"] = np.asarray(v2)
        else:
            flat[k] = np.asarray(v)
    return flat


def _unflatten_state(arrays: Dict[str, np.ndarray]) -> Dict[str, Any]:
    import jax.numpy as jnp

    # jnp.array (copy), NOT jnp.asarray: on CPU a zero-copy view over the
    # unpickled host buffer can alias memory the jitted step later DONATES
    # (donate_argnums on every state step) — XLA then recycles memory that
    # numpy/pickle still own, corrupting the heap (intermittent SIGSEGV /
    # SIGABRT on the post-restore tick)
    state: Dict[str, Any] = {}
    for k, v in arrays.items():
        if "/" in k:
            outer, inner = k.split("/", 1)
            state.setdefault(outer, {})[inner] = jnp.array(v)
        else:
            state[k] = jnp.array(v)
    return state


def _device_caps(dev) -> Dict[str, Any]:
    return {
        "store_capacity": dev.store_capacity,
        "table_store_capacity": dev.table_store_capacity,
        "join_capacities": [js.capacity for js in dev.join_chain],
        "tt_store_capacity": getattr(dev, "tt_store_capacity", 0),
        "fk_store_capacity": getattr(dev, "fk_store_capacity", 0),
        "ss_capacity": getattr(dev, "ss_capacity", 0),
        "ss_out_cap": getattr(dev, "ss_out_cap", 0),
        "session_slots": dev.session_slots,
    }


def _apply_caps(dev, caps: Dict[str, Any]) -> None:
    import dataclasses

    dev.store_capacity = caps["store_capacity"]
    if dev.store_layout is not None:
        dev.store_layout = dataclasses.replace(
            dev.store_layout, capacity=dev.store_capacity
        )
    dev.table_store_capacity = caps["table_store_capacity"]
    jcaps = caps.get("join_capacities") or []
    for js, cap in zip(dev.join_chain, jcaps):
        js.capacity = cap
    if dev.join_chain and not jcaps:
        dev.join_chain[-1].capacity = dev.table_store_capacity
    if caps.get("tt_store_capacity"):
        dev.tt_store_capacity = caps["tt_store_capacity"]
        if hasattr(dev, "_tt_steps"):
            del dev._tt_steps  # statics changed: retrace on next batch
    if caps.get("fk_store_capacity"):
        dev.fk_store_capacity = caps["fk_store_capacity"]
        if hasattr(dev, "_fk_steps"):
            del dev._fk_steps  # statics changed: retrace on next batch
    if caps["ss_capacity"]:
        dev.ss_capacity = caps["ss_capacity"]
        dev.ss_out_cap = caps["ss_out_cap"]
    dev.session_slots = caps["session_slots"]


def _snapshot_device(dev) -> Dict[str, Any]:
    """CompiledDeviceQuery state → host arrays + sizing + dictionary."""
    return {
        "arrays": _flatten_state(dev.state),
        "caps": _device_caps(dev),
        "dictionary": dict(dev.dictionary._map),
        "counters": {
            "_seen_overflow": dev._seen_overflow,
            "_batches": dev._batches,
            "_table_seen_overflow": dev._table_seen_overflow,
        },
    }


def _restore_device(dev, data: Dict[str, Any]) -> None:
    _apply_caps(dev, data["caps"])
    dev._compile_steps()
    dev.state = _unflatten_state(data["arrays"])
    dev.dictionary._map.update(data["dictionary"])
    for k, v in data["counters"].items():
        setattr(dev, k, v)


def _snapshot_device_dist(dist) -> Dict[str, Any]:
    """DistributedDeviceQuery → per-shard host arrays (leading [n_shards]
    axis preserved) + the wrapped compiled query's sizing/dictionary."""
    return {
        "arrays": _flatten_state(dist.state),
        "caps": _device_caps(dist.c),
        "dictionary": dict(dist.c.dictionary._map),
        "counters": {
            "_seen_overflow": dist._seen_overflow,
            "_batches": dist._batches,
            "_table_seen_overflow": dist.c._table_seen_overflow,
        },
        "n_shards": dist.n_shards,
        "bucket_capacity": dist.bucket_capacity,
        "stats": {
            "rows_in": np.asarray(dist.shard_rows_in),
            "rows_out": np.asarray(dist.shard_rows_out),
            "exchange_rows": np.asarray(dist.shard_exchange_rows),
        },
    }


def _restore_device_dist(dist, data: Dict[str, Any]) -> None:
    import jax
    import jax.tree_util as jtu
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ksql_tpu.parallel.mesh import SHARD_AXIS

    # shard-count mismatches never reach here: _restore_query routes them
    # through _prepare_reshard (pure, fallible) + _apply_reshard before
    # any handle mutation
    assert data["n_shards"] == dist.n_shards
    _apply_caps(dist.c, data["caps"])
    dist.c._compile_steps()
    dist.bucket_capacity = data["bucket_capacity"]
    dist._build_steps()  # re-jit the sharded steps against restored sizing
    spec = NamedSharding(dist.mesh, P(SHARD_AXIS))
    dist.state = jtu.tree_map(
        lambda v: jax.device_put(v, spec), _unflatten_state(data["arrays"])
    )
    dist.c.dictionary._map.update(data["dictionary"])
    dist._seen_overflow = data["counters"]["_seen_overflow"]
    dist._batches = data["counters"]["_batches"]
    dist.c._table_seen_overflow = data["counters"]["_table_seen_overflow"]
    stats = data.get("stats", {})
    if stats:
        dist.shard_rows_in = np.array(stats["rows_in"])
        dist.shard_rows_out = np.array(stats["rows_out"])
        dist.shard_exchange_rows = np.array(stats["exchange_rows"])


# ------------------------------------------------------ reshard-on-restore
#
# An N-shard checkpoint restores onto an M-shard mesh by gathering every
# sharded store to host, re-partitioning live rows by ``shard_of(khash)``
# under the new mesh, and re-inserting per target shard with the same host
# probe the store-growth rebuild uses (hash_store.host_insert) — the
# gather → repartition → scatter discipline of make_shard_and_gather_fns.
#
# Split into a PURE prepare phase (everything that can fail: shape checks,
# fit check, per-shard probe inserts) and an apply phase that mutates the
# executor.  A failure in prepare degrades to the pre-reshard refuse-loudly
# posture with the executor and handle untouched — never a torn restore.


def _reshard_refused(data, dist, why: str) -> RuntimeError:
    return RuntimeError(
        f"checkpoint was taken on {data['n_shards']} shards but the mesh "
        f"has {dist.n_shards}, and reshard-on-restore cannot move this "
        f"state ({why}); restart with ksql.device.shards={data['n_shards']}"
    )


def _prepare_reshard(dist, data: Dict[str, Any]) -> Dict[str, Any]:
    """Pure host half of reshard-on-restore — EVERY fallible step lives
    here: shape/key validation against the executor's state template,
    per-shard scalar combination, the capacity fit check, and the
    per-target-shard probe inserts.  Returns the scatter plan; raises
    (refuse-loudly) without touching ``dist`` or the handle."""
    import jax

    from ksql_tpu.parallel.repartition import np_shard_of

    faults.fault_point(
        "checkpoint.reshard", f"{data['n_shards']}->{dist.n_shards}"
    )
    new_n = dist.n_shards
    # cutover phase spans (gather / repartition / insert) land on whatever
    # cutover tick is active (engine._rebuild_body opens one on the
    # query's flight recorder), so a slow reshard-restore is attributable
    # to a phase in /query-trace and the rescale.done evidence — no-ops
    # when tracing is off or the restore runs outside a tick
    with tracing.span("cutover.gather"):
        arrays = {k: np.asarray(v) for k, v in data["arrays"].items()}
        # stream-stream join ring buffers are arrival-ordered per shard
        # (cursor/seq state the matcher depends on): rows cannot change
        # shards without rewriting that order — keep refuse-loudly
        if any(k.startswith(("ssl_", "ssr_")) for k in arrays):
            raise _reshard_refused(
                data, dist, "stream-stream join buffers are arrival-"
                "ordered per shard"
            )
        top = {k: v for k, v in arrays.items() if "/" not in k}
        nested_names = {k.split("/", 1)[0] for k in arrays if "/" in k}
        # classify the CURRENT executor's state template without building
        # it: eval_shape yields keys + shapes only.  Capacity-independent
        # classification: dict = replicated table store, leading axis ==
        # capacity+1 = per-slot, anything else = per-shard scalar.
        template = jax.eval_shape(dist.c.init_state)
        cur_c1 = dist.c.store_capacity + 1
        per_slot, scalars_plan = [], {}
        for name, tmpl in template.items():
            if isinstance(tmpl, dict):
                if name not in nested_names:
                    raise _reshard_refused(
                        data, dist, f"missing saved {name}"
                    )
                continue
            if tmpl.ndim >= 1 and tmpl.shape[0] == cur_c1:
                if name not in top:
                    raise _reshard_refused(
                        data, dist, f"missing saved state {name}"
                    )
                per_slot.append(name)
                continue
            old = top.get(name)
            if old is None:
                raise _reshard_refused(
                    data, dist, f"missing saved state {name}"
                )
            # per-shard scalar: max_ts folds to the global stream clock
            # (the conservative, oracle-parity bound); overflow keeps its
            # total in lane 0; anything else must have been replicated
            # (all lanes equal) or the state is not movable
            if name == "max_ts":
                scalars_plan[name] = np.full((new_n,), old.max(), old.dtype)
            elif name == "overflow":
                col = np.zeros((new_n,), old.dtype)
                col[0] = old.sum()
                scalars_plan[name] = col
            elif all((old[0] == old[i]).all() for i in range(old.shape[0])):
                scalars_plan[name] = np.repeat(
                    np.ascontiguousarray(old[:1]), new_n, axis=0
                )
            else:
                raise _reshard_refused(
                    data, dist, f"per-shard state '{name}' diverges "
                    "across shards and has no repartition rule"
                )
    plan: Dict[str, Any] = {
        "target_cap": None, "per_slot": per_slot, "scalars": scalars_plan,
    }
    if "occ" not in top:
        return plan  # no keyed store: scalars + replicated tables only
    with tracing.span("cutover.repartition"):
        old_cap = top["occ"].shape[1] - 1
        live_s, live_slot = np.nonzero(top["occ"][:, :old_cap])
        dest = np_shard_of(top["khash"][live_s, live_slot], new_n)
        counts = np.bincount(dest, minlength=new_n)
        # old-shard -> new-shard live-key movement histogram: the
        # attribution key for carrying per-shard stat totals
        # (rows/exchange) through the mesh change instead of lumping them
        # into lane 0
        move = np.zeros((int(data["n_shards"]), new_n), np.int64)
        np.add.at(move, (live_s, dest), 1)
        plan["move_counts"] = move
        plan["target_live"] = counts.astype(np.int64)
        # a shrink concentrates keys: grow the per-shard capacity until
        # the fullest target shard sits at <= 50% load (under the
        # runtime's 60% grow/stop guard, and a load factor the probe
        # always completes at)
        target_cap = old_cap
        while counts.size and counts.max() > target_cap // 2:
            target_cap *= 2
    from ksql_tpu.ops.hash_store import host_insert

    with tracing.span("cutover.insert"):
        occ = np.zeros((new_n, target_cap + 1), bool)
        kh = np.zeros((new_n, target_cap + 1), np.int64)
        ws = np.zeros((new_n, target_cap + 1), np.int64)
        rows_of: Dict[int, np.ndarray] = {}
        slots_of: Dict[int, np.ndarray] = {}
        for d in range(new_n):
            rows = np.nonzero(dest == d)[0]
            if not rows.size:
                continue
            s_, p_ = live_s[rows], live_slot[rows]
            try:
                slots = host_insert(
                    occ[d], kh[d], ws[d], target_cap,
                    top["khash"][s_, p_], top["wstart"][s_, p_],
                )
            except RuntimeError as e:
                raise _reshard_refused(data, dist, str(e)) from e
            rows_of[d] = rows
            slots_of[d] = slots
    plan.update(
        target_cap=target_cap, occ=occ, khash=kh, wstart=ws,
        live_s=live_s, live_slot=live_slot,
        rows_of=rows_of, slots_of=slots_of,
    )
    return plan


def _reattribute_totals(old: "np.ndarray", move, new_n: int) -> "np.ndarray":
    """Re-key cumulative per-old-shard totals onto the new mesh.

    Each old shard's total is split across destination shards proportional
    to how many of its live keys moved there (largest-remainder rounding,
    so the global sum is preserved EXACTLY — the counters stay monotone).
    An old shard with no live keys (or a stateless query with no keyed
    store at all, ``move is None``) folds onto ``old_shard % new_n``."""
    out = np.zeros(new_n, np.int64)
    for s, total in enumerate(old.tolist()):
        if total == 0:
            continue
        m = move[s] if move is not None else None
        msum = int(m.sum()) if m is not None else 0
        if msum == 0:
            out[s % new_n] += total
            continue
        shares = (m.astype(np.int64) * int(total)) // msum
        out += shares
        out[int(m.argmax())] += int(total) - int(shares.sum())
    return out


def _apply_reshard(dist, data: Dict[str, Any], plan: Dict[str, Any]) -> None:
    """Mutating half of reshard-on-restore: size the wrapped compiled query
    from the (possibly grown) plan capacity, recompile the sharded steps,
    and scatter the prepared rows into fresh per-shard stores.  All
    validation and fallible combination already ran in _prepare_reshard —
    nothing here raises on snapshot content."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import jax.tree_util as jtu
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ksql_tpu.parallel.mesh import SHARD_AXIS

    new_n = dist.n_shards
    arrays = {k: np.asarray(v) for k, v in data["arrays"].items()}
    top = {k: v for k, v in arrays.items() if "/" not in k}
    nested: Dict[str, Dict[str, np.ndarray]] = {}
    for k, v in arrays.items():
        if "/" in k:
            outer, inner = k.split("/", 1)
            nested.setdefault(outer, {})[inner] = v
    caps = dict(data["caps"])
    if plan["target_cap"] is not None:
        caps["store_capacity"] = plan["target_cap"]
    _apply_caps(dist.c, caps)
    if "slice_id" in top and dist.c.store_layout is not None:
        # sliced hopping store: the ring width is a jit static and a state
        # shape — carry the SAVED width into the fresh layout (the ring
        # remap itself is slot-local, so the scatter below moves it intact)
        ring = int(top["slice_id"].shape[2])
        if ring != dist.c.slice_ring:
            dist.c.slice_ring = ring
            dist.c.store_layout = dataclasses.replace(
                dist.c.store_layout,
                components=tuple(
                    dataclasses.replace(c, width=ring)
                    for c in dist.c.store_layout.components
                ),
            )
    dist.c._compile_steps()
    # bucket_capacity stays the freshly-constructed one: it is sized from
    # the NEW mesh's per-shard batch capacity, not the old mesh's
    dist._build_steps()
    base = jtu.tree_map(
        lambda v: np.array(v), jax.device_get(dist.c.init_state())
    )
    new_state: Dict[str, Any] = dict(plan["scalars"])
    for name, tmpl in base.items():
        if isinstance(tmpl, dict):
            # replicated join-table store (broadcast changelog): every old
            # lane holds the same full copy — rebroadcast lane 0
            new_state[name] = {
                k2: np.repeat(np.ascontiguousarray(v2[:1]), new_n, axis=0)
                for k2, v2 in nested[name].items()
            }
            continue
        if name not in plan["per_slot"]:
            continue  # per-shard scalar, combined in prepare
        old = top[name]
        if name == "occ":
            col = plan["occ"].copy()
        elif name == "khash":
            col = plan["khash"].copy()
        elif name == "wstart":
            col = plan["wstart"].copy()
        else:
            col = np.repeat(tmpl[None], new_n, axis=0)
            for d, rows in plan["rows_of"].items():
                col[d][plan["slots_of"][d]] = old[
                    plan["live_s"][rows], plan["live_slot"][rows]
                ]
        new_state[name] = col
    spec = NamedSharding(dist.mesh, P(SHARD_AXIS))
    # jnp.array (copy) before device_put, NOT a zero-copy view: the rebuilt
    # host buffers must never alias memory the donating sharded step later
    # hands to XLA to recycle (the PR-2 heap-corruption class — the
    # donated-aliasing lint tracks this handoff)
    dist.state = jtu.tree_map(
        lambda v: jax.device_put(jnp.array(v), spec), new_state,
        is_leaf=lambda v: isinstance(v, np.ndarray),
    )
    dist.c.dictionary._map.update(data["dictionary"])
    dist._seen_overflow = data["counters"]["_seen_overflow"]
    dist._batches = data["counters"]["_batches"]
    dist.c._table_seen_overflow = data["counters"]["_table_seen_overflow"]
    stats = data.get("stats", {})
    if stats:
        # per-shard stat totals are re-keyed to the NEW mesh: each old
        # shard's rows/exchange totals follow its live keys proportionally
        # (the scatter plan's movement histogram), so post-cutover /metrics
        # still attributes history to the shards now owning those keys —
        # and the cumulative sums stay exactly monotone across a reshard
        move = plan.get("move_counts")
        for attr, key in (("shard_rows_in", "rows_in"),
                          ("shard_rows_out", "rows_out"),
                          ("shard_exchange_rows", "exchange_rows")):
            setattr(dist, attr, _reattribute_totals(
                np.asarray(stats[key], dtype=np.int64), move, new_n
            ))
    live = plan.get("target_live")
    dist.shard_store_occupancy = (
        np.asarray(live, np.int64) if live is not None
        else np.zeros(new_n, np.int64)
    )
    dist.shard_watermark_ms = np.full(new_n, -1, np.int64)


#: which attributes of each oracle node class constitute its state
_ORACLE_STATE_ATTRS = {
    "AggregateNode": ("state", "session_windows", "max_ts"),
    "SuppressNode": ("buffer", "emitted", "prev_time"),
    "StreamStreamJoinNode": ("left_buf", "right_buf"),
    "StreamTableJoinNode": ("table",),
    "TableTableJoinNode": ("left", "right"),
    "FkJoinNode": ("left", "right", "fk_index"),
}


def _snapshot_oracle(executor) -> Dict[str, Any]:
    from ksql_tpu.execution import steps as st

    nodes = []
    for node in executor.nodes:
        attrs = _ORACLE_STATE_ATTRS.get(type(node).__name__, ())
        nodes.append(
            {a: getattr(node, a) for a in attrs if hasattr(node, a)}
        )
    tables = {}
    for i, step in enumerate(st.walk_steps(executor.plan.physical_plan)):
        ts = step.__dict__.get("_table_state")
        if ts is not None:
            tables[i] = ts
    return {"nodes": nodes, "tables": tables}


def _restore_oracle(executor, data: Dict[str, Any]) -> None:
    from ksql_tpu.execution import steps as st

    for node, nd in zip(executor.nodes, data["nodes"]):
        for a, v in nd.items():
            setattr(node, a, v)
    steps = list(st.walk_steps(executor.plan.physical_plan))
    for i, ts in data["tables"].items():
        steps[i].__dict__["_table_state"] = ts


def _is_dist(dev) -> bool:
    from ksql_tpu.parallel.distributed import DistributedDeviceQuery

    return isinstance(dev, DistributedDeviceQuery)


def _snapshot_query(handle) -> Dict[str, Any]:
    ex = handle.executor
    out: Dict[str, Any] = {
        "backend": handle.backend,
        "positions": dict(handle.consumer.positions),
        "materialized": dict(handle.materialized),
        "stream_time": getattr(ex, "stream_time", None),
        "state": "running" if handle.is_running() else "paused",
    }
    wtr = getattr(ex, "sink_writer", None)
    if wtr is not None:
        # durable sink high-water: restore re-arms the 1-based emit
        # ordinal so the effectively-once fence (runtime/changelog.py)
        # lines up with replayed derivations
        out["emit_seq"] = int(getattr(wtr, "emit_seq", 0))
    dev = getattr(ex, "device", None)
    if dev is not None and _is_dist(dev):
        out["device_dist"] = _snapshot_device_dist(dev)
    elif dev is not None:
        out["device"] = _snapshot_device(dev)
    else:
        out["oracle"] = _snapshot_oracle(ex)
    return out


def _restore_query(handle, data: Dict[str, Any]) -> None:
    ex = handle.executor
    dev = getattr(ex, "device", None)
    if (
        "device_dist" in data and dev is not None and _is_dist(dev)
        and data["device_dist"]["n_shards"] != dev.n_shards
    ):
        # reshard-on-restore: run the fallible prepare half BEFORE any
        # handle mutation, so a refused reshard leaves offsets, the
        # materialization shadow, and the executor exactly as they were
        # (refuse-loudly, never a torn restore)
        reshard_plan = _prepare_reshard(dev, data["device_dist"])
    handle.consumer.positions.update(data["positions"])
    handle.materialized.update(data["materialized"])
    if data.get("stream_time") is not None and hasattr(ex, "stream_time"):
        ex.stream_time = data["stream_time"]
    wtr = getattr(ex, "sink_writer", None)
    if wtr is not None and data.get("emit_seq") is not None:
        wtr.emit_seq = int(data["emit_seq"])
    if "device_dist" in data and dev is not None and _is_dist(dev):
        if data["device_dist"]["n_shards"] != dev.n_shards:
            _apply_reshard(dev, data["device_dist"], reshard_plan)
        else:
            _restore_device_dist(dev, data["device_dist"])
    elif "device" in data and dev is not None and not _is_dist(dev):
        _restore_device(dev, data["device"])
    elif "oracle" in data and dev is None:
        _restore_oracle(ex, data["oracle"])
    # backend mismatch (e.g. config changed between runs): offsets still
    # restore; state starts empty on the new backend — loud, not silent
    elif "device" in data or "device_dist" in data or "oracle" in data:
        raise RuntimeError(
            f"checkpoint backend mismatch for {handle.query_id}: "
            f"snapshot={data['backend']}, running={handle.backend}"
        )


# -------------------------------------------------- integrity + generations


class CheckpointCorrupt(RuntimeError):
    """One checkpoint generation failed integrity verification —
    truncated, bit-flipped, bad checksum, or an unreadable pickle."""


def _read_verified(path: str) -> Dict[str, Any]:
    """Read ONE checkpoint generation and verify its integrity: the
    sha256 envelope must check out before the payload is unpickled.
    Pre-envelope files (no recorded checksum) still load — they predate
    the chain and cannot be verified, only parsed.  Raises
    :class:`CheckpointCorrupt` on any integrity failure."""
    with open(path, "rb") as f:
        raw = f.read()
    try:
        env = pickle.loads(raw)
    except Exception as e:  # noqa: BLE001 — truncation/bit-flip lands here
        raise CheckpointCorrupt(
            f"unreadable checkpoint at {path}: {type(e).__name__}: {e}"
        ) from e
    if isinstance(env, dict) and "sha256" in env and "payload" in env:
        digest = hashlib.sha256(env["payload"]).hexdigest()
        if digest != env["sha256"]:
            raise CheckpointCorrupt(
                f"checkpoint checksum mismatch at {path}: recorded "
                f"{env['sha256'][:12]}.., read {digest[:12]}.."
            )
        try:
            data = pickle.loads(env["payload"])
        except Exception as e:  # noqa: BLE001
            raise CheckpointCorrupt(
                f"checkpoint payload undecodable at {path} despite a "
                f"matching checksum: {type(e).__name__}: {e}"
            ) from e
    else:
        data = env  # pre-envelope legacy layout: no checksum to verify
    if not isinstance(data, dict):
        raise CheckpointCorrupt(
            f"checkpoint at {path} is not a snapshot dict"
        )
    return data


def _corruption_loud(engine, generation: str, path: str,
                     err: Exception) -> None:
    """The loud-surface contract for a corrupt generation: one
    ``checkpoint.corrupt`` plog entry plus an /alerts evidence event on
    every query's progress ring (corruption is engine-wide — any query
    may silently lose restored state because of it)."""
    msg = f"{generation} generation unreadable at {path}: {err}"
    try:
        engine._plog_append("checkpoint.corrupt", msg)
    except Exception:  # noqa: BLE001 — surfacing must never block restore
        pass
    for h in list(getattr(engine, "queries", {}).values()):
        prog = getattr(h, "progress", None)
        if prog is None:
            continue
        try:
            prog.note_event(
                "checkpoint.corrupt", generation=generation, error=str(err)
            )
        except Exception:  # noqa: BLE001
            pass


def _load_generations(engine, directory: str):
    """Load the newest INTACT generation: the current file first, then
    the rotated ``ckpt.prev``.  Every corrupt generation surfaces loudly
    (see :func:`_corruption_loud`) and the chain moves on — restore never
    raises out of the rebuild path over corruption.  Returns
    ``(data_or_None, current_was_corrupt)``; a version mismatch on an
    intact file still raises."""
    current_corrupt = False
    for generation, fname in (
        ("current", CHECKPOINT_FILE), ("prev", CHECKPOINT_PREV_FILE)
    ):
        path = os.path.join(directory, fname)
        if not os.path.exists(path):
            continue
        try:
            data = _read_verified(path)
        except CheckpointCorrupt as e:
            if generation == "current":
                current_corrupt = True
            _corruption_loud(engine, generation, path, e)
            continue
        if data.get("version") != CHECKPOINT_VERSION:
            raise RuntimeError(
                f"unsupported checkpoint version {data.get('version')} "
                f"at {path}"
            )
        return data, current_corrupt
    return None, current_corrupt


# ------------------------------------------------------------------- entry


def save_checkpoint(engine, directory: str) -> str:
    """Atomic snapshot of broker + all query state to ``directory``.

    Queries in ERROR are NOT re-snapshotted: a mid-tick crash leaves the
    executor's state torn relative to its rewound consumer offsets (some
    micro-batches applied, offsets back at tick start), and snapshotting
    that tear would make the restart-restore path double-count the applied
    prefix on replay.  Their last CONSISTENT snapshot is carried forward
    from the previous checkpoint file instead (or omitted if none exists,
    which degrades that query to the at-least-once empty-state replay)."""
    faults.fault_point("checkpoint.save", directory)
    path = os.path.join(directory, CHECKPOINT_FILE)
    prev_path = os.path.join(directory, CHECKPOINT_PREV_FILE)
    # the carry source reads through the verified generation chain: a
    # torn CURRENT file must not block a fresh snapshot, but it must not
    # silently drop ERROR queries' carried snapshots either — the prev
    # generation usually still holds them
    prior_queries: Dict[str, Any] = {}
    prior_corrupt = False
    for p in (path, prev_path):
        if not os.path.exists(p):
            continue
        try:
            prior = _read_verified(p)
        except CheckpointCorrupt as e:
            prior_corrupt = True
            try:
                engine._plog_append(
                    "checkpoint.corrupt",
                    f"prior generation unreadable at {p} while carrying "
                    f"ERROR-query snapshots forward: {e}",
                )
            except Exception:  # noqa: BLE001 — never block the snapshot
                pass
            continue
        if prior.get("version") == CHECKPOINT_VERSION:
            prior_queries = prior.get("queries", {})
        break
    queries: Dict[str, Any] = {}
    for qid, h in engine.queries.items():
        if h.state == "ERROR":
            if qid in prior_queries:
                queries[qid] = prior_queries[qid]
            elif prior_corrupt:
                # satellite fix (ISSUE 16): the carried last-consistent
                # snapshot is GONE because every prior generation was
                # corrupt — the query degrades to the at-least-once
                # empty-state replay on its next restart.  Say so.
                try:
                    engine._plog_append(
                        f"checkpoint.carry.lost:{qid}",
                        "ERROR query's carried last-consistent snapshot "
                        "was lost to prior-checkpoint corruption; next "
                        "restart replays from empty state (at-least-once)",
                    )
                    prog = getattr(h, "progress", None)
                    if prog is not None:
                        prog.note_event("checkpoint.carry.lost",
                                        query=qid)
                except Exception:  # noqa: BLE001
                    pass
            continue
        queries[qid] = _snapshot_query(h)
    data = {
        "version": CHECKPOINT_VERSION,
        # generation id: incremental changelog frames chain to it, so a
        # kill between this save and the journal truncation can never
        # replay stale frames over the newer snapshot
        "ckpt_id": os.urandom(8).hex(),
        # save wall-clock: restore seeds the ksql_checkpoint_age_seconds
        # gauge from it, so a freshly-recovered process reports how stale
        # the generation it booted from is (it has not saved locally yet)
        "saved_ms": int(time.time() * 1000),
        "topics": _snapshot_broker(engine.broker),
        "queries": queries,
    }
    blob = pickle.dumps(data, protocol=pickle.HIGHEST_PROTOCOL)
    # sha256 envelope: restore verifies the digest before trusting the
    # payload, so a torn write or bit flip is DETECTED, not unpickled
    # into half a snapshot
    envelope = pickle.dumps(
        {"sha256": hashlib.sha256(blob).hexdigest(), "payload": blob},
        protocol=pickle.HIGHEST_PROTOCOL,
    )
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".ckpt-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(envelope)
            f.flush()
            os.fsync(f.fileno())
        # generation rotation: the prior file survives as ckpt.prev, so
        # corruption of the (new) current generation always leaves one
        # intact fallback; a kill between the two renames leaves prev
        # holding the old generation, which restore falls back to
        if os.path.exists(path):
            os.replace(path, prev_path)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    # journal rotation: the snapshot now covers every changelog frame, so
    # the per-query journals truncate and re-chain to the new generation.
    # Ordering makes a crash here safe: the journals' frames still carry
    # the OLD generation id, and a restore over the new snapshot skips
    # them as stale — truncation is cleanup, not correctness.
    rotate = getattr(engine, "_changelog_rotate", None)
    if rotate is not None:
        rotate(data["ckpt_id"], queries)
    return path


def restore_query_checkpoint(engine, handle, directory: str,
                             live=None) -> bool:
    """Restore ONE query's state + offsets from the last snapshot — the
    self-healing restart path (engine._maybe_restart).  Broker topics are
    deliberately left alone: the in-process log still holds every record,
    so replaying from the snapshot's offsets re-derives everything after
    it; restoring topics would clobber records produced since.  Returns
    True when the query's state was restored.

    ``live`` is the supervised-rebuild fence: the hang-prone steps (the
    fault point, the unpickle) run BEFORE any handle mutation, and the
    fence is re-checked after them — a rebuild worker abandoned mid-
    restore that later wakes must not rewind the offsets or clobber the
    materialized rows of the query a newer rebuild now owns."""
    faults.fault_point("checkpoint.restore", directory)
    data, _ = _load_generations(engine, directory)
    if data is None:
        # no generation readable (missing, or every file corrupt —
        # surfaced loudly above): the restart degrades to the
        # at-least-once empty-state replay instead of dying here
        return False
    qd = data["queries"].get(handle.query_id)
    if qd is None:
        return False  # query created after the snapshot: nothing to restore
    if live is not None and not live():
        return False  # fenced off while loading: a newer rebuild owns it
    # changelog tail replay (runtime/changelog.py): patch the snapshot
    # with the journal's intact frames so the replay window shrinks to
    # ticks-since-last-checkpoint.  The broker is live here, so the
    # journaled sink records are NOT re-appended (they are still in the
    # topic) and no fence is armed — re-derivation is bounded to the
    # in-flight tick past the journal tail.
    from ksql_tpu.runtime import changelog as clog

    info = clog.recover_query(
        engine, directory, handle.query_id, qd, data.get("ckpt_id")
    )
    if live is not None and not live():
        return False  # re-check: journal replay is a hang-prone step too
    _restore_query(handle, info["qd"])
    saved_ms = data.get("saved_ms")
    if saved_ms:
        getattr(engine, "_checkpoint_saved_at", {})[handle.query_id] = (
            saved_ms / 1000.0
        )
    note = getattr(engine, "_changelog_note_restore", None)
    if note is not None:
        note(handle, info, data.get("ckpt_id"), startup=False)
    return True


def restore_checkpoint(engine, directory: str) -> bool:
    """Load the snapshot (if any) into an engine whose queries have already
    been re-created by WAL replay.  Returns True when state was restored."""
    faults.fault_point("checkpoint.restore", directory)
    data, _ = _load_generations(engine, directory)
    if data is None:
        return False  # nothing intact: boot fresh (loud, not fatal)
    from ksql_tpu.runtime import changelog as clog

    engine._ckpt_id = data.get("ckpt_id")
    _restore_broker(engine.broker, data["topics"])
    for qid, qd in data["queries"].items():
        handle = engine.queries.get(qid)
        if handle is None:
            continue  # query dropped from the WAL since the snapshot
        # three-tier recovery ladder, tier 1: checkpoint generation +
        # changelog tail replay.  The journaled sink records died with
        # the in-memory broker, so they re-append here; the fence at the
        # journal's durable high-water makes any re-derivation of those
        # ordinals (the tier-degraded fallback) suppress instead of
        # duplicate — effectively-once across the kill.
        info = clog.recover_query(
            engine, directory, qid, qd, data.get("ckpt_id")
        )
        _restore_query(handle, info["qd"])
        if info["sink"]:
            clog.replay_sink_records(engine.broker, info["sink"])
        wtr = getattr(handle.executor, "sink_writer", None)
        if wtr is not None and info["emit_high"]:
            wtr.fence_seq = int(info["emit_high"])
        saved_ms = data.get("saved_ms")
        if saved_ms:
            # seed snapshot staleness (ksql_checkpoint_age_seconds): the
            # recovered process has not saved locally yet, but how stale
            # the generation it booted from is must be visible NOW
            getattr(engine, "_checkpoint_saved_at", {})[qid] = (
                saved_ms / 1000.0
            )
        note = getattr(engine, "_changelog_note_restore", None)
        if note is not None:
            note(handle, info, data.get("ckpt_id"), startup=True)
    return True
