"""Per-query incremental changelog journal — crash-consistent durability.

The checkpoint (runtime/checkpoint.py) is a monolithic generation: a kill
-9 loses everything since the last save and the restart replays the whole
batch since it.  This module closes that window with an append-only,
CRC-framed journal per query (``<checkpoint.dir>/<qid>.changelog``,
StreamBox-HBM's sequential-write-friendly host tier): at every tick
commit point the engine captures the query's state through the dirty-set
seam (``CompiledDeviceQuery.changelog_dirty_state`` /
``DistributedDeviceQuery.changelog_dirty_state`` /
``OracleExecutor.changelog_dirty_state`` — checkpoint-serde shapes, host
resident) and appends only the DELTA against the previous tick's shadow:
keys touched this tick with their new agg/join/ring state, sparse flat
indices for device arrays, the commit positions, the sink emit_seq
high-water, and the tick's durable sink emissions.

Recovery = newest intact checkpoint generation + changelog tail replay:
frames are chained to the checkpoint generation that was current when
they were written (``ckpt`` id), so a kill between a checkpoint save and
the journal truncation can never replay stale frames over a newer
snapshot — they are skipped, not applied.  A torn tail frame (the frame
a kill -9 cut mid-write) fails its CRC and is dropped LOUDLY
(``changelog.corrupt-tail`` plog) with the file truncated back to the
intact prefix; every intact frame replays byte-identically.  The journal
truncates on each successful checkpoint rotation, and a journal past
``ksql.changelog.max.bytes`` forces an early checkpoint.

Egress: each frame records the sink writer's durable ``emit_seq``
high-water.  When the tail cannot be applied (torn mid-chain, injected
``changelog.replay`` fault), restore falls back to the checkpoint-only
state, re-appends the journaled sink records (they were durable), and
arms ``SinkWriter.fence_seq`` at the high-water — replayed emissions
at-or-below it are suppressed, so duplicates across a process death are
bounded by the single in-flight tick (effectively-once).
"""

from __future__ import annotations

import copy
import os
import pickle
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ksql_tpu.common import faults

#: frame header: magic, payload length, crc32(payload)
_MAGIC = b"KCLG"
_HEADER = struct.Struct("<4sII")

#: an array delta switches from sparse (flat indices + values) to a full
#: replacement once more than this fraction of elements changed
_SPARSE_MAX_FRACTION = 0.5


# ------------------------------------------------------------- deep diff
#
# Deltas operate on the checkpoint-serde snapshot shapes: nested dicts of
# numpy arrays (device stores), dicts/lists of plain host values (oracle
# node state, materialization shadow), scalars.  A delta node is one of
#   None                      unchanged
#   ("full", value)           replace wholesale
#   ("sparse", idx, vals)     same-shape ndarray, changed flat elements
#   ("dict", sets, dels)      per-key deltas + deleted keys
#   ("list", {i: delta})      same-length list, per-index deltas


def _host_copy(v: Any) -> Any:
    """Copy a snapshot so the shadow survives the live state (and, for
    device arrays, the donated buffer) mutating underneath it."""
    if isinstance(v, np.ndarray):
        return np.array(v)  # real copy, never a device_get view
    if isinstance(v, dict):
        return {k: _host_copy(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_host_copy(x) for x in v]
    if isinstance(v, tuple):
        return tuple(_host_copy(x) for x in v)
    if isinstance(v, (str, bytes, int, float, bool, type(None))):
        return v
    return copy.deepcopy(v)


def _eq(a: Any, b: Any) -> bool:
    if a is b:
        return True
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return (
            isinstance(a, np.ndarray) and isinstance(b, np.ndarray)
            and a.shape == b.shape and a.dtype == b.dtype
            and bool(np.array_equal(a, b))
        )
    try:
        return bool(a == b)
    except Exception:  # noqa: BLE001 — ambiguous compare = treat changed
        return False


def _diff(old: Any, new: Any) -> Any:
    if old is None and new is not None:
        return ("full", _host_copy(new))
    if isinstance(old, np.ndarray) and isinstance(new, np.ndarray):
        if old.shape != new.shape or old.dtype != new.dtype:
            return ("full", np.array(new))
        if old.dtype == object:
            return None if _eq(old, new) else ("full", np.array(new))
        changed = (old != new).reshape(-1)
        nnz = int(np.count_nonzero(changed))
        if nnz == 0:
            return None
        if nnz > changed.size * _SPARSE_MAX_FRACTION:
            return ("full", np.array(new))
        idx = np.nonzero(changed)[0].astype(np.int64)
        return ("sparse", idx, np.array(new.reshape(-1)[idx]))
    if isinstance(old, dict) and isinstance(new, dict):
        sets: Dict[Any, Any] = {}
        for k, v in new.items():
            if k not in old:
                sets[k] = ("full", _host_copy(v))
                continue
            d = _diff(old[k], v)
            if d is not None:
                sets[k] = d
        dels = [k for k in old if k not in new]
        if not sets and not dels:
            return None
        return ("dict", sets, dels)
    if isinstance(old, list) and isinstance(new, list) \
            and len(old) == len(new):
        per = {
            i: d for i, d in (
                (i, _diff(o, n)) for i, (o, n) in enumerate(zip(old, new))
            ) if d is not None
        }
        return ("list", per) if per else None
    return None if _eq(old, new) else ("full", _host_copy(new))


def _patch(base: Any, delta: Any) -> Any:
    """Apply one delta node; returns the patched value (bases are copied
    before in-place mutation, so a failed replay chain never tears the
    caller's snapshot)."""
    if delta is None:
        return base
    kind = delta[0]
    if kind == "full":
        return _host_copy(delta[1])
    if kind == "sparse":
        _, idx, vals = delta
        if not isinstance(base, np.ndarray):
            raise ValueError("sparse delta over a non-array base")
        out = np.array(base)
        flat = out.reshape(-1)
        flat[idx] = vals
        return out
    if kind == "dict":
        _, sets, dels = delta
        if not isinstance(base, dict):
            raise ValueError("dict delta over a non-dict base")
        out = dict(base)
        for k in dels:
            out.pop(k, None)
        for k, d in sets.items():
            out[k] = _patch(out.get(k), d)
        return out
    if kind == "list":
        _, per = delta
        if not isinstance(base, list):
            raise ValueError("list delta over a non-list base")
        out = list(base)
        for i, d in per.items():
            out[i] = _patch(out[i], d)
        return out
    raise ValueError(f"unknown delta kind {kind!r}")


# --------------------------------------------------------- state capture


def capture_query_state(handle, executor, positions: Dict) -> Optional[
    Dict[str, Any]
]:
    """One commit-point state capture in ``_snapshot_query`` shape,
    through the executors' dirty-set seam.  Returns None when the
    executor exposes no seam (family members ride their primary's
    pipeline and keep the full-checkpoint posture)."""
    out: Dict[str, Any] = {
        "backend": handle.backend,
        "positions": dict(positions),
        "materialized": dict(handle.materialized),
        "stream_time": getattr(executor, "stream_time", None),
        "state": "running" if handle.is_running() else "paused",
    }
    wtr = getattr(executor, "sink_writer", None)
    if wtr is not None:
        out["emit_seq"] = int(getattr(wtr, "emit_seq", 0))
    dev = getattr(executor, "device", None)
    if dev is not None and hasattr(dev, "changelog_dirty_state"):
        from ksql_tpu.runtime.checkpoint import _is_dist

        key = "device_dist" if _is_dist(dev) else "device"
        out[key] = dev.changelog_dirty_state()
        return out
    if dev is None and hasattr(executor, "changelog_dirty_state"):
        out["oracle"] = executor.changelog_dirty_state()
        return out
    return None


# -------------------------------------------------------------- journal


def journal_path(directory: str, query_id: str) -> str:
    return os.path.join(str(directory), f"{query_id}.changelog")


class QueryChangelog:
    """Append side of one query's journal.  The engine owns one instance
    per journaled query; appends happen at the tick commit point (after
    the drain, under the zombie fence), truncation on each successful
    checkpoint rotation."""

    def __init__(self, directory: str, query_id: str, fsync: bool = True):
        self.query_id = query_id
        self.path = journal_path(directory, query_id)
        self.fsync = fsync
        #: monotone frame sequence within the current generation
        self.seq = 0
        #: checkpoint generation id the frames chain to (None = not armed:
        #: no generation exists yet, appends are skipped by the engine)
        self.ckpt_id: Optional[str] = None
        #: last captured state — the diff base.  None forces the next
        #: frame to be a FULL snapshot (recovery fallback re-basing).
        self._shadow: Optional[Dict[str, Any]] = None
        #: bytes of verified-intact frames; a partial in-process write is
        #: truncated back to this before the next append
        self._good_size = 0
        #: durable sink emissions whose frame FAILED to write (injected
        #: raise, ENOSPC): carried into the next frame so a later crash
        #: still recovers them — an append failure degrades latency, never
        #: durability of records that entered the log
        self.pending_sink: List[Tuple] = []

    @property
    def size_bytes(self) -> int:
        return self._good_size

    def arm(self, ckpt_id: Optional[str], shadow: Optional[Dict[str, Any]],
            *, reset: bool, seq: int = 0, good_size: int = 0) -> None:
        """Chain the journal to a checkpoint generation.  ``reset=True``
        truncates the file (checkpoint rotation — the snapshot now covers
        every frame); ``reset=False`` resumes appending after the intact
        prefix (startup recovery)."""
        self.ckpt_id = ckpt_id
        # copy: checkpoint-save snapshots may hold device_get views and
        # live materialization tuples — the shadow must not move with them
        self._shadow = _host_copy(shadow) if shadow is not None else None
        if reset:
            self.seq = 0
            self._good_size = 0
            # the fresh snapshot's broker section covers these records
            self.pending_sink = []
            fd = os.open(self.path, os.O_WRONLY | os.O_CREAT, 0o644)
            try:
                os.ftruncate(fd, 0)
                if self.fsync:
                    os.fsync(fd)
            finally:
                os.close(fd)
        else:
            self.seq = seq
            self._good_size = good_size

    def append(self, snap: Dict[str, Any],
               sink_records: List[Tuple]) -> int:
        """Append one commit-point frame (delta vs the shadow + the
        tick's durable sink emissions).  Returns the journal size in
        bytes.  Raises on write failure — the caller surfaces it and the
        partial write is truncated away before the next append."""
        shadow = self._shadow
        snap = _host_copy(snap)
        delta = _diff(shadow, snap) if shadow is not None else ("full", snap)
        # sink records from a previously-failed frame ride this one
        sink_records = self.pending_sink + list(sink_records)
        self.seq += 1
        payload = pickle.dumps(
            {
                "v": 1,
                "seq": self.seq,
                "ckpt": self.ckpt_id,
                "delta": delta,
                "emit_seq": snap.get("emit_seq"),
                "sink": sink_records,
            },
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        frame = _HEADER.pack(_MAGIC, len(payload),
                             zlib.crc32(payload) & 0xFFFFFFFF) + payload
        fd = os.open(self.path, os.O_WRONLY | os.O_CREAT, 0o644)
        try:
            # a previous append may have died mid-write (injected raise,
            # ENOSPC): drop the partial tail so frames stay contiguous
            if os.fstat(fd).st_size != self._good_size:
                os.ftruncate(fd, self._good_size)
            os.lseek(fd, self._good_size, os.SEEK_SET)
            os.write(fd, frame[:_HEADER.size])
            # chaos seam BETWEEN the header and payload writes: a hang
            # here + SIGKILL leaves a genuinely torn frame on disk (the
            # mid-changelog-append kill class of chaos_soak.py --crash)
            faults.fault_point(
                "changelog.append", f"{self.query_id}#{self.seq}#"
            )
            os.write(fd, frame[_HEADER.size:])
            if self.fsync:
                os.fsync(fd)
        except BaseException:
            self.seq -= 1
            self.pending_sink = sink_records
            raise
        finally:
            os.close(fd)
        self._good_size += len(frame)
        self._shadow = snap
        self.pending_sink = []
        return self._good_size

    def rebase(self, shadow: Optional[Dict[str, Any]]) -> None:
        """Replace the diff base without touching the file (self-heal
        restore: the executor state moved under the journal)."""
        self._shadow = _host_copy(shadow) if shadow is not None else None


def read_frames(path: str) -> Tuple[List[Dict[str, Any]], int, bool]:
    """Read every intact frame.  Returns ``(frames, good_bytes, torn)``:
    ``good_bytes`` is the verified prefix length, ``torn`` is True when
    trailing bytes failed the header/CRC/unpickle check (the kill-9 torn
    tail — the caller drops it loudly and truncates)."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return [], 0, False
    frames: List[Dict[str, Any]] = []
    off = 0
    while off + _HEADER.size <= len(raw):
        magic, length, crc = _HEADER.unpack_from(raw, off)
        if magic != _MAGIC:
            break
        start = off + _HEADER.size
        end = start + length
        if end > len(raw):
            break
        payload = raw[start:end]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            break
        try:
            frames.append(pickle.loads(payload))
        except Exception:  # noqa: BLE001 — undecodable despite CRC: torn
            break
        off = end
    return frames, off, off < len(raw)


# ------------------------------------------------------------- recovery


# graftlint: entrypoint=changelog-recovery
def recover_query(engine, directory: str, query_id: str,
                  qd: Dict[str, Any], ckpt_id: Optional[str]
                  ) -> Dict[str, Any]:
    """Changelog-tail recovery for one query: read the journal, drop a
    torn tail loudly, skip frames chained to a different checkpoint
    generation, and patch the snapshot ``qd`` with each intact frame in
    order.  Never raises: a frame that fails to apply degrades to the
    checkpoint-only state with the sink fence armed at the journaled
    high-water (the effectively-once fallback).

    Returns a dict:
      ``qd``        the (possibly patched) snapshot to restore
      ``applied``   frames applied onto the snapshot
      ``total``     intact frames chained to this generation
      ``sink``      journaled sink records (durable — re-append on the
                    startup path, where the broker lost them)
      ``emit_high`` durable emit_seq high-water across the tail
      ``fence``     True when the tail did NOT fully apply (arm the sink
                    fence and journal a full re-base frame next)
      ``last_seq``  last intact frame's sequence (append continuation)
      ``good_size`` verified journal prefix in bytes
    """
    path = journal_path(directory, query_id)
    frames, good, torn = read_frames(path)
    if torn:
        try:
            engine._plog_append(
                f"changelog.corrupt-tail:{query_id}",
                f"torn tail frame dropped at byte {good} of {path}; "
                f"{len(frames)} intact frames kept",
            )
        except Exception:  # noqa: BLE001 — surfacing never blocks restore
            pass
        try:
            fd = os.open(path, os.O_WRONLY)
            try:
                os.ftruncate(fd, good)
            finally:
                os.close(fd)
        except OSError:
            pass
    live = [f for f in frames if ckpt_id is not None
            and f.get("ckpt") == ckpt_id]
    out = {
        "qd": qd, "applied": 0, "total": len(live), "sink": [],
        "emit_high": None, "fence": False,
        "last_seq": live[-1]["seq"] if live else 0, "good_size": good,
    }
    if not live:
        return out
    patched = qd
    applied = 0
    try:
        for f in live:
            faults.fault_point(
                "changelog.replay", f"{query_id}#{f['seq']}#"
            )
            patched = _patch(patched, f["delta"])
            applied += 1
    except Exception as e:  # noqa: BLE001 — a frame that cannot apply
        # degrades to the checkpoint-only state; the journaled sink
        # records below are still durable and the fence bounds dupes
        try:
            engine._on_error(f"changelog.replay:{query_id}", e)
        except Exception:  # noqa: BLE001
            pass
        patched = qd
        applied = 0
        out["fence"] = True
    out["qd"] = patched
    out["applied"] = applied
    for f in live:
        out["sink"].extend(f.get("sink") or ())
        if f.get("emit_seq") is not None:
            out["emit_high"] = int(f["emit_seq"])
    # The journal advances commit positions past the broker snapshot (the
    # snapshot is older than the tail).  The server's WAL replay
    # re-produces those source rows before restore, realigning the ends;
    # an embedding without a WAL has lost them — clamp to the live ends so
    # the consumer doesn't point past end-of-topic and silently skip
    # every future row produced at a lower offset.
    if applied:
        pos = out["qd"].get("positions")
        if isinstance(pos, dict):
            clamped = {}
            for key_, off in pos.items():
                try:
                    tn, p = key_
                    ends = engine.broker.topic(tn).end_offsets()
                    if p < len(ends) and off > ends[p]:
                        off = ends[p]
                except Exception:  # noqa: BLE001 — topic gone: keep as-is
                    pass
                clamped[key_] = off
            out["qd"] = dict(out["qd"])
            out["qd"]["positions"] = clamped
    return out


def replay_window(handle) -> int:
    """Rows between the restored consumer positions and the topic ends —
    the measured recovery replay window
    (``ksql_query_recovery_replayed_rows_total``).  With the changelog
    tail applied this is ticks-since-last-checkpoint, never the whole
    batch."""
    n = 0
    consumer = getattr(handle, "consumer", None)
    if consumer is None:
        return 0
    for (tn, p), off in consumer.positions.items():
        try:
            ends = consumer.broker.topic(tn).end_offsets()
        except Exception:  # noqa: BLE001 — topic dropped since snapshot
            continue
        if p < len(ends):
            n += max(0, ends[p] - off)
    return n


def replay_sink_records(broker, records: List[Tuple]) -> int:
    """Re-append journaled sink emissions to the (restored) broker — the
    startup-path durability of records produced after the checkpoint.
    Records re-enter per-topic in original order, so offsets and the
    key-hash partitioning reproduce exactly."""
    from ksql_tpu.runtime.topics import Record

    n = 0
    for topic, key, value, ts, window in records:
        t = broker.create_topic(topic)
        t.produce(Record(key=key, value=value, timestamp=ts,
                         partition=-1, window=window))
        n += 1
    return n
