"""Extension-dir function loading.

The UserFunctionLoader analog (ksqldb-engine/src/main/java/io/confluent/
ksql/function/UserFunctionLoader.java:45,113-131): where the reference
scans ``ksql.extension.dir`` jars with ClassGraph for @UdfDescription /
@UdafDescription / @UdtfDescription classes, this scans the directory for
``*.py`` modules, imports them, and collects every object carrying
``__ksql_specs__`` markers (the decorators in ksql_tpu/functions/ext.py).

Modules are cached by (path, mtime) so the per-engine cost is one
registry-fork + re-registration, not a re-import — an engine is created
per QTT case and per sandbox validation.
"""

from __future__ import annotations

import importlib.util
import os
import sys
import threading
from typing import Any, Dict, List, Optional, Tuple

from ksql_tpu.common.errors import KsqlException
from ksql_tpu.common.types import SqlType
from ksql_tpu.functions.ext import _parse_params, _parse_returns, _UdfSpec
from ksql_tpu.functions.registry import (
    FunctionRegistry,
    ScalarFunction,
    ScalarVariant,
    Udaf,
    Udtf,
)

_cache_lock = threading.Lock()
#: abs dir -> (snapshot of (path, mtime) pairs, collected specs)
_dir_cache: Dict[str, Tuple[Tuple[Tuple[str, float], ...], List[_UdfSpec]]] = {}


def _scan_dir(directory: str) -> List[_UdfSpec]:
    files = tuple(sorted(
        (os.path.join(directory, f), os.path.getmtime(os.path.join(directory, f)))
        for f in os.listdir(directory)
        if f.endswith(".py") and not f.startswith("_")
    ))
    with _cache_lock:
        cached = _dir_cache.get(directory)
        if cached is not None and cached[0] == files:
            return cached[1]
    specs: List[_UdfSpec] = []
    for path, _mt in files:
        mod_name = f"ksql_ext_{abs(hash(path)) & 0xFFFFFFFF:x}_" + (
            os.path.splitext(os.path.basename(path))[0]
        )
        spec = importlib.util.spec_from_file_location(mod_name, path)
        if spec is None or spec.loader is None:
            continue
        module = importlib.util.module_from_spec(spec)
        sys.modules[mod_name] = module
        try:
            spec.loader.exec_module(module)
        except Exception as e:  # noqa: BLE001 — one bad module must not
            sys.modules.pop(mod_name, None)  # take down engine start
            import warnings

            warnings.warn(
                f"skipping extension module {path}: {type(e).__name__}: {e}",
                stacklevel=2,
            )
            continue
        for obj in vars(module).values():
            for s in getattr(obj, "__ksql_specs__", ()):
                if isinstance(s, _UdfSpec):
                    specs.append(s)
    with _cache_lock:
        _dir_cache[directory] = (files, specs)
    return specs


def _adapt_udaf(spec: _UdfSpec) -> Udaf:
    """Bridge the ext class protocol (initialize/aggregate/merge/map/undo +
    constructor init args) onto the registry's Udaf callables.

    State is ``(instance, inner_state)``; the instance is constructed at
    first accumulate from the trailing literal args (UdafFactory init
    args), which arrive per row as constant expressions."""
    col_matchers, col_var, _, col_gen = _parse_params(spec.params)
    init_matchers, init_var, _, init_gen = _parse_params(spec.init_params)
    if col_var is not None and init_var is not None:
        raise KsqlException(
            f"{spec.name}: variadic column and init args cannot be combined"
        )
    n_cols = len(col_matchers)
    n_init = len(init_matchers)
    cls = spec.fn
    generics = list(col_gen) + list(init_gen)
    variadic_index_ = col_var if col_var is not None else (
        n_cols + init_var if init_var is not None else None
    )

    def arg_constraint(arg_types):
        """Same-letter generic args must bind to one SQL type."""
        letters = list(generics)
        if variadic_index_ is not None:
            k = len(arg_types) - (len(letters) - 1)
            letters = (letters[:variadic_index_]
                       + [letters[variadic_index_]] * k
                       + letters[variadic_index_ + 1:])
        bound = {}
        for letter, t in zip(letters, arg_types):
            if letter is None or t is None:
                continue
            if letter in bound and bound[letter] != t:
                return False
            bound[letter] = t
        return True

    def split(args):
        """(col_values_tuple_or_scalar, init_values) for one row's args.
        Column args come first; init literals trail (only one side may be
        variadic, so the boundary is always determined)."""
        if col_var is not None:  # variadic columns, fixed init tail
            init_vals = args[len(args) - n_init:] if n_init else ()
            cols = args[:len(args) - n_init] if n_init else args
            k = len(cols) - (n_cols - 1)
            grouped = (tuple(cols[:col_var]) + (tuple(cols[col_var:col_var + k]),)
                       + tuple(cols[col_var + k:]))
            cur = grouped if len(grouped) > 1 else grouped[0]
        else:  # fixed columns; init tail may be variadic
            cols = args[:n_cols]
            init_vals = args[n_cols:]
            cur = tuple(cols) if n_cols != 1 else cols[0]
        return cur, tuple(init_vals)

    def accumulate(state, *args):
        inst, s = state
        cur, init_vals = split(args)
        if inst is None:
            inst = cls(*init_vals)
            s = inst.initialize()
        return (inst, inst.aggregate(cur, s))

    def undo(state, *args):
        inst, s = state
        cur, init_vals = split(args)
        if inst is None:
            inst = cls(*init_vals)
            s = inst.initialize()
        return (inst, inst.undo(cur, s))

    def merge(a, b):
        # a side whose instance never materialized holds no contribution
        # (session-window merges always start from a fresh init state)
        if a[0] is None:
            return b
        if b[0] is None:
            return a
        return (a[0], a[0].merge(a[1], b[1]))

    def result(state):
        inst, s = state
        if inst is None:
            return None
        return inst.map(s)

    returns = _parse_returns(spec.returns)
    ret_rule = returns
    if callable(returns) and not isinstance(returns, SqlType):
        # the rule sees COLUMN arg types only (init literals excluded)
        if init_var is not None:  # variadic init tail: fixed col prefix
            def ret_rule(ts, _returns=returns):
                return _returns(list(ts[:n_cols]))
        elif n_init:
            def ret_rule(ts, _returns=returns):
                return _returns(list(ts[:len(ts) - n_init]))

    return Udaf(
        name=spec.name,
        params=list(col_matchers) + list(init_matchers),
        returns=ret_rule,
        init=lambda: (None, None),
        accumulate=accumulate,
        merge=merge,
        result=result,
        undo=undo if hasattr(cls, "undo") else None,
        description=spec.description,
        literal_params=n_init,
        variadic_index=variadic_index_,
        arg_constraint=arg_constraint if any(g for g in generics) else None,
        device_kind=spec.device_kind,
    )


def _adapt_scalar(spec: _UdfSpec) -> ScalarFunction:
    matchers, var_idx, _, _gen = _parse_params(spec.params)
    if var_idx is not None and var_idx != len(matchers) - 1:
        raise KsqlException(f"{spec.name}: scalar variadic must be last")
    fn = spec.fn
    if spec.stateful:
        # typed_factory: a fresh stateful closure per resolved query
        variant = ScalarVariant(
            params=matchers,
            returns=_parse_returns(spec.returns),
            fn=lambda arg_types, _f=fn: _f(),
            variadic=var_idx is not None,
            null_tolerant=spec.null_tolerant,
            typed_factory=True,
        )
    else:
        variant = ScalarVariant(
            params=matchers,
            returns=_parse_returns(spec.returns),
            fn=fn,
            variadic=var_idx is not None,
            null_tolerant=spec.null_tolerant,
        )
    return ScalarFunction(spec.name, [variant], spec.description)


def _adapt_udtf(spec: _UdfSpec) -> Udtf:
    matchers, var_idx, _, _gen = _parse_params(spec.params)
    if var_idx is not None:
        raise KsqlException(f"{spec.name}: variadic UDTF params unsupported")
    return Udtf(
        name=spec.name,
        params=matchers,
        returns=_parse_returns(spec.returns),
        fn=spec.fn,
        description=spec.description,
    )


def load_extensions(directory: str, registry: FunctionRegistry) -> List[str]:
    """Scan ``directory`` and register everything found into ``registry``.
    Returns the loaded function names.  Missing directory = no-op (the
    reference only scans when the configured dir exists)."""
    if not directory or not os.path.isdir(directory):
        return []
    names: List[str] = []
    for spec in _scan_dir(os.path.abspath(directory)):
        if spec.kind == "udf":
            registry.register_scalar(_adapt_scalar(spec))
        elif spec.kind == "udaf":
            registry.register_udaf(_adapt_udaf(spec))
        elif spec.kind == "udtf":
            registry.register_udtf(_adapt_udtf(spec))
        names.append(spec.name)
    return names
