"""Built-in scalar functions.

Covers the reference's UDF categories (ksqldb-engine/.../function/udf/: 132
classes in 14 categories — string, math, datetime, json, url, geo, nulls,
lambda, array, map, conversions, bytes, list, AsValue).  Host implementations
define parity semantics; numeric ones carry `jax_fn` so the columnar compiler
keeps them fused on device.
"""

from __future__ import annotations

import base64
import datetime as _dt
import json as _json
import math
import re
import struct
import urllib.parse
import uuid as _uuid
from typing import Any, List, Optional
from zoneinfo import ZoneInfo

from ksql_tpu.common import types as T
from ksql_tpu.common.errors import FunctionException
from ksql_tpu.common.types import SqlBaseType, SqlType
from ksql_tpu.functions.registry import (
    FunctionRegistry,
    ScalarFunction,
    ScalarVariant,
    t_any,
    t_array,
    t_base,
    t_lambda,
    t_map,
    t_numeric,
)

STR = t_base(SqlBaseType.STRING)
BYT = t_base(SqlBaseType.BYTES)
NUM = t_numeric()
INT = t_base(SqlBaseType.INTEGER)
BIG = t_base(SqlBaseType.BIGINT, SqlBaseType.INTEGER)
# DOUBLE parameter positions accept anything numerically widenable (implicit
# cast, UdfIndex behavior in the reference)
DBL = t_numeric()
BOOL = t_base(SqlBaseType.BOOLEAN)
TS = t_base(SqlBaseType.TIMESTAMP)
DATE_T = t_base(SqlBaseType.DATE)
TIME_T = t_base(SqlBaseType.TIME)

# Functions whose given argument position is a bare interval-unit identifier
# (parsed as a ColumnRef); the analyzer rewrites it to a StringLiteral.
UNIT_ARG_FUNCTIONS = {
    "TIMESTAMPADD": 0,
    "TIMESTAMPSUB": 0,
    "DATEADD": 0,
    "DATESUB": 0,
    "TIMEADD": 0,
    "TIMESUB": 0,
}

_UNIT_MS = {
    "MILLISECONDS": 1,
    "MILLISECOND": 1,
    "SECONDS": 1000,
    "SECOND": 1000,
    "MINUTES": 60_000,
    "MINUTE": 60_000,
    "HOURS": 3_600_000,
    "HOUR": 3_600_000,
    "DAYS": 86_400_000,
    "DAY": 86_400_000,
}


def _same_type(arg_types: List[SqlType]) -> SqlType:
    return arg_types[0]


def _widest(arg_types: List[SqlType]) -> SqlType:
    t = arg_types[0]
    for other in arg_types[1:]:
        t = T.common_numeric_type(t, other)
    return t


# ------------------------------------------------------- datetime helpers

_JAVA_TOKENS = [
    ("yyyy", "%Y"),
    ("yy", "%y"),
    ("MMM", "%b"),
    ("MM", "%m"),
    ("dd", "%d"),
    ("HH", "%H"),
    ("hh", "%I"),
    ("mm", "%M"),
    ("ss", "%S"),
    ("SSS", "%f"),
    ("SS", "%f"),
    ("S", "%f"),  # strptime %f accepts 1-6 fraction digits
    ("EEE", "%a"),
    ("a", "%p"),
    ("XXX", "%z"),
    ("XX", "%z"),
    ("X", "%z"),
    ("zzz", "%Z"),
    ("z", "%Z"),
]


def java_format_to_strftime(fmt: str) -> str:
    out = []
    i = 0
    while i < len(fmt):
        if fmt[i] == "'":
            # quoted literal
            j = fmt.find("'", i + 1)
            if j < 0:
                out.append(fmt[i + 1 :])
                break
            out.append(fmt[i + 1 : j].replace("%", "%%"))
            i = j + 1
            continue
        for tok, rep in _JAVA_TOKENS:
            if fmt.startswith(tok, i):
                out.append(rep)
                i += len(tok)
                break
        else:
            out.append(fmt[i].replace("%", "%%") if fmt[i] == "%" else fmt[i])
            i += 1
    return "".join(out)


#: java.util.TimeZone three-letter ids that still resolve to region zones
_TZ_ABBREV = {
    "PST": "America/Los_Angeles",
    "PDT": "America/Los_Angeles",
    "MST": "America/Denver",
    "CST": "America/Chicago",
    "CDT": "America/Chicago",
    "EST": "America/New_York",
    "EDT": "America/New_York",
    "GMT": "UTC",
    "UTC": "UTC",
    "CET": "Europe/Paris",
    "IST": "Asia/Kolkata",
    "JST": "Asia/Tokyo",
}


def _tz(tz: Optional[str]) -> _dt.tzinfo:
    if not tz:
        return _dt.timezone.utc
    m = re.fullmatch(r"(?:UTC|GMT)?([+-])(\d{1,2}):?(\d{2})?", tz)
    if m:  # offset forms: +0200, -05:30, UTC+2
        sign = 1 if m.group(1) == "+" else -1
        mins = int(m.group(2)) * 60 + int(m.group(3) or 0)
        return _dt.timezone(sign * _dt.timedelta(minutes=mins))
    try:
        return ZoneInfo(tz)
    except Exception as e:
        raise FunctionException(f"unknown time zone {tz!r}") from e


def _ts_to_string(ts_ms: int, fmt: str, tz: Optional[str] = None) -> str:
    dt = _dt.datetime.fromtimestamp(ts_ms / 1000.0, _tz(tz))
    py = java_format_to_strftime(fmt)
    s = dt.strftime(py)
    # strftime %f is microseconds; java SSS is milliseconds
    if "%f" in py:
        us = dt.strftime("%f")
        s = s.replace(us, us[:3])
    return s


def _string_to_ts(s: str, fmt: str, tz: Optional[str] = None) -> int:
    py = java_format_to_strftime(fmt)
    if py.endswith("%z") and s.endswith("z"):
        s = s[:-1] + "Z"  # Java's X accepts a lowercase zulu marker
    try:
        dt = _dt.datetime.strptime(s, py)
    except ValueError:
        if "%Z" in py:
            # named-zone abbreviations (PST, EST, ...): resolve through the
            # region zone so DST applies, as java.text zone parsing does
            m = re.search(r"\b([A-Z]{2,5})\s*$", s)
            zone = _TZ_ABBREV.get(m.group(1)) if m else None
            if zone is not None:
                naive = _dt.datetime.strptime(
                    s[: m.start()].rstrip(), py.replace("%Z", "").rstrip()
                )
                dt = naive.replace(tzinfo=ZoneInfo(zone))
                return int(dt.timestamp() * 1000)
        if "%f" in py:
            # retry padding 3-digit millis to 6-digit micros
            def pad(mo):
                return mo.group(0) + "000"

            s2 = re.sub(r"(?<=[.:])(\d{3})(?!\d)", pad, s)
            dt = _dt.datetime.strptime(s2, py)
        else:
            raise
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_tz(tz))
    return int(dt.timestamp() * 1000)


# ----------------------------------------------------------- json helpers


def _json_path_get(doc: Any, path: str) -> Any:
    """Minimal JSONPath: $.a.b[2].c  (EXTRACTJSONFIELD semantics)."""
    if not path.startswith("$"):
        raise FunctionException(f"invalid JSON path {path!r}")
    i = 1
    cur = doc
    while i < len(path) and cur is not None:
        if path[i] == ".":
            j = i + 1
            while j < len(path) and path[j] not in ".[":
                j += 1
            key = path[i + 1 : j]
            cur = cur.get(key) if isinstance(cur, dict) else None
            i = j
        elif path[i] == "[":
            j = path.find("]", i)
            idx = path[i + 1 : j].strip("'\"")
            if isinstance(cur, list):
                k = int(idx)
                cur = cur[k] if 0 <= k < len(cur) else None
            elif isinstance(cur, dict):
                cur = cur.get(idx)
            else:
                cur = None
            i = j + 1
        else:
            raise FunctionException(f"invalid JSON path {path!r}")
    return cur


def _mask_char(c: str, upper: str, lower: str, digit: str, other: str) -> str:
    if c.isupper():
        return upper if upper != "\x00" else c
    if c.islower():
        return lower if lower != "\x00" else c
    if c.isdigit():
        return digit if digit != "\x00" else c
    return other if other != "\x00" else c


def _mask(s: str, upper="X", lower="x", digit="n", other="-") -> str:
    return "".join(_mask_char(c, upper, lower, digit, other) for c in s)


# ------------------------------------------------------------ registration


def register_all(reg: FunctionRegistry) -> None:  # noqa: C901
    def scalar(name, params, returns, fn, variadic=False, null_tolerant=False,
               jax_fn=None, desc="", typed_factory=False):
        reg.register_scalar(
            ScalarFunction(
                name=name,
                variants=[
                    ScalarVariant(
                        params=params, returns=returns, fn=fn,
                        variadic=variadic, null_tolerant=null_tolerant,
                        typed_factory=typed_factory,
                    )
                ],
                description=desc,
                jax_fn=jax_fn,
            )
        )

    import jax.numpy as jnp

    # ------------------------------------------------------------- string
    scalar("UCASE", [STR], T.STRING, lambda s: s.upper(), desc="Upper-case")
    scalar("LCASE", [STR], T.STRING, lambda s: s.lower(), desc="Lower-case")
    scalar("TRIM", [STR], T.STRING, lambda s: s.strip())
    scalar("LTRIM", [STR], T.STRING, lambda s: s.lstrip())
    scalar("RTRIM", [STR], T.STRING, lambda s: s.rstrip())
    scalar("INITCAP", [STR], T.STRING, lambda s: " ".join(w.capitalize() for w in s.split(" ")))
    scalar("LEN", [STR], T.INTEGER, lambda s: len(s))
    reg.scalar("LEN").variants.append(ScalarVariant(params=[BYT], returns=T.INTEGER, fn=lambda b: len(b)))
    scalar(
        "SUBSTRING",
        [STR, INT],
        T.STRING,
        lambda s, start: _substring(s, start, None),
    )
    reg.scalar("SUBSTRING").variants.append(
        ScalarVariant(params=[STR, INT, INT], returns=T.STRING,
                      fn=lambda s, start, length: _substring(s, start, length))
    )
    reg.scalar("SUBSTRING").variants.append(
        ScalarVariant(params=[BYT, INT], returns=T.BYTES,
                      fn=lambda s, start: _substring(s, start, None))
    )
    reg.scalar("SUBSTRING").variants.append(
        ScalarVariant(params=[BYT, INT, INT], returns=T.BYTES,
                      fn=lambda s, start, length: _substring(s, start, length))
    )
    scalar("REPLACE", [STR, STR, STR], T.STRING, lambda s, old, new: s.replace(old, new))
    def _t_concat(ts):
        real = [t for t in ts if t is not None]
        if real and all(t.base == SqlBaseType.BYTES for t in real):
            return T.BYTES
        return T.STRING

    def _concat(*xs):
        vals = [x for x in xs if x is not None]
        if vals and all(isinstance(v, (bytes, bytearray)) for v in vals):
            return b"".join(vals)
        return "".join(_to_str(x) for x in vals)

    def _concat_ws(sep, *xs):
        if sep is None:
            return None
        vals = [x for x in xs if x is not None]
        if isinstance(sep, (bytes, bytearray)):
            return sep.join(bytes(v) for v in vals)
        return sep.join(_to_str(x) for x in vals)

    scalar("CONCAT", [t_any(), t_any()], _t_concat, _concat,
           variadic=True, null_tolerant=True)
    scalar("CONCAT_WS", [t_any(), t_any(), t_any()], lambda ts: _t_concat(ts[1:]),
           _concat_ws, variadic=True, null_tolerant=True)
    scalar("SPLIT", [STR, STR], SqlType.array(T.STRING),
           # Java split of "" is [""]; empty delimiter splits to characters
           lambda s, d: ([""] if s == "" else list(s)) if d == "" else s.split(d))
    reg.scalar("SPLIT").variants.append(
        ScalarVariant(params=[BYT, BYT], returns=SqlType.array(T.BYTES),
                      fn=lambda s, d: _split_bytes(s, d))
    )
    scalar("SPLIT_TO_MAP", [STR, STR, STR], SqlType.map(T.STRING, T.STRING),
           _split_to_map)
    scalar("LPAD", [STR, INT, STR], T.STRING, lambda s, n, p: _pad(s, n, p, left=True))
    scalar("RPAD", [STR, INT, STR], T.STRING, lambda s, n, p: _pad(s, n, p, left=False))
    reg.scalar("LPAD").variants.append(
        ScalarVariant(params=[BYT, INT, BYT], returns=T.BYTES,
                      fn=lambda s, n, p: _pad_bytes(s, n, p, left=True)))
    reg.scalar("RPAD").variants.append(
        ScalarVariant(params=[BYT, INT, BYT], returns=T.BYTES,
                      fn=lambda s, n, p: _pad_bytes(s, n, p, left=False)))
    scalar("INSTR", [STR, STR], T.INTEGER, lambda s, sub: s.find(sub) + 1)
    reg.scalar("INSTR").variants.append(
        ScalarVariant(params=[STR, STR, INT], returns=T.INTEGER,
                      fn=lambda s, sub, pos: _instr(s, sub, pos, 1)))
    reg.scalar("INSTR").variants.append(
        ScalarVariant(params=[STR, STR, INT, INT], returns=T.INTEGER,
                      fn=lambda s, sub, pos, occ: _instr(s, sub, pos, occ)))
    scalar("REGEXP_EXTRACT", [STR, STR], T.STRING, lambda p, s: _re_extract(p, s, 0))
    reg.scalar("REGEXP_EXTRACT").variants.append(
        ScalarVariant(params=[STR, STR, INT], returns=T.STRING,
                      fn=lambda p, s, g: _re_extract(p, s, g)))
    scalar("REGEXP_EXTRACT_ALL", [STR, STR], SqlType.array(T.STRING),
           lambda p, s: [m.group(0) for m in re.finditer(p, s)])
    reg.scalar("REGEXP_EXTRACT_ALL").variants.append(
        ScalarVariant(params=[STR, STR, INT], returns=SqlType.array(T.STRING),
                      fn=lambda p, s, g: [m.group(g) for m in re.finditer(p, s)]))
    scalar("REGEXP_REPLACE", [STR, STR, STR], T.STRING,
           lambda s, p, r: re.sub(p, r, s))
    scalar("REGEXP_SPLIT_TO_ARRAY", [STR, STR], SqlType.array(T.STRING),
           _java_regex_split)
    scalar("MASK", [STR], T.STRING, lambda s: _mask(s))
    scalar("MASK_LEFT", [STR, INT], T.STRING, lambda s, n: _mask(s[:n]) + s[n:])
    scalar("MASK_RIGHT", [STR, INT], T.STRING,
           lambda s, n: s[: len(s) - n] + _mask(s[len(s) - n :]) if n > 0 else s)
    scalar("MASK_KEEP_LEFT", [STR, INT], T.STRING, lambda s, n: s[:n] + _mask(s[n:]))
    scalar("MASK_KEEP_RIGHT", [STR, INT], T.STRING,
           lambda s, n: _mask(s[: len(s) - n]) + s[len(s) - n :] if n > 0 else _mask(s))
    scalar("UUID", [], T.STRING, lambda: str(_uuid.uuid4()))
    reg.scalar("UUID").variants.append(
        ScalarVariant(params=[BYT], returns=T.STRING,
                      fn=lambda b: str(_uuid.UUID(bytes=b))))
    scalar("CHR", [INT], T.STRING, lambda n: chr(n))
    reg.scalar("CHR").variants.append(
        ScalarVariant(params=[STR], returns=T.STRING, fn=_chr_str))
    scalar("ENCODE", [STR, STR, STR], T.STRING, _encode)
    scalar("TO_BYTES", [STR, STR], T.BYTES, _to_bytes)
    scalar("FROM_BYTES", [BYT, STR], T.STRING, _from_bytes)
    scalar("POSITION", [STR, STR], T.INTEGER, lambda sub, s: s.find(sub) + 1)
    scalar("INT_FROM_BYTES", [BYT], T.INTEGER, lambda b: _int_from_bytes(b, 4, "BIG"))
    reg.scalar("INT_FROM_BYTES").variants.append(
        ScalarVariant(params=[BYT, STR], returns=T.INTEGER,
                      fn=lambda b, o: _int_from_bytes(b, 4, o)))
    scalar("BIGINT_FROM_BYTES", [BYT], T.BIGINT, lambda b: _int_from_bytes(b, 8, "BIG"))
    reg.scalar("BIGINT_FROM_BYTES").variants.append(
        ScalarVariant(params=[BYT, STR], returns=T.BIGINT,
                      fn=lambda b, o: _int_from_bytes(b, 8, o)))
    scalar("DOUBLE_FROM_BYTES", [BYT], T.DOUBLE, lambda b: _double_from_bytes(b, "BIG"))
    reg.scalar("DOUBLE_FROM_BYTES").variants.append(
        ScalarVariant(params=[BYT, STR], returns=T.DOUBLE, fn=_double_from_bytes))

    # --------------------------------------------------------------- math
    scalar("ABS", [NUM], _same_type, lambda x: abs(x), jax_fn=jnp.abs)
    scalar("CEIL", [NUM], _same_type,
           lambda x: math.ceil(x) if not isinstance(x, float) else float(math.ceil(x)),
           jax_fn=jnp.ceil)
    scalar("FLOOR", [NUM], _same_type,
           lambda x: math.floor(x) if not isinstance(x, float) else float(math.floor(x)),
           jax_fn=jnp.floor)
    def _t_round0(ts):
        t = ts[0]
        if t.base == SqlBaseType.DOUBLE:
            return T.BIGINT
        if t.base == SqlBaseType.DECIMAL:
            # BigDecimal.setScale(0): integer part may grow one digit
            return SqlType.decimal(max(t.precision - t.scale + 1, 1), 0)
        return t

    scalar("ROUND", [NUM], _t_round0, _round0, jax_fn=None)
    reg.scalar("ROUND").variants.append(
        ScalarVariant(params=[NUM, INT], returns=_same_type, fn=_round_n))
    def _jm(f):
        # Java Math.* returns NaN on domain errors instead of raising
        def g(*a):
            try:
                return f(*a)
            except (ValueError, OverflowError):
                return float("nan")
        return g

    scalar("SQRT", [NUM], T.DOUBLE, _jm(math.sqrt), jax_fn=jnp.sqrt)
    scalar("EXP", [NUM], T.DOUBLE, lambda x: math.exp(x), jax_fn=jnp.exp)
    scalar("LN", [NUM], T.DOUBLE, lambda x: math.log(x) if x > 0 else (float("-inf") if x == 0 else float("nan")), jax_fn=jnp.log)
    scalar("LOG", [NUM], T.DOUBLE, lambda x: math.log(x) if x > 0 else (float("-inf") if x == 0 else float("nan")))
    reg.scalar("LOG").variants.append(
        ScalarVariant(params=[NUM, NUM], returns=T.DOUBLE, fn=_log_base))
    scalar("SIGN", [NUM], T.INTEGER, lambda x: (x > 0) - (x < 0), jax_fn=jnp.sign)
    scalar("POWER", [NUM, NUM], T.DOUBLE, lambda x, y: float(x) ** y, jax_fn=jnp.power)
    scalar("RANDOM", [], T.DOUBLE, lambda: __import__("random").random())
    scalar("PI", [], T.DOUBLE, lambda: math.pi)
    for nm, f, jf in [
        ("SIN", math.sin, jnp.sin), ("COS", math.cos, jnp.cos), ("TAN", math.tan, jnp.tan),
        ("ASIN", _jm(math.asin), jnp.arcsin), ("ACOS", _jm(math.acos), jnp.arccos),
        ("ATAN", math.atan, jnp.arctan), ("SINH", math.sinh, jnp.sinh),
        ("COSH", math.cosh, jnp.cosh), ("TANH", math.tanh, jnp.tanh),
        ("CBRT", lambda x: math.copysign(abs(x) ** (1 / 3), x), jnp.cbrt),
        ("DEGREES", math.degrees, jnp.degrees), ("RADIANS", math.radians, jnp.radians),
    ]:
        scalar(nm, [NUM], T.DOUBLE, f, jax_fn=jf)
    scalar("ATAN2", [NUM, NUM], T.DOUBLE, math.atan2, jax_fn=jnp.arctan2)
    scalar("COT", [NUM], T.DOUBLE, lambda x: 1.0 / math.tan(x) if math.tan(x) != 0 else float("inf"))
    scalar("TRUNC", [NUM],
           lambda ts: (
               T.BIGINT
               if ts[0] is not None
               and ts[0].base in (SqlBaseType.DOUBLE, SqlBaseType.DECIMAL)
               else (ts[0] or T.BIGINT)
           ),
           lambda x: math.trunc(x) if not isinstance(x, int) else x)
    reg.scalar("TRUNC").variants.append(
        ScalarVariant(params=[NUM, INT], returns=_same_type, fn=_trunc_n))
    # GREATEST/LEAST: generic same-type comparables (reference GreatestKudf):
    # exact same-type args resolve directly; mixed numerics resolve only when
    # DOUBLE disambiguates the implicit cast, else "ambiguous method
    # parameters"; string literals coerce to a temporal operand type; nulls
    # are ignored at runtime.
    def _minmax_resolve(fname, arg_types):
        ts = [t for t in arg_types if t is not None]
        if not ts:
            raise FunctionException(
                f"Function '{fname}' cannot be resolved: all arguments are "
                "untyped nulls."
            )
        if all(t.base == SqlBaseType.DECIMAL for t in ts):
            out = ts[0]
            for t in ts[1:]:
                out = T.common_numeric_type(out, t)
            return out
        uniq: list = []
        for t in ts:
            if t not in uniq:
                uniq.append(t)
        if len(uniq) == 1:
            return uniq[0]
        non_str = [t for t in uniq if t.base != SqlBaseType.STRING]
        temporal = (SqlBaseType.DATE, SqlBaseType.TIME, SqlBaseType.TIMESTAMP)
        if len(non_str) == 1 and non_str[0].base in temporal:
            return non_str[0]  # string literals coerce to the temporal type
        if all(t.is_numeric() for t in uniq):
            if any(t.base == SqlBaseType.DOUBLE for t in uniq):
                return T.DOUBLE
        raise FunctionException(
            f"Function '{fname}' cannot be resolved due to ambiguous method "
            f"parameters ({', '.join(str(t) for t in ts)})."
        )

    def _minmax_factory(fname, pick):
        def factory(arg_types):
            tgt = _minmax_resolve(fname, arg_types)
            b = tgt.base

            def conv(v):
                if (
                    b == SqlBaseType.DOUBLE
                    and isinstance(v, (int, float))
                    and not isinstance(v, bool)
                ):
                    return float(v)
                if isinstance(v, str) and b != SqlBaseType.STRING:
                    if b == SqlBaseType.DATE:
                        import datetime as dt

                        return (dt.date.fromisoformat(v) - dt.date(1970, 1, 1)).days
                    if b == SqlBaseType.TIMESTAMP:
                        from ksql_tpu.execution.interpreter import _parse_timestamp_text

                        return _parse_timestamp_text(v)
                    if b == SqlBaseType.TIME:
                        from ksql_tpu.execution.interpreter import _parse_time_text

                        return _parse_time_text(v)
                return v

            def fn(*xs):
                vals = [conv(x) for x in xs if x is not None]
                if not vals:
                    return None
                return pick(vals)

            return fn

        return factory

    scalar("GREATEST", [t_any(), t_any()],
           lambda ts: _minmax_resolve("greatest", ts),
           _minmax_factory("greatest", max), variadic=True,
           null_tolerant=True, typed_factory=True)
    scalar("LEAST", [t_any(), t_any()],
           lambda ts: _minmax_resolve("least", ts),
           _minmax_factory("least", min), variadic=True,
           null_tolerant=True, typed_factory=True)

    # -------------------------------------------------------------- nulls
    scalar("COALESCE", [t_any(), t_any()], _same_type,
           lambda *xs: next((x for x in xs if x is not None), None),
           variadic=True, null_tolerant=True)
    scalar("IFNULL", [t_any(), t_any()], _same_type,
           lambda x, d: d if x is None else x, null_tolerant=True)
    scalar("NULLIF", [t_any(), t_any()], _same_type,
           lambda x, y: None if x == y else x, null_tolerant=True)

    # ----------------------------------------------------------- datetime
    scalar("UNIX_TIMESTAMP", [], T.BIGINT, lambda: int(_dt.datetime.now().timestamp() * 1000))
    reg.scalar("UNIX_TIMESTAMP").variants.append(
        ScalarVariant(params=[TS], returns=T.BIGINT, fn=lambda ts: ts))
    scalar("UNIX_DATE", [], T.INTEGER, lambda: (_dt.date.today() - _dt.date(1970, 1, 1)).days)
    reg.scalar("UNIX_DATE").variants.append(
        ScalarVariant(params=[DATE_T], returns=T.INTEGER, fn=lambda d: d))
    scalar("FROM_UNIXTIME", [BIG], T.TIMESTAMP, lambda ms: ms)
    # FromDays.java:31 — epoch days -> DATE (host rep of DATE is epoch days)
    scalar("FROM_DAYS", [INT], T.DATE, lambda days: days)
    scalar("TIMESTAMPTOSTRING", [BIG, STR], T.STRING, lambda ts, f: _ts_to_string(ts, f))
    reg.scalar("TIMESTAMPTOSTRING").variants.append(
        ScalarVariant(params=[BIG, STR, STR], returns=T.STRING,
                      fn=lambda ts, f, tz: _ts_to_string(ts, f, tz)))
    scalar("STRINGTOTIMESTAMP", [STR, STR], T.BIGINT, lambda s, f: _string_to_ts(s, f))
    reg.scalar("STRINGTOTIMESTAMP").variants.append(
        ScalarVariant(params=[STR, STR, STR], returns=T.BIGINT,
                      fn=lambda s, f, tz: _string_to_ts(s, f, tz)))
    scalar("FORMAT_TIMESTAMP", [TS, STR], T.STRING, lambda ts, f: _ts_to_string(ts, f))
    reg.scalar("FORMAT_TIMESTAMP").variants.append(
        ScalarVariant(params=[TS, STR, STR], returns=T.STRING,
                      fn=lambda ts, f, tz: _ts_to_string(ts, f, tz)))
    scalar("PARSE_TIMESTAMP", [STR, STR], T.TIMESTAMP, lambda s, f: _string_to_ts(s, f))
    reg.scalar("PARSE_TIMESTAMP").variants.append(
        ScalarVariant(params=[STR, STR, STR], returns=T.TIMESTAMP,
                      fn=lambda s, f, tz: _string_to_ts(s, f, tz)))
    scalar("FORMAT_DATE", [DATE_T, STR], T.STRING,
           lambda d, f: (_dt.date(1970, 1, 1) + _dt.timedelta(days=d)).strftime(java_format_to_strftime(f)))
    def _parse_date_or_null(s, f):
        try:
            return (
                _strptime_prefix(s, java_format_to_strftime(f)).date()
                - _dt.date(1970, 1, 1)
            ).days
        except ValueError:
            return None  # reference PARSE_DATE yields null, not an error

    scalar("PARSE_DATE", [STR, STR], T.DATE, _parse_date_or_null)
    def _format_time(t, f):
        d = _dt.datetime(1970, 1, 1) + _dt.timedelta(milliseconds=t)
        py = java_format_to_strftime(f)
        out = d.strftime(py)
        if "%f" in py:
            us = d.strftime("%f")
            out = out.replace(us, us[:3])
        return out

    scalar("FORMAT_TIME", [TIME_T, STR], T.STRING, _format_time)
    scalar("PARSE_TIME", [STR, STR], T.TIME, _parse_time)
    scalar("TIMESTAMPADD", [STR, BIG, TS], T.TIMESTAMP,
           lambda unit, n, ts: ts + n * _unit_ms(unit))
    scalar("TIMESTAMPSUB", [STR, BIG, TS], T.TIMESTAMP,
           lambda unit, n, ts: ts - n * _unit_ms(unit))
    scalar("DATEADD", [STR, BIG, DATE_T], T.DATE,
           lambda unit, n, d: d + n * _unit_ms(unit) // 86_400_000)
    scalar("DATESUB", [STR, BIG, DATE_T], T.DATE,
           lambda unit, n, d: d - n * _unit_ms(unit) // 86_400_000)
    # legacy string<->date/time conversions (StringToDate.java etc.)
    scalar("STRINGTODATE", [STR, STR], T.INTEGER,
           lambda s, f: (_dt.datetime.strptime(s, java_format_to_strftime(f)).date()
                         - _dt.date(1970, 1, 1)).days)
    scalar("DATETOSTRING", [INT, STR], T.STRING,
           lambda d, f: (_dt.date(1970, 1, 1) + _dt.timedelta(days=d)).strftime(java_format_to_strftime(f)))
    scalar("TIMEADD", [STR, BIG, TIME_T], T.TIME,
           lambda unit, n, t: (t + n * _unit_ms(unit)) % 86_400_000)
    scalar("TIMESUB", [STR, BIG, TIME_T], T.TIME,
           lambda unit, n, t: (t - n * _unit_ms(unit)) % 86_400_000)
    scalar("CONVERT_TZ", [TS, STR, STR], T.TIMESTAMP, _convert_tz)

    # --------------------------------------------------------------- json
    scalar("EXTRACTJSONFIELD", [STR, STR], T.STRING, _extract_json_field)
    # JsonArrayContains.java:44 — token-type-gated containment over a JSON
    # array rendered as text; malformed JSON -> false
    scalar("JSON_ARRAY_CONTAINS", [STR, t_any()], T.BOOLEAN,
           _json_array_contains, null_tolerant=True)
    # JsonItems.java:36 — split a JSON array string into compact per-item
    # JSON strings (JsonNode.toString)
    scalar("JSON_ITEMS", [STR], SqlType.array(T.STRING), _json_items)
    scalar("IS_JSON_STRING", [STR], T.BOOLEAN, _is_json, null_tolerant=True)
    scalar("JSON_ARRAY_LENGTH", [STR], T.INTEGER,
           lambda s: len(_json.loads(s)) if isinstance(_json.loads(s), list) else None)
    scalar("JSON_KEYS", [STR], SqlType.array(T.STRING),
           lambda s: list(_json.loads(s).keys()) if isinstance(_json.loads(s), dict) else None)
    scalar("JSON_RECORDS", [STR], SqlType.map(T.STRING, T.STRING),
           # textual nodes render unquoted (JsonNode.asText); others as JSON
           lambda s: {
               k: v if isinstance(v, str) else _json.dumps(v, separators=(",", ":"))
               for k, v in _json.loads(s).items()
           }
           if isinstance(_json.loads(s), dict) else None)
    def _to_json_factory(arg_types):
        t0 = arg_types[0] if arg_types else None

        def render(v, t):
            import decimal as _decml

            if v is None:
                return None
            b = t.base if t is not None else None
            if b == SqlBaseType.DATE and isinstance(v, int):
                return str((_dt.date(1970, 1, 1) + _dt.timedelta(days=v)))
            if b == SqlBaseType.TIME and isinstance(v, int):
                sec, ms = divmod(v, 1000)
                h, rem = divmod(sec, 3600)
                m, s_ = divmod(rem, 60)
                return f"{h:02d}:{m:02d}:{s_:02d}" + (f".{ms:03d}" if ms else "")
            if b == SqlBaseType.TIMESTAMP and isinstance(v, int):
                d = _dt.datetime.fromtimestamp(v / 1000.0, _dt.timezone.utc)
                return d.strftime("%Y-%m-%dT%H:%M:%S.") + f"{v % 1000:03d}"
            if isinstance(v, bytes):
                return base64.b64encode(v).decode("ascii")
            if isinstance(v, list):
                et = t.element if t is not None else None
                return [render(x, et) for x in v]
            if isinstance(v, dict):
                if t is not None and t.base == SqlBaseType.STRUCT:
                    fts = dict(t.fields or ())
                    return {k: render(x, fts.get(k)) for k, x in v.items()}
                et = t.element if t is not None else None
                return {k: render(x, et) for k, x in v.items()}
            return v

        def write(v):
            import decimal as _decml

            if v is None:
                return "null"
            if isinstance(v, bool):
                return "true" if v else "false"
            if isinstance(v, _decml.Decimal):
                return format(v, "f")  # exact bare number text
            if isinstance(v, (int, float)):
                return _json.dumps(v)
            if isinstance(v, str):
                return _json.dumps(v)
            if isinstance(v, list):
                return "[" + ",".join(write(x) for x in v) + "]"
            if isinstance(v, dict):
                return (
                    "{"
                    + ",".join(
                        f"{_json.dumps(str(k))}:{write(x)}" for k, x in v.items()
                    )
                    + "}"
                )
            return _json.dumps(str(v))

        def fn(x):
            if x is None:
                return "null"  # JSON text of null, not a SQL null
            return write(render(x, t0))

        return fn

    scalar("TO_JSON_STRING", [t_any()], T.STRING, _to_json_factory,
           null_tolerant=True, typed_factory=True)
    scalar("JSON_CONCAT", [STR, STR], T.STRING, _json_concat, variadic=True)

    # ---------------------------------------------------------------- url
    scalar("URL_EXTRACT_HOST", [STR], T.STRING, lambda u: urllib.parse.urlparse(u).hostname)
    scalar("URL_EXTRACT_PATH", [STR], T.STRING, lambda u: urllib.parse.urlparse(u).path)
    scalar("URL_EXTRACT_PORT", [STR], T.INTEGER, lambda u: urllib.parse.urlparse(u).port)
    scalar("URL_EXTRACT_PROTOCOL", [STR], T.STRING, lambda u: urllib.parse.urlparse(u).scheme or None)
    scalar("URL_EXTRACT_QUERY", [STR], T.STRING, lambda u: urllib.parse.urlparse(u).query or None)
    scalar("URL_EXTRACT_FRAGMENT", [STR], T.STRING, lambda u: urllib.parse.urlparse(u).fragment or None)
    scalar("URL_EXTRACT_PARAMETER", [STR, STR], T.STRING,
           lambda u, p: (urllib.parse.parse_qs(urllib.parse.urlparse(u).query).get(p) or [None])[0])
    scalar("URL_ENCODE_PARAM", [STR], T.STRING, lambda s: urllib.parse.quote_plus(s))
    scalar("URL_DECODE_PARAM", [STR], T.STRING, lambda s: urllib.parse.unquote_plus(s))

    # ---------------------------------------------------------------- geo
    scalar("GEO_DISTANCE", [DBL, DBL, DBL, DBL], T.DOUBLE,
           lambda la1, lo1, la2, lo2: _geo_distance(la1, lo1, la2, lo2, "KM"))
    reg.scalar("GEO_DISTANCE").variants.append(
        ScalarVariant(params=[DBL, DBL, DBL, DBL, STR], returns=T.DOUBLE,
                      fn=lambda la1, lo1, la2, lo2, u: (
                          None if None in (la1, lo1, la2, lo2)
                          else _geo_distance(la1, lo1, la2, lo2, u or "KM")
                      ),
                      null_tolerant=True))

    # -------------------------------------------------------------- array
    def _el(ts):
        return ts[0].element

    scalar("ARRAY_LENGTH", [t_array()], T.INTEGER, lambda a: len(a))
    scalar("ARRAY_CONTAINS", [t_array(), t_any()], T.BOOLEAN, lambda a, x: x in a)
    reg.register_scalar(ScalarFunction("CONTAINS", [
        ScalarVariant(params=[t_array(), t_any()], returns=T.BOOLEAN, fn=lambda a, x: x in a),
        ScalarVariant(params=[STR, STR], returns=T.BOOLEAN, fn=lambda s, sub: sub in s),
    ]))
    scalar("ARRAY_DISTINCT", [t_array()], _same_type, _array_distinct)
    scalar("ARRAY_EXCEPT", [t_array(), t_array()], _same_type,
           lambda a, b: [x for x in _array_distinct(a) if x not in b])
    scalar("ARRAY_INTERSECT", [t_array(), t_array()], _same_type,
           lambda a, b: [x for x in _array_distinct(a) if x in b])
    scalar("ARRAY_UNION", [t_array(), t_array()], _same_type,
           lambda a, b: _array_distinct(list(a) + list(b)))
    # nulls render as "null" (Java Objects.toString); a null delimiter joins
    # with the empty string (reference ArrayJoin)
    scalar("ARRAY_JOIN", [t_array()], T.STRING,
           lambda a: ",".join("null" if x is None else _to_str(x) for x in a))
    reg.scalar("ARRAY_JOIN").variants.append(
        ScalarVariant(params=[t_array(), STR], returns=T.STRING, null_tolerant=True,
                      fn=lambda a, d: None if a is None else
                      (d if d is not None else "").join(
                          "null" if x is None else _to_str(x) for x in a)))
    scalar("ARRAY_MAX", [t_array()], _el, lambda a: max((x for x in a if x is not None), default=None))
    scalar("ARRAY_MIN", [t_array()], _el, lambda a: min((x for x in a if x is not None), default=None))
    def _array_remove(a, x):
        # a NULL victim removes the NULL elements (reference ArrayRemove);
        # otherwise NULL elements are kept
        if a is None:
            return None
        if x is None:
            return [v for v in a if v is not None]
        return [v for v in a if v is None or v != x]

    scalar("ARRAY_REMOVE", [t_array(), t_any()], _same_type, _array_remove,
           null_tolerant=True)
    scalar("ARRAY_SORT", [t_array()], _same_type, _array_sort)
    reg.scalar("ARRAY_SORT").variants.append(
        ScalarVariant(params=[t_array(), STR], returns=_same_type,
                      fn=lambda a, order: _array_sort(a, order)))
    scalar("ARRAY_CONCAT", [t_array(), t_array()], _same_type,
           lambda a, b: (list(a) + list(b)) if a is not None and b is not None else (a if b is None else b),
           null_tolerant=True)
    scalar("SLICE", [t_array(), INT, INT], _same_type,
           lambda a, frm, to: a[frm - 1 : to])
    scalar("GENERATE_SERIES", [BIG, BIG], lambda ts: SqlType.array(ts[0]),
           # default step follows the direction (reference GenerateSeries)
           lambda a, b: list(range(a, b + 1)) if b >= a else list(range(a, b - 1, -1)))
    reg.scalar("GENERATE_SERIES").variants.append(
        ScalarVariant(params=[BIG, BIG, INT], returns=lambda ts: SqlType.array(ts[0]),
                      fn=lambda a, b, step: list(range(a, b + (1 if step > 0 else -1), step))))

    # -------------------------------------------------------------- lambda
    scalar("TRANSFORM", [t_array(), t_lambda(1)],
           lambda ts: SqlType.array(ts[1]) if isinstance(ts[1], SqlType) else SqlType.array(T.STRING),
           lambda a, f: _transform_array(a, f))
    reg.scalar("TRANSFORM").variants.append(
        ScalarVariant(params=[t_map(), t_lambda(2), t_lambda(2)], returns=t_map_transform,
                      fn=lambda m, kf, vf: _transform_map(m, kf, vf)))
    scalar("FILTER", [t_array(), t_lambda(1)], _same_type,
           lambda a, f: _filter_array(a, f))
    reg.scalar("FILTER").variants.append(
        ScalarVariant(params=[t_map(), t_lambda(2)], returns=_same_type,
                      fn=lambda m, f: _filter_map(m, f)))
    scalar("REDUCE", [t_array(), t_any(), t_lambda(2)], lambda ts: ts[1],
           lambda a, init, f: _reduce(a, init, f), null_tolerant=True)
    reg.scalar("REDUCE").variants.append(
        ScalarVariant(params=[t_map(), t_any(), t_lambda(3)], returns=lambda ts: ts[1],
                      fn=lambda m, init, f: _reduce_map(m, init, f),
                      null_tolerant=True))

    # ----------------------------------------------------------------- map
    # Entries.java:41 — map -> array of {K, V} structs, optionally key-sorted
    scalar("ENTRIES", [t_map(), t_base(SqlBaseType.BOOLEAN)],
           lambda ts: SqlType.array(SqlType.struct(
               [("K", ts[0].key or T.STRING), ("V", ts[0].element)])),
           lambda m, sorted_: [
               {"K": k, "V": v}
               for k, v in (sorted(m.items()) if sorted_ else m.items())
           ])
    scalar("MAP_KEYS", [t_map()], lambda ts: SqlType.array(ts[0].key), lambda m: list(m.keys()))
    scalar("MAP_VALUES", [t_map()], lambda ts: SqlType.array(ts[0].element), lambda m: list(m.values()))
    scalar("MAP_UNION", [t_map(), t_map()], _same_type,
           lambda a, b: None if a is None and b is None else {**(a or {}), **(b or {})},
           null_tolerant=True)
    scalar("AS_MAP", [t_array(), t_array()],
           lambda ts: SqlType.map(T.STRING, ts[1].element),
           lambda ks, vs: dict(zip(ks, vs)))
    scalar("ELT", [INT, STR, STR], T.STRING,
           lambda n, *xs: xs[n - 1] if 1 <= n <= len(xs) else None, variadic=True,
           null_tolerant=True)
    scalar("FIELD", [STR, STR, STR], T.INTEGER,
           lambda x, *xs: (xs.index(x) + 1) if x in xs else 0, variadic=True,
           null_tolerant=True)

    # ---------------------------------------------------------------- misc
    scalar("AS_VALUE", [t_any()], _same_type, lambda x: x, null_tolerant=True)


# ------------------------------------------------------------ helper impls


def t_map_transform(ts):
    # ts = [map type, key-lambda return, value-lambda return]
    v = ts[2] if len(ts) > 2 and isinstance(ts[2], SqlType) else T.STRING
    return SqlType.map(T.STRING, v)


def _to_str(x: Any) -> str:
    if isinstance(x, bool):
        return "true" if x else "false"
    if isinstance(x, float):
        from ksql_tpu.execution.interpreter import java_double_str

        return java_double_str(x)
    return str(x)


def _substring(s: str, start: int, length: Optional[int]) -> str:
    # 1-based; negative start counts from the end (Java SubString.java)
    n = len(s)
    if start < 0:
        begin = max(n + start, 0)
    elif start == 0:
        begin = 0
    else:
        begin = start - 1
    end = n if length is None else min(begin + max(length, 0), n)
    return s[begin:end]


def _split_bytes(s: bytes, d: bytes) -> List[bytes]:
    if d == b"":
        return [b""] if s == b"" else [bytes([c]) for c in s]
    return s.split(d)


def _pad(s, n: int, p, left: bool):
    """Shared str/bytes padding (reference LPad/RPad semantics)."""
    if n < 0 or len(p) == 0:
        return None
    if len(s) >= n:
        return s[:n]
    fill = (p * ((n - len(s)) // len(p) + 1))[: n - len(s)]
    return fill + s if left else s + fill


_pad_bytes = _pad


def _int_from_bytes(b: bytes, size: int, order: str) -> int:
    # reference BytesUtils.checkBytesSize: exact length required
    if len(b) != size:
        raise FunctionException(
            f"Number of bytes must be equal to {size}, but found {len(b)}"
        )
    return int.from_bytes(b, "little" if order.upper().startswith("LITTLE") else "big",
                          signed=True)


def _double_from_bytes(b: bytes, order: str) -> float:
    if len(b) != 8:
        raise FunctionException(
            f"Number of bytes must be equal to 8, but found {len(b)}"
        )
    return struct.unpack("<d" if order.upper().startswith("LITTLE") else ">d", b)[0]


def _instr(s: str, sub: str, pos: int, occurrence: int) -> int:
    if pos < 0:
        # search backwards from len+pos
        idx = len(s) + pos
        found = -1
        count = 0
        while idx >= 0:
            j = s.rfind(sub, 0, idx + len(sub))
            if j < 0:
                break
            count += 1
            if count == occurrence:
                found = j
                break
            idx = j - 1
        return found + 1
    idx = pos - 1
    for _ in range(occurrence):
        j = s.find(sub, idx)
        if j < 0:
            return 0
        idx = j + 1
    return idx


def _re_extract(pattern: str, s: str, group: int) -> Optional[str]:
    m = re.search(pattern, s)
    return m.group(group) if m else None


def _round0(x):
    import decimal as _decml

    if isinstance(x, float):
        return math.floor(x + 0.5)  # HALF_UP like the reference
    if isinstance(x, _decml.Decimal):
        return x.quantize(_decml.Decimal(1), rounding=_decml.ROUND_HALF_UP)
    return x


def _round_n(x, n):
    import decimal as _decml

    if isinstance(x, float):
        shifted = x * (10**n)
        return math.floor(shifted + 0.5) / (10**n)
    if isinstance(x, _decml.Decimal):
        # round at position n but keep the input scale (reference Round:
        # the return schema preserves the decimal's scale)
        orig_exp = x.as_tuple().exponent
        q = _decml.Decimal(1).scaleb(-n)
        r = x.quantize(q, rounding=_decml.ROUND_HALF_UP)
        if isinstance(orig_exp, int) and orig_exp < -n:
            r = r.quantize(_decml.Decimal(1).scaleb(orig_exp))
        return r
    return x


def _trunc_n(x, n):
    import decimal as _decml

    if isinstance(x, _decml.Decimal):
        q = _decml.Decimal(1).scaleb(-n)
        return x.quantize(q, rounding=_decml.ROUND_DOWN)
    if isinstance(x, int):
        if n >= 0:
            return x
        q = 10 ** (-n)
        r = (abs(x) // q) * q
        return r if x >= 0 else -r
    shifted = x * (10.0 ** n)
    return math.trunc(shifted) / (10.0 ** n)


def _encode(s: str, in_enc: str, out_enc: str) -> str:
    raw = _decode_to_bytes(s, in_enc.lower())
    return _encode_from_bytes(raw, out_enc.lower())


def _strip_hex_prefix(s: str) -> str:
    if s[:2].lower() == "0x":
        h = s[2:]
        # 0x-prefixed odd-length hex is left-padded (reference Encode.hex)
        return "0" + h if len(h) % 2 else h
    if len(s) >= 3 and s[:2].lower() == "x'" and s.endswith("'"):
        return s[2:-1]
    return s


def _decode_to_bytes(s: str, enc: str) -> bytes:
    if enc == "hex":
        return bytes.fromhex(_strip_hex_prefix(s))
    if enc == "utf8":
        return s.encode("utf-8")
    if enc == "ascii":
        # Java String.getBytes(US_ASCII): unmappable chars become '?'
        return s.encode("ascii", errors="replace")
    if enc == "base64":
        return base64.b64decode(s)
    raise FunctionException(f"unknown encoding {enc!r}")


def _encode_from_bytes(b: bytes, enc: str, hex_upper: bool = False) -> str:
    if enc == "hex":
        return b.hex().upper() if hex_upper else b.hex()
    if enc == "utf8":
        return b.decode("utf-8", errors="replace")
    if enc == "ascii":
        # new String(b, US_ASCII): bytes >127 become U+FFFD
        return "".join(chr(x) if x < 128 else "�" for x in b)
    if enc == "base64":
        return base64.b64encode(b).decode("ascii")
    raise FunctionException(f"unknown encoding {enc!r}")


def _to_bytes(s: str, enc: str) -> bytes:
    return _decode_to_bytes(s, enc.lower())


def _from_bytes(b: bytes, enc: str) -> str:
    # BytesUtils hex rendering is upper-case base16 (FROM_BYTES), unlike
    # ENCODE's lower-case hex output
    return _encode_from_bytes(b, enc.lower(), hex_upper=True)


def _chr_str(s: str) -> Optional[str]:
    """CHR(STRING) accepts only \\uXXXX escape sequences (reference Chr:
    a bare number or arbitrary text yields null)."""
    if not re.fullmatch(r"(?:\\u[0-9a-fA-F]{4})+", s or ""):
        return None
    try:
        return s.encode("ascii").decode("unicode_escape").encode(
            "utf-16", "surrogatepass"
        ).decode("utf-16")
    except Exception:
        return None


def _split_to_map(s: str, entry_d: str, kv_d: str) -> dict:
    """SplitToMap: entries split on the delimiter (empties dropped), each
    entry split fully on the kv delimiter taking parts[0]/parts[1]; first
    key wins."""
    out: dict = {}
    for entry in s.split(entry_d):
        if not entry:
            continue
        parts = entry.split(kv_d)
        if len(parts) >= 2 and parts[0] not in out:
            out[parts[0]] = parts[1]
    return out


def _java_regex_split(s: str, p: str) -> List[str]:
    """Java String.split semantics: capture groups are NOT included in the
    result and trailing empty strings are removed (limit 0)."""
    parts: List[str] = []
    last = 0
    for m in re.finditer(p, s):
        if m.end() == 0:
            continue  # zero-width match at the start is skipped (Java 8+)
        parts.append(s[last : m.start()])
        last = m.end()
    parts.append(s[last:])
    while parts and parts[-1] == "":
        parts.pop()
    if not parts:
        return [""] if s == "" else []
    return parts


def _log_base(b, x) -> float:
    """log(base, x) = Math.log(x)/Math.log(b) with IEEE double division."""
    import numpy as _np

    def jlog(v):
        v = float(v)
        if v > 0:
            return math.log(v)
        return float("-inf") if v == 0 else float("nan")

    if float(b) <= 0 or float(b) == 1.0:
        return float("nan")  # non-positive or unit base (reference Log)
    with _np.errstate(divide="ignore", invalid="ignore"):
        return float(_np.float64(jlog(x)) / _np.float64(jlog(b)))


def _parse_time(s: str, f: str) -> int:
    dt = _dt.datetime.strptime(s, java_format_to_strftime(f))
    return (dt.hour * 3600 + dt.minute * 60 + dt.second) * 1000 + dt.microsecond // 1000


def _strptime_prefix(s: str, fmt: str) -> "_dt.datetime":
    """strptime that, like Java's DateTimeFormatter.parse(CharSequence,
    ParsePosition), accepts trailing text beyond the pattern."""
    try:
        return _dt.datetime.strptime(s, fmt)
    except ValueError as e:
        msg = str(e)
        marker = "unconverted data remains: "
        if marker in msg:
            rem = msg.split(marker, 1)[1]
            if rem and s.endswith(rem):
                return _dt.datetime.strptime(s[: -len(rem)], fmt)
        raise


def _unit_ms(unit: str) -> int:
    u = unit.upper()
    if u not in _UNIT_MS:
        raise FunctionException(f"unknown interval unit {unit!r}")
    return _UNIT_MS[u]


def _convert_tz(ts: int, from_tz: str, to_tz: str) -> int:
    """The stored ms reading is a wall clock in from_tz; re-express the same
    instant as a wall clock in to_tz (reference ConvertTz:
    LocalDateTime.atZone(from).withZoneSameInstant(to))."""
    wall = _dt.datetime.fromtimestamp(ts / 1000.0, _dt.timezone.utc).replace(
        tzinfo=None
    )
    instant = wall.replace(tzinfo=_tz(from_tz))
    wall_to = instant.astimezone(_tz(to_tz)).replace(tzinfo=None)
    return int(wall_to.replace(tzinfo=_dt.timezone.utc).timestamp() * 1000)


def _extract_json_field(s: str, path: str) -> Optional[str]:
    import decimal as _dec

    try:
        # raw_decode: the first complete JSON value parses even with
        # trailing garbage (Jackson's streaming reader behaves the same);
        # floats keep their exact source text ("1.23450" stays padded)
        doc, _end = _json.JSONDecoder(parse_float=_dec.Decimal).raw_decode(
            s.lstrip()
        )
    except (ValueError, TypeError, AttributeError, _dec.InvalidOperation):
        return None
    v = _json_path_get(doc, path)
    if v is None:
        return None
    if isinstance(v, (dict, list)):
        def undec(o):
            if isinstance(o, _dec.Decimal):
                return float(o)
            if isinstance(o, dict):
                return {k: undec(x) for k, x in o.items()}
            if isinstance(o, list):
                return [undec(x) for x in o]
            return o

        return _json.dumps(undec(v))
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _is_json(s: Optional[str]) -> bool:
    if s is None:
        return False
    try:
        _json.loads(s)
        return True
    except ValueError:
        return False


def _json_array_contains(json_array: Optional[str], val: Any) -> bool:
    """JsonArrayContains.java:44: containment gated by JSON token type —
    an int value only matches integer tokens, a double only float tokens,
    etc.; any parse failure returns false."""
    if json_array is None:
        return False
    try:
        arr = _json.loads(json_array)
    except ValueError:
        return False
    if not isinstance(arr, list):
        return False
    for e in arr:
        if val is None:
            if e is None:
                return True
        elif isinstance(val, bool):
            if isinstance(e, bool) and e == val:
                return True
        elif isinstance(val, int):
            if isinstance(e, int) and not isinstance(e, bool) and e == val:
                return True
        elif isinstance(val, float):
            if isinstance(e, float) and e == val:
                return True
        elif isinstance(val, str):
            if isinstance(e, str) and e == val:
                return True
    return False


def _json_items(json_items: Optional[str]) -> Optional[List[str]]:
    """JsonItems.java:36: each array element rendered as compact JSON."""
    if json_items is None:
        return None
    items = _json.loads(json_items)
    if not isinstance(items, list):
        raise FunctionException(
            f"The provided string is not a Json array: {json_items!r}"
        )
    return [_json.dumps(e, separators=(",", ":")) for e in items]


def _json_concat(*docs: str) -> Optional[str]:
    vals = [_json.loads(d) for d in docs]
    if all(isinstance(v, dict) for v in vals):
        merged: Any = {}
        for v in vals:
            merged.update(v)
    else:
        # non-object docs wrap into single-element arrays (JsonConcat)
        merged = []
        for v in vals:
            merged.extend(v if isinstance(v, list) else [v])
    return _json.dumps(merged, separators=(",", ":"))


def _geo_distance(lat1: float, lon1: float, lat2: float, lon2: float, unit: str = "KM") -> float:
    lat1, lon1, lat2, lon2 = float(lat1), float(lon1), float(lat2), float(lon2)
    try:
        r = float(unit)  # a numeric 5th arg is a custom sphere radius
    except (TypeError, ValueError):
        r = 6371.0 if unit.upper().startswith("KM") else 3959.0
    p1, p2 = math.radians(lat1), math.radians(lat2)
    dp = math.radians(lat2 - lat1)
    dl = math.radians(lon2 - lon1)
    a = math.sin(dp / 2) ** 2 + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2
    return 2 * r * math.asin(math.sqrt(a))


def _array_distinct(a: List[Any]) -> List[Any]:
    seen = []
    for x in a:
        if x not in seen:
            seen.append(x)
    return seen


def _array_sort(a: List[Any], order: str = "ASC") -> List[Any]:
    non_null = [x for x in a if x is not None]
    nulls = [None] * (len(a) - len(non_null))
    out = sorted(non_null, reverse=order.upper().startswith("DESC"))
    return out + nulls


def _transform_array(a: Optional[List[Any]], f) -> Optional[List[Any]]:
    """NULL lambda results stay as NULL elements; evaluation *errors*
    (lambda arithmetic on NULL — the codegen NPE) null the whole output by
    propagating out of the UDF."""
    if a is None:
        return None
    return [f(x) for x in a]


def _transform_map(m: Optional[dict], kf, vf) -> Optional[dict]:
    """NULL key/value results — and key collisions — null the whole output
    (reference TransformMap puts into a HashMap and rejects duplicates)."""
    if m is None:
        return None
    out = {}
    for k, v in m.items():
        nk = kf(k, v)
        nv = vf(k, v)
        if nk is None or nv is None or nk in out:
            return None
        out[nk] = nv
    return out


def _filter_array(a: Optional[List[Any]], f) -> Optional[List[Any]]:
    """NULL/false predicate drops the element (comparisons with NULL are
    false); lambda arithmetic on NULL raises and nulls the whole output."""
    if a is None:
        return None
    return [x for x in a if f(x)]


def _filter_map(m: Optional[dict], f) -> Optional[dict]:
    if m is None:
        return None
    return {k: v for k, v in m.items() if f(k, v)}


def java_hashmap_order(keys) -> List[Any]:
    """Iteration order of a java.util.HashMap holding these insertion-ordered
    keys: buckets ascending by (h ^ h>>>16) & (cap-1), insertion order within
    a bucket.  Lambda REDUCE over a deserialized map observes this order in
    the reference, and non-commutative reducers make it visible."""
    keys = list(keys)
    # deserializers presize: new HashMap<>((int)(n/0.75f) + 1) -> next pow2
    c = int(len(keys) / 0.75) + 1
    cap = 1
    while cap < c:
        cap *= 2
    def bucket(k):
        if isinstance(k, str):
            h = 0
            for ch in k:
                h = (31 * h + ord(ch)) & 0xFFFFFFFF
        else:
            h = int(k) & 0xFFFFFFFF
        h ^= h >> 16
        return h & (cap - 1)
    order = sorted(range(len(keys)), key=lambda i: bucket(keys[i]))
    return [keys[i] for i in order]


def _reduce(a: Optional[List[Any]], init: Any, f) -> Any:
    if init is None:
        return None  # null initial state: null result (reference Reduce)
    if a is None:
        return init  # null collection: initial state passes through
    acc = init
    for x in a:
        acc = f(acc, x)
    return acc


def _reduce_map(m: Optional[dict], init: Any, f) -> Any:
    if init is None:
        return None
    if m is None:
        return init
    acc = init
    for k in java_hashmap_order(m.keys()):
        acc = f(acc, k, m[k])
    return acc
