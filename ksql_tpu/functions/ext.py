"""User-defined function API for extension modules.

The analog of the reference's UDF annotations (@UdfDescription/@Udf,
@UdafDescription/@UdafFactory, @UdtfDescription/@Udtf —
ksqldb-engine/src/main/java/io/confluent/ksql/function/udf/UdfDescription
.java and friends).  An extension module is a plain Python file in
``ksql.extension.dir`` (UserFunctionLoader.java:45) that declares functions
with these decorators:

    from ksql_tpu.functions.ext import udf, udaf, udtf

    @udf("multiply", params="INT, INT", returns="BIGINT")
    def multiply(a, b):
        return a * b

    @udaf("my_sum", params="BIGINT", returns="BIGINT")
    class MySum:
        def initialize(self): return 0
        def aggregate(self, value, agg): return agg + value   # per row
        def merge(self, a, b): return a + b
        def map(self, agg): return agg                        # final value
        def undo(self, value, agg): return agg - value        # optional

    @udtf("dup", params="STRING", returns="STRING")
    def dup(s):
        return [s, s]

Type strings are SQL type names (``BIGINT``, ``ARRAY<STRING>``,
``STRUCT<A VARCHAR>``, ...), ``ANY`` for a generic parameter, and a
trailing ``...`` marks the parameter variadic.  ``returns`` may also be a
callable ``(arg_types) -> SqlType`` for type-dependent results, or
``"ARG0"``/``"ARRAY<ARG0>"`` shorthand for "same type as argument 0".
UDAF classes may take constructor args declared with ``init_params`` —
the trailing literal arguments of the SQL call (UdafFactory init args):

    @udaf("scaled_sum", params="BIGINT", init_params="INT", returns="BIGINT")
    class ScaledSum:
        def __init__(self, factor): self.factor = factor
        ...

Multi-parameter UDAF ``aggregate``/``undo`` receive the column values as a
tuple (Pair/Triple/VariadicArgs analog), with a variadic group passed as a
nested tuple.  Raise ``KsqlFunctionError`` (or any exception) to signal a
per-row processing error — the row lands in the processing log, matching
the reference's error-handling contract.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, List, Optional, Sequence, Union

from ksql_tpu.common.errors import KsqlException
from ksql_tpu.common.types import SqlType
from ksql_tpu.functions.registry import Matcher, t_any, t_base

__all__ = [
    "udf", "udaf", "udtf", "KsqlFunctionError", "SqlType", "sql_type",
]


class KsqlFunctionError(KsqlException):
    """Raised by extension functions to signal a per-row error."""


def sql_type(text: str) -> SqlType:
    """Parse a SQL type string (full generics) via the SQL parser."""
    from ksql_tpu.parser.parser import Parser

    return Parser(text).parse_type()


def _parse_params(text: Optional[str]):
    """"BIGINT, STRING..." -> ([matchers], variadic_index, [types-or-None],
    [generic-letter-or-None]).  A bare capital letter (``A``, ``B``, ...) is
    a generic type variable: it matches anything, but every argument bound
    to the same letter must resolve to the same SQL type."""
    if not text or not text.strip():
        return [], None, [], []
    matchers: List[Matcher] = []
    types: List[Optional[SqlType]] = []
    generics: List[Optional[str]] = []
    variadic_index = None
    # split on top-level commas (not inside <...> or (...))
    parts, depth, cur = [], 0, []
    for ch in text:
        if ch in "<(":
            depth += 1
        elif ch in ">)":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    for i, raw in enumerate(parts):
        p = raw.strip()
        if p.endswith("..."):
            if variadic_index is not None:
                raise KsqlException("only one variadic parameter allowed")
            variadic_index = i
            p = p[:-3].strip()
        if p.upper() == "ANY":
            matchers.append(t_any())
            types.append(None)
            generics.append(None)
        elif re.fullmatch(r"[A-Z]", p):
            matchers.append(t_any())
            types.append(None)
            generics.append(p)
        else:
            t = sql_type(p)
            matchers.append(_type_matcher(t))
            types.append(t)
            generics.append(None)
    return matchers, variadic_index, types, generics


#: implicit widening accepted by a declared parameter type (UdfIndex's
#: implicit-cast rules: INT->BIGINT->DOUBLE, ints->DECIMAL); exact-type
#: overloads should be declared first so they win resolution
from ksql_tpu.common.types import SqlBaseType as _B  # noqa: E402

_WIDEN = {
    _B.BIGINT: {_B.INTEGER},
    _B.DOUBLE: {_B.INTEGER, _B.BIGINT},
    _B.DECIMAL: {_B.INTEGER, _B.BIGINT},
}


def _compatible(x: SqlType, t: SqlType) -> bool:
    """Structural parameter compatibility: exact match, numeric widening,
    or recursive container compatibility — an ARRAY<INTEGER> overload must
    NOT swallow ARRAY<DOUBLE> arguments (UdfIndex resolves parameterized
    types structurally)."""
    if x == t:
        return True
    if x.base != t.base:
        return x.base in _WIDEN.get(t.base, ())
    b = t.base
    if b == _B.ARRAY:
        return _compatible(x.element, t.element)
    if b == _B.MAP:
        return ((x.key is None or t.key is None or _compatible(x.key, t.key))
                and _compatible(x.element, t.element))
    if b == _B.STRUCT:
        xf, tf = list(x.fields or ()), list(t.fields or ())
        if len(xf) != len(tf):
            return False
        return all(
            xn.upper() == tn.upper() and _compatible(xt, tt)
            for (xn, xt), (tn, tt) in zip(xf, tf)
        )
    return True  # same-base scalar (DECIMAL of any precision, etc.)


def _type_matcher(t: SqlType) -> Matcher:
    return lambda x: _compatible(x, t)


def _parse_returns(returns: Union[str, SqlType, Callable]) -> Any:
    if callable(returns) and not isinstance(returns, SqlType):
        return returns
    if isinstance(returns, SqlType):
        return returns
    text = str(returns).strip()
    m = re.fullmatch(r"ARG(\d+)", text, re.I)
    if m:
        i = int(m.group(1))
        return lambda ts: ts[i]
    m = re.fullmatch(r"ARRAY\s*<\s*ARG(\d+)\s*>", text, re.I)
    if m:
        i = int(m.group(1))
        return lambda ts: SqlType.array(ts[i])
    return sql_type(text)


@dataclasses.dataclass
class _UdfSpec:
    kind: str  # "udf" | "udaf" | "udtf"
    name: str
    params: str
    returns: Any
    fn: Any  # function (udf/udtf) or class (udaf)
    variadic: bool = False
    null_tolerant: bool = True
    init_params: Optional[str] = None
    description: str = ""
    stateful: bool = False  # fresh callable per resolved query
    device_kind: Optional[str] = None  # device decomposition name (udaf)


def udf(name: str, params: str = "", returns: Union[str, Callable] = "STRING",
        description: str = "", null_tolerant: bool = True,
        stateful: bool = False):
    """Register a scalar function.  Overloads = multiple decorated
    functions with the same name.  ``stateful`` wraps the function in a
    per-query factory so internal state doesn't leak across queries."""

    def deco(fn):
        specs = getattr(fn, "__ksql_specs__", [])
        specs.append(_UdfSpec("udf", name.upper(), params, returns, fn,
                              null_tolerant=null_tolerant,
                              description=description, stateful=stateful))
        fn.__ksql_specs__ = specs
        return fn

    return deco


def udaf(name: str, params: str, returns: Union[str, Callable],
         init_params: Optional[str] = None, description: str = "",
         device_kind: Optional[str] = None):
    """Register an aggregate function.  Decorates a class with
    ``initialize``/``aggregate``/``merge``/``map`` (+ optional ``undo``)
    methods; ``init_params`` declares trailing literal constructor args.
    ``device_kind`` optionally names a device decomposition
    (ops/device_aggs.py) whose semantics the class's host fold matches —
    queries using the function then lower to the XLA backend."""

    def deco(cls):
        specs = getattr(cls, "__ksql_specs__", [])
        specs.append(_UdfSpec("udaf", name.upper(), params, returns, cls,
                              init_params=init_params, description=description,
                              device_kind=device_kind))
        cls.__ksql_specs__ = specs
        return cls

    return deco


def udtf(name: str, params: str = "", returns: Union[str, Callable] = "STRING",
         description: str = ""):
    """Register a table function: returns a list of output values per row."""

    def deco(fn):
        specs = getattr(fn, "__ksql_specs__", [])
        specs.append(_UdfSpec("udtf", name.upper(), params, returns, fn,
                              description=description))
        fn.__ksql_specs__ = specs
        return fn

    return deco
