"""Built-in table functions (UDTFs): EXPLODE, CUBE_EXPLODE
(ksqldb-engine/.../function/udtf/array/Explode.java, Cube.java)."""

from __future__ import annotations

import itertools
from typing import Any, List

from ksql_tpu.common import types as T
from ksql_tpu.common.types import SqlBaseType, SqlType
from ksql_tpu.functions.registry import FunctionRegistry, Udtf, t_array


def register_all(reg: FunctionRegistry) -> None:
    reg.register_udtf(Udtf(
        name="EXPLODE",
        params=[t_array()],
        returns=lambda ts: ts[0].element,
        fn=lambda a: list(a) if a is not None else [],
        description="One output row per array element",
    ))
    reg.register_udtf(Udtf(
        name="CUBE_EXPLODE",
        params=[t_array()],
        returns=lambda ts: ts[0],
        fn=_cube,
        description="All combinations of the given columns and NULL",
    ))


def _cube(a: List[Any]) -> List[List[Any]]:
    if a is None:
        return []
    options = [[None, x] if x is not None else [None] for x in a]
    return [list(combo) for combo in itertools.product(*options)]
