"""Function registry — scalar UDFs, aggregate UDAFs, table UDTFs.

Analog of FunctionRegistry/InternalFunctionRegistry
(ksqldb-common/.../function/FunctionRegistry.java:27,
ksqldb-engine/.../function/InternalFunctionRegistry.java:29).

Each scalar function registers one or more *variants* (overloads).  A variant
declares parameter matchers, a return-type rule, and a host (row-oriented)
implementation used by the parity oracle and by the device path's dictionary
trick (string functions are applied to per-batch dictionaries, not rows).
Numeric functions may also declare a `jax_fn` used by the columnar compiler
to stay fused on device.

Aggregates (Udaf) declare host fold/merge/undo semantics plus a
``device_kind`` that the XLA lowering maps onto segment-reduction kernels
(KudafAggregator analog — ksqldb-execution/.../KudafAggregator.java:32).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ksql_tpu.common.errors import FunctionException
from ksql_tpu.common.types import SqlBaseType, SqlType

# A parameter matcher: SqlType -> bool
Matcher = Callable[[SqlType], bool]


def t_exact(t: SqlType) -> Matcher:
    return lambda x: x == t


def t_base(*bases: SqlBaseType) -> Matcher:
    return lambda x: x.base in bases


def t_numeric() -> Matcher:
    return lambda x: x.is_numeric()


def t_any() -> Matcher:
    return lambda x: True


def t_array() -> Matcher:
    return lambda x: x.base == SqlBaseType.ARRAY


def t_map() -> Matcher:
    return lambda x: x.base == SqlBaseType.MAP


def t_lambda(n_params: int) -> Matcher:
    # lambda args are typed structurally during resolution; marker matcher
    m = lambda x: True  # noqa: E731
    m.lambda_params = n_params  # type: ignore[attr-defined]
    return m


@dataclasses.dataclass
class ScalarVariant:
    """One overload of a scalar function."""

    params: Sequence[Matcher]
    # return type: fixed SqlType or fn(arg_types) -> SqlType
    returns: Any
    # host implementation: fn(*args) -> value.  Receives Python values; null
    # handling is done by the caller unless null_tolerant.
    fn: Callable[..., Any]
    variadic: bool = False  # last matcher repeats
    null_tolerant: bool = False  # fn wants to see Nones
    # when True, ``fn`` is a factory: fn(arg_types) -> callable(*values)
    # (for functions whose runtime behavior depends on the resolved types)
    typed_factory: bool = False

    def matches(self, arg_types: Sequence[SqlType]) -> bool:
        ps = list(self.params)
        if self.variadic:
            if len(arg_types) < len(ps) - 1:
                return False
            ps = ps[:-1] + [ps[-1]] * (len(arg_types) - len(ps) + 1)
        elif len(arg_types) != len(ps):
            return False
        # an untyped NULL literal (None) matches any parameter
        return all(t is None or m(t) for m, t in zip(ps, arg_types))

    def return_type(self, arg_types: Sequence[SqlType]) -> SqlType:
        if callable(self.returns):
            return self.returns(list(arg_types))
        return self.returns


@dataclasses.dataclass
class ScalarFunction:
    name: str
    variants: List[ScalarVariant]
    description: str = ""
    # device/columnar implementation: fn(*jnp_arrays) -> jnp_array, fused by
    # the compiler when every argument is device-resident numeric.
    jax_fn: Optional[Callable[..., Any]] = None

    def resolve(self, arg_types: Sequence[SqlType]) -> ScalarVariant:
        for v in self.variants:
            if v.matches(arg_types):
                return v
        # message mirrors UdfIndex.getFunction's resolution failure
        raise FunctionException(
            f"Function '{self.name}' does not accept parameters "
            f"({', '.join(str(t) for t in arg_types)})."
        )


@dataclasses.dataclass
class Udaf:
    """Aggregate function.  Host semantics (init/accumulate/merge/result/undo)
    define parity; device_kind tells the XLA backend which segment-reduction
    to emit ('count','sum','min','max','avg','count_distinct','stddev',
    'collect', 'earliest', 'latest', 'topk', 'histogram', 'correlation')."""

    name: str
    params: Sequence[Matcher]
    returns: Any  # SqlType or fn(arg_types)->SqlType
    init: Callable[[], Any]
    accumulate: Callable[..., Any]  # (state, *args) -> state
    merge: Callable[[Any, Any], Any]
    result: Callable[[Any], Any]
    undo: Optional[Callable[..., Any]] = None  # (state, *args) -> state
    device_kind: Optional[str] = None
    description: str = ""
    # extra non-column literal args (e.g. TOPK(col, k)): count of trailing
    # literal parameters
    literal_params: int = 0
    # position of a repeating parameter (VariadicArgs analog,
    # UdafFactory variadic col/init args): the matcher at this index
    # matches 0+ consecutive arguments
    variadic_index: Optional[int] = None
    # cross-argument check run after per-arg matching (generic type
    # variables: VariadicArgs<C> requires every C-typed arg to agree)
    arg_constraint: Optional[Callable[[Sequence[SqlType]], bool]] = None

    def matches(self, arg_types: Sequence[SqlType]) -> bool:
        ps = list(self.params)
        if self.variadic_index is not None:
            i = self.variadic_index
            k = len(arg_types) - (len(ps) - 1)
            if k < 0:
                return False
            ps = ps[:i] + [ps[i]] * k + ps[i + 1:]
        elif len(arg_types) != len(ps):
            return False
        if not all(t is None or m(t) for m, t in zip(ps, arg_types)):
            return False
        return self.arg_constraint is None or self.arg_constraint(list(arg_types))

    def return_type(self, arg_types: Sequence[SqlType]) -> SqlType:
        if callable(self.returns):
            return self.returns(list(arg_types))
        return self.returns


@dataclasses.dataclass
class Udtf:
    """Table function: one row in, N rows out (KudtfFlatMapper analog)."""

    name: str
    params: Sequence[Matcher]
    returns: Any  # element type rule: fn(arg_types)->SqlType
    fn: Callable[..., List[Any]]
    description: str = ""

    def matches(self, arg_types: Sequence[SqlType]) -> bool:
        if len(arg_types) != len(self.params):
            return False
        return all(t is None or m(t) for m, t in zip(self.params, arg_types))

    def return_type(self, arg_types: Sequence[SqlType]) -> SqlType:
        if callable(self.returns):
            return self.returns(list(arg_types))
        return self.returns


class FunctionRegistry:
    def __init__(self) -> None:
        self._scalars: Dict[str, ScalarFunction] = {}
        self._udafs: Dict[str, List[Udaf]] = {}
        self._udtfs: Dict[str, List[Udtf]] = {}

    def copy(self) -> "FunctionRegistry":
        """Fork for per-engine extension loading: built-ins are shared
        immutably, variant lists are copied so registrations into the fork
        don't leak into the process-wide default registry."""
        c = FunctionRegistry()
        c._scalars = {
            n: ScalarFunction(f.name, list(f.variants), f.description, f.jax_fn)
            for n, f in self._scalars.items()
        }
        c._udafs = {n: list(v) for n, v in self._udafs.items()}
        c._udtfs = {n: list(v) for n, v in self._udtfs.items()}
        return c

    # ------------------------------------------------------------- scalars
    def register_scalar(self, fn: ScalarFunction) -> None:
        existing = self._scalars.get(fn.name)
        if existing:
            existing.variants.extend(fn.variants)
        else:
            self._scalars[fn.name] = fn

    def scalar(self, name: str) -> ScalarFunction:
        f = self._scalars.get(name.upper())
        if f is None:
            raise FunctionException(f"unknown function {name.upper()}")
        return f

    def is_scalar(self, name: str) -> bool:
        return name.upper() in self._scalars

    # --------------------------------------------------------------- udafs
    def register_udaf(self, u: Udaf) -> None:
        self._udafs.setdefault(u.name, []).append(u)

    def is_aggregate(self, name: str) -> bool:
        return name.upper() in self._udafs

    def udaf(self, name: str, arg_types: Sequence[SqlType]) -> Udaf:
        for u in self._udafs.get(name.upper(), ()):
            if u.matches(arg_types):
                return u
        raise FunctionException(
            f"Function '{name.upper()}' does not accept parameters "
            f"({', '.join(str(t) for t in arg_types)})."
        )

    # --------------------------------------------------------------- udtfs
    def register_udtf(self, u: Udtf) -> None:
        self._udtfs.setdefault(u.name, []).append(u)

    def is_table_function(self, name: str) -> bool:
        return name.upper() in self._udtfs

    def udtf(self, name: str, arg_types: Sequence[SqlType]) -> Udtf:
        for u in self._udtfs.get(name.upper(), ()):
            if u.matches(arg_types):
                return u
        raise FunctionException(
            f"Function '{name.upper()}' does not accept parameters "
            f"({', '.join(str(t) for t in arg_types)})."
        )

    # ---------------------------------------------------------------- info
    def list_functions(self) -> List[Tuple[str, str]]:
        out = [(n, "SCALAR") for n in self._scalars]
        out += [(n, "AGGREGATE") for n in self._udafs]
        out += [(n, "TABLE") for n in self._udtfs]
        return sorted(out)

    def describe(self, name: str) -> str:
        name = name.upper()
        if name in self._scalars:
            return self._scalars[name].description or name
        if name in self._udafs:
            return self._udafs[name][0].description or name
        if name in self._udtfs:
            return self._udtfs[name][0].description or name
        raise FunctionException(f"unknown function {name}")


_DEFAULT: Optional[FunctionRegistry] = None


def default_registry() -> FunctionRegistry:
    """The process-wide registry with all built-ins loaded."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = FunctionRegistry()
        from ksql_tpu.functions import udafs, udfs, udtfs

        udfs.register_all(_DEFAULT)
        udafs.register_all(_DEFAULT)
        udtfs.register_all(_DEFAULT)
    return _DEFAULT
