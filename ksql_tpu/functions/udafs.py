"""Built-in aggregate functions.

The 14 UDAF families of the reference (ksqldb-engine/.../function/udaf/:
count, count_distinct, sum, min, max, avg (average), stddev, correlation,
topk, topkdistinct, collect_list, collect_set, histogram,
earliest/latest_by_offset).

Host semantics (init/accumulate/merge/result/undo) are the parity oracle and
power the per-record changelog path; ``device_kind`` maps each family onto
the XLA segment-reduction kernels in ops/segments.py.  ``undo`` mirrors
KudafUndoAggregator (table changelog retractions).
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Tuple

from ksql_tpu.common import types as T
from ksql_tpu.common.types import SqlBaseType, SqlType
from ksql_tpu.functions.registry import (
    FunctionRegistry,
    Udaf,
    t_any,
    t_base,
    t_numeric,
)

NUM = t_numeric()
STR = t_base(SqlBaseType.STRING)
ANY = t_any()
INT = t_base(SqlBaseType.INTEGER)
COMPARABLE = t_base(
    SqlBaseType.INTEGER, SqlBaseType.BIGINT, SqlBaseType.DOUBLE,
    SqlBaseType.DECIMAL, SqlBaseType.STRING, SqlBaseType.DATE,
    SqlBaseType.TIME, SqlBaseType.TIMESTAMP, SqlBaseType.BOOLEAN,
    # BYTES compare lexicographically unsigned (Java Bytes.compareTo ==
    # Python bytes ordering) — min-/max-/topk-distinct bytes cases
    SqlBaseType.BYTES,
)


def _sum_type(ts: List[SqlType]) -> SqlType:
    # reference SumKudaf: SUM(INT)->INT, SUM(BIGINT)->BIGINT, etc.
    return ts[0]


def register_all(reg: FunctionRegistry) -> None:
    # ----------------------------------------------------------- COUNT(*)
    reg.register_udaf(Udaf(
        name="COUNT",
        params=[],
        returns=T.BIGINT,
        init=lambda: 0,
        accumulate=lambda s: s + 1,
        merge=lambda a, b: a + b,
        result=lambda s: s,
        undo=lambda s: s - 1,
        device_kind="count_star",
        description="Count of records",
    ))
    # COUNT(col) — non-null count
    reg.register_udaf(Udaf(
        name="COUNT",
        params=[ANY],
        returns=T.BIGINT,
        init=lambda: 0,
        accumulate=lambda s, v: s + (v is not None),
        merge=lambda a, b: a + b,
        result=lambda s: s,
        undo=lambda s, v: s - (v is not None),
        device_kind="count",
    ))
    reg.register_udaf(Udaf(
        name="COUNT_DISTINCT",
        params=[ANY],
        returns=T.BIGINT,
        init=lambda: set(),
        accumulate=lambda s, v: (s.add(_hashable(v)) or s) if v is not None else s,
        merge=lambda a, b: a | b,
        result=lambda s: len(s),
        device_kind="count_distinct",
    ))
    # --------------------------------------------------------------- SUM
    # reference SumKudaf initializes to 0 and skips nulls (SUM of only-null
    # input is 0, not NULL)
    reg.register_udaf(Udaf(
        name="SUM",
        params=[NUM],
        returns=_sum_type,
        init=lambda: 0,
        accumulate=lambda s, v: s if v is None else s + v,
        merge=lambda a, b: a + b,
        result=lambda s: s,
        undo=lambda s, v: s if v is None else s - v,
        device_kind="sum",
    ))
    # ----------------------------------------------------------- MIN/MAX
    for name, better, kind in (("MIN", lambda a, b: b < a, "min"), ("MAX", lambda a, b: b > a, "max")):
        reg.register_udaf(Udaf(
            name=name,
            params=[COMPARABLE],
            returns=lambda ts: ts[0],
            init=lambda: None,
            accumulate=(lambda better: lambda s, v: s if v is None else (v if s is None or better(s, v) else s))(better),
            merge=(lambda better: lambda a, b: b if a is None else (a if b is None else (b if better(a, b) else a)))(better),
            result=lambda s: s,
            device_kind=kind,
        ))
    # --------------------------------------------------------------- AVG
    reg.register_udaf(Udaf(
        name="AVG",
        params=[NUM],
        returns=T.DOUBLE,
        init=lambda: (0.0, 0),
        accumulate=lambda s, v: s if v is None else (s[0] + v, s[1] + 1),
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
        result=lambda s: (s[0] / s[1]) if s[1] else None,
        undo=lambda s, v: s if v is None else (s[0] - v, s[1] - 1),
        device_kind="avg",
    ))
    # ------------------------------------------------------------ STDDEV
    # STDDEV_SAMPLE (StddevKudaf) returns the sample standard deviation;
    # STDDEV_SAMP is a DIFFERENT reference function that returns the sample
    # VARIANCE (observed reference behavior, standarddeviation.json)
    for stddev_name in ("STDDEV_SAMPLE",):
        reg.register_udaf(Udaf(
            name=stddev_name,
            params=[NUM],
            returns=T.DOUBLE,
            init=lambda: (0.0, 0.0, 0),  # sum, sumsq, n
            accumulate=lambda s, v: s if v is None else (s[0] + v, s[1] + v * v, s[2] + 1),
            merge=lambda a, b: (a[0] + b[0], a[1] + b[1], a[2] + b[2]),
            result=_stddev_samp,
            undo=lambda s, v: s if v is None else (s[0] - v, s[1] - v * v, s[2] - 1),
            device_kind="stddev",
        ))
    reg.register_udaf(Udaf(
        name="STDDEV_SAMP",
        params=[NUM],
        returns=T.DOUBLE,
        init=lambda: (0.0, 0.0, 0),
        accumulate=lambda s, v: s if v is None else (s[0] + v, s[1] + v * v, s[2] + 1),
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1], a[2] + b[2]),
        result=_var_samp,
        undo=lambda s, v: s if v is None else (s[0] - v, s[1] - v * v, s[2] - 1),
        device_kind=None,  # variance result: no stddev device kernel match
    ))
    reg.register_udaf(Udaf(
        name="STDDEV_POP",
        params=[NUM],
        returns=T.DOUBLE,
        init=lambda: (0.0, 0.0, 0),
        accumulate=lambda s, v: s if v is None else (s[0] + v, s[1] + v * v, s[2] + 1),
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1], a[2] + b[2]),
        result=_stddev_pop,
        device_kind="stddev",
    ))
    # ------------------------------------------------------- CORRELATION
    reg.register_udaf(Udaf(
        name="CORRELATION",
        params=[NUM, NUM],
        returns=T.DOUBLE,
        init=lambda: (0, 0.0, 0.0, 0.0, 0.0, 0.0),  # n, sx, sy, sxx, syy, sxy
        accumulate=_corr_acc,
        merge=lambda a, b: tuple(x + y for x, y in zip(a, b)),
        result=_corr_result,
        undo=_corr_undo,
        device_kind="correlation",
    ))
    # -------------------------------------------------------------- TOPK
    reg.register_udaf(Udaf(
        name="TOPK",
        params=[COMPARABLE, INT],
        returns=lambda ts: SqlType.array(ts[0]),
        init=lambda: [],
        accumulate=_topk_acc,
        merge=lambda a, b: _topk_merge(a, b, distinct=False),
        result=lambda s: [v for v, _ in s],
        device_kind="topk",
        literal_params=1,
    ))
    # TOPK with additional columns: TOPK(sort_col, col0..colN, k) returns
    # ARRAY<STRUCT<sort_col, col0, ...>> ordered by sort_col desc (reference
    # topk/TopkKudaf variadic form, topk-group-by.json struct cases)
    for extra in range(1, 5):
        reg.register_udaf(Udaf(
            name="TOPK",
            params=[COMPARABLE] + [ANY] * extra + [INT],
            returns=(lambda extra: lambda ts: SqlType.array(SqlType.struct(
                [("sort_col", ts[0])]
                + [(f"col{i}", ts[1 + i]) for i in range(extra)]
            )))(extra),
            init=lambda: [],
            accumulate=_topk_struct_acc,
            merge=_topk_struct_merge,
            result=(lambda extra: lambda s: [
                {"sort_col": v, **{f"col{i}": e[i] for i in range(extra)}}
                for v, e, _ in s
            ])(extra),
            device_kind=None,
            literal_params=1,
        ))
    reg.register_udaf(Udaf(
        name="TOPKDISTINCT",
        params=[COMPARABLE, INT],
        returns=lambda ts: SqlType.array(ts[0]),
        init=lambda: [],
        accumulate=_topk_distinct_acc,
        merge=lambda a, b: _topk_merge(a, b, distinct=True),
        result=lambda s: [v for v, _ in s],
        device_kind="topk",
        literal_params=1,
    ))
    # ----------------------------------------------------------- COLLECT
    # cap during accumulation like the reference (CollectListUdaf LIMIT 1000)
    reg.register_udaf(Udaf(
        name="COLLECT_LIST",
        params=[ANY],
        returns=lambda ts: SqlType.array(ts[0]),
        init=lambda: [],
        accumulate=_collect_list_acc,
        merge=lambda a, b: (a + b)[: _limit_of("collect_list")],
        result=lambda s: list(s),
        undo=_collect_undo,
        device_kind="collect",
    ))
    reg.register_udaf(Udaf(
        name="COLLECT_SET",
        params=[ANY],
        returns=lambda ts: SqlType.array(ts[0]),
        init=lambda: [],
        accumulate=_collect_set_acc,
        merge=lambda a, b: _dedupe(a + b)[: _limit_of("collect_set")],
        result=lambda s: list(s),
        device_kind="collect",
    ))
    # --------------------------------------------------------- HISTOGRAM
    reg.register_udaf(Udaf(
        name="HISTOGRAM",
        params=[STR],
        returns=SqlType.map(T.STRING, T.BIGINT),
        init=lambda: {},
        accumulate=_hist_acc,
        merge=_hist_merge,
        result=lambda s: dict(s),
        undo=_hist_undo,
        device_kind="histogram",
    ))
    # ------------------------------------------- EARLIEST/LATEST_BY_OFFSET
    # reference default ignoreNulls=true (EarliestByOffset.java/LatestByOffset)
    reg.register_udaf(Udaf(
        name="EARLIEST_BY_OFFSET",
        params=[ANY],
        returns=lambda ts: ts[0],
        init=lambda: _ABSENT,
        accumulate=lambda s, v: v if (s is _ABSENT and v is not None) else s,
        merge=lambda a, b: a if a is not _ABSENT else b,
        result=lambda s: None if s is _ABSENT else s,
        device_kind="earliest",
    ))
    reg.register_udaf(Udaf(
        name="LATEST_BY_OFFSET",
        params=[ANY],
        returns=lambda ts: ts[0],
        init=lambda: _ABSENT,
        accumulate=lambda s, v: v if v is not None else s,
        merge=lambda a, b: b if b is not _ABSENT else a,
        result=lambda s: None if s is _ABSENT else s,
        device_kind="latest",
    ))
    BOOL = t_base(SqlBaseType.BOOLEAN)
    # (col, ignoreNulls) variants
    for nm, earliest in (("EARLIEST_BY_OFFSET", True), ("LATEST_BY_OFFSET", False)):
        reg.register_udaf(Udaf(
            name=nm,
            params=[ANY, BOOL],
            returns=lambda ts: ts[0],
            init=lambda: _ABSENT,
            accumulate=(lambda earliest: lambda s, v, ignore_nulls: _el_acc(s, v, ignore_nulls, earliest))(earliest),
            merge=(lambda earliest: (lambda a, b: (a if a is not _ABSENT else b) if earliest else (b if b is not _ABSENT else a)))(earliest),
            result=lambda s: None if s is _ABSENT else s,
            device_kind="earliest" if earliest else "latest",
            literal_params=1,
        ))
        # (col, n) and (col, n, ignoreNulls): earliest/latest N as an array;
        # state entries carry (value, n) so merge can re-cap (like TOPK)
        for params, lits in (([ANY, INT], 1), ([ANY, INT, BOOL], 2)):
            reg.register_udaf(Udaf(
                name=nm,
                params=params,
                returns=lambda ts: SqlType.array(ts[0]),
                init=lambda: [],
                accumulate=(lambda earliest: lambda s, v, n, *rest: _eln_acc(s, v, n, (rest[0] if rest else True), earliest))(earliest),
                merge=(lambda earliest: lambda a, b: _eln_merge(a, b, earliest))(earliest),
                result=lambda s: [v for v, _ in s],
                device_kind="collect",
                literal_params=lits,
            ))
    # ---------------------------------------------------------------- ATTR
    # udaf/attr/Attr.java:34 — collect (value, count) entries; the result
    # is the single distinct value when exactly one has count>0, else NULL
    # (signals "expected a singular value but saw many"); TableUdaf w/ undo
    reg.register_udaf(Udaf(
        name="ATTR",
        params=[ANY],
        returns=lambda ts: ts[0],
        init=lambda: (),
        accumulate=lambda s, v: _attr_update(s, v, 1),
        merge=_attr_merge,
        result=_attr_result,
        undo=lambda s, v: _attr_update(s, v, -1),
        device_kind="attr",
        description="Collect as a singleton; NULL when multiple values seen",
    ))
    # ------------------------------------------------------------ SUM_LIST
    # udaf/sum/ListSumUdaf.java:28 — sums the elements of each list value
    for mk, t in ((t_exact_array(T.DOUBLE), T.DOUBLE),
                  (t_exact_array(T.INTEGER), T.INTEGER),
                  (t_exact_array(T.BIGINT), T.BIGINT)):
        reg.register_udaf(Udaf(
            name="SUM_LIST",
            params=[mk],
            returns=t,
            init=lambda: 0,
            accumulate=lambda s, v: s if v is None else s + sum(x for x in v if x is not None),
            merge=lambda a, b: a + b,
            result=lambda s: s,
            undo=lambda s, v: s if v is None else s - sum(x for x in v if x is not None),
            description="Returns the sum of elements contained in the list.",
        ))


# ------------------------------------------------------------------ helpers

_ABSENT = object()
_COLLECT_LIMIT = 1000
#: per-engine overrides from ksql.functions.<name>.limit, installed by the
#: engine's poll loop for the duration of its processing tick
_LIMIT_OVERRIDES: dict = {}


def _limit_of(name: str) -> int:
    try:
        return int(_LIMIT_OVERRIDES.get(name, _COLLECT_LIMIT))
    except (TypeError, ValueError):
        return _COLLECT_LIMIT


def _collect_list_acc(s, v):
    if len(s) < _limit_of("collect_list"):
        s = s + [v]
    return s


def _collect_undo(s, v):
    # remove first occurrence (reference CollectListUdaf undo)
    out = list(s)
    try:
        out.remove(v)
    except ValueError:
        pass
    return out


def _collect_set_acc(s, v):
    if len(s) < _limit_of("collect_set") and _hashable(v) not in {
        _hashable(x) for x in s
    }:
        s = s + [v]
    return s


def _el_acc(s, v, ignore_nulls, earliest):
    if v is None and ignore_nulls:
        return s
    if earliest:
        return v if s is _ABSENT else s
    return v


def _eln_acc(s, v, n, ignore_nulls, earliest):
    if v is None and ignore_nulls:
        return s
    s = s + [(v, n)]
    if len(s) > n:
        s = s[:n] if earliest else s[-n:]
    return s


def _eln_merge(a, b, earliest):
    merged = list(a) + list(b)
    if not merged:
        return []
    n = merged[0][1]
    if len(merged) > n:
        merged = merged[:n] if earliest else merged[-n:]
    return merged


def _hashable(v: Any) -> Any:
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


def _dedupe(xs: List[Any]) -> List[Any]:
    seen = set()
    out = []
    for x in xs:
        h = _hashable(x)
        if h not in seen:
            seen.add(h)
            out.append(x)
    return out


def _stddev_samp(s: Tuple[float, float, int]) -> Optional[float]:
    total, sumsq, n = s
    if n < 2:
        return 0.0 if n == 1 else None
    var = (sumsq - total * total / n) / (n - 1)
    return math.sqrt(max(var, 0.0))


def _var_samp(s: Tuple[float, float, int]) -> Optional[float]:
    total, sumsq, n = s
    if n < 2:
        return 0.0 if n == 1 else None
    return (sumsq - total * total / n) / (n - 1)


def _stddev_pop(s: Tuple[float, float, int]) -> Optional[float]:
    total, sumsq, n = s
    if n < 1:
        return None
    var = (sumsq - total * total / n) / n
    return math.sqrt(max(var, 0.0))


def _corr_acc(s, x, y):
    if x is None or y is None:
        return s
    n, sx, sy, sxx, syy, sxy = s
    return (n + 1, sx + x, sy + y, sxx + x * x, syy + y * y, sxy + x * y)


def _corr_undo(s, x, y):
    if x is None or y is None:
        return s
    n, sx, sy, sxx, syy, sxy = s
    return (n - 1, sx - x, sy - y, sxx - x * x, syy - y * y, sxy - x * y)


def _corr_result(s) -> Optional[float]:
    # matches Apache Commons PearsonsCorrelation: NaN until there are two
    # points / any variance (reference CorrelationUdaf)
    n, sx, sy, sxx, syy, sxy = s
    if n < 2:
        return float("nan")
    cov = sxy - sx * sy / n
    vx = sxx - sx * sx / n
    vy = syy - sy * sy / n
    if vx <= 0 or vy <= 0:
        return float("nan")
    return cov / math.sqrt(vx * vy)


def _topk_acc(s, v, k):
    if v is None:
        return s
    s = s + [(v, k)]
    s.sort(key=lambda t: t[0], reverse=True)
    return s[:k]


def _topk_distinct_acc(s, v, k):
    if v is None or any(x == v for x, _ in s):
        return s
    s = s + [(v, k)]
    s.sort(key=lambda t: t[0], reverse=True)
    return s[:k]


def _topk_struct_acc(s, v, *rest):
    extras, k = rest[:-1], rest[-1]
    if v is None:
        return s
    s = s + [(v, tuple(extras), k)]
    s.sort(key=lambda t: t[0], reverse=True)
    return s[:k]


def _topk_struct_merge(a, b):
    if not a and not b:
        return []
    k = (a or b)[0][2]
    merged = list(a) + list(b)
    merged.sort(key=lambda t: t[0], reverse=True)
    return merged[:k]


def _topk_merge(a, b, distinct: bool):
    if not a and not b:
        return []
    k = (a or b)[0][1]
    merged = list(a) + list(b)
    if distinct:
        seen = set()
        deduped = []
        for v, kk in merged:
            if v not in seen:
                seen.add(v)
                deduped.append((v, kk))
        merged = deduped
    merged.sort(key=lambda t: t[0], reverse=True)
    return merged[:k]


def _hist_acc(s, v):
    if v is None:
        return s
    if len(s) >= 1000 and v not in s:
        return s
    s = dict(s)
    s[v] = s.get(v, 0) + 1
    return s


def _hist_merge(a, b):
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) + v
    return out


def _hist_undo(s, v):
    if v is None or v not in s:
        return s
    s = dict(s)
    s[v] -= 1
    if s[v] <= 0:
        del s[v]
    return s


# ------------------------------------------------------------------- ATTR


def t_exact_array(el: SqlType):
    """Matcher for ARRAY<el> exactly (SUM_LIST's per-element-type overloads)."""
    return lambda x: x.base == SqlBaseType.ARRAY and x.element == el


def _attr_update(s, v, count):
    """State: tuple of (hashable_key, original_value, count) entries —
    Attr.java's List<Struct{VALUE,COUNT}> with Math.max(0, n+count)."""
    k = _hashable(v)
    out = []
    found = False
    for ek, ev, n in s:
        if ek == k:
            found = True
            out.append((ek, ev, max(0, n + count)))
        else:
            out.append((ek, ev, n))
    if not found and count > 0:
        out.append((k, v, count))
    return tuple(out)


def _attr_merge(a, b):
    out = a
    for ek, ev, n in b:
        out = _attr_update(out, ev, n)
    return out


def _attr_result(s):
    live = [(ev, n) for _ek, ev, n in s if n > 0]
    if len(live) != 1:
        return None
    return live[0][0]
