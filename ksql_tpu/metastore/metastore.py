"""Metastore: named data sources (streams/tables) + custom types +
referential integrity.

Analog of ksqldb-metastore (MetaStore.java:26, MetaStoreImpl.java,
model/KsqlStream.java, model/KsqlTable.java).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional, Set, Tuple

from ksql_tpu.common.errors import AnalysisException, KsqlException
from ksql_tpu.common.schema import LogicalSchema
from ksql_tpu.common.types import SqlType


class DataSourceType:
    STREAM = "STREAM"
    TABLE = "TABLE"


@dataclasses.dataclass(frozen=True)
class KeyFormat:
    format: str = "KAFKA"
    window_type: Optional[str] = None  # TUMBLING/HOPPING/SESSION for windowed keys
    window_size_ms: Optional[int] = None
    # single keys inferred from an SR record schema keep the record envelope
    # (no UNWRAP_SINGLES key feature)
    wrapped: bool = False

    @property
    def windowed(self) -> bool:
        return self.window_type is not None


@dataclasses.dataclass(frozen=True)
class DataSource:
    """A registered stream or table (model/DataSource.java)."""

    name: str
    source_type: str  # DataSourceType
    schema: LogicalSchema
    topic: str
    key_format: KeyFormat = KeyFormat()
    value_format: str = "JSON"
    # SerdeFeature WRAP/UNWRAP_SINGLES for the value serde (None = default)
    wrap_single_values: Optional[bool] = None
    value_delimiter: Optional[str] = None  # DELIMITED value_delimiter property
    key_delimiter: Optional[str] = None  # DELIMITED key_delimiter property
    timestamp_column: Optional[str] = None
    timestamp_format: Optional[str] = None
    sql_expression: str = ""  # original DDL text
    is_source: bool = False  # read-only source (CREATE SOURCE STREAM/TABLE)
    # created by CREATE ... AS SELECT (DataSource.isCasTarget): such sources
    # reject ALTER since their schema is derived from the query
    is_cas_target: bool = False
    # [(column, header_key-or-None)] for HEADERS-backed value columns
    header_columns: tuple = ()
    # PROTOBUF nullable representation ('OPTIONAL'/'WRAPPER': scalar fields
    # are nullable instead of proto3-defaulted) and inferred float32 fields
    proto_nullable_rep: Optional[str] = None
    proto_float32: tuple = ()

    def is_stream(self) -> bool:
        return self.source_type == DataSourceType.STREAM

    def is_table(self) -> bool:
        return self.source_type == DataSourceType.TABLE

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "type": self.source_type,
            "schema": self.schema.to_json(),
            "topic": self.topic,
            "keyFormat": {
                "format": self.key_format.format,
                "windowType": self.key_format.window_type,
                "windowSizeMs": self.key_format.window_size_ms,
            },
            "valueFormat": self.value_format,
            "timestampColumn": self.timestamp_column,
            "timestampFormat": self.timestamp_format,
            "isSource": self.is_source,
            "isCasTarget": self.is_cas_target,
        }

    @staticmethod
    def from_json(obj: Dict[str, Any]) -> "DataSource":
        kf = obj.get("keyFormat", {})
        return DataSource(
            name=obj["name"],
            source_type=obj["type"],
            schema=LogicalSchema.from_json(obj["schema"]),
            topic=obj["topic"],
            key_format=KeyFormat(
                format=kf.get("format", "KAFKA"),
                window_type=kf.get("windowType"),
                window_size_ms=kf.get("windowSizeMs"),
            ),
            value_format=obj.get("valueFormat", "JSON"),
            timestamp_column=obj.get("timestampColumn"),
            timestamp_format=obj.get("timestampFormat"),
            is_source=obj.get("isSource", False),
            is_cas_target=obj.get("isCasTarget", False),
        )


@dataclasses.dataclass(frozen=True)
class ConnectorInfo:
    """A registered connector (the engine-visible projection of a Kafka
    Connect connector: DefaultConnectClient's ConnectorInfo).  The actual
    Connect-cluster call is stubbed behind services/connect.py; state here
    is what LIST/DESCRIBE CONNECTORS render."""

    name: str
    connector_type: str  # SOURCE | SINK
    properties: Tuple[Tuple[str, str], ...]  # sorted, hashable
    state: str = "RUNNING"

    @property
    def connector_class(self) -> str:
        return dict(self.properties).get("connector.class", "")


class MetaStore:
    """Thread-safe map SourceName -> DataSource, custom type registry and
    source->query reference tracking (MetaStoreImpl.java)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._sources: Dict[str, DataSource] = {}
        self._types: Dict[str, SqlType] = {}
        # referential integrity: source name -> query ids reading / writing it
        self._read_by: Dict[str, Set[str]] = {}
        self._written_by: Dict[str, Set[str]] = {}
        # connector registry (metastore-backed analog of the Connect
        # cluster's connector set so sandbox forks see a consistent view;
        # external Connect calls sit behind services/connect.py)
        self._connectors: Dict[str, "ConnectorInfo"] = {}

    # -------------------------------------------------------------- sources
    def put_source(self, source: DataSource, allow_replace: bool = False) -> None:
        with self._lock:
            existing = self._sources.get(source.name)
            if existing is not None and not allow_replace:
                raise KsqlException(
                    f"Cannot add {source.source_type.lower()} '{source.name}': "
                    f"A {existing.source_type.lower()} with the same name already exists"
                )
            self._sources[source.name] = source

    def get_source(self, name: str) -> Optional[DataSource]:
        with self._lock:
            return self._sources.get(name)

    def require_source(self, name: str) -> DataSource:
        s = self.get_source(name)
        if s is None:
            raise AnalysisException(f"{name} does not exist.")
        return s

    def delete_source(self, name: str, check_constraints: bool = True) -> None:
        with self._lock:
            if name not in self._sources:
                raise KsqlException(f"No data source with name {name} exists.")
            if check_constraints:
                constraints = self.source_constraints(name)
                if constraints:
                    raise KsqlException(
                        f"Cannot drop {name}.\n"
                        "The following queries read from or write to this "
                        f"source: [{', '.join(sorted(constraints))}].\n"
                        f"You need to terminate them before dropping {name}."
                    )
            del self._sources[name]

    def readers_of(self, name: str) -> Set[str]:
        with self._lock:
            return set(self._read_by.get(name, ()))

    def writers_of(self, name: str) -> Set[str]:
        with self._lock:
            return set(self._written_by.get(name, ()))

    def all_sources(self) -> List[DataSource]:
        with self._lock:
            return list(self._sources.values())

    # ------------------------------------------------------- custom types
    def register_type(self, name: str, t: SqlType, if_not_exists: bool = False) -> bool:
        with self._lock:
            key = name.upper()
            if key in self._types:
                if if_not_exists:
                    return False
                raise KsqlException(f"Cannot register custom type '{name}': it already exists")
            self._types[key] = t
            return True

    def drop_type(self, name: str, if_exists: bool = False) -> bool:
        with self._lock:
            key = name.upper()
            if key not in self._types:
                if if_exists:
                    return False
                raise KsqlException(f"Type {name} does not exist")
            del self._types[key]
            return True

    def resolve_type(self, name: str) -> Optional[SqlType]:
        with self._lock:
            return self._types.get(name.upper())

    def all_types(self) -> Dict[str, SqlType]:
        with self._lock:
            return dict(self._types)

    # ------------------------------------------- referential integrity
    def add_source_references(self, query_id: str, reads: List[str], writes: List[str]) -> None:
        with self._lock:
            for s in reads:
                self._read_by.setdefault(s, set()).add(query_id)
            for s in writes:
                self._written_by.setdefault(s, set()).add(query_id)

    def remove_query_references(self, query_id: str) -> None:
        with self._lock:
            for m in (self._read_by, self._written_by):
                for refs in m.values():
                    refs.discard(query_id)

    def source_constraints(self, name: str) -> Set[str]:
        with self._lock:
            return set(self._read_by.get(name, ())) | set(self._written_by.get(name, ()))

    # --------------------------------------------------------------- copy
    def copy(self) -> "MetaStore":
        """Deep-enough copy for sandboxed validation
        (SandboxedExecutionContext forks the metastore)."""
        with self._lock:
            c = MetaStore()
            c._sources = dict(self._sources)
            c._types = dict(self._types)
            c._read_by = {k: set(v) for k, v in self._read_by.items()}
            c._written_by = {k: set(v) for k, v in self._written_by.items()}
            c._connectors = dict(self._connectors)
            return c

    # ----------------------------------------------------------- connectors
    def put_connector(self, info: "ConnectorInfo") -> None:
        with self._lock:
            if info.name in self._connectors:
                raise KsqlException(f"Connector {info.name} already exists")
            self._connectors[info.name] = info

    def get_connector(self, name: str) -> Optional["ConnectorInfo"]:
        with self._lock:
            return self._connectors.get(name)

    def drop_connector(self, name: str) -> None:
        with self._lock:
            if name not in self._connectors:
                raise KsqlException(f"Connector {name} does not exist.")
            del self._connectors[name]

    def list_connectors(self) -> List["ConnectorInfo"]:
        with self._lock:
            return sorted(self._connectors.values(), key=lambda c: c.name)
