"""Kafka Connect client seam.

Analog of DefaultConnectClient (ksqldb-engine/src/main/java/io/confluent/
ksql/services/DefaultConnectClient.java) + the Sandboxed* mirror: the
engine talks to Connect only through this interface, so a real HTTP client
can slot in where the in-process default just validates and echoes.  The
engine-visible connector registry itself lives in the metastore
(metastore.ConnectorInfo) so sandbox forks stay consistent.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

from ksql_tpu.common.errors import KsqlException


class ConnectClient:
    """Interface + in-process default.

    ``create``/``delete`` return None on success and raise KsqlException
    with the Connect error body otherwise (ConnectExecutor.java:48 surfaces
    these verbatim)."""

    def create(self, name: str, config: Dict[str, Any]) -> None:
        if not config.get("connector.class"):
            raise KsqlException(
                "Validation error: Connector config {connector.class=null} "
                "contains no connector type"
            )

    def status(self, name: str) -> str:
        return "RUNNING"

    def delete(self, name: str) -> None:
        return None


class HttpConnectClient(ConnectClient):
    """Real Connect REST client (ksql.connect.url): POST /connectors,
    DELETE /connectors/<name>, GET /connectors/<name>/status."""

    def __init__(self, base_url: str, timeout_s: float = 5.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def _request(self, method: str, path: str, body: Optional[dict] = None):
        req = urllib.request.Request(
            self.base_url + path,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"},
            method=method,
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                raw = resp.read()
                return json.loads(raw) if raw else None
        except urllib.error.HTTPError as e:  # connect error body verbatim
            raise KsqlException(
                f"Failed to {method} {path}: {e.read().decode(errors='replace')}"
            ) from e
        except OSError as e:
            raise KsqlException(
                f"Failed to reach Connect at {self.base_url}: {e}"
            ) from e

    def create(self, name: str, config: Dict[str, Any]) -> None:
        super().create(name, config)
        self._request("POST", "/connectors", {"name": name, "config": config})

    def status(self, name: str) -> str:
        out = self._request("GET", f"/connectors/{name}/status") or {}
        return str(out.get("connector", {}).get("state", "UNKNOWN"))

    def delete(self, name: str) -> None:
        self._request("DELETE", f"/connectors/{name}")


def client_for(config) -> ConnectClient:
    """In-process client unless ksql.connect.url points at a real cluster."""
    url = str(config.get("ksql.connect.url") or "")
    if url:
        return HttpConnectClient(url)
    return ConnectClient()
