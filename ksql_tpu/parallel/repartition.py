"""ICI all-to-all repartition — the repartition-topic replacement.

In the reference, GROUP BY / PARTITION BY with a new key writes every record
to an internal repartition topic and reads it back through the broker
(StreamGroupByBuilderBase.java:39, PartitionByParamsFactory) — a network
round-trip per shuffle.  Here the shuffle is a single XLA all-to-all over
ICI inside ``shard_map``: rows are bucketed by destination shard
(``hash mod n_shards``) into fixed-capacity per-destination lanes, exchanged
in one collective, and land on the device that owns their key's state shard.

Static shapes: each (src, dst) bucket has fixed ``bucket_capacity`` lanes;
rows that overflow a bucket are counted (``overflow``) rather than silently
dropped — the host reacts by lowering batch fill or raising capacity, the
moral analog of broker backpressure.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ksql_tpu.parallel.mesh import SHARD_AXIS


def shard_of(khash: jnp.ndarray, n_shards: int) -> jnp.ndarray:
    """Destination shard for each row.  Uses high bits so the store's slot
    probing (low bits of a different mix) stays decorrelated."""
    u = jax.lax.shift_right_logical(khash, 40)
    return (u % n_shards).astype(jnp.int32)


def np_shard_of(khash, n_shards: int):
    """Host (numpy) replica of :func:`shard_of` — must stay bit-identical;
    used by checkpoint reshard-on-restore to re-partition saved store rows
    under a different mesh size."""
    import numpy as np

    u = np.asarray(khash, np.int64).view(np.uint64) >> np.uint64(40)
    return (u % np.uint64(n_shards)).astype(np.int64)


def all_to_all_exchange(
    payload: Dict[str, jnp.ndarray],
    dest: jnp.ndarray,
    n_shards: int,
    bucket_capacity: int,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Exchange a per-row payload so each row lands on shard ``dest[row]``.

    Must be called inside shard_map over the ``shards`` axis.  Input arrays
    are the local [n] rows; outputs are the local
    [n_shards * bucket_capacity] received rows.  ``payload['active']`` marks
    live lanes in and out.  Returns (received payload, overflow count).
    """
    active = payload["active"]
    n = active.shape[0]
    cap = bucket_capacity
    total = n_shards * cap
    trash = jnp.int32(total)  # scatter sink for inactive/overflowed rows
    target = jnp.full(n, trash, jnp.int32)
    overflow = jnp.zeros((), jnp.int64)
    for d in range(n_shards):
        mask = active & (dest == d)
        idx = jnp.cumsum(mask.astype(jnp.int32)) - 1
        ok = mask & (idx < cap)
        overflow = overflow + jnp.sum(mask & ~ok)
        target = jnp.where(ok, d * cap + idx, target)
    received: Dict[str, jnp.ndarray] = {}
    for name, arr in payload.items():
        buf = jnp.zeros((total + 1,) + arr.shape[1:], arr.dtype)
        buf = buf.at[target].set(arr)
        bucketed = buf[:total].reshape((n_shards, cap) + arr.shape[1:])
        swapped = jax.lax.all_to_all(
            bucketed, SHARD_AXIS, split_axis=0, concat_axis=0
        )
        received[name] = swapped.reshape((total,) + arr.shape[1:])
    return received, overflow
