"""Multi-chip execution: shard_map over the mesh, all-to-all repartition,
device-sharded keyed state.

Layout (SURVEY §2.3 mapping):
* data parallelism — incoming micro-batches carry a leading [n_shards] axis
  split across devices (the Kafka-partition analog);
* shuffle — rows cross to the shard owning their key via one ICI all-to-all
  (parallel/repartition.py), replacing the repartition topic;
* state sharding — every store array carries the same leading axis, so each
  device owns the hash-range of keys that route to it (co-partitioned state,
  exactly Kafka Streams' task/store ownership);
* stream time is per state shard, matching the reference's per-task stream
  time semantics.

Stateless pipelines skip the exchange (pure DP) — the analog of a filter/
project query with no repartition topic.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # JAX ≥ 0.6 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from ksql_tpu.common import faults, tracing
from ksql_tpu.common.batch import HostBatch
from ksql_tpu.compiler.jax_expr import DeviceUnsupported
from ksql_tpu.parallel.mesh import SHARD_AXIS
from ksql_tpu.parallel.repartition import all_to_all_exchange, shard_of
from ksql_tpu.runtime.lowering import CompiledDeviceQuery
from ksql_tpu.runtime.oracle import SinkEmit


def _take_rows(batch: HostBatch, sel: np.ndarray) -> HostBatch:
    """Row-subset view of a host batch (round-robin lanes, table chunks)."""
    return HostBatch(
        schema=batch.schema,
        num_rows=len(sel),
        columns={k: v[sel] for k, v in batch.columns.items()},
        valid={k: v[sel] for k, v in batch.valid.items()},
        timestamps=batch.timestamps[sel],
        partitions=None if batch.partitions is None else batch.partitions[sel],
        offsets=None if batch.offsets is None else batch.offsets[sel],
    )


class DistributedDeviceQuery:
    """A CompiledDeviceQuery executed across a device mesh.

    Beyond the library stepping API (process/process_table/process_ss) this
    also implements the executor-facing host surface DeviceExecutor drives —
    flush/ss_expire_host/flush_pipeline, sharded pull-query serving
    (scan_store / lookup_store routed by ``shard_of(key)``), and per-shard
    runtime stats — so the engine's backend seam can treat a mesh exactly
    like one device.  Attributes not defined here delegate to the wrapped
    CompiledDeviceQuery (plan analysis, layouts, sizing)."""

    #: distributed stepping has no host-side emission pipelining — emits
    #: decode at each sharded step (the all-to-all is the latency hider)
    pipeline = False

    def __init__(
        self,
        compiled: CompiledDeviceQuery,
        mesh: Mesh,
        bucket_capacity: Optional[int] = None,
    ):
        if compiled.suppress:
            raise DeviceUnsupported(
                "EMIT FINAL is not yet distributed (per-shard flush pending); "
                "run it single-device or on the row oracle"
            )
        # stream-stream joins distribute: both sides exchange to the shard
        # owning their join key, whose local ring buffers hold that key's
        # WITHIN-window state (see _build_ss below)
        if len(compiled.join_chain) > 1:
            raise DeviceUnsupported(
                "distributed n-way stream-table join chains pending; run "
                "them single-device"
            )
        if getattr(compiled, "_needs_seq", False):
            raise DeviceUnsupported(
                "distributed EARLIEST/LATEST pending (needs a global arrival "
                "sequence across shards); run them single-device"
            )
        self.c = compiled
        self.mesh = mesh
        self.n_shards = int(np.prod(mesh.devices.shape))
        # capacity × window-expansion is the always-safe bound (a batch that
        # hashes entirely to one shard still fits); production tuning
        # shrinks it and watches the overflow counter
        self.bucket_capacity = bucket_capacity or (
            compiled.capacity * compiled.expansion
        )
        nd = self.n_shards
        # per-shard runtime stats (cumulative; occupancy is last-observed) —
        # surfaced through /metrics by DistributedDeviceExecutor
        self.shard_rows_in = np.zeros(nd, np.int64)
        self.shard_rows_out = np.zeros(nd, np.int64)
        self.shard_exchange_rows = np.zeros(nd, np.int64)
        self.shard_store_occupancy = np.zeros(nd, np.int64)
        # per-shard event-time watermark (max record timestamp a shard's
        # lane ingested; -1 = nothing yet) — folds to the per-query
        # watermark in /query-lag and spots a starved/skewed lane
        self.shard_watermark_ms = np.full(nd, -1, np.int64)
        self.last_pull_slots_decoded = 0
        self.shards_touched_last_pull: List[int] = []
        # per-row wire estimate for the all-to-all payload (8B data + 1B
        # mask per layout column, plus ts/khash/active lanes) — feeds the
        # flight recorder's exchange-bytes counter; the exchange itself is
        # fused inside the jitted step, so bytes are derived, not measured
        self._exch_row_bytes = 9 * len(compiled.layout.specs) + 24
        self._qid = str(getattr(compiled.plan, "query_id", "") or "")
        # suspect-shard marker: set while a shard lane's host-side dispatch
        # section runs, cleared when the per-shard section completes.  A
        # hang wedged inside the ``mesh.shard.dispatch`` seam leaves it
        # set, so the tick-deadline watchdog can attribute the blown
        # deadline to the exact lane (engine mesh fault-domain containment)
        self.current_shard: Optional[int] = None
        self._build_steps()
        self.state = self.init_state()

    def _shard_fault_point(self, shard: int) -> None:
        """Per-shard-lane chaos seam (``mesh.shard.dispatch``, context
        ``<qid>#<shard>#`` so a rule can target one lane).  A raise is
        stamped with ``mesh_shard`` so the engine's strike bookkeeping can
        contain the failure to this shard; a hang sleeps with
        ``current_shard`` still set for the same attribution."""
        self.current_shard = shard
        try:
            faults.fault_point(
                "mesh.shard.dispatch", f"{self._qid}#{shard}#"
            )
        except Exception as e:  # noqa: BLE001 — annotate + re-raise
            e.mesh_shard = shard
            raise

    def jit_cache_entries(self) -> int:
        """Sharded-step jit cache entries + the wrapped compiled query's —
        the executor's compile-vs-execute split samples this around each
        device call (see DeviceExecutor._device_step)."""
        fns = [
            self.__dict__.get("_step"),
            self.__dict__.get("_ss_expire"),
            self.__dict__.get("_table_step"),
            self.__dict__.get("_evict"),
        ]
        fns.extend((self.__dict__.get("_ss_steps") or {}).values())
        return self.c.jit_cache_entries() + tracing.jit_cache_size(fns)

    def __getattr__(self, name: str):
        # executor-facing delegation: anything not distributed-specific
        # reads through to the wrapped compiled query
        c = self.__dict__.get("c")
        if c is None or name.startswith("_"):
            raise AttributeError(name)
        return getattr(c, name)

    @property
    def capacity(self) -> int:
        """Host micro-batch capacity: the mesh absorbs ``n_shards`` lanes of
        the compiled per-shard capacity per step."""
        return self.n_shards * self.c.capacity

    def _build_steps(self) -> None:
        """(Re)build the jitted shard_map steps — also called by checkpoint
        restore after store capacities change."""
        compiled = self.c
        mesh = self.mesh
        nd = self.n_shards
        import jax.tree_util as jtu

        def strip(tree):
            return jtu.tree_map(lambda v: v[0], tree)

        def add_axis(tree):
            return jtu.tree_map(lambda v: v[None], tree)

        def local_step(state, arrays):
            state = strip(state)
            arrays = strip(arrays)
            if self.c.agg is None:
                state, emits = self.c._trace_step(state, arrays)
                emits["exch_rows"] = jnp.zeros((), jnp.int64)
            elif self.c.session:
                # SESSION windows: same exchange discipline as fixed
                # windows — per-row phase locally, rows cross to the shard
                # owning their key, the interval-merge runs shard-local
                payload = self.c.pre_session_exchange(state["max_ts"], arrays)
                dest = shard_of(payload["khash"], nd)
                recv, ovf = all_to_all_exchange(
                    payload, dest, nd, self.bucket_capacity
                )
                exch = jnp.sum(recv["active"].astype(jnp.int64))
                state, emits = self.c.post_session_exchange(state, recv)
                state["overflow"] = state["overflow"] + ovf
                emits["overflow"] = state["overflow"]
                emits["exch_rows"] = exch
            else:
                payload = self.c.pre_exchange(
                    state["max_ts"], arrays,
                    jtabs=(
                        self.c._jtabs_of(state) if self.c.join_chain else None
                    ),
                )
                dest = shard_of(payload["khash"], nd)
                recv, ovf = all_to_all_exchange(
                    payload, dest, nd, self.bucket_capacity
                )
                exch = jnp.sum(recv["active"].astype(jnp.int64))
                state, emits = self.c.post_exchange(state, recv)
                # fold exchange overflow in before emits surface it, so the
                # batch that dropped rows is the batch that reports them
                state["overflow"] = state["overflow"] + ovf
                emits["overflow"] = state["overflow"]
                emits["exch_rows"] = exch
            return add_axis(state), add_axis(emits)

        def build_step():
            # sessions stay undonated: a sess_ovf retry re-runs the same
            # state after growing session_slots (mirrors the single-device
            # process_arrays retry loop)
            return jax.jit(
                shard_map(
                    local_step,
                    mesh=mesh,
                    in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                    out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                ),
                donate_argnums=() if compiled.session else (0,),
            )

        self._build_step = build_step
        self._step = None
        if compiled.ss_join is None:
            self._step = build_step()

        if compiled.ss_join is not None:
            # per-side sharded ss-join step: route rows by join-key hash,
            # then run the ordinary buffer step shard-local (the trace is
            # shape-generic over the received width)
            def make_ss(side):
                trace = (
                    self.c._trace_ss_l if side == "l" else self.c._trace_ss_r
                )

                def local_ss(state, arrays):
                    state = strip(state)
                    arrays = strip(arrays)
                    khash, active = self.c.ss_routing_hash(side, arrays)
                    dest = shard_of(khash, nd)
                    payload = dict(arrays)
                    # only rows surviving this side's pre-op filters cross
                    # the ICI — dropped rows must not burn bucket slots;
                    # 'active' replaces (not duplicates) the row_valid lane
                    payload["active"] = payload.pop("row_valid") & active
                    # ...but every ingested row's timestamp still advances
                    # stream time everywhere (single-device cm_global/smax
                    # advance from pre-filter row_valid rows): pmax the
                    # batch max across shards and fold it in post-step
                    neg = jnp.asarray(np.iinfo(np.int64).min, jnp.int64)
                    batch_max = jnp.max(
                        jnp.where(arrays["row_valid"], arrays["ts"], neg)
                    )
                    gmax = jax.lax.pmax(batch_max, SHARD_AXIS)
                    recv, ovf = all_to_all_exchange(
                        payload, dest, nd, self.bucket_capacity
                    )
                    exch = jnp.sum(recv["active"].astype(jnp.int64))
                    recv["row_valid"] = recv.pop("active")
                    state, emits = trace(state, recv)
                    state["max_ts"] = jnp.maximum(state["max_ts"], gmax)
                    smax_key = f"ss{side}_smax"
                    state[smax_key] = jnp.maximum(state[smax_key], gmax)
                    emits["ss_exch_ovf"] = ovf
                    emits["exch_rows"] = exch
                    return add_axis(state), add_axis(emits)

                return jax.jit(
                    shard_map(
                        local_ss,
                        mesh=mesh,
                        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                    ),
                    donate_argnums=0,
                )

            def local_ss_expire(state):
                state, emits = self.c._trace_ss_expire(strip(state))
                return add_axis(state), add_axis(emits)

            self._ss_steps = {"l": make_ss("l"), "r": make_ss("r")}
            self._ss_expire = jax.jit(
                shard_map(
                    local_ss_expire,
                    mesh=mesh,
                    in_specs=(P(SHARD_AXIS),),
                    out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                ),
                donate_argnums=0,
            )

        if compiled.join is not None:
            # the join table store is REPLICATED: every shard folds the same
            # full table batch into its local copy (broadcast changelog —
            # the GlobalKTable analog), so stream-side probes stay local and
            # no join-key exchange is needed.  The batch ships pre-stacked
            # [n_shards, ...] (one identical lane per shard) so every array
            # entering the trace is device-varying — jax.lax.pcast, the
            # in-trace replicated→varying cast, only exists on newer jax
            def local_table_step(state, arrays):
                state, emits = self.c._trace_table_step(
                    strip(state), strip(arrays)
                )
                return add_axis(state), add_axis(emits)

            self._table_step = jax.jit(
                shard_map(
                    local_table_step,
                    mesh=mesh,
                    in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                    out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                ),
                donate_argnums=0,
            )

        def local_evict(state):
            state = self.c._trace_evict(strip(state))
            return add_axis(state)

        self._evict = jax.jit(
            shard_map(
                local_evict,
                mesh=mesh,
                in_specs=(P(SHARD_AXIS),),
                out_specs=P(SHARD_AXIS),
            ),
            donate_argnums=0,
        )

    def init_state(self) -> Dict[str, jnp.ndarray]:
        import jax.tree_util as jtu

        base = self.c.init_state()
        spec = NamedSharding(self.mesh, P(SHARD_AXIS))
        return jtu.tree_map(
            lambda v: jax.device_put(
                jnp.broadcast_to(v[None], (self.n_shards,) + v.shape), spec
            ),
            base,
        )

    def device_state_bytes(self) -> Dict[str, int]:
        """PER-SHARD live state bytes per memory-model component (the
        leading ``[n_shards]`` axis divided out), matching the model's
        per-shard report point — total device bytes are ``n_shards x``
        these.  Same single classification loop as the single-device
        seam (analysis/mem_model.measure_state_bytes)."""
        from ksql_tpu.analysis.mem_model import measure_state_bytes

        return {
            comp: b // self.n_shards
            for comp, b in measure_state_bytes(
                self.state, sliced=self.c.sliced
            ).items()
        }

    def process_table(
        self,
        batch: HostBatch,
        deletes: Optional[np.ndarray] = None,
        idx: int = -1,
    ) -> None:
        """Fold one table-changelog batch into every shard's replica.
        ``idx`` matches the executor's join-chain routing signature — only
        single-probe chains distribute, so it is accepted and ignored."""
        if faults.armed():
            # the broadcast changelog folds into EVERY shard's replica:
            # each lane is a dispatch seam (a one-lane rule models one
            # replica's fold failing)
            faults.fault_point("mesh.encode", self._qid)
            for d in range(self.n_shards):
                self._shard_fault_point(d)
            self.current_shard = None
        cap = self.c.capacity
        for start in range(0, max(batch.num_rows, 1), cap):
            sel = np.arange(start, min(start + cap, batch.num_rows))
            hb = _take_rows(batch, sel) if batch.num_rows > cap else batch
            arrays = self.c.table_layout.encode(hb)
            pad = np.zeros(cap, bool)
            if deletes is not None:
                chunk_del = np.asarray(deletes)[sel]
                pad[: len(chunk_del)] = chunk_del
            arrays["delete"] = pad
            # one identical lane per shard (broadcast changelog)
            nd = self.n_shards
            arrays = {
                k: np.ascontiguousarray(
                    np.broadcast_to(v[None], (nd,) + np.asarray(v).shape)
                )
                for k, v in arrays.items()
            }
            tracing.counter(
                "device.transfer",
                h2d_bytes=int(sum(v.nbytes for v in arrays.values())),
            )
            self.state, metrics = self._table_step(self.state, arrays)
        occ = int(np.asarray(metrics["occupancy"]).max())
        if occ > 0.6 * self.c.table_store_capacity:
            raise RuntimeError(
                "replicated join-table store nearing capacity "
                f"({occ}/{self.c.table_store_capacity}); restart with a "
                "larger table_store_capacity"
            )

    # ------------------------------------------------------------- host API
    def encode(self, batch: HostBatch, layout=None) -> Dict[str, np.ndarray]:
        """Split one host batch round-robin across shards and stack to the
        [n_shards, capacity] layout."""
        nd = self.n_shards
        layout = layout or self.c.layout
        armed = faults.armed()
        if armed:
            faults.fault_point("mesh.encode", self._qid)
        ts = np.asarray(batch.timestamps) if batch.num_rows else None
        stacked: Dict[str, List[np.ndarray]] = {}
        for d in range(nd):
            if armed:
                self._shard_fault_point(d)
            sel = np.arange(d, batch.num_rows, nd)
            self.shard_rows_in[d] += len(sel)
            if ts is not None and len(sel):
                self.shard_watermark_ms[d] = max(
                    self.shard_watermark_ms[d], int(ts[sel].max())
                )
            arrays = layout.encode(_take_rows(batch, sel))
            for k, v in arrays.items():
                stacked.setdefault(k, []).append(v)
        if armed:
            # lane split complete: later failures in this tick (exchange,
            # XLA step) are whole-mesh, not attributable to the last lane
            self.current_shard = None
        out = {k: np.stack(vs) for k, vs in stacked.items()}
        tracing.counter(
            "device.transfer",
            h2d_bytes=int(sum(v.nbytes for v in out.values())),
        )
        return out

    def _account(self, emits: Dict[str, jnp.ndarray]) -> None:
        """Fold one sharded step's emits into the per-shard stat gauges."""
        if faults.armed():
            # whole-collective seam: the all-to-all is fused inside the
            # jitted step, so its host boundary is this accounting pass —
            # a raise here is NOT shard-attributable (ordinary ladder)
            faults.fault_point("mesh.exchange", self._qid)
        nd = self.n_shards
        if "emit_mask" in emits:
            self.shard_rows_out += (
                np.asarray(emits["emit_mask"]).reshape(nd, -1).sum(axis=1)
            )
        if "exch_rows" in emits:
            per_shard = (
                np.asarray(emits["exch_rows"]).reshape(nd).astype(np.int64)
            )
            self.shard_exchange_rows += per_shard
            total = int(per_shard.sum())
            if total:
                # fused into the sharded step, so no separate timing — the
                # volume counters are what EXPLAIN ANALYZE / Prometheus need
                tracing.counter(
                    "exchange", rows=total,
                    bytes=total * self._exch_row_bytes,
                )
        if "occupancy" in emits:
            self.shard_store_occupancy = (
                np.asarray(emits["occupancy"]).reshape(nd).astype(np.int64)
            )

    def process_ss(self, batch: HostBatch, side: str) -> List[SinkEmit]:
        """One side's micro-batch through the sharded stream-stream join:
        key exchange, then the ordinary ring-buffer step shard-local.
        Buffer/match-cap sizing is fixed at construction in distributed
        mode — overflow stops loudly rather than resizing online."""
        layout = self.c.layout if side == "l" else self.c.right_layout
        arrays = self.encode(batch, layout=layout)
        self.state, emits = self._ss_steps[side](self.state, arrays)
        self._account(emits)
        lost = int(np.asarray(emits["ss_lost"]).sum())
        movf = int(np.asarray(emits["ss_matchovf"]).sum())
        xovf = int(np.asarray(emits["ss_exch_ovf"]).sum())
        if lost or movf or xovf:
            raise RuntimeError(
                "distributed ss-join overflow "
                f"(ring lost={lost}, match cap={movf}, exchange={xovf}); "
                "restart with larger ss_buffer_capacity / ss_out_capacity / "
                "bucket_capacity"
            )
        out = self.c._decode_emits(self._flatten(emits))
        # record-driven time advance: expire the shard-local buffers AFTER
        # matching, emitting deferred GRACE null-pads (the executor's
        # ss_expire_host cadence — oracle _advance_time after each record)
        out.extend(self.ss_expire_host())
        return out

    @staticmethod
    def _flatten(emits: Dict[str, jnp.ndarray]) -> Dict[str, np.ndarray]:
        """[n_shards, n, ...] emits → the flat [n_shards*n, ...] layout the
        compiled query's emission decoder expects."""
        return {
            k: np.asarray(v).reshape((-1,) + np.asarray(v).shape[2:])
            for k, v in emits.items()
        }

    def process_columns(
        self, n, columns, timestamps, offsets=None, partitions=None,
    ) -> List[SinkEmit]:
        """Mesh-aware native-ingest entry: split decoded (data, valid)
        column slices round-robin into per-shard lanes and run the
        sharded step — the columnar analog of encode() + process(), with
        the same fault seams and per-shard accounting.  Each lane is
        assembled at the per-shard static shape; assemble COPIES the
        decoder's slices into fresh padded buffers, so they are never
        aliased into donated jit state."""
        nd = self.n_shards
        layout = self.c.layout
        armed = faults.armed()
        if armed:
            faults.fault_point("mesh.encode", self._qid)
        ts = np.asarray(timestamps, np.int64)
        offs = (
            np.asarray(offsets, np.int64)
            if offsets is not None else np.zeros(n, np.int64)
        )
        parts = (
            np.asarray(partitions, np.int32)
            if partitions is not None else np.zeros(n, np.int32)
        )
        stacked: Dict[str, List[np.ndarray]] = {}
        for d in range(nd):
            if armed:
                self._shard_fault_point(d)
            sel = np.arange(d, n, nd)
            self.shard_rows_in[d] += len(sel)
            if len(sel):
                self.shard_watermark_ms[d] = max(
                    self.shard_watermark_ms[d], int(ts[sel].max())
                )
            lane = {k: (v[sel], ok[sel]) for k, (v, ok) in columns.items()}
            arrays = layout.assemble(
                len(sel), lane, ts[sel],
                offsets=offs[sel], partitions=parts[sel],
            )
            for k, v in arrays.items():
                stacked.setdefault(k, []).append(v)
        if armed:
            # lane split complete: later failures in this tick (exchange,
            # XLA step) are whole-mesh, not attributable to the last lane
            self.current_shard = None
        out = {k: np.stack(vs) for k, vs in stacked.items()}
        tracing.counter(
            "device.transfer",
            h2d_bytes=int(sum(v.nbytes for v in out.values())),
        )
        return self._process_encoded(out)

    _seen_overflow = 0
    _batches = 0

    def process(self, batch: HostBatch) -> List[SinkEmit]:
        if self.c.ss_join is not None:
            return self.process_ss(batch, "l")
        return self._process_encoded(self.encode(batch))

    def _process_encoded(self, arrays: Dict[str, np.ndarray]) -> List[SinkEmit]:
        """The sharded step over already-lane-split arrays: session
        slot-growth retry, per-shard accounting, eviction cadence and
        overflow tripwires — shared by process() and process_columns()."""
        if self.c.session:
            while True:
                new_state, emits = self._step(self.state, arrays)
                if int(np.asarray(emits["sess_ovf"]).sum()) > 0:
                    # more concurrent sessions per key than tracked slots on
                    # some shard: grow, recompile the sharded step, re-run
                    self.c.session_slots *= 2
                    self._step = self._build_step()
                    continue
                break
            self.state = new_state
        else:
            self.state, emits = self._step(self.state, arrays)
        self._account(emits)
        if self.c.agg is not None:
            self._batches += 1
            if (
                self.c.retention_ms is not None
                and self._batches % self.c.EVICT_INTERVAL == 0
            ):
                self.state = self._evict(self.state)
            overflow = int(np.asarray(emits["overflow"]).sum())
            if overflow > self._seen_overflow:
                self._seen_overflow = overflow
                raise RuntimeError(
                    f"sharded state store / exchange overflowed ({overflow} "
                    "rows lost); raise store_capacity or bucket_capacity"
                )
            # online distributed growth is not implemented yet: stop loudly
            # BEFORE loss once any shard nears saturation
            occ = int(np.asarray(emits["occupancy"]).max())
            if occ > 0.6 * self.c.store_capacity:
                raise RuntimeError(
                    "sharded state store nearing capacity "
                    f"({occ}/{self.c.store_capacity} on the fullest shard); "
                    "restart the query with a larger store_capacity"
                )
        return self.c._decode_emits(self._flatten(emits))

    # -------------------------------------------------- executor-facing API
    def flush_pipeline(self) -> List[SinkEmit]:
        """No deferred emissions in distributed mode (pipeline = False)."""
        return []

    def ss_expire_host(self) -> List[SinkEmit]:
        """Expire the shard-local ss-join ring buffers (deferred GRACE
        null-pads) — the drain-tick analog of CompiledDeviceQuery's."""
        self.state, emits = self._ss_expire(self.state)
        return self.c._decode_emits(self._flatten(emits))

    def flush(self, stream_time: Optional[int] = None) -> List[SinkEmit]:
        """Advance event time explicitly.  EMIT FINAL never reaches the
        distributed runner (rejected at construction); only ss-joins hold
        time-gated emission state to flush."""
        if self.c.ss_join is None:
            return []
        if faults.armed():
            faults.fault_point("mesh.exchange", self._qid)
        if stream_time is not None:
            state = dict(self.state)
            state["max_ts"] = jnp.maximum(
                state["max_ts"], jnp.asarray(stream_time, jnp.int64)
            )
            for side in ("l", "r"):
                k = f"ss{side}_smax"
                state[k] = jnp.maximum(
                    state[k], jnp.asarray(stream_time, jnp.int64)
                )
            self.state = state
        return self.ss_expire_host()

    # ------------------------------------------------- sharded pull serving
    def _shard_state_view(self, shard: int) -> Dict[str, jnp.ndarray]:
        import jax.tree_util as jtu

        return jtu.tree_map(lambda v: jnp.asarray(np.asarray(v[shard])),
                            self.state)

    def _with_shard_state(self, shard: int, fn):
        """Run ``fn()`` with the compiled query's state pointed at one
        shard's slice (read-only use: pull serving).

        The zero-copy shard view is deliberate: in distributed mode the
        wrapped compiled query's own (donating) step functions are never
        invoked — only scan/lookup run against this state, op-by-op with
        no donation — and copying the full shard store per pull would put
        an O(store) tax on the read path."""
        saved = self.c._state
        self.c.state = self._shard_state_view(shard)  # graftlint: disable=donated-aliasing
        try:
            return fn()
        finally:
            self.c._state = saved

    def shard_of_key(self, reprs: List[int]) -> int:
        """Owning shard for a key given its 64-bit column reprs — the same
        hash + high-bit routing the exchange uses (pre_exchange/shard_of)."""
        from ksql_tpu.ops.hash_store import combine_hash

        parts = [jnp.asarray([r], jnp.int64) for r in reprs]
        parts.append(jnp.zeros(1, jnp.int64))  # knull: stored keys are 0
        khash = combine_hash(parts)
        return int(np.asarray(shard_of(khash, self.n_shards))[0])

    def scan_store(self) -> List[SinkEmit]:
        """Materialized-state scan across every shard's store slice."""
        out: List[SinkEmit] = []
        decoded = 0
        for s in range(self.n_shards):
            out.extend(self._with_shard_state(s, self.c.scan_store))
            decoded += self.c.last_pull_slots_decoded
        self.last_pull_slots_decoded = decoded
        self.shards_touched_last_pull = list(range(self.n_shards))
        return out

    def lookup_store(self, key_tuples) -> Optional[List[SinkEmit]]:
        """Keyed pull fast path over the mesh: route each key to
        ``shard_of(key)`` and probe ONLY the owning shards' stores.  Returns
        None when a key has no 64-bit repr (caller falls back to scan)."""
        from ksql_tpu.runtime.lowering import _host_repr64

        if self.c.store_layout is None:
            return None
        by_shard: Dict[int, list] = {}
        for kt in key_tuples:
            reprs = []
            for v, t in zip(kt, self.c.key_types):
                r = _host_repr64(v, t)
                if r is None:
                    return None
                reprs.append(r)
            by_shard.setdefault(self.shard_of_key(reprs), []).append(kt)
        out: List[SinkEmit] = []
        decoded = 0
        for s in sorted(by_shard):
            kts = by_shard[s]
            got = self._with_shard_state(s, lambda: self.c.lookup_store(kts))
            if got is None:
                return None
            decoded += self.c.last_pull_slots_decoded
            out.extend(got)
        self.last_pull_slots_decoded = decoded
        self.shards_touched_last_pull = sorted(by_shard)
        return out

    def changelog_dirty_state(self) -> Dict[str, Any]:
        """Dirty-set seam for the incremental changelog journal
        (runtime/changelog.py): per-shard host capture (leading
        [n_shards] axis preserved) in checkpoint-serde shape, diffed
        against the previous tick by the journal."""
        from ksql_tpu.runtime.checkpoint import _snapshot_device_dist

        return _snapshot_device_dist(self)

    def changelog_apply_state(self, data: Dict[str, Any]) -> None:
        """Restore a (possibly journal-patched) capture; arrays re-enter
        through _unflatten_state's jnp.array copy so journal-decoded
        buffers never alias donated jit state."""
        from ksql_tpu.runtime.checkpoint import _restore_device_dist

        _restore_device_dist(self, data)
