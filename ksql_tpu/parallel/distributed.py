"""Multi-chip execution: shard_map over the mesh, all-to-all repartition,
device-sharded keyed state.

Layout (SURVEY §2.3 mapping):
* data parallelism — incoming micro-batches carry a leading [n_shards] axis
  split across devices (the Kafka-partition analog);
* shuffle — rows cross to the shard owning their key via one ICI all-to-all
  (parallel/repartition.py), replacing the repartition topic;
* state sharding — every store array carries the same leading axis, so each
  device owns the hash-range of keys that route to it (co-partitioned state,
  exactly Kafka Streams' task/store ownership);
* stream time is per state shard, matching the reference's per-task stream
  time semantics.

Stateless pipelines skip the exchange (pure DP) — the analog of a filter/
project query with no repartition topic.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # JAX ≥ 0.6 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from ksql_tpu.common.batch import HostBatch
from ksql_tpu.compiler.jax_expr import DeviceUnsupported
from ksql_tpu.parallel.mesh import SHARD_AXIS
from ksql_tpu.parallel.repartition import all_to_all_exchange, shard_of
from ksql_tpu.runtime.lowering import CompiledDeviceQuery
from ksql_tpu.runtime.oracle import SinkEmit


class DistributedDeviceQuery:
    """A CompiledDeviceQuery executed across a device mesh."""

    def __init__(
        self,
        compiled: CompiledDeviceQuery,
        mesh: Mesh,
        bucket_capacity: Optional[int] = None,
    ):
        if compiled.suppress:
            raise DeviceUnsupported(
                "EMIT FINAL is not yet distributed (per-shard flush pending); "
                "run it single-device or on the row oracle"
            )
        # stream-stream joins distribute: both sides exchange to the shard
        # owning their join key, whose local ring buffers hold that key's
        # WITHIN-window state (see _build_ss below)
        if len(compiled.join_chain) > 1:
            raise DeviceUnsupported(
                "distributed n-way stream-table join chains pending; run "
                "them single-device"
            )
        if getattr(compiled, "_needs_seq", False):
            raise DeviceUnsupported(
                "distributed EARLIEST/LATEST pending (needs a global arrival "
                "sequence across shards); run them single-device"
            )
        self.c = compiled
        self.mesh = mesh
        self.n_shards = int(np.prod(mesh.devices.shape))
        # capacity × window-expansion is the always-safe bound (a batch that
        # hashes entirely to one shard still fits); production tuning
        # shrinks it and watches the overflow counter
        self.bucket_capacity = bucket_capacity or (
            compiled.capacity * compiled.expansion
        )
        nd = self.n_shards
        import jax.tree_util as jtu

        def strip(tree):
            return jtu.tree_map(lambda v: v[0], tree)

        def add_axis(tree):
            return jtu.tree_map(lambda v: v[None], tree)

        def local_step(state, arrays):
            state = strip(state)
            arrays = strip(arrays)
            if self.c.agg is None:
                state, emits = self.c._trace_step(state, arrays)
            elif self.c.session:
                # SESSION windows: same exchange discipline as fixed
                # windows — per-row phase locally, rows cross to the shard
                # owning their key, the interval-merge runs shard-local
                payload = self.c.pre_session_exchange(state["max_ts"], arrays)
                dest = shard_of(payload["khash"], nd)
                recv, ovf = all_to_all_exchange(
                    payload, dest, nd, self.bucket_capacity
                )
                state, emits = self.c.post_session_exchange(state, recv)
                state["overflow"] = state["overflow"] + ovf
                emits["overflow"] = state["overflow"]
            else:
                payload = self.c.pre_exchange(
                    state["max_ts"], arrays,
                    jtabs=(
                        self.c._jtabs_of(state) if self.c.join_chain else None
                    ),
                )
                dest = shard_of(payload["khash"], nd)
                recv, ovf = all_to_all_exchange(
                    payload, dest, nd, self.bucket_capacity
                )
                state, emits = self.c.post_exchange(state, recv)
                # fold exchange overflow in before emits surface it, so the
                # batch that dropped rows is the batch that reports them
                state["overflow"] = state["overflow"] + ovf
                emits["overflow"] = state["overflow"]
            return add_axis(state), add_axis(emits)

        def build_step():
            # sessions stay undonated: a sess_ovf retry re-runs the same
            # state after growing session_slots (mirrors the single-device
            # process_arrays retry loop)
            return jax.jit(
                shard_map(
                    local_step,
                    mesh=mesh,
                    in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                    out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                ),
                donate_argnums=() if compiled.session else (0,),
            )

        self._build_step = build_step
        self._step = None
        if compiled.ss_join is None:
            self._step = build_step()

        if compiled.ss_join is not None:
            # per-side sharded ss-join step: route rows by join-key hash,
            # then run the ordinary buffer step shard-local (the trace is
            # shape-generic over the received width)
            def make_ss(side):
                trace = (
                    self.c._trace_ss_l if side == "l" else self.c._trace_ss_r
                )

                def local_ss(state, arrays):
                    state = strip(state)
                    arrays = strip(arrays)
                    khash, active = self.c.ss_routing_hash(side, arrays)
                    dest = shard_of(khash, nd)
                    payload = dict(arrays)
                    # only rows surviving this side's pre-op filters cross
                    # the ICI — dropped rows must not burn bucket slots;
                    # 'active' replaces (not duplicates) the row_valid lane
                    payload["active"] = payload.pop("row_valid") & active
                    # ...but every ingested row's timestamp still advances
                    # stream time everywhere (single-device cm_global/smax
                    # advance from pre-filter row_valid rows): pmax the
                    # batch max across shards and fold it in post-step
                    neg = jnp.asarray(np.iinfo(np.int64).min, jnp.int64)
                    batch_max = jnp.max(
                        jnp.where(arrays["row_valid"], arrays["ts"], neg)
                    )
                    gmax = jax.lax.pmax(batch_max, SHARD_AXIS)
                    recv, ovf = all_to_all_exchange(
                        payload, dest, nd, self.bucket_capacity
                    )
                    recv["row_valid"] = recv.pop("active")
                    state, emits = trace(state, recv)
                    state["max_ts"] = jnp.maximum(state["max_ts"], gmax)
                    smax_key = f"ss{side}_smax"
                    state[smax_key] = jnp.maximum(state[smax_key], gmax)
                    emits["ss_exch_ovf"] = ovf
                    return add_axis(state), add_axis(emits)

                return jax.jit(
                    shard_map(
                        local_ss,
                        mesh=mesh,
                        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                    ),
                    donate_argnums=0,
                )

            def local_ss_expire(state):
                state, emits = self.c._trace_ss_expire(strip(state))
                return add_axis(state), add_axis(emits)

            self._ss_steps = {"l": make_ss("l"), "r": make_ss("r")}
            self._ss_expire = jax.jit(
                shard_map(
                    local_ss_expire,
                    mesh=mesh,
                    in_specs=(P(SHARD_AXIS),),
                    out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                ),
                donate_argnums=0,
            )

        if compiled.join is not None:
            # the join table store is REPLICATED: every shard folds the same
            # full table batch into its local copy (broadcast changelog —
            # the GlobalKTable analog), so stream-side probes stay local and
            # no join-key exchange is needed
            def local_table_step(state, arrays):
                # the replicated batch must become device-varying before it
                # meets the (varying) store in probe_insert's loop carries
                arrays = jtu.tree_map(
                    lambda v: jax.lax.pcast(v, (SHARD_AXIS,), to="varying"),
                    arrays,
                )
                state, emits = self.c._trace_table_step(strip(state), arrays)
                return add_axis(state), add_axis(emits)

            self._table_step = jax.jit(
                shard_map(
                    local_table_step,
                    mesh=mesh,
                    in_specs=(P(SHARD_AXIS), P()),
                    out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                ),
                donate_argnums=0,
            )

        def local_evict(state):
            state = self.c._trace_evict(strip(state))
            return add_axis(state)

        self._evict = jax.jit(
            shard_map(
                local_evict,
                mesh=mesh,
                in_specs=(P(SHARD_AXIS),),
                out_specs=P(SHARD_AXIS),
            ),
            donate_argnums=0,
        )
        self.state = self.init_state()

    def init_state(self) -> Dict[str, jnp.ndarray]:
        import jax.tree_util as jtu

        base = self.c.init_state()
        spec = NamedSharding(self.mesh, P(SHARD_AXIS))
        return jtu.tree_map(
            lambda v: jax.device_put(
                jnp.broadcast_to(v[None], (self.n_shards,) + v.shape), spec
            ),
            base,
        )

    def process_table(
        self, batch: HostBatch, deletes: Optional[np.ndarray] = None
    ) -> None:
        """Fold one table-changelog batch into every shard's replica."""
        arrays = self.c.table_layout.encode(batch)
        pad = np.zeros(self.c.capacity, bool)
        if deletes is not None:
            pad[: len(deletes)] = deletes
        arrays["delete"] = pad
        self.state, metrics = self._table_step(self.state, arrays)
        occ = int(np.asarray(metrics["occupancy"]).max())
        if occ > 0.6 * self.c.table_store_capacity:
            raise RuntimeError(
                "replicated join-table store nearing capacity "
                f"({occ}/{self.c.table_store_capacity}); restart with a "
                "larger table_store_capacity"
            )

    # ------------------------------------------------------------- host API
    def encode(self, batch: HostBatch, layout=None) -> Dict[str, np.ndarray]:
        """Split one host batch round-robin across shards and stack to the
        [n_shards, capacity] layout."""
        nd = self.n_shards
        layout = layout or self.c.layout
        stacked: Dict[str, List[np.ndarray]] = {}
        for d in range(nd):
            sel = np.arange(d, batch.num_rows, nd)
            hb = HostBatch(
                schema=batch.schema,
                num_rows=len(sel),
                columns={k: v[sel] for k, v in batch.columns.items()},
                valid={k: v[sel] for k, v in batch.valid.items()},
                timestamps=batch.timestamps[sel],
                partitions=None if batch.partitions is None else batch.partitions[sel],
                offsets=None if batch.offsets is None else batch.offsets[sel],
            )
            arrays = layout.encode(hb)
            for k, v in arrays.items():
                stacked.setdefault(k, []).append(v)
        return {k: np.stack(vs) for k, vs in stacked.items()}

    def process_ss(self, batch: HostBatch, side: str) -> List[SinkEmit]:
        """One side's micro-batch through the sharded stream-stream join:
        key exchange, then the ordinary ring-buffer step shard-local.
        Buffer/match-cap sizing is fixed at construction in distributed
        mode — overflow stops loudly rather than resizing online."""
        layout = self.c.layout if side == "l" else self.c.right_layout
        arrays = self.encode(batch, layout=layout)
        self.state, emits = self._ss_steps[side](self.state, arrays)
        lost = int(np.asarray(emits["ss_lost"]).sum())
        movf = int(np.asarray(emits["ss_matchovf"]).sum())
        xovf = int(np.asarray(emits["ss_exch_ovf"]).sum())
        if lost or movf or xovf:
            raise RuntimeError(
                "distributed ss-join overflow "
                f"(ring lost={lost}, match cap={movf}, exchange={xovf}); "
                "restart with larger ss_buffer_capacity / ss_out_capacity / "
                "bucket_capacity"
            )
        flat = {k: np.asarray(v).reshape((-1,) + np.asarray(v).shape[2:])
                for k, v in emits.items()}
        out = self.c._decode_emits(flat)
        # record-driven time advance: expire the shard-local buffers AFTER
        # matching, emitting deferred GRACE null-pads (the executor's
        # ss_expire_host cadence — oracle _advance_time after each record)
        self.state, xemits = self._ss_expire(self.state)
        xflat = {k: np.asarray(v).reshape((-1,) + np.asarray(v).shape[2:])
                 for k, v in xemits.items()}
        out.extend(self.c._decode_emits(xflat))
        return out

    _seen_overflow = 0
    _batches = 0

    def process(self, batch: HostBatch) -> List[SinkEmit]:
        if self.c.ss_join is not None:
            return self.process_ss(batch, "l")
        arrays = self.encode(batch)
        if self.c.session:
            while True:
                new_state, emits = self._step(self.state, arrays)
                if int(np.asarray(emits["sess_ovf"]).sum()) > 0:
                    # more concurrent sessions per key than tracked slots on
                    # some shard: grow, recompile the sharded step, re-run
                    self.c.session_slots *= 2
                    self._step = self._build_step()
                    continue
                break
            self.state = new_state
        else:
            self.state, emits = self._step(self.state, arrays)
        if self.c.agg is not None:
            self._batches += 1
            if (
                self.c.retention_ms is not None
                and self._batches % self.c.EVICT_INTERVAL == 0
            ):
                self.state = self._evict(self.state)
            overflow = int(np.asarray(emits["overflow"]).sum())
            if overflow > self._seen_overflow:
                self._seen_overflow = overflow
                raise RuntimeError(
                    f"sharded state store / exchange overflowed ({overflow} "
                    "rows lost); raise store_capacity or bucket_capacity"
                )
            # online distributed growth is not implemented yet: stop loudly
            # BEFORE loss once any shard nears saturation
            occ = int(np.asarray(emits["occupancy"]).max())
            if occ > 0.6 * self.c.store_capacity:
                raise RuntimeError(
                    "sharded state store nearing capacity "
                    f"({occ}/{self.c.store_capacity} on the fullest shard); "
                    "restart the query with a larger store_capacity"
                )
        flat = {k: np.asarray(v).reshape((-1,) + np.asarray(v).shape[2:])
                for k, v in emits.items()}
        return self.c._decode_emits(flat)
