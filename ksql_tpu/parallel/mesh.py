"""Device-mesh helpers.

The reference's compute parallelism is Kafka-partition data parallelism:
one Kafka Streams task per partition, spread across threads/servers by the
consumer-group protocol (docs/operate-and-deploy/capacity-planning.md:295).
Here the analog is a 1-D ``jax.sharding.Mesh`` over the ``"shards"`` axis:
each device owns (a) a lane of the incoming micro-batch (data parallelism)
and (b) the hash-range of the keyed state store whose keys map to it (state
sharding) — the same owner-computes layout Kafka Streams gets from
co-partitioning, with the repartition topic replaced by an ICI all-to-all
(parallel/repartition.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shards"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (SHARD_AXIS,))
