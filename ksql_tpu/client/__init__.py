from ksql_tpu.client.client import Client, KsqlRestClient  # noqa: F401
