"""Client library for the HTTP API.

Analog of ksqldb-rest-client (KsqlRestClient, used by the CLI and HA
forwarding) and the reactive api-client (Client.java:31: streamQuery:47,
executeQuery:77, insertInto:103, admin ops).  Blocking HTTP on stdlib
urllib; streaming queries expose an iterator (the reactive-streams
publisher's pull analog).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, Iterator, List, Optional

from ksql_tpu.common import faults
from ksql_tpu.common.errors import KsqlException


class KsqlRestClient:
    """Low-level REST client (rest-client module analog)."""

    def __init__(self, server_url: str, timeout: float = 30.0):
        self.server_url = server_url.rstrip("/")
        self.timeout = timeout

    # ------------------------------------------------------------- plumbing
    def _post(self, path: str, body: Dict[str, Any]) -> Any:
        # chaos seam: an injected raise here models a client-side network
        # failure (connection refused, DNS, TLS) before anything is sent
        faults.fault_point("client.request", path)
        req = urllib.request.Request(
            self.server_url + path,
            data=json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read().decode("utf-8"))
                raise KsqlException(payload.get("message", str(e))) from None
            except ValueError:
                raise KsqlException(str(e)) from None

    def _get(self, path: str) -> Any:
        faults.fault_point("client.request", path)
        try:
            with urllib.request.urlopen(self.server_url + path, timeout=self.timeout) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            raise KsqlException(str(e)) from None

    # --------------------------------------------------------------- calls
    def make_ksql_request(self, ksql: str, properties: Optional[Dict] = None) -> List[Dict]:
        return self._post("/ksql", {"ksql": ksql, "streamsProperties": properties or {}})

    def make_query_request(self, ksql: str) -> Dict[str, Any]:
        return self._post("/query", {"ksql": ksql})

    def query_stream(self, sql: str, timeout_s: float = 10.0) -> Iterator[Any]:
        """POST /query-stream; yields the header dict then row lists."""
        req = urllib.request.Request(
            self.server_url + "/query-stream",
            data=json.dumps({"sql": sql}).encode("utf-8"),
            headers={
                "Content-Type": "application/json",
                "X-Query-Timeout-Seconds": str(timeout_s),
            },
        )
        with urllib.request.urlopen(req, timeout=timeout_s + 5) as resp:
            for line in resp:
                line = line.strip()
                if line:
                    yield json.loads(line.decode("utf-8"))

    def server_info(self) -> Dict[str, Any]:
        return self._get("/info")

    def healthcheck(self) -> Dict[str, Any]:
        return self._get("/healthcheck")

    def cluster_status(self) -> Dict[str, Any]:
        return self._get("/clusterStatus")

    def alerts(self) -> Dict[str, Any]:
        """Current LAGGING/STALLED queries with evidence (GET /alerts)."""
        return self._get("/alerts")

    def query_lag(self, query_id: str) -> Dict[str, Any]:
        """One query's progress time series (GET /query-lag/<id>).  For a
        push-registry tap the body carries a ``tap`` section: the shared
        pipeline behind the session plus the tap's ring-cursor lag and
        delivered/evicted/gap accounting."""
        return self._get(f"/query-lag/{query_id}")

    def metrics(self) -> Dict[str, Any]:
        """The JSON /metrics snapshot (server counters + engine gauges)."""
        return self._get("/metrics")


class Row:
    """One result row (api-client Row analog)."""

    def __init__(self, column_names: List[str], values: List[Any]):
        self.column_names = column_names
        self.values = values

    def value(self, name: str) -> Any:
        return self.values[self.column_names.index(name)]

    def as_dict(self) -> Dict[str, Any]:
        return dict(zip(self.column_names, self.values))

    def __repr__(self) -> str:
        return f"Row({self.as_dict()!r})"


class Client:
    """High-level client (api-client Client.java:31 analog)."""

    def __init__(self, host: str = "localhost", port: int = 8088):
        self._rest = KsqlRestClient(f"http://{host}:{port}")

    @staticmethod
    def create(host: str = "localhost", port: int = 8088) -> "Client":
        return Client(host, port)

    def execute_statement(self, sql: str, properties: Optional[Dict] = None) -> List[Dict]:
        return self._rest.make_ksql_request(sql, properties)

    def execute_query(self, sql: str) -> List[Row]:
        res = self._rest.make_query_request(sql)
        cols = res.get("columnNames", [])
        return [Row(cols, r) for r in res.get("rows", [])]

    def stream_query(self, sql: str, timeout_s: float = 10.0) -> Iterator[Any]:
        """Yields Row instances; a self-healed push session interleaves
        ``{"gap": {...}}`` resume markers on the wire — those are yielded
        as the marker dict itself, not wrapped in a (corrupt) Row."""
        it = self._rest.query_stream(sql, timeout_s)
        header = next(it)
        cols = header.get("columnNames", [])
        for values in it:
            if isinstance(values, dict):
                yield values  # gap / protocol marker object
            else:
                yield Row(cols, values)

    def insert_into(self, stream_name: str, row: Dict[str, Any]) -> None:
        cols = ", ".join(row.keys())
        vals = ", ".join(_sql_literal(v) for v in row.values())
        self._rest.make_ksql_request(
            f"INSERT INTO {stream_name} ({cols}) VALUES ({vals});"
        )

    def list_streams(self) -> List[Dict]:
        return self._entity_rows("LIST STREAMS;")

    def list_tables(self) -> List[Dict]:
        return self._entity_rows("LIST TABLES;")

    def list_topics(self) -> List[Dict]:
        return self._entity_rows("LIST TOPICS;")

    def list_queries(self) -> List[Dict]:
        return self._entity_rows("LIST QUERIES;")

    def describe_source(self, name: str) -> List[Dict]:
        return self._entity_rows(f"DESCRIBE {name};")

    def server_info(self) -> Dict[str, Any]:
        return self._rest.server_info()

    def alerts(self) -> List[Dict]:
        return self._rest.alerts().get("alerts", [])

    def query_lag(self, query_id: str) -> Dict[str, Any]:
        return self._rest.query_lag(query_id)

    def push_serving_stats(self) -> Dict[str, Any]:
        """The push registry's fan-out view (shared pipelines, taps per
        registry, delivered/evicted/gap counters) from /metrics."""
        return (
            self._rest.metrics().get("engine", {}).get("push-registry", {})
        )

    def _entity_rows(self, sql: str) -> List[Dict]:
        out = self._rest.make_ksql_request(sql)
        return out[0].get("rows", []) if out else []


def _sql_literal(v: Any) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    if isinstance(v, (list, tuple)):
        return "ARRAY[" + ", ".join(_sql_literal(x) for x in v) + "]"
    if isinstance(v, dict):
        return "MAP(" + ", ".join(
            f"{_sql_literal(k)} := {_sql_literal(x)}" for k, x in v.items()
        ) + ")"
    return str(v)
