"""Query analysis: AST -> Analysis.

Analog of ksqldb-engine/.../analyzer/ (QueryAnalyzer.java:62, Analyzer.java,
AggregateAnalyzer.java).  Responsibilities:

* resolve FROM sources against the metastore, build the (left-deep) join tree;
* expand ``*`` / ``alias.*`` / ``expr->*`` select items;
* rewrite every column reference to its *internal* flat name — single-source
  queries use bare column names, joins use ``ALIAS_COLUMN`` prefixed names
  (matching the reference's join schema naming, JoinNode.java);
* synthesize aliases (``KSQL_COL_<position>``) for unaliased expressions;
* aggregate analysis: collect distinct aggregate calls from SELECT + HAVING,
  split select items into key items (exact group-by matches) and value items,
  enforce the reference's "key missing from projection" / "non-aggregate
  column must be in GROUP BY" rules.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from ksql_tpu.common.errors import AnalysisException
from ksql_tpu.common.schema import (
    LogicalSchema,
    PSEUDOCOLUMNS,
    WINDOW_BOUNDS,
)
from ksql_tpu.common.types import SqlBaseType, SqlType
from ksql_tpu.execution import expressions as ex
from ksql_tpu.functions.registry import FunctionRegistry
from ksql_tpu.functions.udfs import UNIT_ARG_FUNCTIONS
from ksql_tpu.metastore.metastore import DataSource, MetaStore
from ksql_tpu.parser import ast_nodes as ast


@dataclasses.dataclass
class AliasedSource:
    alias: str
    source: DataSource


@dataclasses.dataclass
class JoinInfo:
    join_type: ast.JoinType
    left: "RelationRef"
    right: AliasedSource
    left_key: ex.Expression  # rewritten to internal names
    right_key: ex.Expression
    within: Optional[ast.WithinExpression] = None


# a relation is either a single aliased source or a join of relations
RelationRef = object  # AliasedSource | JoinInfo


@dataclasses.dataclass
class SelectItem:
    alias: str
    expression: ex.Expression  # rewritten
    is_key: bool = False  # exact match of a grouping / key expression


@dataclasses.dataclass
class Analysis:
    sources: List[AliasedSource]
    relation: RelationRef
    select_items: List[SelectItem]
    where: Optional[ex.Expression]
    group_by: List[ex.Expression]
    partition_by: List[ex.Expression]
    having: Optional[ex.Expression]
    window: Optional[ast.WindowExpression]
    refinement: Optional[ast.Refinement]
    limit: Optional[int]
    is_aggregate: bool
    agg_calls: List[ex.FunctionCall]
    table_function_items: List[SelectItem]
    # internal scope: flat name -> SqlType for the combined relation
    scope_types: Dict[str, SqlType]
    key_names: List[str]  # internal names of the relation's key columns
    # per key column: internal column names that alias it.  Equi-joins make
    # every side's join column an alias of the single output key (reference
    # JoinNode.getKeyColumnNames); single sources have one name per key.
    key_equiv: List[List[str]] = dataclasses.field(default_factory=list)
    # name of the synthesized join key column (ROWKEY or clash-free ROWKEY_n)
    # when the join criteria matched no plain column reference, else None
    synthetic_key: Optional[str] = None
    # projection contained a star (SELECT * / alias.*) — exempts PARTITION BY
    # expressions from the must-be-projected rule
    has_star: bool = False


class Scope:
    """Column resolution for the FROM relation."""

    def __init__(self, sources: List[AliasedSource]):
        self.sources = sources
        self.joined = len(sources) > 1
        # (alias, col) -> internal ; col -> [internal...]
        self.qualified: Dict[Tuple[str, str], str] = {}
        self.unqualified: Dict[str, List[str]] = {}
        self.types: Dict[str, SqlType] = {}
        self.key_names: List[str] = []
        self.synthetic_key: Optional[str] = None
        for asrc in sources:
            for col in asrc.source.schema.columns():
                internal = (
                    f"{asrc.alias}_{col.name}" if self.joined else col.name
                )
                self.qualified[(asrc.alias, col.name)] = internal
                self.unqualified.setdefault(col.name, []).append(internal)
                self.types[internal] = col.type
            for col in asrc.source.schema.key_columns:
                internal = (
                    f"{asrc.alias}_{col.name}" if self.joined else col.name
                )
                if not self.joined:
                    self.key_names.append(internal)
        for name, t in PSEUDOCOLUMNS.items():
            self.types.setdefault(name, t)
            self.unqualified.setdefault(name, [name])
        # windowed sources expose window bounds
        if any(s.source.key_format.windowed for s in sources):
            for name, t in WINDOW_BOUNDS.items():
                self.types.setdefault(name, t)
                self.unqualified.setdefault(name, [name])
        # joins: each side's pseudocolumns resolve per-side (S.ROWTIME ->
        # S_ROWTIME = the left record's timestamp; QTT joins.json
        # 'on non-STRING value column' expects S_ROWTIME/T_ROWTIME)
        if self.joined:
            for asrc in sources:
                per_side = dict(PSEUDOCOLUMNS)
                if asrc.source.key_format.windowed:
                    per_side.update(WINDOW_BOUNDS)
                for name, t in per_side.items():
                    internal = f"{asrc.alias}_{name}"
                    self.qualified[(asrc.alias, name)] = internal
                    self.types[internal] = t

    def resolve(self, name: str, source: Optional[str]) -> str:
        if source is not None:
            hit = self.qualified.get((source, name))
            if hit is None:
                if name in PSEUDOCOLUMNS or name in WINDOW_BOUNDS:
                    return name
                legacy = self._legacy_rowkey(name, source)
                if legacy is not None:
                    return legacy
                raise AnalysisException(
                    f"Column '{source}.{name}' cannot be resolved."
                )
            return hit
        hits = self.unqualified.get(name)
        if not hits:
            legacy = self._legacy_rowkey(name, None)
            if legacy is not None:
                return legacy
            raise AnalysisException(f"Column '{name}' cannot be resolved.")
        if len(set(hits)) > 1:
            raise AnalysisException(
                f"Column '{name}' is ambiguous. Could be any of: "
                + ", ".join(sorted(set(hits)))
            )
        return hits[0]

    def _legacy_rowkey(self, name: str, source: Optional[str]) -> Optional[str]:
        """Legacy `ROWKEY` references resolve to the (single) key column of
        the named/only source (pre-0.10 ksql key naming, still present in the
        QTT corpus)."""
        if name != "ROWKEY":
            return None
        for asrc in self.sources:
            if source is not None and asrc.alias != source:
                continue
            keys = asrc.source.schema.key_columns
            if len(keys) == 1:
                return self.qualified[(asrc.alias, keys[0].name)]
        return None

    def type_of(self, internal: str) -> SqlType:
        return self.types[internal]


def analyze_query(
    query: ast.Query,
    metastore: MetaStore,
    registry: FunctionRegistry,
    sink_name: Optional[str] = None,
) -> Analysis:
    sources: List[AliasedSource] = []
    relation = _build_relation(query.from_, metastore, sources)
    scope = Scope(sources)
    if query.window is not None and not scope.joined:
        # windowed aggregations expose WINDOWSTART/WINDOWEND in the
        # projection; over a join the bounds are not resolvable (reference:
        # "SELECT column 'WINDOWSTART' cannot be resolved.")
        for n, t in WINDOW_BOUNDS.items():
            scope.types.setdefault(n, t)
            scope.unqualified.setdefault(n, [n])

    rewrite = lambda e: _rewrite_refs(e, scope)  # noqa: E731

    # resolve join criteria now that scope exists; the join key becomes the
    # combined relation's key
    _resolve_join_keys(relation, scope)
    key_equiv: List[List[str]] = []
    synthetic_key: Optional[str] = None
    if isinstance(relation, JoinInfo):
        if _is_fk_join(relation):
            # FK table-table join keeps the LEFT table's primary key
            left = relation.left
            scope.key_names = [
                scope.qualified[(left.alias, c.name)]
                for c in left.source.schema.key_columns
            ]
            key_name = scope.key_names[0] if scope.key_names else "ROWKEY"
            key_equiv = [[k] for k in scope.key_names]
        else:
            key_name, members, _exprs = _join_key_info(relation)
            if key_name == "ROWKEY":
                # synthetic key: pick a clash-free name against the sources'
                # original column names (ROWKEY, ROWKEY_1, ... — reference
                # generated-name collision handling, joins.json)
                taken = {
                    c.name
                    for asrc in sources
                    for c in asrc.source.schema.columns()
                }
                key_name = "ROWKEY"
                n = 0
                while key_name in taken:
                    n += 1
                    key_name = f"ROWKEY_{n}"
                members = [key_name]
                synthetic_key = key_name
            scope.key_names = [key_name]
            key_equiv = [members or [key_name]]
        if synthetic_key is not None:
            # expression join key: synthesize the key column into the scope
            from ksql_tpu.execution.interpreter import ExpressionCompiler, TypeResolver

            kt = ExpressionCompiler(TypeResolver(scope.types), registry).infer(
                relation.left_key
            )
            scope.types[synthetic_key] = kt or SqlType.of(SqlBaseType.BIGINT)
            scope.unqualified.setdefault(synthetic_key, [synthetic_key])
    if not key_equiv:
        key_equiv = [[k] for k in scope.key_names]
    scope.synthetic_key = synthetic_key

    where = rewrite(query.where) if query.where is not None else None
    group_by = [rewrite(g) for g in query.group_by]
    partition_by = [rewrite(p) for p in query.partition_by]
    if len(partition_by) > 1 and any(
        isinstance(p, ex.NullLiteral) for p in partition_by
    ):
        raise AnalysisException("Cannot PARTITION BY multiple columns including NULL")
    having = rewrite(query.having) if query.having is not None else None

    # ------------------------------------------------------ select items
    items: List[SelectItem] = []
    table_fn_items: List[SelectItem] = []
    synth_counter = 0  # KSQL_COL_<n> counts synthesized aliases only
    has_star = False
    for item in query.select.items:
        if isinstance(item, ast.AllColumns):
            has_star = True
            for alias, expr in _expand_star(
                item, scope, repartition=bool(query.partition_by)
            ):
                items.append(SelectItem(alias=alias, expression=expr))
            continue
        expr = item.expression
        if isinstance(expr, ex.StructAll):
            base = rewrite(expr.base)
            base_t = _expr_type(base, scope, registry)
            if base_t is None or base_t.base != SqlBaseType.STRUCT:
                raise AnalysisException(f"Cannot expand non-struct: {expr}")
            for fname, _ft in base_t.fields or ():
                items.append(
                    SelectItem(alias=fname, expression=ex.Dereference(base=base, field=fname))
                )
            continue
        if item.alias is None:
            # synthesized KSQL_COL_<n> aliases skip indices taken by source
            # column names (reference generated-alias collision handling)
            while f"KSQL_COL_{synth_counter}" in scope.types:
                synth_counter += 1
            alias = _default_alias(expr, synth_counter, scope)
            if alias == f"KSQL_COL_{synth_counter}":
                synth_counter += 1
            # generated struct-field aliases avoid clashing with source
            # columns and earlier aliases via _N suffixes (reference
            # AliasUtil: `a->b` aliases to B_1 when B is taken)
            if isinstance(expr, ex.Dereference):
                used = {si.alias for si in items}
                taken = used | set(scope.types)
                if alias in taken:
                    n = 1
                    while f"{alias}_{n}" in taken:
                        n += 1
                    alias = f"{alias}_{n}"
        else:
            alias = item.alias
        expr = rewrite(expr)
        si = SelectItem(alias=alias, expression=expr)
        if _contains_table_function(expr, registry):
            table_fn_items.append(si)
        items.append(si)

    # dedupe output aliases
    seen = {}
    for si in items:
        if si.alias in seen:
            raise AnalysisException(f"Duplicate output column name '{si.alias}'. "
                                    "Use AS to provide unique names.")
        seen[si.alias] = si

    # unknown functions fail fast (reference UdfIndex lookup behavior)
    from ksql_tpu.common.errors import FunctionException

    for si in items:
        for n in ex.walk(si.expression):
            if isinstance(n, ex.FunctionCall) and not (
                registry.is_scalar(n.name)
                or registry.is_aggregate(n.name)
                or registry.is_table_function(n.name)
            ):
                raise FunctionException(f"unknown function {n.name.upper()}")

    # -------------------------------------------------- aggregate analysis
    agg_calls: List[ex.FunctionCall] = []

    def collect_aggs(e: Optional[ex.Expression]):
        if e is None:
            return
        for n in ex.walk(e):
            if isinstance(n, ex.FunctionCall) and registry.is_aggregate(n.name):
                # no nested aggregates
                for inner in n.args:
                    for nn in ex.walk(inner):
                        if isinstance(nn, ex.FunctionCall) and registry.is_aggregate(nn.name):
                            raise AnalysisException(
                                f"Aggregate functions can not be nested: {n}"
                            )
                if n not in agg_calls:
                    agg_calls.append(n)

    for si in items:
        collect_aggs(si.expression)
    collect_aggs(having)
    if where is not None:
        for n in ex.walk(where):
            if isinstance(n, ex.FunctionCall) and registry.is_aggregate(n.name):
                raise AnalysisException(
                    f"Aggregate functions are not allowed in WHERE: {n.name}"
                )

    is_aggregate = bool(group_by) or bool(agg_calls)
    if agg_calls and not group_by:
        raise AnalysisException(
            "Use of aggregate function "
            f"{agg_calls[0].name} requires a GROUP BY clause."
        )
    if is_aggregate:
        _validate_aggregate(items, group_by, agg_calls, registry, having,
                            sink_name)
        if query.partition_by:
            raise AnalysisException("PARTITION BY cannot be used with GROUP BY.")
    if query.window is not None and not group_by:
        raise AnalysisException("WINDOW clause requires a GROUP BY clause.")

    # mark key items
    if group_by:
        for si in items:
            si.is_key = any(si.expression == g for g in group_by)
    elif partition_by:
        for si in items:
            si.is_key = any(si.expression == p for p in partition_by)
    else:
        # claim priority follows member order (left join column first), not
        # projection order — verified against joins.json
        for members in key_equiv:
            for m in members:
                hit = next(
                    (
                        si
                        for si in items
                        if isinstance(si.expression, ex.ColumnRef)
                        and si.expression.name == m
                    ),
                    None,
                )
                if hit is not None:
                    hit.is_key = True
                    break

    return Analysis(
        sources=sources,
        relation=relation,
        select_items=items,
        where=where,
        group_by=group_by,
        partition_by=partition_by,
        having=having,
        window=query.window,
        refinement=query.refinement,
        limit=query.limit,
        is_aggregate=is_aggregate,
        agg_calls=agg_calls,
        table_function_items=table_fn_items,
        scope_types=dict(scope.types),
        key_names=list(scope.key_names),
        key_equiv=key_equiv,
        synthetic_key=synthetic_key,
        has_star=has_star,
    )


# ----------------------------------------------------------------- helpers


def _build_relation(rel: ast.Relation, metastore: MetaStore, out: List[AliasedSource]):
    if isinstance(rel, ast.Table):
        src = metastore.require_source(rel.name)
        asrc = AliasedSource(alias=rel.name, source=src)
        out.append(asrc)
        return asrc
    if isinstance(rel, ast.AliasedRelation):
        inner = rel.relation
        if not isinstance(inner, ast.Table):
            raise AnalysisException("Only table references can be aliased")
        src = metastore.require_source(inner.name)
        asrc = AliasedSource(alias=rel.alias, source=src)
        out.append(asrc)
        return asrc
    if isinstance(rel, ast.Join):
        left = _build_relation(rel.left, metastore, out)
        right = _build_relation(rel.right, metastore, out)
        if not isinstance(right, AliasedSource):
            raise AnalysisException("Right side of a join must be a single source")
        left_names = {a.source.name for a in out if a is not right}
        if right.source.name in left_names:
            raise AnalysisException(
                f"Can not join '{right.source.name}' to '{right.source.name}': "
                "self joins are not yet supported."
            )
        return JoinInfo(
            join_type=rel.join_type,
            left=left,
            right=right,
            left_key=rel.criteria.expression if rel.criteria else None,  # resolved later
            right_key=None,
            within=rel.within,
        )
    raise AnalysisException(f"Unsupported relation {type(rel).__name__}")


def _resolve_join_keys(relation, scope: Scope) -> None:
    """Split each join's ON expression into left/right key expressions."""
    if not isinstance(relation, JoinInfo):
        return
    _resolve_join_keys(relation.left, scope)
    cond = relation.left_key  # raw ON expression stashed by _build_relation
    if cond is None:
        raise AnalysisException("Join criteria required")
    if not isinstance(cond, ex.Comparison) or cond.op != ex.CompareOp.EQ:
        raise AnalysisException(
            "Only equality join criteria are supported (ON a = b)"
        )
    left_aliases = _aliases_of(relation.left)
    right_alias = relation.right.alias

    def side_of(e: ex.Expression) -> str:
        aliases = set()
        for n in ex.walk(e):
            if isinstance(n, ex.ColumnRef):
                if n.source is not None:
                    aliases.add(n.source)
                else:
                    internal = scope.resolve(n.name, None)
                    for (a, c), i in scope.qualified.items():
                        if i == internal:
                            aliases.add(a)
                            break
        if aliases <= left_aliases and aliases:
            return "L"
        if aliases == {right_alias}:
            return "R"
        # JoinNode's wording ("comparision" spelled as the reference does)
        raise AnalysisException(
            f"Invalid comparison expression '{ex.format_expression(e)}' in "
            f"join '{ex.format_expression(cond)}'. Each side of the join "
            "comparision must contain references from exactly one source."
        )

    lhs_side = side_of(cond.left)
    rhs_side = side_of(cond.right)
    if {lhs_side, rhs_side} != {"L", "R"}:
        raise AnalysisException(
            f"Invalid join condition '{ex.format_expression(cond)}'. Each "
            "side of the join comparision must contain references from "
            "exactly one source."
        )
    lexpr = cond.left if lhs_side == "L" else cond.right
    rexpr = cond.right if lhs_side == "L" else cond.left
    relation.left_key = _rewrite_refs(lexpr, scope)
    relation.right_key = _rewrite_refs(rexpr, scope)


def _aliases_of(rel) -> set:
    if isinstance(rel, AliasedSource):
        return {rel.alias}
    return _aliases_of(rel.left) | {rel.right.alias}


def _is_fk_join(join: "JoinInfo") -> bool:
    """Table-table join whose left key expression is not the left table's
    primary key -> foreign-key join (keeps the left table's key)."""
    if not isinstance(join.left, AliasedSource):
        return False
    if not (join.left.source.is_table() and join.right.source.is_table()):
        return False
    left_keys = [
        f"{join.left.alias}_{c.name}" for c in join.left.source.schema.key_columns
    ]
    return not (
        isinstance(join.left_key, ex.ColumnRef) and [join.left_key.name] == left_keys
    )


def _join_key_info(join: "JoinInfo") -> Tuple[str, List[str], List[ex.Expression]]:
    """Output key info for a join: ``(key_name, members, exprs)``.

    ``members`` are plain columns that alias the output key, in claim-priority
    order (left side first — reference JoinNode.getKeyColumnNames); ``exprs``
    are all expressions known equal to the key (used to detect that a chained
    join's criteria preserves the child key, so no re-key happens).  A simple
    column on either side donates its name (left preferred);
    expression-vs-expression keys and FULL OUTER joins (where either side's
    key may be null) synthesize ROWKEY (verified against joins.json)."""
    if join.join_type == ast.JoinType.OUTER:
        return "ROWKEY", ["ROWKEY"], []
    if _is_fk_join(join):
        # FK joins key by the LEFT table's primary key, not the criteria
        pk = [
            f"{join.left.alias}_{c.name}"
            for c in join.left.source.schema.key_columns
        ]
        return pk[0], pk, [ex.ColumnRef(name=n) for n in pk]
    this_exprs = [join.left_key, join.right_key]
    members_here = [k.name for k in this_exprs if isinstance(k, ex.ColumnRef)]
    if isinstance(join.left, JoinInfo):
        lname, lmembers, lexprs = _join_key_info(join.left)
        if any(join.left_key == e for e in lexprs):
            # chained equi-join against the child's key: key is preserved
            members = lmembers + [m for m in members_here if m not in lmembers]
            exprs = lexprs + [e for e in this_exprs if e not in lexprs]
            return lname, members, exprs
    if isinstance(join.left_key, ex.ColumnRef):
        return join.left_key.name, members_here, this_exprs
    if isinstance(join.right_key, ex.ColumnRef):
        return join.right_key.name, members_here, this_exprs
    return "ROWKEY", ["ROWKEY"], this_exprs


def _join_key_name(join: "JoinInfo") -> str:
    return _join_key_info(join)[0]


def _rewrite_refs(e: ex.Expression, scope: Scope) -> ex.Expression:
    """Resolve column refs to internal names, skipping lambda-bound names and
    interval-unit arguments (TIMESTAMPADD(MINUTES, ...))."""
    import dataclasses as dc

    def go(node, bound):
        if isinstance(node, ex.LambdaExpression):
            return ex.LambdaExpression(
                params=node.params, body=go(node.body, bound | set(node.params))
            )
        if isinstance(node, ex.ColumnRef):
            if node.source is None and node.name in bound:
                return node  # lambda variable
            return ex.ColumnRef(name=scope.resolve(node.name, node.source))
        if isinstance(node, ex.FunctionCall) and node.name.upper() in UNIT_ARG_FUNCTIONS:
            from ksql_tpu.functions.udfs import _UNIT_MS

            pos = UNIT_ARG_FUNCTIONS[node.name.upper()]
            args = list(node.args)
            if (
                pos < len(args)
                and isinstance(args[pos], ex.ColumnRef)
                and args[pos].source is None
                and args[pos].name.upper() in _UNIT_MS
            ):
                # only genuine interval-unit keywords rewrite; a column that
                # happens to sit in the unit position stays a column (and
                # fails overload resolution, as the reference does)
                args[pos] = ex.StringLiteral(value=args[pos].name)
            return ex.FunctionCall(
                name=node.name,
                args=tuple(a if i == pos else go(a, bound) for i, a in enumerate(args)),
                distinct=node.distinct,
            )
        if isinstance(node, ex.Expression):
            changed = {}
            for f in dc.fields(node):
                old = getattr(node, f.name)
                new = _go_any(old, bound, go)
                if new is not old:
                    changed[f.name] = new
            return dc.replace(node, **changed) if changed else node
        return node

    return go(e, set())


def _go_any(v, bound, go):
    if isinstance(v, ex.Expression):
        return go(v, bound)
    if isinstance(v, tuple):
        new = tuple(_go_any(x, bound, go) for x in v)
        return new if any(a is not b for a, b in zip(new, v)) else v
    return v


def _rewrite_topdown(e, fn):
    e = fn(e)
    if isinstance(e, ex.Expression):
        import dataclasses as dc

        changed = {}
        for f in dc.fields(e):
            old = getattr(e, f.name)
            new = _rewrite_topdown(old, fn) if isinstance(old, (ex.Expression, tuple, list)) else old
            if new is not old:
                changed[f.name] = new
        if changed:
            e = dc.replace(e, **changed)
        return e
    if isinstance(e, tuple):
        return tuple(_rewrite_topdown(x, fn) for x in e)
    if isinstance(e, list):
        return [_rewrite_topdown(x, fn) for x in e]
    return e


def _expand_star(
    item: ast.AllColumns, scope: Scope, repartition: bool = False
) -> List[Tuple[str, ex.Expression]]:
    out = []
    # a bare `*` over a join with a synthetic key includes the synthetic
    # ROWKEY column (reference join schema includes it; qualified stars do not)
    if item.source is None and scope.joined and scope.synthetic_key is not None:
        out.append((scope.synthetic_key, ex.ColumnRef(name=scope.synthetic_key)))
    for asrc in scope.sources:
        if item.source is not None and asrc.alias != item.source:
            continue
        if repartition and scope.joined:
            # a repartition of a join materializes the per-side pseudocolumns
            # into the value schema, so `*` includes them (reference
            # UserRepartitionNode over a join — partition-by.json)
            for pname in PSEUDOCOLUMNS:
                internal = f"{asrc.alias}_{pname}"
                out.append((internal, ex.ColumnRef(name=internal)))
        if repartition:
            # the repartitioned schema orders value columns first and appends
            # the old key columns at the end (PartitionByParamsFactory)
            cols = list(asrc.source.schema.value_columns) + list(
                asrc.source.schema.key_columns
            )
        else:
            cols = list(asrc.source.schema.columns())
        for col in cols:
            internal = scope.qualified[(asrc.alias, col.name)]
            out.append((internal if scope.joined else col.name, ex.ColumnRef(name=internal)))
        if scope.joined and asrc.source.key_format.windowed:
            for wname in WINDOW_BOUNDS:
                internal = f"{asrc.alias}_{wname}"
                out.append((internal, ex.ColumnRef(name=internal)))
    if item.source is not None and not out:
        raise AnalysisException(f"Unknown source {item.source} in {item.source}.*")
    return out


def _default_alias(expr: ex.Expression, position: int, scope: Scope) -> str:
    if isinstance(expr, ex.ColumnRef):
        if expr.source is not None and scope.joined:
            if expr.name in PSEUDOCOLUMNS or expr.name in WINDOW_BOUNDS:
                return f"{expr.source}_{expr.name}"
            hits = set(scope.unqualified.get(expr.name, ()))
            if len(hits) > 1:
                # ambiguous across join sides: default alias keeps the prefix
                return f"{expr.source}_{expr.name}"
        return expr.name
    if isinstance(expr, ex.Dereference):
        return expr.field
    return f"KSQL_COL_{position}"


def _contains_table_function(e: ex.Expression, registry: FunctionRegistry) -> bool:
    return any(
        isinstance(n, ex.FunctionCall) and registry.is_table_function(n.name)
        for n in ex.walk(e)
    )


def _validate_aggregate(
    items: List[SelectItem],
    group_by: List[ex.Expression],
    agg_calls: List[ex.FunctionCall],
    registry: FunctionRegistry,
    having: Optional[ex.Expression],
    sink_name: Optional[str] = None,
) -> None:
    # every group-by expression must appear in the projection
    # (PlanNode.throwKeysNotIncludedError wording)
    target = f"`{sink_name}`" if sink_name else "the table"
    for g in group_by:
        if not any(si.expression == g for si in items):
            nm = ex.format_expression(g)
            raise AnalysisException(
                f"The query used to build {target} must include the "
                f"grouping expression {nm} in its projection "
                f"(eg, SELECT {nm}...)."
            )
    # non-aggregate select expressions must be group-by expressions or
    # composed of them (+ columns referenced inside aggregate args are fine)
    group_cols = set()
    for g in group_by:
        for n in ex.walk(g):
            if isinstance(n, ex.ColumnRef):
                group_cols.add(n.name)

    def check_non_agg(e: ex.Expression):
        if any(e == g for g in group_by):
            return
        if isinstance(e, ex.FunctionCall) and registry.is_aggregate(e.name):
            return
        if isinstance(e, ex.ColumnRef):
            if e.name in PSEUDOCOLUMNS or e.name in WINDOW_BOUNDS:
                return
            if e.name not in group_cols:
                raise AnalysisException(
                    f"Non-aggregate SELECT expression(s) not part of GROUP BY: "
                    f"{e.name}"
                )
            return
        import dataclasses as dc

        if isinstance(e, ex.Expression):
            for f in dc.fields(e):
                v = getattr(e, f.name)
                if isinstance(v, ex.Expression):
                    check_non_agg(v)
                elif isinstance(v, tuple):
                    for x in v:
                        if isinstance(x, ex.Expression):
                            check_non_agg(x)
                        elif isinstance(x, tuple):
                            for y in x:
                                if isinstance(y, ex.Expression):
                                    check_non_agg(y)

    for si in items:
        check_non_agg(si.expression)


def _expr_type(e: ex.Expression, scope: Scope, registry: FunctionRegistry) -> Optional[SqlType]:
    from ksql_tpu.execution.interpreter import ExpressionCompiler, TypeResolver

    compiler = ExpressionCompiler(TypeResolver(scope.types), registry)
    return compiler.infer(e)
