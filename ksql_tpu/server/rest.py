"""HTTP API server.

Analog of ksqldb-rest-app (api/server/Server.java:63, routes at
api/server/ServerVerticle.java:116-233, KsqlResource.java:283,
QueryStreamHandler.java:53).  Stdlib threading HTTP server; each request
runs on its own thread (the reference's worker pool `ksql-workers`).

Routes:
  POST /ksql          DDL/DML statement list (distributed via the command log)
  POST /query         pull or push query; JSON array response
  POST /query-stream  streaming query; newline-delimited JSON chunks
  POST /close-query   terminate a running push query
  GET  /info /healthcheck /status
  GET  /clusterStatus POST /heartbeat POST /lag   (HA agents, HeartbeatAgent.java:67)
  GET  /query-lag/<id>  per-query progress time series (lag, watermark, e2e)
  GET  /alerts          current LAGGING/STALLED queries with evidence
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from ksql_tpu.common import faults
from ksql_tpu.common.errors import KsqlException
from ksql_tpu.engine.engine import KsqlEngine, StatementResult
from ksql_tpu.parser import ast_nodes as ast
from ksql_tpu.server.command_log import Command, CommandLog, CommandRunner

SERVER_VERSION = "0.1.0"

# statements that mutate cluster state -> distributed via the command log.
# InsertValues is durable here too: the reference's data durability comes
# from Kafka itself (InsertValuesExecutor produces straight to the topic);
# with the in-process broker the command log is the durable tier.
_DISTRIBUTED = (
    ast.CreateStream, ast.CreateTable, ast.CreateStreamAsSelect,
    ast.CreateTableAsSelect, ast.InsertInto, ast.InsertValues, ast.DropSource,
    ast.TerminateQuery, ast.PauseQuery, ast.ResumeQuery,
    ast.RegisterType, ast.DropType,
)


class PushQuerySession:
    """A server-held transient push query (TransientQueryQueue analog).

    Supervised (PR 5): the session drives its own consumer/executor outside
    the engine's poll loop, so it carries its own copy of the engine's
    self-healing machinery — a fault in the private consumer/executor is
    classified, the consumer rewinds to the pre-poll snapshot, the executor
    rebuilds, and the retry/backoff ladder (the same
    ``ksql.query.retry.*`` knobs) schedules the resume.  The client's
    stream stays open across the incident: it sees a *gap marker* object
    (``{"gap": {...}}`` on the chunked/websocket wire) instead of a dead
    HTTP stream.  Exhausting the retry budget is terminal: the final gap
    marker carries ``terminal: true`` and the stream closes.  The session
    also owns a :class:`QueryProgress` tracker (lag/watermark sampling),
    closing the isolation gap PR 1 noted."""

    def __init__(self, engine: KsqlEngine, sql: str):
        from ksql_tpu.analyzer.analyzer import analyze_query
        from ksql_tpu.common import health as qhealth
        from ksql_tpu.common import config as cfg
        from ksql_tpu.runtime.topics import Consumer
        from ksql_tpu.execution import steps as st

        self.id = f"transient_{uuid.uuid4().hex[:12]}"
        self.engine = engine
        prepared = engine.parse(sql)
        q = prepared[0].statement
        if not isinstance(q, ast.Query):
            raise KsqlException("expected a query")
        self.limit = q.limit
        analysis = analyze_query(q, engine.metastore, engine.registry)
        planned = engine.planner.plan(analysis, self.id)
        self._planned = planned  # kept for self-healing executor rebuilds
        out_schema = planned.plan.physical_plan.schema
        self.columns = [c.name for c in out_schema.key_columns] + [
            c.name for c in out_schema.value_columns
        ]
        self.column_types = [str(c.type) for c in out_schema.key_columns] + [
            str(c.type) for c in out_schema.value_columns
        ]
        self._key_names = [c.name for c in out_schema.key_columns]
        self.rows: List[dict] = []
        self._emitted = 0
        self._results = 0  # result rows only (gap markers don't count)
        self._lock = threading.Lock()
        self.closed = False
        # self-healing bookkeeping (the engine's ladder, session-scoped)
        self.restart_count = 0
        self.retry_at_ms = 0.0
        self.retry_backoff_ms = 0.0
        self.terminal = False
        # set when a self-heal's executor rebuild itself failed: the next
        # poll retries the rebuild before consuming (resuming on the STALE
        # executor would double-absorb the replayed records)
        self._needs_rebuild = False
        # stateful self-heal: positions up to which the replay re-derives
        # state SILENTLY — rows from already-delivered records are
        # suppressed so duplicates neither reach the client nor consume
        # its LIMIT
        self._replay_until = None
        self._suppressing = False
        # progress tracker (PR-4 parity): sampled on every poll
        self.progress = qhealth.QueryProgress(
            self.id,
            history_size=int(
                engine.effective_property(cfg.HEALTH_HISTORY_SIZE, 256)
            ),
            stall_ticks=int(
                engine.effective_property(cfg.HEALTH_STALL_TICKS, 8)
            ),
        )

        # -------- scalable push, tentpole tier (push registry): a latest-
        # offset push whose plan is a filter/projection over one stream
        # source becomes a TAP on a shared pipeline — the registry runs the
        # common prefix once, this session only evaluates its per-session
        # residual against the shared emission ring (push_registry.py)
        self._unsubscribe = None
        self.consumer = None
        self.executor = None
        self.tap = None
        offset_reset = str(
            engine.session_properties.get("auto.offset.reset", "")
        ).lower()
        from ksql_tpu.execution import expressions as _ex

        if offset_reset == "latest" and cfg._bool(
            engine.effective_property(cfg.PUSH_REGISTRY_ENABLE, True)
        ):
            self.tap = engine.get_push_registry().try_attach(
                self, planned, analysis
            )
        # legacy single-session attach (pre-registry scalable path): only
        # reachable with the registry disabled, since the registry shape
        # check is a strict superset of this one
        simple = (
            not analysis.is_aggregate
            and not analysis.partition_by
            and not analysis.table_function_items
            and len(analysis.sources) == 1
            and analysis.where is None
            and all(
                isinstance(si.expression, _ex.ColumnRef)
                for si in analysis.select_items
            )
        )
        if self.tap is None and offset_reset == "latest" and simple:
            src_name = analysis.sources[0].source.name
            self._unsubscribe = engine.register_push_listener(
                src_name, self._on_emit
            )
        if self.tap is None and self._unsubscribe is None:
            source_topics = sorted({
                step.topic for step in st.walk_steps(planned.plan.physical_plan)
                if hasattr(step, "topic") and not isinstance(step, (st.StreamSink, st.TableSink))
            })
            for t in source_topics:
                engine.broker.create_topic(t)
            # an explicit latest reset consumes from the live end (the
            # semantics a registry tap gets); the default replays the
            # topic from the beginning as before
            self.consumer = Consumer(
                engine.broker, source_topics,
                from_beginning=offset_reset != "latest",
            )
            # stateful self-healing: a rebuilt executor starts EMPTY, so a
            # stateful session must re-consume from its start positions to
            # re-derive correct aggregates (see _session_failed)
            self._start_positions = dict(self.consumer.positions)
            self.executor = self._build_executor()

    def _build_executor(self):
        from ksql_tpu.runtime.oracle import OracleExecutor

        return OracleExecutor(
            self._planned.plan, self.engine.broker, self.engine.registry,
            on_error=self.engine._on_error, emit_callback=self._on_emit,
        )

    # thread entrypoint: for scalable sessions this callback fires from
    # whichever thread drives engine.poll_once — the server's steady-state
    # process loop — concurrently with the HTTP thread polling the session
    # graftlint: entrypoint=engine-emit
    def _on_emit(self, e) -> bool:
        """Returns True when the emission became a client-visible row (the
        tap delivery counters ride this)."""
        # scalable sessions own no consumer to sample, so the tracker is
        # fed from the emission stream itself (watermark + e2e)
        self.progress.note_watermark(e.ts)
        self.progress.record_e2e(e.ts)
        if self._suppressing:
            # stateful self-heal replay: this emission re-derives from a
            # record the client already saw rows for — state absorbs it,
            # the stream does not
            return False
        with self._lock:
            if self.limit is not None and self._results >= self.limit:
                return False
            row = dict(zip(self._key_names, e.key))
            if e.row:
                row.update(e.row)
            if e.window is not None:
                row.setdefault("WINDOWSTART", e.window[0])
                row.setdefault("WINDOWEND", e.window[1])
            self.rows.append(row)
            self._results += 1
            return True

    def _enqueue_gap(self, marker: dict) -> None:
        """Queue a gap marker (shared-pipeline heal, ring eviction span,
        or terminal) onto this session's stream — the PR-5 resumable-gap
        contract, fed by the push registry for tap sessions."""
        with self._lock:
            if marker.get("terminal"):
                self.terminal = True
                self.closed = True
            self.rows.append({"__gap__": dict(marker)})

    @property
    def scalable(self) -> bool:
        """True when this session reprocesses nothing itself: a registry
        tap or a legacy emission-listener attach."""
        return self.tap is not None or self._unsubscribe is not None

    @property
    def shared(self) -> bool:
        """True when this session is a tap on a shared registry pipeline."""
        return self.tap is not None

    def poll(self) -> List[dict]:
        """Drain newly available records; return any new result rows (and
        gap-marker entries after a self-healed fault)."""
        if self.tap is not None:  # registry tap: residual over the shared
            # pipeline's ring (the tap advances the pipeline itself)
            if not self.terminal:
                self.tap.poll()
                self.progress.sample_ring(self.tap.cursor, self.tap.lag())
            return self._drain_new()
        if self.executor is None:  # scalable: rows arrive via the listener
            self.engine.run_until_quiescent(max_iters=1)
            return self._drain_new()
        if self.terminal or time.time() * 1000 < self.retry_at_ms:
            return self._drain_new()  # terminal, or backing off: no poll
        if self._needs_rebuild:
            try:
                self.executor = self._build_executor()
                self._needs_rebuild = False
            except Exception as e:  # noqa: BLE001 — still failing: treat
                # as another incident (backoff, gap marker, retry budget)
                self._session_failed(e, dict(self.consumer.positions))
                return self._drain_new()
        snapshot = dict(self.consumer.positions)
        try:
            records = self.consumer.poll()
            for topic, rec in records:
                # stateful replay window: records before the pre-fault
                # snapshot re-derive state with their emissions suppressed.
                # Single-writer claim: only this HTTP-thread poll path ever
                # writes the flag; the engine-emit entrypoint only reads it
                # (and only for NON-scalable sessions, whose executor runs
                # synchronously inside this very loop)
                self._suppressing = (  # graftlint: owner=http
                    self._replay_until is not None
                    and rec.offset < self._replay_until.get(
                        (topic, rec.partition), 0
                    )
                )
                try:
                    self.executor.process(topic, rec)
                except Exception as e:  # noqa: BLE001
                    if self.engine._is_poison(e):
                        # poison record: skip-and-log, the stream flows on
                        self.engine._on_error(f"poison:{self.id}:{topic}", e)
                        continue
                    raise
                finally:
                    # same single-writer claim as the set above
                    self._suppressing = False  # graftlint: owner=http
            if self._replay_until is not None and all(
                self.consumer.positions.get(k, 0) >= v
                for k, v in self._replay_until.items()
            ):
                self._replay_until = None  # caught back up to the fault
            if records:
                self.progress.note_watermark(
                    max(r.timestamp for _, r in records)
                )
                if self.restart_count:
                    # healthy records after a restart close the incident
                    self.restart_count = 0
                    self.retry_backoff_ms = 0.0
        except Exception as e:  # noqa: BLE001 — session self-healing
            self._session_failed(e, snapshot)
        self.progress.sample(self.consumer)
        return self._drain_new()

    def _session_failed(self, e: Exception, snapshot) -> None:
        """classify → rewind → rebuild → backoff, session-scoped; queues a
        gap marker so the client sees a resumable gap, not a dead stream."""
        from ksql_tpu.common import config as cfg

        eng = self.engine
        eng._on_error(f"push-session:{self.id}", e)
        # the rebuilt executor starts with EMPTY state: a stateless session
        # resumes from the pre-poll snapshot, but a STATEFUL one must
        # re-consume from its start positions or its aggregates would
        # silently reset.  The re-derivation is silent: rows from records
        # the client already saw are suppressed (they re-build state but
        # neither duplicate the stream nor consume the LIMIT); the gap
        # marker flags it as stateReplayed
        stateful = bool(getattr(self.executor, "stateful", False))
        self.consumer.positions.clear()
        if stateful:
            self.consumer.positions.update(self._start_positions)
            self._replay_until = dict(snapshot)
        else:
            self.consumer.positions.update(snapshot)
        self.restart_count += 1
        eng.push_session_restarts += 1
        marker = {
            "queryId": self.id,
            "error": f"{type(e).__name__}: {e}",
            "restarts": self.restart_count,
        }
        if stateful:
            marker["stateReplayed"] = True
        retry_max = int(eng.effective_property(cfg.QUERY_RETRY_MAX, 2 ** 31))
        if self.restart_count > retry_max:
            with self._lock:
                self.terminal = True
                self.closed = True
            marker["terminal"] = True
        else:
            initial = float(eng.effective_property(
                cfg.QUERY_RETRY_BACKOFF_INITIAL_MS, 15000
            ))
            maximum = float(eng.effective_property(
                cfg.QUERY_RETRY_BACKOFF_MAX_MS, 900000
            ))
            self.retry_backoff_ms = min(
                (self.retry_backoff_ms * 2) or initial, maximum
            )
            self.retry_at_ms = time.time() * 1000 + self.retry_backoff_ms
            try:
                self.executor = self._build_executor()
                self._needs_rebuild = False
            except Exception as e2:  # noqa: BLE001 — rebuild failed: the
                # next poll retries it after the backoff (the stale
                # executor must not consume the replayed records)
                self._needs_rebuild = True
                eng._on_error(f"push-session:{self.id}:rebuild", e2)
        with self._lock:
            self.rows.append({"__gap__": marker})

    def _drain_new(self) -> List[dict]:
        with self._lock:
            new = self.rows[self._emitted:]
            self._emitted = len(self.rows)
            return new

    def done(self) -> bool:
        with self._lock:
            return self.closed or (
                self.limit is not None
                and self._results >= self.limit
                and self._emitted >= len(self.rows)
            )

    def close(self):
        with self._lock:
            self.closed = True
        if self.tap is not None:
            # refcounted teardown: the last tap detaching arms the
            # registry's linger clock (ksql.push.registry.linger.ms)
            tap, self.tap = self.tap, None  # graftlint: owner=http
            tap.close()
        if self._unsubscribe is not None:
            self._unsubscribe()
            # single-writer claim: only close(), on the session's own HTTP
            # thread, clears the listener hook; other entrypoints only read
            self._unsubscribe = None  # graftlint: owner=http


class KsqlServer:
    """Server state shared across requests (KsqlRestApplication analog)."""

    def __init__(
        self,
        engine: Optional[KsqlEngine] = None,
        command_log_path: Optional[str] = None,
        host: str = "127.0.0.1",
        port: int = 8088,
        peers: Optional[List[str]] = None,
        broker=None,
        command_log: Optional[CommandLog] = None,
    ):
        # a shared broker + command log makes this node one of a cluster
        # over a single data plane: statements propagate through the log,
        # every node materializes replica state, and exactly one node per
        # query (rendezvous-hashed over alive nodes) publishes to the sink
        # — the others are standby replicas (num.standby.replicas analog)
        self.shared_data = broker is not None
        if engine is None:
            engine = KsqlEngine(broker=broker) if broker is not None else KsqlEngine()
        self.engine = engine
        # one engine, many threads (HTTP handlers, command runner, the
        # steady-state process loop): engine access is serialized — XLA
        # dispatch and metastore mutation are not thread-safe
        self.engine_lock = threading.RLock()
        self.host = host
        self.port = port
        self.service_id = "default_"
        self.command_log = command_log or CommandLog(command_log_path)
        self.command_runner = CommandRunner(self.command_log, self._apply_command)
        self.push_queries: Dict[str, PushQuerySession] = {}
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # HA state (HeartbeatAgent.java:67: HostStatus per node)
        self.peers = list(peers or [])
        self.host_status: Dict[str, Dict[str, Any]] = {}
        # host_status is written by HTTP handler threads
        # (receive_heartbeat) while the heartbeat loop iterates and ages
        # it — a race the shared-state-race lint surfaced (PR 8): an
        # insert during iteration raises RuntimeError and kills the loop
        self._status_lock = threading.Lock()
        self.lags: Dict[str, Dict[str, Any]] = {}
        self._heartbeat_thread: Optional[threading.Thread] = None
        self._process_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._started_at = time.time()
        self.headless = False  # set by start() from ksql.queries.file
        # counter increments come from HTTP handler threads, the process
        # loop, and peer forwards concurrently; a bare dict += is a
        # read-modify-write that loses updates (PR-8 race lint finding) —
        # all writers go through mark_metric
        self._metrics_lock = threading.Lock()
        self.metrics: Dict[str, float] = {
            "statements-executed": 0,
            "queries-started": 0,
            "errors": 0,
            "overload-shed": 0,
        }
        # live count of concurrent streaming responses (/query-stream,
        # /ws/query) — the inflight resource the overload monitor samples;
        # writes funnel through _inflight_enter/_inflight_exit under the
        # metrics lock, the monitor reads the int (atomic) lock-free
        self._inflight = 0
        self.engine.overload.set_inflight_source(lambda: self._inflight)

    def mark_metric(self, name: str, n: float = 1) -> None:
        """The one server-counter write path (thread-safe)."""
        with self._metrics_lock:
            self.metrics[name] = self.metrics.get(name, 0) + n

    def _inflight_enter(self) -> None:
        with self._metrics_lock:
            self._inflight += 1

    def _inflight_exit(self) -> None:
        with self._metrics_lock:
            self._inflight = max(0, self._inflight - 1)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        """startKsql(:395): replay the command log, restore the state
        checkpoint over the re-created queries, then serve.  With
        ``ksql.queries.file`` set the node boots HEADLESS
        (StandaloneExecutor.java:73): it executes the SQL file and serves
        only the query endpoints — REST statements cannot mutate it."""
        queries_file = str(self.engine.config.get("ksql.queries.file") or "")
        self.headless = bool(queries_file)
        if self.headless:
            with open(queries_file) as f:
                sql = f.read()
            with self.engine_lock:
                for prepared in self.engine.parse(sql):
                    self.engine.execute_statement(prepared)
        else:
            # a headless node has no command topic (StandaloneExecutor):
            # neither prior-WAL replay nor the live tail may mutate it
            self.command_runner.process_prior_commands()
        self.engine.restore_checkpoint()
        if self.shared_data:
            # replayed queries must be assigned BEFORE the first poll: a
            # (re)joining node starts as standby for anything a live peer
            # is already publishing — no duplicate sink records
            self._refresh_assignments()
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        self._heartbeat_thread = threading.Thread(target=self._heartbeat_loop, daemon=True)
        self._heartbeat_thread.start()
        # steady-state processing: persistent queries advance continuously
        # (the Kafka Streams stream-thread analog) so pulls observe inserts
        # without an open push session driving the engine
        # anchor the election grace at serve time: log replay / checkpoint
        # restore above may take arbitrarily long.  Single-writer claim:
        # this line runs before the process thread below starts, and
        # nothing writes the anchor again
        self._started_at = time.time()  # graftlint: owner=main
        self._process_thread = threading.Thread(target=self._process_loop, daemon=True)
        self._process_thread.start()
        # overload monitor thread: pressure is observed (and admission
        # reacts) even while a poll tick holds the engine lock through a
        # long device compile
        self.engine.overload.start_monitor()

    def _process_loop(self) -> None:
        idle_wait = 0.02
        last_assign = 0.0
        while not self._stop.is_set():
            try:
                with self.engine_lock:
                    # tail the (possibly shared) command log: statements
                    # distributed by peer nodes apply here
                    # (CommandRunner.fetchAndRunCommands analog); headless
                    # nodes have no command topic to tail
                    # reviewed (blocking-under-lock): the engine lock IS
                    # the statement-serialization point — WAL commands
                    # must apply under it or a concurrent /ksql statement
                    # would interleave with replay; contenders tolerate
                    # statement latency by design (PR-8 deadline
                    # supervision bounds the wedge case)
                    n_cmds = (
                        0 if getattr(self, "headless", False)
                        else self.command_runner.fetch_and_run()  # graftlint: disable=blocking-under-lock
                    )
                    if self.shared_data and n_cmds:
                        # assign BEFORE the first poll over a new query so
                        # a standby never publishes a record.  reviewed
                        # (blocking-under-lock): assignment must not race
                        # the poll tick — a promotion's state republish
                        # under the lock IS the no-torn-failover contract
                        self._refresh_assignments()  # graftlint: disable=blocking-under-lock
                        last_assign = time.time()
                    # reviewed (blocking-under-lock): the poll tick owns
                    # the whole engine — device dispatch and the periodic
                    # checkpoint's state gather under the lock are the
                    # consistency contract (a snapshot racing statement
                    # execution would tear); tick/rebuild deadlines bound
                    # a wedged holder
                    n = n_cmds + self.engine.poll_once()  # graftlint: disable=blocking-under-lock
                if self.shared_data and time.time() - last_assign > 0.5:
                    self._refresh_assignments()
                    last_assign = time.time()
            except Exception as e:  # noqa: BLE001 — per-query errors are
                # already routed to the query error queue; anything reaching
                # here is an infra failure: record it, back off, keep serving
                n = 0
                self.mark_metric("errors")
                try:
                    with self.engine_lock:
                        self.engine._on_error("process-loop", e)
                except Exception:
                    pass
                self._stop.wait(0.5)
            if not n:
                self._stop.wait(idle_wait)

    def stop(self) -> None:
        self._stop.set()
        if self._process_thread is not None:
            self._process_thread.join(timeout=30)
        try:
            with self.engine_lock:
                # reviewed (blocking-under-lock): the clean-shutdown
                # snapshot must quiesce the engine — holding the lock is
                # the point (nothing else may mutate state mid-snapshot),
                # and the process is exiting anyway
                self.engine.checkpoint()  # clean-shutdown snapshot  # graftlint: disable=blocking-under-lock
        except Exception:
            pass  # never block shutdown on a failed snapshot
        # drain the engine's tick-supervision workers (incl. a bounded
        # join of deadline-abandoned zombies): a daemon worker killed by
        # interpreter exit mid-XLA-dispatch aborts the whole process
        self.engine.shutdown()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        self.command_log.close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ----------------------------------------------------------- statements
    def _refresh_assignments(self) -> None:
        """Rendezvous-hash every persistent query onto one ACTIVE publisher
        among the alive nodes; everyone else holds a standby replica.  When
        the active dies (heartbeat liveness), the hash re-lands on a
        survivor, which starts publishing — failover without state movement
        because every replica has been materializing all along
        (RuntimeAssignor + HeartbeatAgent -> HostStatus analog).

        Known tradeoff: election is computed independently per node from
        its local heartbeat view, so during the failover-detection window
        (or a divergent view) two nodes can briefly both publish
        (duplicate, not lost, sink records) — the same at-least-once window
        Kafka Streams has during rebalance.  Detection hysteresis (3
        consecutive missed heartbeat checks) keeps the window rare."""
        from ksql_tpu.common.batch import stable_hash64

        # publisher election needs CONFIRMED liveness: a configured peer
        # that never heartbeated must not win (the sink would never be
        # written); within a short startup grace it still counts so a
        # simultaneously-booting cluster elects consistently
        now = time.time()
        candidates = {self.url}
        for host in self.peers:
            st = self.host_status.get(host)
            if st is not None:
                if st.get("hostAlive"):
                    candidates.add(host)
            elif now - self._started_at < 5.0:
                candidates.add(host)
        alive = sorted(candidates)
        with self.engine_lock:
            for qid, h in list(self.engine.queries.items()):
                active = max(
                    alive, key=lambda u: stable_hash64(f"{u}|{qid}")
                )
                # reviewed (blocking-under-lock): a promotion republishes
                # the replica's table state; doing it under the engine
                # lock is the no-torn-failover contract (a poll tick
                # racing the republish would interleave stale rows)
                self.engine.set_query_standby(qid, active != self.url)  # graftlint: disable=blocking-under-lock

    def _apply_command(self, cmd: Command) -> None:
        with self.engine_lock:
            saved = dict(self.engine.session_properties)
            try:
                self.engine.session_properties.update(cmd.session_properties)
                for prepared in self.engine.parse(cmd.statement):
                    self.engine.execute_statement(prepared)
            finally:
                self.engine.session_properties = saved

    def execute_statements(self, sql: str, properties: Optional[Dict] = None) -> List[Dict]:
        """POST /ksql handler body (RequestHandler.java:79): validate, then
        either run directly (SHOW/LIST/...) or distribute via the command
        log and apply."""
        out = []
        with self.engine_lock:
            # reviewed (blocking-under-lock): statement execution is
            # DEFINED to serialize on the engine lock (the reference's
            # single-threaded command runner); fault points inside it are
            # chaos seams that only fire under injection
            return self._execute_statements_locked(sql, out)  # graftlint: disable=blocking-under-lock

    def _execute_statements_locked(self, sql: str, out: List[Dict]) -> List[Dict]:
        for prepared in self.engine.parse(sql):
            s = prepared.statement
            self.mark_metric("statements-executed")
            if getattr(self, "headless", False) and isinstance(s, _DISTRIBUTED):
                self.mark_metric("errors")
                raise KsqlException(
                    "The server is running in headless ('ksql.queries.file') "
                    "mode: the SQL file defines the queries and the REST API "
                    "cannot mutate them. Pull/push query endpoints remain "
                    "available."
                )
            distributed = isinstance(s, _DISTRIBUTED)
            if distributed and self.shared_data and isinstance(s, ast.InsertValues):
                # shared data plane: values land on the shared broker once —
                # the reference produces straight to Kafka, no command topic
                distributed = False
            if distributed:
                # validate BEFORE the append: a user error must fail the
                # request without entering the (shared) log
                try:
                    self.engine.validate_statement(prepared)
                except Exception:
                    self.mark_metric("errors")
                    raise
                # CommandLog.append serializes internally (its own RLock);
                # the mutator-name heuristic cannot see across the module
                # boundary  # graftlint: disable=shared-state-race
                cmd = self.command_log.append(
                    prepared.text + (";" if not prepared.text.rstrip().endswith(";") else ""),
                    self.engine.session_properties,
                )
                # serialize after peers' earlier statements, then apply
                # locally (other nodes pick ours up via their tail loop)
                self.command_runner.catch_up_to(cmd.seq)
                try:
                    result = self.engine.execute_statement(prepared)
                except Exception:
                    self.mark_metric("errors")
                    raise
                self.command_runner.mark_applied(cmd.seq)
                if self.shared_data and result.query_id:
                    # assign the new query before its first poll tick
                    self._refresh_assignments()
                status = {
                    "status": "SUCCESS",
                    "message": result.message,
                    "queryId": result.query_id,
                    "commandSequenceNumber": cmd.seq,
                }
                out.append({
                    "statementText": prepared.text,
                    "commandId": f"{type(s).__name__}/{cmd.seq}",
                    "commandStatus": status,
                })
            elif isinstance(s, ast.Query):
                raise KsqlException(
                    "The following statement types should be issued to the "
                    "websocket endpoint '/query': SELECT"
                )
            else:
                result = self.engine.execute_statement(prepared)
                out.append(_entity_of(prepared.text, result))
        return out

    # --------------------------------------------------------------- query
    def run_query(self, sql: str, forwarded: bool = False) -> Dict[str, Any]:
        """Pull query or finite push query -> complete result set.

        HARouting analog: when this node can't serve the pull (table not
        materialized here — e.g. its query failed or was never assigned),
        the request forwards to an ALIVE peer chosen from the
        heartbeat-derived host status, instead of failing the client."""
        try:
            with self.engine_lock:
                results = self.engine.execute_sql(sql)
        except Exception as e:
            msg = str(e)
            routable = (
                "materialized" in msg or "does not exist" in msg
                or "Unknown source" in msg
            )
            if forwarded or not routable or not self.peers:
                raise
            result = self._forward_query(sql)
            if result is not None:
                return result
            raise
        r = results[0]
        self.mark_metric("queries-started")
        return {
            "queryId": r.query_id,
            "columnNames": r.columns or [],
            "rows": [[row.get(c) for c in (r.columns or [])] for row in (r.rows or [])],
        }

    def _alive_peers(self) -> List[str]:
        """Routing filter (LivenessFilter analog): only peers whose
        heartbeats are inside the liveness window."""
        now = int(time.time() * 1000)
        out = []
        for host in self.peers:
            st = self.host_status.get(host)
            if st is None:
                out.append(host)  # never heard from: optimistically routable
            elif st.get("hostAlive") or now - st.get("lastStatusUpdateMs", 0) < 2000:
                out.append(host)
        return out

    def _routable_peers(self) -> List[str]:
        """Alive peers ordered best-first for pull routing: peers that
        gossiped query freshness sort by total offset lag (least-lagging
        standby serves the freshest materialization), peers that never
        reported come last in configuration order (liveness-only, the
        pre-gossip behavior)."""
        alive = self._alive_peers()
        lags = {h: self._peer_reported_lag(h) for h in alive}
        known = sorted(
            (h for h in alive if lags[h] is not None), key=lambda h: lags[h]
        )
        return known + [h for h in alive if lags[h] is None]

    def _forward_query(self, sql: str) -> Optional[Dict[str, Any]]:
        import urllib.request

        for host in self._routable_peers():
            try:
                # chaos seam: an injected raise here behaves exactly like a
                # dead/partitioned peer — the router tries the next one
                faults.fault_point("http.peer.forward", host)
                req = urllib.request.Request(
                    host.rstrip("/") + "/query",
                    data=json.dumps({"ksql": sql, "forwarded": True}).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=10) as resp:
                    self.mark_metric("queries-started")
                    return json.loads(resp.read())
            except Exception:
                continue  # next candidate (HARouting tries hosts in order)
        return None

    def open_push_query(self, sql: str) -> PushQuerySession:
        with self.engine_lock:
            sess = PushQuerySession(self.engine, sql)
        self.push_queries[sess.id] = sess
        self.mark_metric("queries-started")
        return sess

    def poll_push_query(self, sess: PushQuerySession) -> List[dict]:
        with self.engine_lock:
            return sess.poll()

    # ------------------------------------------------------------------ HA
    def _gossip_queries(self) -> Dict[str, Any]:
        """Per-query {lag, watermark, health} — the freshness payload
        piggybacked on heartbeat gossip (LagReportingAgent analog, but
        riding the existing heartbeat instead of a second agent).

        Deliberately does NOT take engine_lock: the heartbeat loop must
        keep sending while a poll tick holds the lock for a long device
        compile — blocking here would make peers declare this node dead
        and flap the publisher election.  QueryProgress reads are
        internally locked, and list() snapshots the dict atomically."""
        out: Dict[str, Any] = {}
        for qid, h in list(self.engine.queries.items()):
            prog = getattr(h, "progress", None)
            if prog is not None:
                out[qid] = prog.gossip()
        return out

    def _heartbeat_loop(self):
        """Discover/send/check (HeartbeatAgent's 3 scheduled services)."""
        import urllib.request

        while not self._stop.wait(0.5):
            me = self.url
            gossip = self._gossip_queries()
            for peer in self.peers:
                try:
                    req = urllib.request.Request(
                        peer.rstrip("/") + "/heartbeat",
                        data=json.dumps({
                            "hostInfo": me,
                            "timestamp": int(time.time() * 1000),
                            # per-query freshness rides the heartbeat so
                            # /clusterStatus shows it per host and pull
                            # routing can prefer the least-lagging peer
                            "queries": gossip,
                        }).encode(),
                        headers={"Content-Type": "application/json"},
                    )
                    urllib.request.urlopen(req, timeout=1).read()
                except Exception:
                    pass
            # check: mark peers dead after 3 consecutive stale checks (no
            # heartbeat in 2s) — hysteresis so one dropped packet can't
            # trigger a publisher re-election flap.  Locked: HTTP handler
            # threads insert entries concurrently (receive_heartbeat), and
            # a dict insert during this iteration raises RuntimeError —
            # the PR-8 race lint caught exactly that
            now = int(time.time() * 1000)
            with self._status_lock:
                for host, st in self.host_status.items():
                    if now - st.get("lastStatusUpdateMs", 0) < 2000:
                        st["missedCount"] = 0
                        st["hostAlive"] = True
                    else:
                        st["missedCount"] = st.get("missedCount", 0) + 1
                        if st["missedCount"] >= 3:
                            st["hostAlive"] = False

    def receive_heartbeat(self, host: str, ts: int,
                          queries: Optional[Dict[str, Any]] = None) -> None:
        with self._status_lock:
            self.host_status[host] = {
                "hostAlive": True, "lastStatusUpdateMs": ts,
                "queries": dict(queries or {}),
            }

    def cluster_status(self) -> Dict[str, Any]:
        entries = {
            self.url: {"hostAlive": True,
                       "lastStatusUpdateMs": int(time.time() * 1000),
                       "activeStandbyPerQuery": {},
                       "hostStoreLags": self.lags.get(self.url, {}),
                       # per-query freshness: local view for self, the
                       # gossiped view for peers
                       "queries": self._gossip_queries()},
        }
        # snapshot under the status lock: handler threads insert entries
        # while this (another handler thread) renders the view
        with self._status_lock:
            status = {h: dict(st) for h, st in self.host_status.items()}
        for host, st in status.items():
            entries[host] = {
                "hostAlive": st.get("hostAlive", False),
                "lastStatusUpdateMs": st.get("lastStatusUpdateMs", 0),
                "activeStandbyPerQuery": {},
                "hostStoreLags": self.lags.get(host, {}),
                "queries": st.get("queries", {}),
            }
        return {"clusterStatus": entries}

    def _peer_reported_lag(self, host: str) -> Optional[int]:
        """Total offset lag a peer last gossiped, or None if it never
        reported query freshness."""
        st = self.host_status.get(host)
        if not st or not st.get("queries"):
            return None
        return sum(int(q.get("lag") or 0) for q in st["queries"].values())

    def report_lag(self, host: str, lags: Dict[str, Any]) -> None:
        self.lags[host] = lags

    def local_lags(self) -> Dict[str, Any]:
        """Per-query consumer lag (LagReportingAgent.allLocalStorePartitionLags
        analog): end offset - consumed position per source topic."""
        out = {}
        with self.engine_lock:
            for qid, h in list(self.engine.queries.items()):
                stores = {}
                for (tn, p), pos in list(h.consumer.positions.items()):
                    end = self.engine.broker.topic(tn).end_offsets()[p]
                    stores[f"{tn}-{p}"] = {
                        "currentOffsetPosition": pos,
                        "endOffsetPosition": end,
                        "offsetLag": max(0, end - pos),
                    }
                out[qid] = stores
        return {"hostStoreLags": {"stateStoreLags": out,
                                  "updateTimeMs": int(time.time() * 1000)}}


def _entity_of(text: str, r: StatementResult) -> Dict[str, Any]:
    if r.kind == "rows":
        out = {"statementText": text, "columns": r.columns, "rows": r.rows}
        if r.message:
            # EXPLAIN ANALYZE / DESCRIBE EXTENDED header (runtime, shard
            # count, flight-recorder window) rides alongside the table
            out["message"] = r.message
        return out
    return {"statementText": text, "message": r.message}


def _make_handler(server: KsqlServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # silence
            pass

        # ------------------------------------------------------- plumbing
        def _body(self) -> Dict[str, Any]:
            n = int(self.headers.get("Content-Length", 0) or 0)
            raw = self.rfile.read(n) if n else b"{}"
            try:
                return json.loads(raw.decode("utf-8") or "{}")
            except ValueError:
                return {}

        def _send(self, code: int, obj: Any) -> None:
            payload = json.dumps(obj).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def _error(self, code: int, message: str) -> None:
            self._send(code, {
                "@type": "generic_error", "error_code": code * 100,
                "message": message,
            })

        def _error_retry(self, code: int, message: str,
                         retry_after: int) -> None:
            """_error plus a Retry-After header — the 429 shed contract:
            a shed client learns when to come back, it is never hung."""
            payload = json.dumps({
                "@type": "generic_error", "error_code": code * 100,
                "message": message,
            }).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Retry-After", str(int(retry_after)))
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def _overload_reject(self) -> bool:
            """Admission control (overload action 1): True when this
            transient pull/push query was shed with 429 + Retry-After.
            Persistent DDL via /ksql never routes through here — state
            mutations stay accepted under overload."""
            ov = server.engine.overload
            if ov.admission_allowed():
                return False
            ov.note_shed()
            server.mark_metric("overload-shed")
            self._error_retry(
                429,
                "server overloaded: new transient queries are being "
                "shed while pressure drains (persistent statements via "
                "/ksql are still accepted)",
                ov.retry_after_s(),
            )
            return True

        # --------------------------------------------------------- routes
        # ------------------------------------------------ websocket support
        _WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

        def _ws_handshake(self) -> bool:
            import base64 as _b64
            import hashlib as _hl

            key = self.headers.get("Sec-WebSocket-Key")
            if not key or "upgrade" not in str(
                self.headers.get("Connection", "")
            ).lower():
                self._error(400, "expected a WebSocket upgrade request")
                return False
            accept = _b64.b64encode(
                _hl.sha1((key + self._WS_GUID).encode()).digest()
            ).decode()
            self.send_response(101, "Switching Protocols")
            self.send_header("Upgrade", "websocket")
            self.send_header("Connection", "Upgrade")
            self.send_header("Sec-WebSocket-Accept", accept)
            self.end_headers()
            return True

        def _ws_send_text(self, text: str) -> None:
            payload = text.encode("utf-8")
            n = len(payload)
            if n < 126:
                header = bytes([0x81, n])
            elif n < 1 << 16:
                header = bytes([0x81, 126]) + n.to_bytes(2, "big")
            else:
                header = bytes([0x81, 127]) + n.to_bytes(8, "big")
            self.connection.sendall(header + payload)

        def _ws_send_close(self, code: int = 1000) -> None:
            self.connection.sendall(bytes([0x88, 2]) + code.to_bytes(2, "big"))

        def _ws_recv(self, timeout: float = 0.0):
            """One frame -> (opcode, payload) or None on timeout/EOF."""
            self.connection.settimeout(timeout or None)
            try:
                head = self.rfile.read(2)
                if len(head) < 2:
                    return None
                opcode = head[0] & 0x0F
                masked = head[1] & 0x80
                n = head[1] & 0x7F
                if n == 126:
                    n = int.from_bytes(self.rfile.read(2), "big")
                elif n == 127:
                    n = int.from_bytes(self.rfile.read(8), "big")
                mask = self.rfile.read(4) if masked else b""
                data = self.rfile.read(n)
                if masked:
                    data = bytes(b ^ mask[i % 4] for i, b in enumerate(data))
                return opcode, data
            except Exception:
                return None
            finally:
                self.connection.settimeout(None)

        def _ws_query(self):
            """GET /ws/query (ServerVerticle.java:229 / WSQueryEndpoint):
            the query rides the ``request`` query param (JSON, as the
            reference's websocket endpoint takes it) or the first text
            frame; rows stream back as JSON text frames."""
            if self._overload_reject():
                return  # shed BEFORE the 101 upgrade: a plain 429 reply
            server._inflight_enter()
            try:
                self._ws_query_body()
            finally:
                server._inflight_exit()

        def _ws_query_body(self):
            from urllib.parse import parse_qs, urlparse

            if not self._ws_handshake():
                return
            qs = parse_qs(urlparse(self.path).query)
            sql = None
            if "request" in qs:
                try:
                    sql = json.loads(qs["request"][0]).get("ksql")
                except ValueError:
                    sql = None
            if sql is None and "sql" in qs:
                sql = qs["sql"][0]
            if sql is None:
                frame = self._ws_recv(timeout=10)
                if frame is None or frame[0] != 0x1:
                    self._ws_send_close(1002)
                    return
                body = json.loads(frame[1].decode("utf-8"))
                sql = body.get("ksql", body.get("sql", ""))
            try:
                with server.engine_lock:
                    prepared = server.engine.parse(sql)
                q = prepared[0].statement
                is_push = (
                    isinstance(q, ast.Query)
                    and q.refinement is not None
                    and q.refinement.type == ast.RefinementType.CHANGES
                )
                if not is_push:
                    res = server.run_query(sql)
                    self._ws_send_text(json.dumps({
                        "queryId": res["queryId"],
                        "columnNames": res["columnNames"], "columnTypes": [],
                    }))
                    for row in res["rows"]:
                        self._ws_send_text(json.dumps(row))
                    self._ws_send_close()
                    return
                sess = server.open_push_query(sql)
                self._ws_send_text(json.dumps({
                    "queryId": sess.id, "columnNames": sess.columns,
                    "columnTypes": sess.column_types,
                }))
                deadline = time.time() + 10.0
                try:
                    while not sess.done() and time.time() < deadline:
                        rows = server.poll_push_query(sess)
                        for row in rows:
                            if "__gap__" in row:
                                # session self-healed: the client sees a
                                # resume marker, not a dead stream
                                self._ws_send_text(
                                    json.dumps({"gap": row["__gap__"]})
                                )
                            else:
                                self._ws_send_text(json.dumps(
                                    [row.get(c) for c in sess.columns]
                                ))
                        if not rows:
                            time.sleep(0.02)
                    self._ws_send_close()
                finally:
                    sess.close()
                    server.push_queries.pop(sess.id, None)
            except Exception as e:  # noqa: BLE001
                try:
                    self._ws_send_text(json.dumps({"error": str(e)}))
                    self._ws_send_close(1011)
                except Exception:
                    pass

        # thread entrypoint: ThreadingHTTPServer runs each request on its
        # own thread  # graftlint: entrypoint=http
        def do_GET(self):
            path = self.path.split("?")[0]
            if path == "/ws/query":
                self._ws_query()
            elif path == "/info":
                self._send(200, {"KsqlServerInfo": {
                    "version": SERVER_VERSION,
                    "ksqlServiceId": server.service_id,
                    "serverStatus": "RUNNING",
                }})
            elif path == "/healthcheck":
                # the top-level verdict folds in every sub-check: a degraded
                # command runner, a query in terminal ERROR, or a STALLED
                # query (watchdog verdict — offsets frozen while lag grows)
                # makes the node unhealthy (HealthCheckAgent analog)
                from ksql_tpu.common import health as _health

                with server.engine_lock:
                    per_query = {
                        qid: {
                            "state": h.state,
                            "terminal": h.terminal,
                            "restarts": h.restart_count,
                            "backend": h.backend,
                            "health": h.health,
                        }
                        for qid, h in server.engine.queries.items()
                    }
                terminal = sorted(
                    qid for qid, d in per_query.items() if d["terminal"]
                )
                stalled = sorted(
                    qid for qid, d in per_query.items()
                    if d["health"] == _health.STALLED
                )
                runner_ok = not server.command_runner.degraded
                queries_ok = not terminal and not stalled
                self._send(200, {
                    "isHealthy": runner_ok and queries_ok,
                    "details": {
                        "metastore": {"isHealthy": True},
                        "kafka": {"isHealthy": True},
                        "commandRunner": {"isHealthy": runner_ok},
                        "queries": {
                            "isHealthy": queries_ok,
                            "terminalErrorQueryIds": terminal,
                            "stalledQueryIds": stalled,
                            "perQuery": per_query,
                        },
                    },
                })
            elif path == "/alerts":
                # current LAGGING/STALLED queries with the evidence that
                # produced the verdict (the watchdog's operator surface)
                with server.engine_lock:
                    alerts = server.engine.health_alerts()
                    # skew verdicts ride their own section: note_event
                    # evidence only surfaces for LAGGING/STALLED queries,
                    # and a skewed query is often otherwise healthy
                    telemetry = list(server.engine.telemetry_events)
                self._send(200, {
                    "alerts": alerts,
                    # overload posture + the bounded engage/clear evidence
                    # ring (ISSUE 16): every action transition lands here
                    "overload": server.engine.overload.alerts_view(),
                    "telemetry": telemetry,
                    "updatedMs": int(time.time() * 1000),
                })
            elif path.startswith("/query-lag/"):
                # one query's progress: current per-partition offsets/lag,
                # watermark, e2e percentiles, plus the bounded time series
                # (ksql.health.history.size samples)
                qid = path[len("/query-lag/"):]
                with server.engine_lock:
                    h = server.engine.queries.get(qid)
                    prog = getattr(h, "progress", None) if h else None
                    if prog is None:
                        # push-query sessions carry the same tracker (PR-5
                        # supervised-session parity)
                        sess = server.push_queries.get(qid)
                        if sess is not None:
                            prog = sess.progress
                            body = prog.snapshot()
                            body["state"] = (
                                "TERMINAL" if sess.terminal
                                else "CLOSED" if sess.closed else "RUNNING"
                            )
                            body["backend"] = (
                                "push-tap" if sess.shared
                                else "push-session-scalable" if sess.scalable
                                else "push-session"
                            )
                            body["restarts"] = sess.restart_count
                            body["series"] = prog.series()
                            if sess.tap is not None:
                                # per-tap serving view: the shared
                                # pipeline behind this session and the
                                # tap's cursor lag / delivery / gap
                                # accounting against its ring
                                tap = sess.tap
                                pipe = tap.pipeline
                                body["tap"] = {
                                    "pipeline": pipe.id,
                                    "registry": pipe.key,
                                    "mode": pipe.mode,
                                    "pipelineBackend": pipe.backend,
                                    "cursor": tap.cursor,
                                    "ringLag": tap.lag(),
                                    "deliveredRows": tap.delivered_rows,
                                    "evictedRows": tap.evicted_rows,
                                    "gapMarkers": tap.gap_markers,
                                    "pipelineRestarts": pipe.restart_count,
                                }
                    else:
                        body = prog.snapshot()
                        body["state"] = h.state
                        body["backend"] = h.backend
                        body["series"] = prog.series()
                        shard_fn = getattr(h.executor, "shard_metrics", None)
                        if shard_fn is not None:
                            # distributed backend: the per-shard view the
                            # per-query numbers fold over
                            try:
                                body["shards"] = shard_fn()
                            except Exception:  # noqa: BLE001
                                pass
                if prog is None:
                    self._error(404, f"no query or progress for id {qid}")
                else:
                    self._send(200, body)
            elif path == "/clusterStatus":
                self._send(200, server.cluster_status())
            elif path == "/lag":
                self._send(200, server.local_lags())
            elif path == "/metrics":
                # server request counters + the engine's MetricCollectors
                # snapshot (per-query rates, lag, states, device counts).
                # `Accept: text/plain` or ?format=prometheus renders the
                # same data (plus the flight recorder's per-stage
                # histograms) as Prometheus exposition, so the server is
                # scrapable by standard tooling.
                from urllib.parse import parse_qs, urlparse

                qs = parse_qs(urlparse(self.path).query)
                accept = str(self.headers.get("Accept", "")).lower()
                want_prom = (
                    qs.get("format", [""])[0].lower() == "prometheus"
                    or "text/plain" in accept
                )
                with server.engine_lock:
                    snap = server.engine.metrics_snapshot()
                    # stage aggregation is Prometheus-only work: the JSON
                    # response never uses it, so don't pay O(queries×ring)
                    # under the engine lock on every plain scrape
                    stages = {
                        qid: rec.stage_stats()
                        for qid, rec in server.engine.trace_recorders.items()
                    } if want_prom else {}
                if want_prom:
                    from ksql_tpu.common.metrics import prometheus_text

                    body = prometheus_text(
                        snap, stages, server=dict(server.metrics)
                    ).encode("utf-8")
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._send(200, {"server": dict(server.metrics), **snap})
            elif path.startswith("/query-trace/"):
                # recent tick spans for one query, straight off the flight
                # recorder ring (post-mortem / live-profiling endpoint).
                # ?since=<tick_seq> returns only ticks recorded after that
                # seq — same cursor contract as /timeline, so pollers stop
                # re-reading and re-parsing the whole ring every poll
                from urllib.parse import parse_qs, urlparse

                from ksql_tpu.common import timeline as tlm

                qid = path[len("/query-trace/"):]
                try:
                    since = tlm.since_param(
                        parse_qs(urlparse(self.path).query)
                    )
                except ValueError:
                    self._error(400, "since must be an integer tick seq")
                    return
                with server.engine_lock:
                    known = qid in server.engine.queries
                    rec = server.engine.trace_recorders.get(qid)
                    ticks = rec.recent() if rec is not None else []
                if not known and rec is None:
                    self._error(404, f"no query or trace for id {qid}")
                else:
                    if since is not None:
                        ticks = [
                            t for t in ticks if t.get("tick", 0) > since
                        ]
                    next_since = (
                        ticks[-1]["tick"] if ticks
                        else (since if since is not None else 0)
                    )
                    self._send(200, {
                        "queryId": qid,
                        "traceEnabled": server.engine.trace_enabled,
                        "ticks": ticks,
                        "nextSince": next_since,
                    })
            elif path.startswith("/timeline/"):
                # retained telemetry timeline for one query or push
                # pipeline (common/timeline.py): closed interval frames
                # after ?since=<interval_seq> plus the open frame; pass
                # nextSince back to poll incrementally
                from urllib.parse import parse_qs, urlparse

                from ksql_tpu.common import timeline as tlm

                qid = path[len("/timeline/"):]
                try:
                    since = tlm.since_param(
                        parse_qs(urlparse(self.path).query)
                    )
                except ValueError:
                    self._error(
                        400, "since must be an integer interval seq"
                    )
                    return
                with server.engine_lock:
                    known = qid in server.engine.queries
                    tl = server.engine.timelines.get(qid)
                    if tl is None and known and (
                        server.engine.telemetry_enabled
                    ):
                        # known query that has not ticked yet: an empty
                        # timeline, not a 404
                        tl = server.engine.timeline_store(qid)
                    body = tl.since(since) if tl is not None else None
                if body is None and not known:
                    self._error(404, f"no query or timeline for id {qid}")
                elif body is None:
                    self._send(200, {
                        "ownerId": qid, "frames": [], "nextSince": -1,
                        "telemetryEnabled": False,
                    })
                else:
                    body["telemetryEnabled"] = (
                        server.engine.telemetry_enabled
                    )
                    self._send(200, body)
            elif path == "/status":
                self._send(200, {"commandStatuses": {}})
            else:
                self._error(404, f"unknown path {path}")

        # thread entrypoint: ThreadingHTTPServer runs each request on its
        # own thread  # graftlint: entrypoint=http
        def do_POST(self):
            path = self.path.split("?")[0]
            try:
                if path == "/ksql":
                    body = self._body()
                    with server.engine_lock:
                        saved = dict(server.engine.session_properties)
                        try:
                            server.engine.session_properties.update(
                                body.get("streamsProperties", {}) or {}
                            )
                            # reviewed (blocking-under-lock): same
                            # justification as execute_statements — the
                            # engine lock is the statement-serialization
                            # point; the outer hold only extends it over
                            # the session-property save/restore
                            out = server.execute_statements(body.get("ksql", ""))  # graftlint: disable=blocking-under-lock
                        finally:
                            server.engine.session_properties = saved
                    self._send(200, out)
                elif path == "/query":
                    if self._overload_reject():
                        return
                    body = self._body()
                    res = server.run_query(
                        body.get("ksql", body.get("sql", "")),
                        forwarded=bool(body.get("forwarded", False)),
                    )
                    self._send(200, res)
                elif path == "/query-stream":
                    if self._overload_reject():
                        return
                    self._query_stream()
                elif path == "/close-query":
                    qid = self._body().get("queryId", "")
                    sess = server.push_queries.pop(qid, None)
                    if sess is not None:
                        sess.close()
                        self._send(200, {})
                    else:
                        self._error(400, f"No query with id {qid}")
                elif path == "/heartbeat":
                    b = self._body()
                    server.receive_heartbeat(
                        b.get("hostInfo", ""), int(b.get("timestamp", 0)),
                        queries=b.get("queries") or {},
                    )
                    self._send(200, {})
                elif path == "/lag":
                    b = self._body()
                    server.report_lag(b.get("host", ""), b.get("hostStoreLags", {}))
                    self._send(200, {})
                else:
                    self._error(404, f"unknown path {path}")
            except KsqlException as e:
                self._error(400, str(e))
            except Exception as e:  # noqa: BLE001
                server.mark_metric("errors")
                self._error(500, f"{type(e).__name__}: {e}")

        def _query_stream(self):
            """Newline-delimited JSON streaming (QueryStreamHandler.java:53):
            header object first, then one row array per line.  The whole
            response rides the server's inflight gauge — the overload
            monitor's ``inflight`` resource."""
            server._inflight_enter()
            try:
                self._query_stream_body()
            finally:
                server._inflight_exit()

        def _query_stream_body(self):
            body = self._body()
            sql = body.get("sql", body.get("ksql", ""))
            with server.engine_lock:
                prepared = server.engine.parse(sql)
            q = prepared[0].statement
            is_push = (
                isinstance(q, ast.Query)
                and q.refinement is not None
                and q.refinement.type == ast.RefinementType.CHANGES
            )
            if not is_push:
                res = server.run_query(sql)
                self.send_response(200)
                self.send_header("Content-Type", "application/vnd.ksqlapi.delimited.v1")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                self._chunk(json.dumps({
                    "queryId": res["queryId"], "columnNames": res["columnNames"],
                    "columnTypes": [],
                }))
                for row in res["rows"]:
                    self._chunk(json.dumps(row))
                self._chunk_end()
                return
            sess = server.open_push_query(sql)
            self.send_response(200)
            self.send_header("Content-Type", "application/vnd.ksqlapi.delimited.v1")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            self._chunk(json.dumps({
                "queryId": sess.id, "columnNames": sess.columns,
                "columnTypes": sess.column_types,
            }))
            deadline = time.time() + float(
                self.headers.get("X-Query-Timeout-Seconds", 10)
            )
            try:
                while not sess.done() and time.time() < deadline:
                    rows = server.poll_push_query(sess)
                    for row in rows:
                        if "__gap__" in row:
                            # session self-healed mid-stream: emit a gap
                            # marker object instead of a row array
                            self._chunk(json.dumps({"gap": row["__gap__"]}))
                        else:
                            self._chunk(json.dumps(
                                [row.get(c) for c in sess.columns]
                            ))
                    if not rows:
                        time.sleep(0.02)
                self._chunk_end()
            except (BrokenPipeError, ConnectionResetError):
                pass
            finally:
                sess.close()
                server.push_queries.pop(sess.id, None)

        def _chunk(self, line: str) -> None:
            data = (line + "\n").encode("utf-8")
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")

        def _chunk_end(self) -> None:
            self.wfile.write(b"0\r\n\r\n")

    return Handler
