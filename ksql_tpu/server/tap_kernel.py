"""Fused tap residuals — ONE batched device kernel per push pipeline.

PR 10 multiplexed N compatible push sessions as taps over one shared
pipeline, but each tap still evaluated its residual WHERE chain host-side,
row-at-a-time, in Python: per pump step the pipeline paid
O(taps x rows) interpreted predicate evaluations.  This module collapses
that to ONE device pass: the residual predicate chains of every tap are
lowered (through the same columnar expression compiler the device backend
uses, compiler/jax_expr.py) into a single jit-compiled kernel over the
shared emission batch — columnarized once per pump step — returning a
``taps x rows`` match bitmask plus per-tap LIMIT-aware match counts.
Per-tap delivery is then a bitmask read + a column gather of the matching
host rows (projections apply host-side to matched rows only, so delivered
bytes stay byte-identical to a dedicated session's oracle output).

Churn economics (the PR-7 family-attach idiom, applied to predicates):

* taps are grouped into **predicate families** by the *structure* of
  their residual chain — the expression tree with literal values
  abstracted into per-lane parameter vectors (``USER_ID % 64 = 3`` and
  ``USER_ID % 64 = 17`` are one family, two lanes);
* each family compiles at a padded power-of-two lane capacity with
  inactive lanes masked, so attach/detach *within* capacity is a
  parameter/mask update — **no retrace**;
* growth past capacity doubles the lane count and re-jits that family
  once (``device.compile`` lands on the shared pipeline's flight
  recorder, exactly like the pipeline's own executor compiles);
* emission batches pad to power-of-two row buckets, bounding the set of
  traced shapes.

Residuals the lowerer cannot compile (unsupported expressions, UDFs,
string ordering, LIKE, ...) keep the PR-10 host path *per tap*, with the
reason counted in ``engine.fallback_reasons`` (the ``windowing_fallback``
contract).  A kernel failure at evaluation time — including an injected
``push.residual.kernel`` fault — degrades the whole pipeline to host
residuals with one plog entry; taps never die from the fused path.

Thread-safety: all mutable kernel state (lane tables, parameter arrays,
span cache) is guarded by the owning pipeline's registry lock; tap polls
additionally serialize under the server's engine lock.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

jax.config.update("jax_enable_x64", True)  # 64-bit hashes/BIGINTs (as
# runtime/lowering.py does; this module can be reached without it when
# the pipeline itself runs the oracle backend)

import jax.numpy as jnp  # noqa: E402  (x64 must flip before first use)

from ksql_tpu.common import tracing
from ksql_tpu.common import types as T
from ksql_tpu.common.batch import stable_hash64
from ksql_tpu.common.types import SqlBaseType
from ksql_tpu.execution import expressions as ex
from ksql_tpu.execution import steps as st

#: row buckets the kernel traces over: batches pad up to the next bucket
#: so the set of compiled shapes stays logarithmic in the ring size
_ROW_BUCKET_MIN = 256

#: lanes with no LIMIT pass this sentinel (far above any poll bound)
_NO_LIMIT = np.int64(1) << 62


class ResidualUnsupported(Exception):
    """This tap's residual cannot lower to the fused kernel; the tap keeps
    the host path (reason lands in engine.fallback_reasons)."""


# --------------------------------------------------------------- structure
#
# A residual chain's *structure signature* is its expression trees with
# literal values abstracted out: two chains with equal signatures trace to
# the same jax computation and differ only in per-lane parameters.

#: literal classes whose value becomes an int64 lane parameter
_INT_PARAM = (ex.BooleanLiteral, ex.IntegerLiteral, ex.LongLiteral)
#: literal classes whose value becomes a float64 lane parameter
_FLOAT_PARAM = (ex.DoubleLiteral, ex.DecimalLiteral)
#: literal classes parameterized by their stable 64-bit hash (the device
#: encoding for STRING/BYTES — equality-only, like the device backend)
_HASH_PARAM = (ex.StringLiteral, ex.BytesLiteral)


def _param_of(e: ex.Expression) -> Optional[Tuple[str, Any]]:
    """(kind, value) when ``e`` is a parameterizable literal, else None."""
    if isinstance(e, _INT_PARAM):
        v = getattr(e, "value", None)
        return None if v is None else ("i", int(v))
    if isinstance(e, _FLOAT_PARAM):
        if isinstance(e, ex.DecimalLiteral):
            return ("f", float(e.text))
        v = e.value
        return None if v is None else ("f", float(v))
    if isinstance(e, _HASH_PARAM):
        v = e.value
        return None if v is None else ("i", int(stable_hash64(v)))
    return None


def _collect(e: Any, sig: List[str], lits: List[Tuple[str, Any]],
             slots: Optional[Dict[int, Tuple[str, int]]]) -> None:
    """Walk an expression tree appending structure tokens to ``sig`` and
    literal parameters to ``lits`` (pre-order — structurally identical
    trees produce identical signatures and positionally-aligned
    parameter lists).  ``slots`` (id(node) -> (kind, index)) is filled for
    the representative tree the kernel traces."""
    if isinstance(e, ex.Expression):
        p = _param_of(e)
        if p is not None:
            kind, value = p
            idx = sum(1 for k, _ in lits if k == kind)
            lits.append((kind, value))
            if slots is not None:
                slots[id(e)] = (kind, idx)
            # literal class stays in the signature: `x > 5` and `x > 5.0`
            # promote differently and must not share a trace
            sig.append(f"{type(e).__name__}#{kind}")
            return
        sig.append(type(e).__name__ + "(")
        for f in dataclasses.fields(e):
            sig.append(f.name + "=")
            _collect(getattr(e, f.name), sig, lits, slots)
        sig.append(")")
    elif isinstance(e, (list, tuple)):
        sig.append("[")
        for item in e:
            _collect(item, sig, lits, slots)
        sig.append("]")
    else:
        # enums, column/field names, SqlTypes, flags: structural
        sig.append(repr(e) if not hasattr(e, "base") else str(e))


@dataclasses.dataclass
class ResidualSpec:
    """One tap's compiled-residual classification: the predicate family it
    joins (``signature``), its lane parameters, and the source-side step
    prefix (through the root-most filter) the kernel evaluates."""

    signature: str
    params_i: np.ndarray  # (n_i,) int64
    params_f: np.ndarray  # (n_f,) float64
    mask_steps: List[Any]  # source-side-first, ends at the last filter
    slots: Dict[int, Tuple[str, int]]  # id(literal) -> (kind, param index)
    col_names: Tuple[str, ...]  # schema columns the family columnarizes


def classify_residual(residual_steps: List[Any], schema) -> Optional[ResidualSpec]:
    """Classify a tap's residual chain (root-side-first, as the registry
    holds it) for fused evaluation.

    Returns None for a pure projection (no WHERE): there is no predicate
    to fuse and delivery is already a plain gather.  Raises
    :class:`ResidualUnsupported` when the chain references columns the
    shared emission batch cannot columnarize or uses expressions the
    device compiler rejects (probed eagerly at attach, so the fallback
    reason is known before any row flows)."""
    from ksql_tpu.compiler.jax_expr import DeviceUnsupported

    src_first = list(reversed(residual_steps))
    last_filter = -1
    for i, s in enumerate(src_first):
        if isinstance(s, st.StreamFilter):
            last_filter = i
    if last_filter < 0:
        return None
    mask_steps = src_first[: last_filter + 1]

    sig: List[str] = []
    lits: List[Tuple[str, Any]] = []
    slots: Dict[int, Tuple[str, int]] = {}
    for s in mask_steps:
        if isinstance(s, st.StreamFilter):
            sig.append("|F:")
            _collect(s.predicate, sig, lits, slots)
        else:
            sig.append("|S:")
            sig.append(repr(tuple(c.name for c in s.schema.key_columns)))
            sig.append(repr(tuple(c.name for c in s.source.schema.key_columns)))
            for name, e0 in s.selects:
                sig.append(name + "<-")
                _collect(e0, sig, lits, slots)

    params_i = np.asarray([v for k, v in lits if k == "i"], np.int64)
    params_f = np.asarray([v for k, v in lits if k == "f"], np.float64)

    # columns the family needs from the emission batch: every ColumnRef
    # that resolves in the pipeline schema, plus key columns (the select
    # carry-through) and ROWTIME (always columnarized)
    referenced = set()
    for s in mask_steps:
        exprs = (
            [s.predicate] if isinstance(s, st.StreamFilter)
            else [e0 for _, e0 in s.selects]
        )
        for e0 in exprs:
            for node in ex.walk(e0):
                if isinstance(node, ex.ColumnRef):
                    referenced.add(node.name)
    schema_cols = {c.name: c.type for c in schema.columns()}
    key_names = [c.name for c in schema.key_columns]
    col_names = tuple(
        [n for n in schema_cols if n in referenced or n in key_names]
        + ["ROWTIME"]
    )
    spec = ResidualSpec(
        signature="".join(sig),
        params_i=params_i,
        params_f=params_f,
        mask_steps=mask_steps,
        slots=slots,
        col_names=col_names,
    )
    # eager compile probe on a 2-row dummy batch: DeviceUnsupported (and
    # unresolvable columns) surface HERE, at attach, with the reason —
    # not at first delivery
    try:
        _probe(spec, schema_cols)
    except DeviceUnsupported as e:
        raise ResidualUnsupported(str(e)) from e
    return spec


def _dummy_cols(col_names, schema_cols, n: int):
    from ksql_tpu.compiler.jax_expr import _dtype_for
    datas, valids, types = [], [], []
    for name in col_names:
        t = T.BIGINT if name == "ROWTIME" else schema_cols[name]
        datas.append(jnp.zeros(n, _dtype_for(t)))
        valids.append(jnp.ones(n, bool))
        types.append(t)
    return tuple(datas), tuple(valids), tuple(types)


def _probe(spec: ResidualSpec, schema_cols: Dict[str, Any]) -> None:
    """Trace the lane function once, eagerly, over a tiny dummy batch —
    the attach-time compilability check."""
    from ksql_tpu.compiler.jax_expr import DeviceUnsupported
    for name in spec.col_names:
        if name != "ROWTIME" and name not in schema_cols:
            raise DeviceUnsupported(f"column {name} not in the shared batch")
    datas, valids, types = _dummy_cols(spec.col_names, schema_cols, 2)
    lane = _lane_fn(spec, types)
    # eval_shape traces without executing — cheap, and raises the same
    # DeviceUnsupported a real trace would
    jax.eval_shape(
        lane, datas, valids,
        np.zeros_like(spec.params_i), np.zeros_like(spec.params_f),
    )


# ----------------------------------------------------------------- tracing


def _lane_fn(spec: ResidualSpec, col_types):
    """The per-lane traced function: (batch columns, lane params) -> row
    match mask, mirroring the oracle FilterNode/SelectNode semantics the
    host path runs (jax_expr already pins device/oracle parity)."""
    from ksql_tpu.compiler.jax_expr import DCol, JaxExprCompiler, _dtype_for
    class _ParamCompiler(JaxExprCompiler):
        """Literals read from the lane's parameter vectors, so every lane
        of a family shares ONE trace."""

        def __init__(self, env, n, p_i, p_f):
            super().__init__(env, n)
            self._p_i = p_i
            self._p_f = p_f

        def _param_col(self, e, sql_type):
            kind, idx = spec.slots[id(e)]
            vec = self._p_i if kind == "i" else self._p_f
            dt = _dtype_for(sql_type)
            data = jnp.broadcast_to(vec[idx].astype(dt), (self.n,))
            return DCol(data, jnp.ones(self.n, bool), sql_type)

        def _c_BooleanLiteral(self, e):
            return self._param_col(e, T.BOOLEAN)

        def _c_IntegerLiteral(self, e):
            return self._param_col(e, T.INTEGER)

        def _c_LongLiteral(self, e):
            return self._param_col(e, T.BIGINT)

        def _c_DoubleLiteral(self, e):
            return self._param_col(e, T.DOUBLE)

        def _c_DecimalLiteral(self, e):
            return self._param_col(e, T.DOUBLE)

        def _c_StringLiteral(self, e):
            return self._param_col(e, T.STRING)

        def _c_BytesLiteral(self, e):
            return self._param_col(e, T.BYTES)

    col_names = spec.col_names
    # the step chain's name flow is fully static: precompute each select
    # step's key carry-through pairs against the names live at that point,
    # so the traced body below never branches on the (tracer-holding) env
    plans = []
    live = set(col_names)
    for s0 in spec.mask_steps:
        if isinstance(s0, st.StreamFilter):
            plans.append(("filter", s0.predicate, None))
        else:
            carries = [
                (nn.name, on.name)
                for nn, on in zip(
                    s0.schema.key_columns, s0.source.schema.key_columns
                )
                if on.name in live
            ]
            plans.append(("select", s0.selects, carries))
            live = {nn for nn, _ in carries}
            live.update(name for name, _ in s0.selects)
            live.add("ROWTIME")

    # jit-traced (vmapped over lanes inside _trace_group): the expression
    # trees/step plans are trace-time statics from the enclosing spec;
    # only batch columns and lane parameters are traced values
    def _trace_lane(datas, valids, p_i, p_f):
        n = datas[0].shape[0]
        env = {
            name: DCol(d, v, t)
            for name, d, v, t in zip(col_names, datas, valids, col_types)
        }
        mask = jnp.ones(n, bool)
        for kind, payload, carries in plans:
            comp = _ParamCompiler(env, n, p_i, p_f)
            if kind == "filter":
                p = comp.compile(payload)
                # NULL predicate -> not True -> drop (oracle FilterNode)
                mask = mask & p.valid & p.data.astype(bool)
            else:
                out = {nn: env[on] for nn, on in carries}
                for name, e0 in payload:
                    out[name] = comp.compile(e0)
                out["ROWTIME"] = env["ROWTIME"]
                env = out
        return mask

    return _trace_lane


# ------------------------------------------------------------------ family


class _LaneGroup:
    """One predicate family: taps whose residual chains share a structure
    signature, packed into the lanes of one traced kernel."""

    def __init__(self, spec: ResidualSpec, col_types, capacity: int):
        self.signature = spec.signature
        self.rep = spec  # representative tree the kernel traces
        self.col_types = col_types
        self.capacity = capacity
        self.lanes: List[Optional[str]] = [None] * capacity  # tap ids
        self.lane_of: Dict[str, int] = {}
        n_i, n_f = len(spec.params_i), len(spec.params_f)
        self.P_i = np.zeros((capacity, n_i), np.int64)
        self.P_f = np.zeros((capacity, n_f), np.float64)
        self.active = np.zeros(capacity, bool)
        self._fn = None  # jitted; rebuilt on capacity growth

    def n_active(self) -> int:
        return int(self.active.sum())

    def add(self, tap_id: str, spec: ResidualSpec) -> bool:
        """Claim a lane (parameter write, no retrace).  False = full."""
        for i in range(self.capacity):
            if self.lanes[i] is None:
                self.lanes[i] = tap_id
                self.lane_of[tap_id] = i
                self.P_i[i] = spec.params_i
                self.P_f[i] = spec.params_f
                self.active[i] = True
                return True
        return False

    def remove(self, tap_id: str) -> None:
        i = self.lane_of.pop(tap_id, None)
        if i is not None:
            self.lanes[i] = None
            self.active[i] = False  # mask update only — no retrace

    def grow(self) -> None:
        """Double the lane capacity (family-attach idiom): pad the
        parameter/active arrays and drop the jitted fn so the next
        evaluation re-traces once at the new tier."""
        new_cap = self.capacity * 2
        pad = new_cap - self.capacity
        self.P_i = np.concatenate(
            [self.P_i, np.zeros((pad, self.P_i.shape[1]), np.int64)]
        )
        self.P_f = np.concatenate(
            [self.P_f, np.zeros((pad, self.P_f.shape[1]), np.float64)]
        )
        self.active = np.concatenate([self.active, np.zeros(pad, bool)])
        self.lanes.extend([None] * pad)
        self.capacity = new_cap
        self._fn = None

    def fn(self):
        if self._fn is None:
            lane = _lane_fn(self.rep, self.col_types)

            # jit-traced: the whole family in one call — lanes vmapped
            # over the shared batch, inactive lanes and gap/pad rows
            # masked, counts clipped by the per-lane LIMIT budget
            def _trace_group(datas, valids, P_i, P_f, active, row_valid,
                             limits):
                masks = jax.vmap(
                    lane, in_axes=(None, None, 0, 0)
                )(datas, valids, P_i, P_f)
                masks = masks & active[:, None] & row_valid[None, :]
                counts = jnp.minimum(
                    masks.sum(axis=1, dtype=jnp.int64), limits
                )
                return masks, counts

            self._fn = jax.jit(_trace_group)
        return self._fn


# ------------------------------------------------------------------ kernel


def _bucket_rows(n: int) -> int:
    b = _ROW_BUCKET_MIN
    while b < n:
        b *= 2
    return b


class TapKernel:
    """Per-pipeline fused residual kernel: predicate families, the span
    mask cache, and the columnarizer.  All state guarded by ``lock`` (the
    owning registry's RLock); evaluation additionally serializes under the
    server's engine lock like every tap poll."""

    def __init__(self, pipeline, schema, lock, *, capacity_min: int,
                 capacity_max: int, min_taps: int):
        self.pipeline = pipeline
        self.schema = schema
        self.schema_cols = {c.name: c.type for c in schema.columns()}
        self.lock = lock
        self.capacity_min = max(1, capacity_min)
        self.capacity_max = max(self.capacity_min, capacity_max)
        self.min_taps = max(1, min_taps)
        self.groups: Dict[str, _LaneGroup] = {}
        self.group_of: Dict[str, _LaneGroup] = {}  # tap id -> group
        self.epoch = 0  # bumped on any membership change (cache key)
        self.degraded: Optional[str] = None  # reason, once
        self.compile_epochs = 0  # device.compile events (growth tiers)
        self.block_spans = 0  # spans served from device emit blocks
        # span cache: (start_seq, n_entries, epoch) -> evaluated spans;
        # taps polling in lockstep (the steady state) share one kernel
        # run per span
        self._spans: "OrderedDict[tuple, dict]" = OrderedDict()
        self._span_cache_max = 4

    # ---------------------------------------------------------- membership
    def attach(self, tap_id: str, spec: ResidualSpec) -> None:
        """Join the tap's predicate family (creating it at the configured
        base capacity); growth past capacity re-jits, attach within it is
        a parameter write."""
        with self.lock:
            grp = self.groups.get(spec.signature)
            if grp is None:
                cap = 1
                while cap < self.capacity_min:
                    cap *= 2
                _, _, types = _dummy_cols(
                    spec.col_names, self.schema_cols, 1
                )
                grp = _LaneGroup(spec, types, cap)
                self.groups[spec.signature] = grp
            while not grp.add(tap_id, spec):
                if grp.capacity * 2 > self.capacity_max:
                    raise ResidualUnsupported(
                        f"fused lane capacity cap reached "
                        f"({self.capacity_max}); tap keeps the host path"
                    )
                grp.grow()
            self.group_of[tap_id] = grp
            self.epoch += 1

    def detach(self, tap_id: str) -> None:
        with self.lock:
            grp = self.group_of.pop(tap_id, None)
            if grp is not None:
                grp.remove(tap_id)
                if not grp.lane_of:
                    self.groups.pop(grp.signature, None)
                self.epoch += 1

    def fused_tap_count(self) -> int:
        with self.lock:
            return len(self.group_of)

    # ---------------------------------------------------------- evaluation
    def mask_for(self, tap_id: str, start_seq: int, entries) -> Optional[dict]:
        """The evaluated span for a tap's read window: ``{"mask": row mask
        over entries, "count": LIMIT-aware matches, "max_ts": span max
        event time}`` — or None (degraded kernel / below min-taps / tap
        not fused / span not columnarizable), in which case the caller
        runs the host residual path.

        ``count`` is the kernel's matches clipped by the lane's LIMIT
        budget *as of evaluation time*; spans are cached across taps and
        polls, so delivery re-derives the live remaining budget itself
        and treats the cached count as advisory (tracing/diagnostics)."""
        with self.lock:
            if self.degraded is not None:
                return None
            grp = self.group_of.get(tap_id)
            if grp is None or len(self.group_of) < self.min_taps:
                return None
            key = (start_seq, len(entries), self.epoch)
            span = self._spans.get(key)
            if span is None:
                try:
                    span = self._evaluate_span(start_seq, entries)
                except Exception as e:  # noqa: BLE001 — kernel failure
                    # degrades the PIPELINE to host residuals, loudly and
                    # once; taps never die from the fused path
                    self._degrade(e)
                    return None
                self._spans[key] = span
                while len(self._spans) > self._span_cache_max:
                    self._spans.popitem(last=False)
            lane_masks = span["groups"].get(grp.signature)
            if lane_masks is None:
                return None
            lane = grp.lane_of.get(tap_id)
            if lane is None or lane >= lane_masks["masks"].shape[0]:
                return None
            return {
                "mask": lane_masks["masks"][lane],
                "count": int(lane_masks["counts"][lane]),
                "max_ts": span["max_ts"],
            }

    def _degrade(self, e: Exception) -> None:
        """One plog entry, one regime change: every tap on this pipeline
        silently keeps its (always-correct) host residual path."""
        self.degraded = f"{type(e).__name__}: {e}"
        self._spans.clear()
        pipe = self.pipeline
        reg = pipe.registry
        reg.residual_degraded += 1
        pipe.engine._plog_append(
            f"push.residual.degrade:{pipe.id}",
            f"fused residual kernel failed ({self.degraded}); pipeline "
            f"degrades to host residual evaluation for all "
            f"{len(pipe.taps)} tap(s) — delivery continues",
        )

    def _evaluate_span(self, start_seq: int, entries) -> dict:
        """Columnarize the span once and run every family's kernel over
        it; records the ``push.residual.kernel`` span (rows/taps/jit
        hit-miss) — and ``device.compile`` on a re-trace — on the shared
        pipeline's flight recorder."""
        from ksql_tpu.common import faults

        pipe = self.pipeline
        # chaos seam: fail the fused kernel under many taps
        # (scripts/chaos_soak.py --fanout; degrade-to-host contract)
        faults.fault_point("push.residual.kernel", pipe.id)
        n = len(entries)
        bucket = _bucket_rows(n)
        needed = set()
        for grp in self.groups.values():
            needed.update(grp.rep.col_names)
        cols, row_valid, max_ts = self._columnarize(
            start_seq, entries, needed, bucket
        )
        rec = pipe.engine.recorder_if_enabled(pipe.id)
        out_groups: Dict[str, dict] = {}
        with tracing.tick(rec):
            with tracing.span("push.residual.kernel"):
                for sig, grp in self.groups.items():
                    if not grp.n_active():
                        continue
                    limits = np.full(grp.capacity, _NO_LIMIT, np.int64)
                    for tid, lane in grp.lane_of.items():
                        limits[lane] = self._limit_remaining(tid)
                    datas = tuple(cols[c][0] for c in grp.rep.col_names)
                    valids = tuple(cols[c][1] for c in grp.rep.col_names)
                    fn = grp.fn()
                    size = getattr(fn, "_cache_size", None)
                    before = size() if size is not None else 0
                    t0 = time.perf_counter()
                    masks, counts = fn(
                        datas, valids, grp.P_i, grp.P_f,
                        grp.active, row_valid, limits,
                    )
                    masks = np.asarray(masks)[:, :n]
                    counts = np.asarray(counts)
                    missed = (size() if size is not None else 0) - before
                    if missed > 0:
                        # a growth tier (or new family / row bucket)
                        # traced: account it exactly like a device step
                        # compile so the acceptance invariant — one
                        # compile epoch per capacity tier — is countable
                        # on the pipeline's recorder
                        self.compile_epochs += 1
                        pipe.registry.residual_compile_epochs += 1
                        tracing.stage(
                            "device.compile", time.perf_counter() - t0,
                            jit_miss=missed,
                        )
                        tracing.counter(
                            "push.residual.kernel", jit_miss=missed
                        )
                    else:
                        tracing.counter("push.residual.kernel", jit_hit=1)
                    out_groups[sig] = {"masks": masks, "counts": counts}
                tracing.counter(
                    "push.residual.kernel", rows=n,
                    taps=len(self.group_of),
                )
        reg = pipe.registry
        reg.residual_kernel_evals += 1
        reg.residual_kernel_rows += n
        return {"groups": out_groups, "max_ts": max_ts}

    def _limit_remaining(self, tap_id: str):
        tap = self.pipeline.taps.get(tap_id)
        sess = getattr(tap, "session", None)
        limit = getattr(sess, "limit", None)
        if limit is None:
            return _NO_LIMIT
        done = getattr(sess, "_results", 0)
        return np.int64(max(int(limit) - int(done), 0))

    def _columnarize(self, start_seq: int, entries, needed, bucket: int):
        """Ring entries -> padded (data, valid) arrays per needed column
        (+ ROWTIME), a row-validity mask (False on GAP entries, null rows
        and padding), and the span's max event time.  One pass shared by
        every family and every tap reading this span.

        When the pipeline's listener-mode upstream runs on the device
        backend, its emission batches arrive as columnar device blocks
        (``_emit_blocks``) and this host-row re-encode is skipped — the
        arrays stay device-resident (engine handoff satellite)."""
        from ksql_tpu.compiler.jax_expr import _dtype_for
        from ksql_tpu.server.push_registry import ROW

        rows_meta = []  # (index, row dict, ts)
        max_ts = None
        for i, (kind, payload) in enumerate(entries):
            if kind != ROW:
                continue
            _, row, ts0 = payload
            # the watermark folds EVERY emission's event time — null-row
            # tombstones included, exactly like the host path's per-row
            # note_watermark — while only non-null rows columnarize
            max_ts = ts0 if max_ts is None else max(max_ts, ts0)
            if row is None:
                continue
            rows_meta.append((i, row, ts0))
        block = self._block_cols(start_seq, entries, needed, bucket)
        if block is not None:
            self.block_spans += 1
            cols, row_valid = block
            return cols, row_valid, max_ts
        row_valid = np.zeros(bucket, bool)
        cols: Dict[str, tuple] = {}
        for name in needed:
            t = T.BIGINT if name == "ROWTIME" else self.schema_cols.get(name)
            if t is None:
                continue
            dt = _dtype_for(t)
            data = np.zeros(bucket, dt)
            valid = np.zeros(bucket, bool)
            hashed = t.base in (
                SqlBaseType.STRING, SqlBaseType.BYTES, SqlBaseType.ARRAY,
                SqlBaseType.MAP, SqlBaseType.STRUCT,
            )
            for i, row, ts0 in rows_meta:
                v = ts0 if name == "ROWTIME" else row.get(name)
                if v is None:
                    continue
                try:
                    if hashed:
                        data[i] = stable_hash64(v)
                    elif t.base == SqlBaseType.BOOLEAN:
                        data[i] = bool(v)
                    elif np.issubdtype(dt, np.integer):
                        data[i] = int(v)
                    else:
                        data[i] = float(v)
                except (TypeError, ValueError, OverflowError) as e:
                    raise ResidualUnsupported(
                        f"column {name} value {v!r} not columnarizable"
                    ) from e
                valid[i] = True
            cols[name] = (jnp.asarray(data), jnp.asarray(valid))
        for i, _row, _ts0 in rows_meta:
            row_valid[i] = True
        return cols, jnp.asarray(row_valid), max_ts

    def _block_cols(self, start_seq: int, entries, needed, bucket: int):
        """Assemble the span's columns from listener-mode device emission
        blocks when consecutive blocks tile it exactly (and the span has
        no interleaved gap markers) — the device-resident fast path.
        Returns None when blocks are absent/misaligned, and the host
        columnarizer runs instead."""
        from ksql_tpu.server.push_registry import ROW

        blocks = getattr(self.pipeline, "_emit_blocks", None)
        if not blocks:
            return None
        n = len(entries)
        if any(kind != ROW for kind, _ in entries):
            return None
        # pick the consecutive run of blocks tiling [start_seq, start_seq+n)
        run = []
        pos = start_seq
        for bstart, bn, blk in blocks:
            if bstart + bn <= start_seq or pos >= start_seq + n:
                continue
            if bstart != pos:
                return None  # hole (or partial overlap): host path
            run.append(blk)
            pos = bstart + bn
        if pos != start_seq + n:
            return None
        for name in needed:
            if name == "ROWTIME":
                continue
            if any(name not in blk["cols"] for blk in run):
                return None  # 2-D/vector column the block skipped
        cols: Dict[str, tuple] = {}
        for name in needed:
            if name == "ROWTIME":
                parts = [blk["ts"] for blk in run]
                data = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
                valid = jnp.ones(data.shape[0], bool)
            else:
                dparts = [blk["cols"][name][0] for blk in run]
                vparts = [blk["cols"][name][1] for blk in run]
                data = (
                    jnp.concatenate(dparts) if len(dparts) > 1 else dparts[0]
                )
                valid = (
                    jnp.concatenate(vparts) if len(vparts) > 1 else vparts[0]
                )
            if data.shape[0] != bucket:
                pad = bucket - data.shape[0]
                data = jnp.concatenate([data, jnp.zeros(pad, data.dtype)])
                valid = jnp.concatenate([valid, jnp.zeros(pad, bool)])
            cols[name] = (data, valid)
        row_valid = np.zeros(bucket, bool)
        row_none = np.concatenate([blk["row_none"] for blk in run])
        row_valid[:n] = ~row_none
        return cols, jnp.asarray(row_valid)
