"""Server entrypoint — ``python -m ksql_tpu.server``.

KsqlServerMain.java:46 analog: parse flags/properties, build the engine,
serve.  ``--queries-file`` (or ksql.queries.file in --properties) starts
the node headless (StandaloneExecutor.java:73): the SQL file defines the
queries and the REST API serves reads only.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ksql-server")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8088)
    p.add_argument("--properties", help="JSON file of ksql.* config keys")
    p.add_argument("--queries-file",
                   help="headless mode: run this SQL file, serve reads only")
    p.add_argument("--command-log", help="command-log WAL path")
    p.add_argument("--peers", nargs="*", default=None,
                   help="peer server URLs (heartbeats + pull forwarding)")
    args = p.parse_args(argv)

    props = {}
    if args.properties:
        with open(args.properties) as f:
            props.update(json.load(f))
    if args.queries_file:
        props["ksql.queries.file"] = args.queries_file

    import os

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        # a preloaded accelerator registration pins the platform at boot;
        # honor the env var the way tests/bench do
        import jax

        try:
            jax.config.update("jax_platforms", plat)
        except RuntimeError:
            pass

    from ksql_tpu.common.config import KsqlConfig
    from ksql_tpu.engine.engine import KsqlEngine
    from ksql_tpu.server.rest import KsqlServer

    engine = KsqlEngine(KsqlConfig(props))
    server = KsqlServer(
        engine=engine, host=args.host, port=args.port,
        command_log_path=args.command_log, peers=args.peers,
    )
    server.start()
    mode = "headless" if server.headless else "interactive"
    print(f"ksql server listening on {server.url} ({mode})", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
